"""Quantile binning: raw features → small integer bin indices.

The reference samples rows to compute bin boundaries then broadcasts them to
workers (reference: LightGBMBase.scala:499-527 calculateRowStatistics →
sample → collect → broadcast; native binning in the LightGBM C++ lib).
Here binning is explicit: :class:`BinMapper` holds per-feature upper bin
boundaries; mapping is a jit-friendly ``searchsorted``.

TPU notes: bins are ``int32`` (dense, static shape); missing values (NaN)
get their own bin 0 so split decisions can route them; the last bin catches
+inf.  ``max_bin`` defaults to 255 content bins + the NaN bin = 256 total,
keeping histograms at power-of-two lane width.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

MISSING_BIN = 0  # NaN bucket; content bins are 1..max_bin


@dataclasses.dataclass
class BinMapper:
    """Per-feature quantile bin boundaries.

    ``upper_bounds[f, b]`` is the inclusive upper raw-value bound of content
    bin ``b+1``; shape (num_features, max_bin).  Unused trailing bins repeat
    +inf.  ``num_bins[f]`` counts distinct content bins for feature f.
    """
    upper_bounds: np.ndarray          # (F, max_bin) float32
    num_bins: np.ndarray              # (F,) int32
    max_bin: int
    #: categorical features: {feature index: (sorted raw values, bin ids)}
    #: — bin ids are target-statistic ordered (LightGBM's sorted-by-G/H
    #: idea applied at binning time), so range splits in bin space act as
    #: category-subset splits; unseen categories land in bin 0
    cat_features: Optional[dict] = None

    @property
    def num_features(self) -> int:
        return self.upper_bounds.shape[0]

    @property
    def total_bins(self) -> int:      # content bins + missing bin
        return self.max_bin + 1

    @property
    def has_categorical(self) -> bool:
        return bool(self.cat_features)

    def transform(self, features: np.ndarray) -> np.ndarray:
        """Map raw (n, F) floats → (n, F) int32 bins ∈ [0, max_bin].

        Accepts any float dtype (the bf16 colstore's streamed chunks
        arrive as exact f32 upcasts of bf16-rounded values — see
        ``io.colstore.write_matrix(dtype="bf16")``: boundaries are
        quantiles, so bf16-rounding the values moves a row across a
        boundary only when it was within one rounding ulp of it)."""
        features = np.asarray(features, np.float32)
        n, f = features.shape
        out = np.empty((n, f), np.int32)
        cat = self.cat_features or {}
        for j in range(f):
            col = features[:, j]
            if j in cat:
                vals, bins = cat[j]
                if len(vals) == 0:       # all-NaN fit sample: empty LUT
                    out[:, j] = MISSING_BIN
                    continue
                idx = np.searchsorted(vals, col)
                idx_c = np.minimum(idx, len(vals) - 1)
                hit = vals[idx_c] == col
                out[:, j] = np.where(hit, bins[idx_c], MISSING_BIN)
                continue
            # searchsorted over this feature's bounds; bin ids are 1-based
            idx = np.searchsorted(self.upper_bounds[j], col, side="left")
            out[:, j] = np.minimum(idx, self.max_bin - 1) + 1
            out[np.isnan(col), j] = MISSING_BIN
        return out

    def bin_threshold_value(self, feature: int, bin_id: int) -> float:
        """Raw-value threshold for 'bin <= bin_id' splits (for raw predict)."""
        return float(self.upper_bounds[feature, max(bin_id - 1, 0)])


@dataclasses.dataclass
class FeatureBundler:
    """Exclusive feature bundling (EFB) over BINNED features.

    LightGBM's answer to sparse/one-hot data (the native ``enable_bundle``
    machinery behind the config strings of params/BaseTrainParams.scala):
    features that are rarely non-default simultaneously merge into one
    bundled column whose bin space concatenates their non-default bins —
    histogram width drops from O(F·B) to O(bundles·B), which is also this
    build's densification strategy for one-hot-heavy matrices (SURVEY §7
    "sparse data" hard part).

    ``bundle_of[f]`` / ``offset_of[f]`` place original feature ``f``;
    ``owner[b, k]`` inverts a bundled bin back to its original feature so
    split attributions map home.  Default bins (each feature's most common
    bin in the fit sample) collapse to bundled bin 0.
    """
    bundle_of: np.ndarray        # (F,) int32 bundle id per original feature
    offset_of: np.ndarray        # (F,) int32 bin offset inside the bundle
    default_bin: np.ndarray      # (F,) int32 the bin that maps to 0
    num_bins: np.ndarray         # (n_bundles,) int32 total bins per bundle
    owner: list                  # per bundle: (total_bins,) int32 orig feature
    n_features: int

    @property
    def num_bundles(self) -> int:
        return len(self.num_bins)

    @staticmethod
    def fit(binned_sample: np.ndarray, num_bins: np.ndarray,
            max_total_bins: int = 256,
            max_conflict_rate: float = 0.0) -> "FeatureBundler":
        """Greedy conflict-bounded bundling (LightGBM's graph-coloring
        heuristic): features ordered by non-default density each join the
        first bundle whose added conflicts stay within
        ``max_conflict_rate`` of the sample and whose bin budget fits."""
        n, F = binned_sample.shape
        default_bin = np.empty(F, np.int32)
        nondef = np.empty((n, F), bool)
        for f in range(F):
            counts = np.bincount(binned_sample[:, f],
                                 minlength=int(num_bins[f]) + 1)
            default_bin[f] = int(np.argmax(counts))
            nondef[:, f] = binned_sample[:, f] != default_bin[f]
        density = nondef.sum(axis=0)
        order = np.argsort(-density, kind="stable")
        budget = int(max_conflict_rate * n)

        bundle_of = np.full(F, -1, np.int32)
        bundles: list = []          # per bundle: [feature ids]
        bundle_mask: list = []      # per bundle: rows with any non-default
        bundle_bins: list = []      # per bundle: current extra-bin total
        for f in order:
            extra = int(num_bins[f])          # non-default bins of f (+1 slack)
            placed = False
            for bi in range(len(bundles)):
                conflicts = int(np.sum(bundle_mask[bi] & nondef[:, f]))
                if conflicts <= budget and \
                        1 + bundle_bins[bi] + extra <= max_total_bins:
                    bundles[bi].append(int(f))
                    bundle_mask[bi] |= nondef[:, f]
                    bundle_bins[bi] += extra
                    bundle_of[f] = bi
                    placed = True
                    break
            if not placed:
                bundles.append([int(f)])
                bundle_mask.append(nondef[:, f].copy())
                bundle_bins.append(extra)
                bundle_of[f] = len(bundles) - 1

        offset_of = np.zeros(F, np.int32)
        owners = []
        total = np.zeros(len(bundles), np.int32)
        for bi, feats in enumerate(bundles):
            off = 0                            # bundled bin 0 = all-default
            own = [feats[0]]                   # bin 0 owner: first feature
            for f in feats:
                offset_of[f] = off
                own.extend([f] * int(num_bins[f]))
                off += int(num_bins[f])
            total[bi] = off + 1
            owners.append(np.asarray(own, np.int32))
        return FeatureBundler(bundle_of=bundle_of, offset_of=offset_of,
                              default_bin=default_bin, num_bins=total,
                              owner=owners, n_features=F)

    def transform(self, binned: np.ndarray) -> np.ndarray:
        """(n, F) original bins → (n, n_bundles) bundled bins.

        A row's bundled bin is the remapped bin of its LAST-ordered
        non-default feature in the bundle (with max_conflict_rate 0 at most
        one exists; under allowed conflicts this is the deterministic
        tie-break)."""
        n = binned.shape[0]
        out = np.zeros((n, self.num_bundles), binned.dtype
                       if binned.dtype.itemsize >= 2 else np.uint16)
        for f in range(self.n_features):
            bi = self.bundle_of[f]
            col = binned[:, f]
            nd = col != self.default_bin[f]
            # non-default bins rank 1..num_bins in order, skipping default:
            # rank = bin + (bin < default ? 1 : 0) keeps ids dense
            rank = col + np.where(col < self.default_bin[f], 1, 0)
            vals = self.offset_of[f] + rank
            out[nd, bi] = vals[nd].astype(out.dtype)
        return out

    def owner_of_split(self, bundle: int, bundled_bin: int) -> int:
        """Original feature owning a bundled split bin (importance remap)."""
        own = self.owner[bundle]
        return int(own[min(max(bundled_bin, 0), len(own) - 1)])

    def route_tables(self, num_bins: np.ndarray, total_bins: int) -> dict:
        """Static arrays that make EFB invisible to the growers (the
        LightGBM scheme: bundling compresses HISTOGRAM construction, but
        split search and the trees stay in ORIGINAL feature space).

        Per original feature ``f`` (all ``(F,)`` int32):
        - ``col``: the bundled column holding f,
        - ``lo``/``hi``: f's bundled-bin range is ``(lo, hi]`` — a row
          outside it has f at its default bin (``lo`` doubles as the rank
          base for thresholds),
        - ``default_bin``: f's default original bin.

        ``gather_src`` ((F, B) int32) maps the ORIGINAL histogram cell
        (f, b) to a flat index into the bundled histogram, with ``-2``
        marking f's default bin (mass = node total − Σ other bins — rows
        whose f is default sit at bundled bin 0 OR inside other features'
        ranges) and ``-1`` marking out-of-range bins (zero).

        An original split (f, b) routes from the bundled column as::

            in_range = (xb > lo[f]) & (xb <= hi[f])
            go_left  = in_range ? xb <= lo[f] + rank(b) : default_bin[f] <= b

        with ``rank(b) = b + (b < default_bin[f])`` (the skip-default rank
        the transform assigns) — monotone in b, so one threshold suffices.
        """
        F = self.n_features
        col = self.bundle_of.astype(np.int32)
        lo = self.offset_of.astype(np.int32)
        hi = (self.offset_of + num_bins[:F].astype(np.int32)).astype(np.int32)
        gather = np.full((F, total_bins), -1, np.int64)
        Bb = total_bins                       # bundled hists share the width
        for f in range(F):
            d = int(self.default_bin[f])
            for b in range(int(num_bins[f]) + 1):
                if b >= total_bins:
                    break
                if b == d:
                    gather[f, b] = -2
                else:
                    rank = b + (1 if b < d else 0)
                    gather[f, b] = int(col[f]) * Bb + int(lo[f]) + rank
        return {"col": col, "lo": lo, "hi": hi,
                "default_bin": self.default_bin.astype(np.int32),
                "gather_src": gather}

    def to_dict(self) -> dict:
        return {"bundle_of": self.bundle_of.tolist(),
                "offset_of": self.offset_of.tolist(),
                "default_bin": self.default_bin.tolist(),
                "num_bins": self.num_bins.tolist(),
                "owner": [o.tolist() for o in self.owner],
                "n_features": self.n_features}

    @staticmethod
    def from_dict(d: dict) -> "FeatureBundler":
        return FeatureBundler(
            bundle_of=np.asarray(d["bundle_of"], np.int32),
            offset_of=np.asarray(d["offset_of"], np.int32),
            default_bin=np.asarray(d["default_bin"], np.int32),
            num_bins=np.asarray(d["num_bins"], np.int32),
            owner=[np.asarray(o, np.int32) for o in d["owner"]],
            n_features=d["n_features"])


def fit_bin_mapper(features: np.ndarray, max_bin: int = 255,
                   sample_count: int = 200_000,
                   seed: int = 0,
                   categorical_features=None,
                   y: Optional[np.ndarray] = None) -> BinMapper:
    """Compute quantile bin boundaries from a row sample.

    Mirrors the reference's sampled dataset creation
    (LGBM_DatasetCreateFromSampledColumn, StreamingPartitionTask.scala:374):
    sample rows, per-feature quantiles as boundaries, dedup to distinct
    values when a feature has few uniques.

    ``categorical_features``: feature indexes treated as category codes
    (the reference's categoricalSlotIndexes param,
    params/LightGBMParams.scala).  Their bins are ordered by the mean of
    ``y`` per category when labels are provided — the sorted-by-target-
    statistic trick that lets monotone bin-range splits act like
    LightGBM's category-subset splits — else by value; categories beyond
    ``max_bin`` (rarest first) and unseen ones fall into bin 0.
    """
    n, f = features.shape
    if n > sample_count:
        rng = np.random.default_rng(seed)
        pick = rng.choice(n, sample_count, replace=False)
        sample = features[pick]
        y_sample = None if y is None else np.asarray(y)[pick]
    else:
        sample = features
        y_sample = None if y is None else np.asarray(y)
    upper = np.full((f, max_bin), np.inf, np.float32)
    nbins = np.zeros(f, np.int32)
    cat_set = set(int(c) for c in (categorical_features or []))
    cat_out: dict = {}
    for j in range(f):
        col = sample[:, j]
        if j in cat_set:
            valid = ~np.isnan(col)
            vals, inv, counts = np.unique(col[valid], return_inverse=True,
                                          return_counts=True)
            if len(vals) > max_bin:      # keep the most frequent max_bin
                keep = np.sort(np.argsort(-counts)[:max_bin])
                remap = np.full(len(vals), -1)
                remap[keep] = np.arange(len(keep))
                mask = remap[inv] >= 0
                vals, inv, counts = (vals[keep],
                                     remap[inv][mask],
                                     counts[keep])
                yv = (y_sample[valid][mask]
                      if y_sample is not None else None)
            else:
                yv = y_sample[valid] if y_sample is not None else None
            if yv is not None and len(vals):
                sums = np.bincount(inv, weights=yv, minlength=len(vals))
                order = np.argsort(sums / np.maximum(counts, 1),
                                   kind="stable")
            else:
                order = np.arange(len(vals))
            bins = np.empty(len(vals), np.int32)
            bins[order] = np.arange(1, len(vals) + 1)
            cat_out[j] = (vals.astype(np.float32), bins)
            nbins[j] = len(vals)
            continue
        col = col[~np.isnan(col)]
        if col.size == 0:
            nbins[j] = 1
            continue
        uniq = np.unique(col)
        if len(uniq) <= max_bin:
            # one bin per distinct value; boundary midway to the next value
            bounds = (uniq[:-1] + uniq[1:]) / 2 if len(uniq) > 1 else np.array([], np.float64)
            k = len(bounds)
            upper[j, :k] = bounds
            nbins[j] = k + 1
        else:
            qs = np.quantile(col, np.linspace(0, 1, max_bin + 1)[1:-1])
            bounds = np.unique(qs.astype(np.float32))
            k = len(bounds)
            upper[j, :k] = bounds
            nbins[j] = k + 1
    return BinMapper(upper_bounds=upper, num_bins=nbins, max_bin=max_bin,
                     cat_features=cat_out or None)
