"""Quantile binning: raw features → small integer bin indices.

The reference samples rows to compute bin boundaries then broadcasts them to
workers (reference: LightGBMBase.scala:499-527 calculateRowStatistics →
sample → collect → broadcast; native binning in the LightGBM C++ lib).
Here binning is explicit: :class:`BinMapper` holds per-feature upper bin
boundaries; mapping is a jit-friendly ``searchsorted``.

TPU notes: bins are ``int32`` (dense, static shape); missing values (NaN)
get their own bin 0 so split decisions can route them; the last bin catches
+inf.  ``max_bin`` defaults to 255 content bins + the NaN bin = 256 total,
keeping histograms at power-of-two lane width.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

MISSING_BIN = 0  # NaN bucket; content bins are 1..max_bin


@dataclasses.dataclass
class BinMapper:
    """Per-feature quantile bin boundaries.

    ``upper_bounds[f, b]`` is the inclusive upper raw-value bound of content
    bin ``b+1``; shape (num_features, max_bin).  Unused trailing bins repeat
    +inf.  ``num_bins[f]`` counts distinct content bins for feature f.
    """
    upper_bounds: np.ndarray          # (F, max_bin) float32
    num_bins: np.ndarray              # (F,) int32
    max_bin: int

    @property
    def num_features(self) -> int:
        return self.upper_bounds.shape[0]

    @property
    def total_bins(self) -> int:      # content bins + missing bin
        return self.max_bin + 1

    def transform(self, features: np.ndarray) -> np.ndarray:
        """Map raw (n, F) floats → (n, F) int32 bins ∈ [0, max_bin]."""
        n, f = features.shape
        out = np.empty((n, f), np.int32)
        for j in range(f):
            col = features[:, j]
            # searchsorted over this feature's bounds; bin ids are 1-based
            idx = np.searchsorted(self.upper_bounds[j], col, side="left")
            out[:, j] = np.minimum(idx, self.max_bin - 1) + 1
            out[np.isnan(col), j] = MISSING_BIN
        return out

    def bin_threshold_value(self, feature: int, bin_id: int) -> float:
        """Raw-value threshold for 'bin <= bin_id' splits (for raw predict)."""
        return float(self.upper_bounds[feature, max(bin_id - 1, 0)])


def fit_bin_mapper(features: np.ndarray, max_bin: int = 255,
                   sample_count: int = 200_000,
                   seed: int = 0) -> BinMapper:
    """Compute quantile bin boundaries from a row sample.

    Mirrors the reference's sampled dataset creation
    (LGBM_DatasetCreateFromSampledColumn, StreamingPartitionTask.scala:374):
    sample rows, per-feature quantiles as boundaries, dedup to distinct
    values when a feature has few uniques.
    """
    n, f = features.shape
    if n > sample_count:
        rng = np.random.default_rng(seed)
        sample = features[rng.choice(n, sample_count, replace=False)]
    else:
        sample = features
    upper = np.full((f, max_bin), np.inf, np.float32)
    nbins = np.zeros(f, np.int32)
    for j in range(f):
        col = sample[:, j]
        col = col[~np.isnan(col)]
        if col.size == 0:
            nbins[j] = 1
            continue
        uniq = np.unique(col)
        if len(uniq) <= max_bin:
            # one bin per distinct value; boundary midway to the next value
            bounds = (uniq[:-1] + uniq[1:]) / 2 if len(uniq) > 1 else np.array([], np.float64)
            k = len(bounds)
            upper[j, :k] = bounds
            nbins[j] = k + 1
        else:
            qs = np.quantile(col, np.linspace(0, 1, max_bin + 1)[1:-1])
            bounds = np.unique(qs.astype(np.float32))
            k = len(bounds)
            upper[j, :k] = bounds
            nbins[j] = k + 1
    return BinMapper(upper_bounds=upper, num_bins=nbins, max_bin=max_bin)
