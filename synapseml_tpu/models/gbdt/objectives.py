"""Boosting objectives: gradients/hessians as jitted elementwise kernels.

The reference passes an objective *name* through to native LightGBM
(reference: lightgbm/.../params/BaseTrainParams.scala:99 objective param;
custom objectives via FObjTrait, params/FObjTrait.scala:1-17).  Here each
objective is a pure function ``(scores, labels, weights) -> (grad, hess)``
fused by XLA into the training step.  Custom objectives are plain Python
callables with the same signature (the FObj analogue).
"""

from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

ObjectiveFn = Callable[..., Tuple[jnp.ndarray, jnp.ndarray]]


def _binary(scores, labels, weights):
    p = jax.nn.sigmoid(scores)
    grad = (p - labels) * weights
    hess = jnp.maximum(p * (1.0 - p), 1e-16) * weights
    return grad, hess


def _l2(scores, labels, weights):
    return (scores - labels) * weights, weights


def _l1(scores, labels, weights):
    grad = jnp.sign(scores - labels) * weights
    hess = weights  # LightGBM uses constant hessian for L1
    return grad, hess


def _huber(scores, labels, weights, alpha=0.9):
    diff = scores - labels
    grad = jnp.where(jnp.abs(diff) <= alpha, diff, alpha * jnp.sign(diff)) * weights
    hess = jnp.where(jnp.abs(diff) <= alpha, 1.0, 1e-2) * weights
    return grad, hess


def _fair(scores, labels, weights, c=1.0):
    diff = scores - labels
    grad = c * diff / (jnp.abs(diff) + c) * weights
    hess = c * c / (jnp.abs(diff) + c) ** 2 * weights
    return grad, hess


def _poisson(scores, labels, weights):
    exp_s = jnp.exp(scores)
    return (exp_s - labels) * weights, exp_s * weights


def _quantile(scores, labels, weights, alpha=0.5):
    diff = scores - labels
    grad = jnp.where(diff >= 0, 1.0 - alpha, -alpha) * weights
    return grad, weights


def _mape(scores, labels, weights):
    safe = jnp.maximum(jnp.abs(labels), 1.0)
    grad = jnp.sign(scores - labels) / safe * weights
    return grad, weights / safe


def _gamma(scores, labels, weights):
    exp_s = jnp.exp(-scores)
    grad = (1.0 - labels * exp_s) * weights
    hess = labels * exp_s * weights
    return grad, jnp.maximum(hess, 1e-16)


def _tweedie(scores, labels, weights, rho=1.5):
    exp1 = jnp.exp((1.0 - rho) * scores)
    exp2 = jnp.exp((2.0 - rho) * scores)
    grad = (-labels * exp1 + exp2) * weights
    hess = (-labels * (1.0 - rho) * exp1 + (2.0 - rho) * exp2) * weights
    return grad, jnp.maximum(hess, 1e-16)


REGRESSION_OBJECTIVES: Dict[str, ObjectiveFn] = {
    "regression": _l2,
    "regression_l2": _l2,
    "mean_squared_error": _l2,
    "mse": _l2,
    "regression_l1": _l1,
    "mae": _l1,
    "huber": _huber,
    "fair": _fair,
    "poisson": _poisson,
    "quantile": _quantile,
    "mape": _mape,
    "gamma": _gamma,
    "tweedie": _tweedie,
}

BINARY_OBJECTIVES: Dict[str, ObjectiveFn] = {
    "binary": _binary,
}


def softmax_grad_hess(scores, labels_onehot, weights):
    """Multiclass softmax: scores (n, K) → grad/hess (n, K)
    (LightGBM 'multiclass' objective)."""
    p = jax.nn.softmax(scores, axis=-1)
    grad = (p - labels_onehot) * weights[:, None]
    hess = jnp.maximum(2.0 * p * (1.0 - p), 1e-16) * weights[:, None]
    return grad, hess


def get_objective(name: str) -> ObjectiveFn:
    if name in BINARY_OBJECTIVES:
        return BINARY_OBJECTIVES[name]
    if name in REGRESSION_OBJECTIVES:
        return REGRESSION_OBJECTIVES[name]
    raise ValueError(f"unknown objective {name!r}; known: "
                     f"{sorted(BINARY_OBJECTIVES) + sorted(REGRESSION_OBJECTIVES)}")


# -- initial score (boost_from_average semantics) ---------------------------

def initial_score(objective: str, labels, weights) -> float:
    import numpy as np
    labels = np.asarray(labels, np.float64)
    weights = np.asarray(weights, np.float64)
    mean = float((labels * weights).sum() / max(weights.sum(), 1e-12))
    if objective == "binary":
        mean = min(max(mean, 1e-6), 1 - 1e-6)
        return float(np.log(mean / (1 - mean)))
    if objective in ("poisson", "gamma", "tweedie"):
        return float(np.log(max(mean, 1e-12)))
    if objective in ("regression_l1", "mae", "quantile"):
        return float(np.median(labels))
    return mean
