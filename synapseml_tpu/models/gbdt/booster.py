"""Boosting orchestration + the serializable Booster.

Replaces the reference's iteration loop and booster wrapper
(reference: TrainUtils.scala:98-169 executeTrainingIterations/early stop;
booster/LightGBMBooster.scala:212-560 — iterate/predict/feature-importance/
model-string).  Differences by design:

- the per-iteration "histogram build + allreduce + split" that LightGBM does
  in C++ behind ``LGBM_BoosterUpdateOneIter`` is the jitted
  :func:`~synapseml_tpu.models.gbdt.trainer.grow_tree` (psum when sharded);
- scoring is batched XLA traversal, not one JNI call per row
  (LightGBMBooster.scala:394-405 score);
- the model string is JSON of flat tree arrays (saveToString analogue,
  LightGBMBooster.scala:272-284).

Boosting types: gbdt, rf (bagged trees at constant score, averaged), dart
(tree dropout with normalization), goss (gradient one-side sampling inside
the jitted step).
"""

from __future__ import annotations

import dataclasses
import functools
import json
import logging as _logging
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ... import telemetry as _telemetry
from ...parallel.compression import resolve_collective_config
from ...parallel.mesh import DATA_AXIS, batch_sharding, replicated
from . import metrics as metrics_mod
from .binning import BinMapper, FeatureBundler, fit_bin_mapper
from .objectives import (get_objective, initial_score, softmax_grad_hess)
from .trainer import (GrowthParams, Tree, default_n_slots, grow_tree,
                      grow_tree_depthwise, grow_tree_feature_parallel,
                      max_nodes, predict_binned_stacked,
                      predict_raw_features, stack_trees, tree_depth)


@dataclasses.dataclass
class BoostingConfig:
    """TrainParams analogue (reference: params/BaseTrainParams.scala:58-268).
    Field names follow LightGBM's config strings."""
    objective: str = "regression"
    boosting_type: str = "gbdt"            # gbdt | rf | dart | goss
    num_iterations: int = 100
    learning_rate: float = 0.1
    num_leaves: int = 31
    max_depth: int = -1
    min_data_in_leaf: int = 20
    min_sum_hessian_in_leaf: float = 1e-3
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    max_bin: int = 255
    feature_fraction: float = 1.0
    bagging_fraction: float = 1.0
    bagging_freq: int = 0
    seed: int = 0
    num_class: int = 1
    boost_from_average: bool = True
    early_stopping_round: int = 0
    metric: str = ""
    top_rate: float = 0.2                  # goss
    other_rate: float = 0.1                # goss
    drop_rate: float = 0.1                 # dart
    max_drop: int = 50                     # dart
    skip_drop: float = 0.5                 # dart
    scale_pos_weight: float = 1.0
    is_unbalance: bool = False
    alpha: float = 0.9                     # huber / quantile
    tweedie_variance_power: float = 1.5
    fair_c: float = 1.0
    max_position: int = 10                 # lambdarank ndcg@
    label_gain: Optional[List[float]] = None
    bin_sample_count: int = 200_000
    bagging_seed: int = 3
    verbosity: int = -1
    #: data_parallel (histogram psum) | voting_parallel (PV-Tree top-k
    #: vote) | feature_parallel (vertical sharding: local histograms,
    #: gathered best splits, owner-broadcast routing)
    parallelism: str = "data_parallel"
    top_k: int = 20                        # voting-parallel votes per rank
    #: "depthwise": wave growth, all of a level's histograms in one batched
    #: device pass (fast path); "lossguide": strict best-first leaf-wise
    #: (LightGBM's exact growth order).  voting_parallel implies lossguide.
    growth_policy: str = "depthwise"
    #: two-level (coarse-then-refine) histograms for wide-bin depthwise
    #: growth: "auto" (on at >= 500k global rows), "on", "off".
    #: Histograms build at coarse (bin >> TWO_LEVEL_SHIFT, currently
    #: >> 3) resolution; the top
    #: ``refine_features`` features — chosen once per TREE from the
    #: root's coarse gains — are refined at full resolution every wave.
    #: Faster wide-bin training; split quality is preserved unless a
    #: feature outside the root-chosen top-K wins only on a
    #: sub-coarse-boundary cut.  Implemented for depthwise (fused wave
    #: kernel) AND strict leaf-wise growth (per-split nodes-kernel
    #: builds); structurally off for EFB, monotone constraints,
    #: voting/feature parallelism, max_bin < 127
    two_level_hist: str = "auto"
    #: features refined at full resolution under two_level_hist
    refine_features: int = 8
    #: exclusive feature bundling: merge rarely-co-nonzero (binned)
    #: features into shared HISTOGRAM columns — the sparse/one-hot
    #: densification strategy (LightGBM enable_bundle).  Bundling only
    #: compresses histogram construction; split search, routing, and the
    #: trees stay in ORIGINAL feature space, so predict/SHAP/LightGBM
    #: export/monotone constraints/dart and ALL THREE parallelism modes
    #: work unchanged (feature_parallel bundles each rank's slice
    #: independently — bundles never cross rank boundaries).
    enable_bundle: bool = False
    max_conflict_rate: float = 0.0
    #: feature indexes holding category codes (categoricalSlotIndexes,
    #: params/LightGBMParams.scala): binned by target-statistic order so
    #: bin-range splits act as category-subset splits; such models predict
    #: through bin space (no raw-threshold semantics)
    categorical_feature: Optional[List[int]] = None
    #: per-feature monotone direction {-1, 0, +1} (monotoneConstraints,
    #: params/LightGBMParams.scala:168-183): +1 forces predictions
    #: non-decreasing in the feature, -1 non-increasing.  Implemented
    #: method: "basic" (LightGBM's default) — violating splits discarded,
    #: child outputs clamped by bounds propagated down the tree
    monotone_constraints: Optional[List[int]] = None
    monotone_constraints_method: str = "basic"
    #: gain penalization for constrained-feature splits near the root
    #: (monotonePenalty, BaseTrainParams.scala:128-130): 1 forbids them at
    #: the root, larger values reach deeper
    monotone_penalty: float = 0.0
    #: wire codec for the data-parallel histogram allreduce (EQuARX,
    #: arXiv:2506.17615): "none" (default, byte-identical to the f32
    #: path) | "bf16" | "int8" | a full
    #: :class:`~synapseml_tpu.parallel.compression.CollectiveConfig`.
    #: Stateless per histogram (no error feedback — histograms are
    #: re-derived per split, not an accumulating stream); every rank
    #: decodes identical bytes so trees stay identical across ranks.
    #: Ignored by voting/feature parallelism (their collectives are
    #: already top-k-sparse or local) and by single-device fits.
    collective_compression: Any = "none"
    #: fused bf16 histogram ingest: the objective's grad/hess fuse into
    #: the boosting step and materialize as ONE bf16 array pair instead
    #: of (n_rows,) f32 each — every per-wave histogram build then reads
    #: half the g/h bytes, and the f32 g/h arrays never exist between
    #: the objective and the histogram kernel (compute-and-quantize;
    #: accumulation stays f32/int32 so bin sums are exact over the
    #: rounded values).  "auto" (default) = on; False restores the f32
    #: ingest bit-for-bit.  NOT bit-identical to the f32 ingest — the
    #: bench pins holdout-AUC parity (|delta| <= 0.005) and tier-1 pins
    #: fused-vs-unfused parity + preempt->resume bit-exactness WITH the
    #: fused path on.  A checkpoint records its ingest (the resume guard
    #: below refuses a silent fused/unfused mix mid-model).
    fused_ingest: Any = "auto"
    pass_through: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def growth_params(self, num_features: int = 0) -> GrowthParams:
        mono = None
        if self.monotone_constraints and any(self.monotone_constraints):
            mono = tuple(int(c) for c in self.monotone_constraints)
        hist_chunk = 0
        if num_features:
            hist_chunk = _tuned_hist_chunk(
                int(num_features), self.max_bin + 1,
                default_n_slots(self.num_leaves))
        return GrowthParams(
            hist_chunk=hist_chunk,
            num_leaves=self.num_leaves,
            max_depth=self.max_depth,
            min_data_in_leaf=float(self.min_data_in_leaf),
            min_sum_hessian_in_leaf=self.min_sum_hessian_in_leaf,
            lambda_l1=self.lambda_l1,
            lambda_l2=self.lambda_l2,
            min_gain_to_split=self.min_gain_to_split,
            total_bins=self.max_bin + 1,
            voting_k=self.top_k if self.parallelism == "voting_parallel" else 0,
            monotone_constraints=mono,
            monotone_penalty=float(self.monotone_penalty),
            monotone_method=self.monotone_constraints_method,
            two_level=({True: "on", False: "off"}.get(
                self.two_level_hist, str(self.two_level_hist))),
            refine_k=int(self.refine_features),
        )


def _tuned_hist_chunk(num_features: int, total_bins: int,
                      n_slots: int) -> int:
    """Tuned rows-per-chunk for the Pallas histogram kernels, or 0.

    Only a ``gbdt_hist_chunk`` tuning-table entry measured on THIS device
    at exactly this (features, total_bins) geometry applies, and only when
    ``hist_chunk_ok`` re-admits the chunk for the slot count this fit will
    use; anything else keeps the ``_tile_for`` ladder default, so fits
    without a table dispatch byte-identical programs."""
    try:
        from ...telemetry.tunetable import geometry_key, get_tuneplane
        from .pallas_hist import hist_chunk_ok

        def _gate(winner):
            c = winner.get("chunk")
            return (isinstance(c, int) and not isinstance(c, bool)
                    and hist_chunk_ok(num_features, total_bins, n_slots, c))

        won = get_tuneplane().consult(
            "BoostingConfig.growth_params", "gbdt_hist_chunk",
            geometry_key(features=int(num_features),
                         total_bins=int(total_bins)),
            validate=_gate)
        if won is not None:
            return int(won["chunk"])
    except Exception:
        pass
    return 0


class Booster:
    """Trained model: host-resident flat tree arrays + binning metadata.
    Serializable to a JSON model string (LightGBMBooster.saveToString
    analogue)."""

    def __init__(self, trees: List[Tree], tree_class: List[int],
                 tree_weights: List[float], num_class: int, objective: str,
                 init_score: np.ndarray, bin_mapper: BinMapper,
                 feature_names: List[str], config: BoostingConfig,
                 best_iteration: int = -1,
                 bundler: Optional[FeatureBundler] = None):
        self.trees = [Tree(*[np.asarray(a) for a in t]) for t in trees]
        self.tree_class = list(tree_class)
        self.tree_weights = list(tree_weights)
        self.num_class = num_class
        self.objective = objective
        self.init_score = np.asarray(init_score, np.float32).reshape(-1)
        self.bin_mapper = bin_mapper
        self.feature_names = list(feature_names)
        self.config = config
        self.best_iteration = best_iteration
        self.bundler = bundler

    # -- prediction --------------------------------------------------------
    @property
    def num_trees(self) -> int:
        return len(self.trees)

    def depth_bound(self) -> int:
        return max((tree_depth(t) for t in self.trees), default=1)

    def _stacked_for_class(self, k: int, num_iteration: Optional[int]) -> Optional[Tree]:
        sel = [i for i, c in enumerate(self.tree_class) if c == k]
        if num_iteration is not None and num_iteration >= 0:
            sel = sel[:num_iteration]
        if not sel:
            return None
        trees = []
        for i in sel:
            t = self.trees[i]
            w = self.tree_weights[i]
            trees.append(t._replace(leaf_value=t.leaf_value * np.float32(w)))
        return stack_trees(trees)

    def predict_margin(self, features: np.ndarray,
                       num_iteration: Optional[int] = None,
                       return_leaves: bool = False):
        """Raw margin (n,) or (n, K); batched XLA traversal."""
        features = np.ascontiguousarray(features, np.float32)
        n = features.shape[0]
        depth = self.depth_bound()
        bundled = None
        if self.bin_mapper.has_categorical:
            if _placeholder_mapper(self.bin_mapper):
                # imported LightGBM categorical model: numeric bounds are
                # placeholders so numeric nodes keep RAW thresholds, while
                # categorical columns map to their (float) bin ids — the
                # import already rewrote cat thresholds to bin space, so
                # one uniform x <= thr traversal serves both node kinds
                features = self._cat_columns_to_bins(features)
            else:
                # categorical models split in (ORIGINAL) bin space: bin,
                # then traverse by split_bin instead of raw thresholds.
                # EFB models need nothing special — bundling only
                # compresses histogram construction; their trees live in
                # original feature space with raw thresholds (the
                # LightGBM scheme)
                binned = self.bin_mapper.transform(features)
                bundled = jnp.asarray(binned.astype(np.int32))
        outs, leaves = [], []
        for k in range(self.num_class):
            stacked = self._stacked_for_class(k, num_iteration)
            if stacked is None:
                outs.append(np.full(n, self.init_score[min(k, len(self.init_score) - 1)],
                                    np.float32))
                leaves.append(np.zeros((0, n), np.int32))
                continue
            if bundled is not None:
                total, lv = predict_binned_stacked(bundled, stacked, depth)
            else:
                total, lv = predict_raw_features(features, stacked, depth)
            base = self.init_score[min(k, len(self.init_score) - 1)]
            total = np.asarray(total) + base
            if self.config.boosting_type == "rf":
                ntree = stacked.split_feature.shape[0]
                total = base + (np.asarray(total) - base) / max(ntree, 1)
            outs.append(np.asarray(total))
            leaves.append(np.asarray(lv))
        margin = outs[0] if self.num_class == 1 else np.stack(outs, axis=1)
        if return_leaves:
            return margin, leaves
        return margin

    def _cat_columns_to_bins(self, features: np.ndarray) -> np.ndarray:
        """Imported-model hybrid view: categorical columns become their
        bin ids (floats); numeric columns pass through unchanged.  Unseen
        categories and NaN land in bin 0, which every bin-space split
        (bin <= t, t >= 0) sends left — the exported complement-bitset
        convention's missing direction."""
        out = features.copy()
        for f, (vals, bins) in (self.bin_mapper.cat_features or {}).items():
            col = features[:, f]
            if len(vals) == 0:
                out[:, f] = 0.0
                continue
            idx = np.searchsorted(vals, col)
            idx_c = np.minimum(idx, len(vals) - 1)
            hit = np.asarray(vals)[idx_c] == col
            out[:, f] = np.where(hit, np.asarray(bins)[idx_c], 0)
        return out

    def predict_leaf(self, features: np.ndarray) -> np.ndarray:
        """Per-tree leaf index (n, num_trees) — predictLeaf analogue
        (LightGBMBooster.scala:407)."""
        _, leaves = self.predict_margin(features, return_leaves=True)
        return np.concatenate([l for l in leaves if l.size], axis=0).T

    def to_proba(self, margin: np.ndarray) -> np.ndarray:
        if self.objective in ("multiclass", "multiclassova"):
            if self.objective == "multiclassova":
                p = 1.0 / (1.0 + np.exp(-margin))
                return p / np.maximum(p.sum(1, keepdims=True), 1e-12)
            m = margin - margin.max(axis=1, keepdims=True)
            e = np.exp(m)
            return e / e.sum(axis=1, keepdims=True)
        p1 = 1.0 / (1.0 + np.exp(-margin))
        return np.stack([1 - p1, p1], axis=1)

    def predict_contrib(self, features: np.ndarray,
                        approximate: bool = False) -> np.ndarray:
        """Per-feature contributions + bias — the featuresShap analogue
        (LightGBMBooster.featuresShap): EXACT TreeSHAP (Lundberg
        polynomial algorithm over the per-node covers) by default;
        ``approximate=True`` selects Saabas path attribution, which is
        also the automatic fallback for models without cover counts
        (old serialized models, LightGBM imports lacking
        ``internal_count``).

        Returns (n, F+1) for single-output models, (n, K*(F+1)) for
        multiclass (last slot of each block = bias)."""
        # categorical models split in BIN space (target-ordered category
        # bins); SHAP runs over the binned matrix with split_bin routing —
        # exact, since binning is a per-feature transform.  EFB models
        # need nothing special: their trees live in original feature space
        imported_cat = (self.bin_mapper.has_categorical
                        and _placeholder_mapper(self.bin_mapper))
        bin_space = self.bin_mapper.has_categorical and not imported_cat
        if imported_cat:
            # imported categorical model: hybrid view (cat columns as bin
            # ids, numeric raw) with thresholds already rewritten at import
            features = self._cat_columns_to_bins(
                np.ascontiguousarray(features, np.float32))
        from .shap import has_cover_counts, tree_shap_values
        if not approximate and has_cover_counts(self):
            return tree_shap_values(self, features, bin_space=bin_space)
        features = np.ascontiguousarray(features, np.float32)
        if bin_space:
            features = self.bin_mapper.transform(features).astype(np.float32)
        n = features.shape[0]
        F = self.bin_mapper.num_features
        out = np.zeros((n, self.num_class, F + 1), np.float64)
        rows = np.arange(n)
        for i, t in enumerate(self.trees):
            k = self.tree_class[i]
            w = self.tree_weights[i]
            if self.config.boosting_type == "rf":
                cls_count = max(sum(1 for c in self.tree_class if c == k), 1)
                w = w / cls_count
            nv = t.node_value.astype(np.float64)
            cur = np.zeros(n, np.int64)
            out[:, k, F] += nv[0] * w
            for _ in range(tree_depth(t)):
                feat = t.split_feature[cur]
                internal = feat >= 0
                if not internal.any():
                    break
                f = np.maximum(feat, 0)
                x = features[rows, f]
                if bin_space:
                    go_left = x <= np.asarray(t.split_bin)[cur]
                else:
                    miss = np.isnan(x) | (np.asarray(t.missing_zero)[cur]
                                          & (np.abs(x) <= 1e-35))
                    go_left = np.where(miss, t.default_left[cur],
                                       x <= t.threshold[cur])
                nxt = np.where(go_left, t.left_child[cur], t.right_child[cur])
                nxt = np.where(internal, nxt, cur)
                delta = (nv[nxt] - nv[cur]) * w
                np.add.at(out, (rows[internal], np.full(internal.sum(), k),
                                f[internal]), delta[internal])
                cur = nxt
        out[:, :, F] += self.init_score[:self.num_class][None, :]
        if self.num_class == 1:
            return out[:, 0, :]
        return out.reshape(n, -1)

    # -- introspection -----------------------------------------------------
    def feature_importance(self, importance_type: str = "split") -> np.ndarray:
        """Split counts or total gains per ORIGINAL feature
        (getFeatureImportances analogue, LightGBMBooster.scala); bundled
        splits map back to the original feature owning the split bin."""
        out = np.zeros(len(self.feature_names), np.float64)
        for t in self.trees:
            internal = np.nonzero(np.asarray(t.split_feature) >= 0)[0]
            for node in internal:
                f = int(t.split_feature[node])
                w = (1.0 if importance_type == "split"
                     else float(t.split_gain[node]))
                out[f] += w
        return out

    # -- serialization -----------------------------------------------------
    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": 2,
            "num_class": self.num_class,
            "objective": self.objective,
            "init_score": self.init_score.tolist(),
            "feature_names": self.feature_names,
            "tree_class": self.tree_class,
            "tree_weights": self.tree_weights,
            "best_iteration": self.best_iteration,
            "config": dataclasses.asdict(self.config),
            "bin_mapper": {
                "upper_bounds": self.bin_mapper.upper_bounds.tolist(),
                "num_bins": self.bin_mapper.num_bins.tolist(),
                "max_bin": self.bin_mapper.max_bin,
                "cat_features": {
                    str(f): [v.tolist(), b.tolist()]
                    for f, (v, b) in (self.bin_mapper.cat_features or {}).items()
                } or None,
            },
            "bundler": self.bundler.to_dict() if self.bundler else None,
            "trees": [{f: np.asarray(getattr(t, f)).tolist() for f in Tree._fields}
                      for t in self.trees],
        }

    def to_string(self) -> str:
        """LightGBM text model format (saveToString parity,
        LightGBMBooster.scala:272-284) — loadable by any LightGBM runtime.
        Categorical splits export as native bitset thresholds (the
        complement set with children swapped, so unseen/missing categories
        route identically); the JSON form (:meth:`to_dict`) remains the
        internal format."""
        from .lgbm_format import booster_to_lgbm_string
        return booster_to_lgbm_string(self)

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "Booster":
        cfg_d = dict(d["config"])
        cfg = BoostingConfig(**{k: v for k, v in cfg_d.items()
                                if k in {f.name for f in dataclasses.fields(BoostingConfig)}})
        cat_raw = d["bin_mapper"].get("cat_features")
        bm = BinMapper(
            upper_bounds=np.asarray(d["bin_mapper"]["upper_bounds"], np.float32),
            num_bins=np.asarray(d["bin_mapper"]["num_bins"], np.int32),
            max_bin=d["bin_mapper"]["max_bin"],
            cat_features={int(f): (np.asarray(v, np.float32),
                                   np.asarray(b, np.int32))
                          for f, (v, b) in cat_raw.items()}
            if cat_raw else None)
        trees = []
        for td in d["trees"]:
            trees.append(Tree(
                split_feature=np.asarray(td["split_feature"], np.int32),
                split_bin=np.asarray(td["split_bin"], np.int32),
                threshold=np.asarray(td["threshold"], np.float32),
                split_gain=np.asarray(td["split_gain"], np.float32),
                left_child=np.asarray(td["left_child"], np.int32),
                right_child=np.asarray(td["right_child"], np.int32),
                leaf_value=np.asarray(td["leaf_value"], np.float32),
                node_value=np.asarray(td["node_value"], np.float32),
                num_nodes=np.asarray(td["num_nodes"], np.int32),
                default_left=np.asarray(
                    td.get("default_left",
                           np.ones(len(td["leaf_value"]), bool)), bool),
                node_count=np.asarray(
                    td.get("node_count",
                           np.zeros(len(td["leaf_value"]))), np.float32),
                missing_zero=np.asarray(
                    td.get("missing_zero",
                           np.zeros(len(td["leaf_value"]), bool)), bool)))
        if d.get("bundler") and int(d.get("version", 1)) < 2:
            raise ValueError(
                "this EFB model was saved by a pre-round-3 build whose "
                "bundled trees split BUNDLED columns; round 3 stores "
                "original-feature trees (the LightGBM scheme) — re-train "
                "the model")
        bundler = (FeatureBundler.from_dict(d["bundler"])
                   if d.get("bundler") else None)
        return Booster(trees, d["tree_class"], d["tree_weights"], d["num_class"],
                       d["objective"], np.asarray(d["init_score"], np.float32),
                       bm, d["feature_names"], cfg, d["best_iteration"],
                       bundler=bundler)

    @staticmethod
    def from_string(s: str) -> "Booster":
        """Parse either format: LightGBM text models (native interop,
        LightGBMClassifier.scala:196-211) or the internal JSON."""
        if s.lstrip().startswith("{"):
            return Booster.from_dict(json.loads(s))
        from .lgbm_format import booster_from_lgbm_string
        return booster_from_lgbm_string(s)

    @staticmethod
    def from_file(path: str) -> "Booster":
        """loadNativeModelFromFile analogue (LightGBMClassifier.scala:196)."""
        with open(path) as f:
            return Booster.from_string(f.read())


# --------------------------------------------------------------------------
# training
# --------------------------------------------------------------------------

def _step_factory_args(config: "BoostingConfig", K: int, mesh, featpar: bool,
                       use_pallas, objective_fn=None, num_features: int = 0):
    """The exact ``_make_step`` (args, kwargs) — built in ONE place so the
    warm-compile thread and the training loop hit the same lru_cache entry
    (any drift would silently compile a program that is never used).
    ``objective_fn`` overrides the cached-factory objective (lambdarank)."""
    if objective_fn is None and K == 1 and config.objective != "lambdarank":
        obj_kwargs = {}
        if config.objective in ("huber", "quantile"):
            obj_kwargs["alpha"] = config.alpha
        elif config.objective == "fair":
            obj_kwargs["c"] = config.fair_c
        elif config.objective == "tweedie":
            obj_kwargs["rho"] = config.tweedie_variance_power
        # cached factory -> stable function identity, so the _make_step
        # cache hits across train() calls even with objective kwargs
        objective_fn = _objective_with_kwargs(
            config.objective, tuple(sorted(obj_kwargs.items())))
    is_rf = config.boosting_type == "rf"
    use_bagging = (config.bagging_fraction < 1.0
                   and (is_rf or config.bagging_freq > 0))
    args = (config.growth_params(num_features=num_features if use_pallas
                                 else 0), objective_fn, K,
            1.0 if is_rf else config.learning_rate, mesh,
            config.boosting_type == "goss",
            config.top_rate, config.other_rate)
    # compressed histogram wire applies only where the histogram psum
    # exists: data-parallel growth over a real mesh (voting aggregates
    # top-k-sparse, feature_parallel keeps histograms local)
    cconfig = resolve_collective_config(config.collective_compression)
    if _hist_psum_nulled(config, mesh is not None):
        cconfig = None
    kwargs = dict(ova=(config.objective == "multiclassova"),
                  use_pallas=use_pallas,
                  growth_policy=config.growth_policy,
                  feature_parallel=featpar,
                  bundled_featpar=bool(featpar and config.enable_bundle),
                  bagging_fraction=(config.bagging_fraction
                                    if use_bagging else 1.0),
                  cconfig=cconfig,
                  fused_ingest=_fused_ingest_on(config))
    return args, kwargs


def _fused_ingest_on(config: "BoostingConfig") -> bool:
    """Resolve the ``fused_ingest`` knob ("auto" = on) — THE predicate
    both the step factory and the resume guard consult, so a checkpoint
    stamped by one can never disagree with the program the other
    builds."""
    v = config.fused_ingest
    if v in ("auto", "on", True):
        return True
    if v in ("off", False):
        return False
    raise ValueError(f"fused_ingest={v!r}: must be 'auto', 'on', 'off', "
                     "True or False")


#: iterations per scanned dispatch — the whole-run loop runs as
#: ceil(T / SCAN_CHUNK) dispatches of ONE compiled program (the chunk
#: length is static but the iteration offset is a traced operand, so the
#: program is independent of num_iterations and the compile cache hits
#: across runs of any length).  25 divides LightGBM's default 100.
SCAN_CHUNK = 25


@functools.lru_cache(maxsize=16)
def _make_scan(sargs, skw_items, bagging_freq: int,
               seed: int, is_rf: bool, cache_step: bool = True):
    """Chunk-of-the-training-run program: ``lax.scan`` over the step.

    The per-iteration Python loop pays ~3 tunnel/PCIe dispatches per tree
    (fold_in + PRNGKey + step), measured ~36 ms/iteration of pure dispatch
    tax against a 21 ms on-device step — the scan runs SCAN_CHUNK
    iterations per dispatch.  Key derivation matches the Python loop
    exactly (PRNGKey(seed·100003 + it) under 32-bit seeds;
    fold_in(bag_root, it // bagging_freq)), so scanned and looped training
    grow identical trees.  Used for the common fire-and-forget path; dart /
    per-iteration validation / callbacks / checkpoints stay on the Python
    loop, which needs each tree on the host mid-run.
    """
    # lambdarank's objective closes over per-dataset arrays: caching the
    # step would pin them (same reason train() bypasses _make_step's cache)
    maker = _make_step if cache_step else _make_step.__wrapped__
    step = maker(*sargs, **dict(skw_items))
    freq = max(bagging_freq, 1)
    seed_base = (seed * 100003) & 0xffffffff

    def run(bins_t, scores, labels, weights, base_bag, bag_root_key,
            fmask, upper_bounds, num_bins, bundle_map, init_scores, it0):
        def body(sc, it):
            bag_key = jax.random.fold_in(bag_root_key, it // freq)
            key = jax.random.PRNGKey(jnp.uint32(seed_base)
                                     + it.astype(jnp.uint32))
            tstack, new_sc = step(bins_t, sc, labels, weights,
                                  (base_bag, bag_key), fmask, key,
                                  upper_bounds, num_bins, bundle_map)
            if is_rf:
                new_sc = init_scores   # rf: gradients stay at init margin
            return new_sc, tstack
        return lax.scan(body, scores, jnp.arange(SCAN_CHUNK) + it0)
    return jax.jit(run)


#: module-level jit (an inline jit(lambda) would recompile every train()):
#: flattens every chunk's tree stack into one f32 vector for ONE readback
_pack_flat = jax.jit(lambda cs: jnp.concatenate(
    [a.astype(jnp.float32).reshape(-1) for ts in cs for a in ts]))


@functools.lru_cache(maxsize=None)
def _objective_with_kwargs(name, kwargs_items):
    """Objective + frozen kwargs as a STABLE function object, so the
    _make_step cache below keys on something that repeats across calls."""
    base = get_objective(name)
    if not kwargs_items:
        return base
    kw = dict(kwargs_items)
    return lambda s, l, ww: base(s, l, ww, **kw)


@functools.lru_cache(maxsize=16)
def _make_step(p: GrowthParams, objective_fn, num_class: int,
               learning_rate: float, mesh: Optional[Mesh], use_goss: bool,
               top_rate: float, other_rate: float, ova: bool = False,
               use_pallas: bool = False, bagging_fraction: float = 1.0,
               growth_policy: str = "depthwise",
               feature_parallel: bool = False,
               bundled_featpar: bool = False,
               cconfig=None, fused_ingest: bool = True):
    """Build the jitted one-iteration step.

    step(binned, scores, labels, weights, (base_bag, bag_key),
         feature_mask, key, upper_bounds, num_bins, bundle_map)
      -> (trees, new_scores)

    Bagging happens ON DEVICE: ``base_bag`` is the constant pad-row mask
    and the per-iteration row subsample is drawn from ``bag_key`` when
    ``bagging_fraction < 1`` — no per-iteration host mask upload.  Passing
    the same bag_key across iterations reproduces bagging_freq persistence.
    Each shard folds its mesh index into the key, so bagged models are
    deterministic for a fixed mesh size but differ across mesh sizes
    (the unbagged paths remain mesh-invariant).

    For num_class==1 labels are float targets; for multiclass labels are
    int class ids and scores are (N, K).
    """
    axis = DATA_AXIS if mesh is not None else None
    if feature_parallel:
        # strict lossguide order under vertical sharding = the wave
        # grower with ONE slot per wave: the top-1 "wave" is exactly the
        # best-first split, at the cost of one owner-broadcast per SPLIT
        # instead of per level (the native engine's tree_learner=feature
        # runs its default leaf-wise growth the same way)
        fp_slots = (1 if growth_policy == "lossguide"
                    else default_n_slots(p.num_leaves))
        grower = functools.partial(grow_tree_feature_parallel,
                                   n_slots=fp_slots)
    elif growth_policy == "depthwise" and p.voting_k == 0:
        grower = functools.partial(grow_tree_depthwise,
                                   n_slots=default_n_slots(p.num_leaves),
                                   cconfig=cconfig)
    else:
        # lossguide / voting-parallel (the grower itself skips the
        # compressed wire on its voting collectives)
        grower = functools.partial(grow_tree, cconfig=cconfig)

    def goss_weights(g_abs, bag, key):
        """Gradient one-side sampling: keep top_rate by |grad|, sample
        other_rate of the rest with amplification (1-a)/b.  k is computed
        from the REAL (bag>0) row count so pallas pad rows don't distort
        the top-k threshold."""
        n = g_abs.shape[0]
        n_real = jnp.sum((bag > 0).astype(jnp.int32))
        k = jnp.maximum(1, (n_real.astype(jnp.float32) * top_rate).astype(jnp.int32))
        sorted_desc = -jnp.sort(-(g_abs * (bag > 0)))
        thresh = sorted_desc[jnp.minimum(k - 1, n - 1)]
        topset = g_abs >= thresh
        rest_keep = jax.random.uniform(key, (n,)) < other_rate
        amp = (1.0 - top_rate) / jnp.maximum(other_rate, 1e-6)
        return jnp.where(topset, 1.0, jnp.where(rest_keep, amp, 0.0)) * bag

    def one_step(bins_t, scores, labels, weights, bag_in, feature_mask,
                 key, upper_bounds, num_bins, bundle_map=None):
        base_bag, bag_key = bag_in
        if bagging_fraction < 1.0:
            # feature-parallel replicates rows: every rank must draw the
            # SAME bag; data-parallel ranks each own distinct rows
            if axis is not None and not feature_parallel:
                bag_key = jax.random.fold_in(bag_key, lax.axis_index(axis))
            bag_mask = base_bag * (
                jax.random.uniform(bag_key, base_bag.shape)
                < bagging_fraction).astype(jnp.float32)
        else:
            bag_mask = base_bag
        trees = []
        if num_class == 1:
            grad, hess = objective_fn(scores, labels, weights)
            rv = bag_mask
            if use_goss:
                # GOSS ranks |grad| at full f32 resolution, BEFORE the
                # ingest quantization below
                rv = goss_weights(jnp.abs(grad), bag_mask, key)
            if fused_ingest:
                # fused bf16 ingest: the objective's elementwise chain
                # fuses straight into this rounding, so the ONLY
                # materialized g/h arrays are bf16 — every histogram
                # build (all waves of the tree) reads half the bytes;
                # bin accumulation promotes back to f32, exact over the
                # rounded values
                grad = grad.astype(jnp.bfloat16)
                hess = hess.astype(jnp.bfloat16)
            tree, node_id = grower(bins_t, grad, hess, rv, feature_mask,
                                   upper_bounds, num_bins, learning_rate,
                                   p, axis, use_pallas,
                                   bundle_map=bundle_map)
            new_scores = scores + tree.leaf_value[node_id]
            trees.append(tree)
        else:
            onehot = jax.nn.one_hot(labels.astype(jnp.int32), num_class)
            if ova:
                # multiclassova: independent per-class sigmoid losses
                pk = jax.nn.sigmoid(scores)
                grad = (pk - onehot) * weights[:, None]
                hess = jnp.maximum(pk * (1.0 - pk), 1e-16) * weights[:, None]
            else:
                grad, hess = softmax_grad_hess(scores, onehot, weights)
            g_hist, h_hist = grad, hess
            if fused_ingest:       # see the single-class branch above
                g_hist = grad.astype(jnp.bfloat16)
                h_hist = hess.astype(jnp.bfloat16)
            new_scores = scores
            for k in range(num_class):
                rv = bag_mask
                if use_goss:
                    rv = goss_weights(jnp.abs(grad[:, k]), bag_mask,
                                      jax.random.fold_in(key, k))
                tree, node_id = grower(bins_t, g_hist[:, k], h_hist[:, k],
                                       rv, feature_mask, upper_bounds,
                                       num_bins, learning_rate, p, axis,
                                       use_pallas, bundle_map=bundle_map)
                new_scores = new_scores.at[:, k].add(tree.leaf_value[node_id])
                trees.append(tree)
        return stack_trees(trees), new_scores

    if mesh is None:
        return jax.jit(one_step)

    ndim_scores = 1 if num_class == 1 else 2
    if feature_parallel:
        # vertical sharding: FEATURES split over the axis, rows replicated.
        # Under EFB the per-rank route tables shard on their (stacked)
        # original-feature axis exactly like bounds/nbins
        bm_spec = ({"col": P(DATA_AXIS), "lo": P(DATA_AXIS),
                    "hi": P(DATA_AXIS), "default_bin": P(DATA_AXIS),
                    "gather_src": P(DATA_AXIS, None)}
                   if bundled_featpar else P())
        in_specs = (P(DATA_AXIS, None),                    # bins_t (Fb, N)
                    P(), P(), P(),                         # scores/labels/w
                    (P(), P()),                            # (base_bag, key)
                    P(DATA_AXIS), P(),                     # fmask/key
                    P(DATA_AXIS, None), P(DATA_AXIS),      # bounds/nbins
                    bm_spec)                               # route tables
        out_specs = (P(), P())                             # all replicated
    else:
        in_specs = (P(None, DATA_AXIS),                    # bins_t (F, N)
                    P(DATA_AXIS) if ndim_scores == 1 else P(DATA_AXIS, None),
                    P(DATA_AXIS), P(DATA_AXIS),            # labels/weights
                    (P(DATA_AXIS), P()),                   # (base_bag, bag_key)
                    P(), P(), P(), P(), P())   # fmask/key/bounds/nbins/bundle
        out_specs = (P(),                                  # trees replicated
                     P(DATA_AXIS) if ndim_scores == 1 else P(DATA_AXIS, None))
    return jax.jit(jax.shard_map(one_step, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False))


@functools.partial(jax.jit, static_argnames=("depth_bound",))
def _predict_binned_tree(bins_t, tree: Tree, depth_bound: int,
                         bundle_map=None, total_bins: int = 1 << 20):
    """Leaf values of one tree on (F, N) binned features (dart/valid eval).

    ``bundle_map``: when the device matrix is EFB-BUNDLED, trees still
    live in ORIGINAL feature space — each node's split routes through the
    same universal form training uses (``x in (rlo, rhi] ? x <= t1 :
    default``, trainer._slot_route_params), so dart rescoring traverses
    the bundled matrix exactly."""
    from .trainer import _route_left, _slot_route_params

    N = bins_t.shape[1]
    rows = jnp.arange(N)

    def step(_, node):
        feat = tree.split_feature[node]
        is_leaf = feat < 0
        f = jnp.maximum(feat, 0)
        col, t1, rlo, rhi, dflt = _slot_route_params(
            f, tree.split_bin[node], total_bins, bundle_map)
        go_left = _route_left(bins_t[col, rows], t1, rlo, rhi, dflt)
        child = jnp.where(go_left, tree.left_child[node], tree.right_child[node])
        return jnp.where(is_leaf, node, child)

    leaf = lax.fori_loop(0, depth_bound, step, jnp.zeros(N, jnp.int32))
    return tree.leaf_value[leaf]


@dataclasses.dataclass
class EvalRecord:
    iteration: int
    metric: str
    value: float


@dataclasses.dataclass
class InstrumentationMeasures:
    """Per-phase wall-clock training instrumentation (reference:
    TaskInstrumentationMeasures / InstrumentationMeasures,
    lightgbm/.../LightGBMPerformance.scala:11-111).  Attached to the
    trained Booster as ``.measures`` and surfaced by the estimators."""
    binning_s: float = 0.0            # bin-mapper fit + transform (sampling)
    data_prep_s: float = 0.0          # labels/weights/padding/device put
    compile_s: float = 0.0            # first-iteration jit compile + run
    training_s: float = 0.0           # whole boosting loop
    eval_s: float = 0.0               # validation metric evaluation
    iterations: int = 0
    total_s: float = 0.0

    def iterations_per_sec(self) -> float:
        post = self.training_s - self.compile_s
        steady = max(self.iterations - 1, 1)
        return steady / post if post > 0 else 0.0

    def as_dict(self) -> Dict[str, float]:
        d = dataclasses.asdict(self)
        d["iterations_per_sec"] = self.iterations_per_sec()
        return d


def _hist_psum_nulled(config: "BoostingConfig", mesh_present: bool) -> bool:
    """True where the data-parallel histogram psum does not exist (no
    mesh, feature/voting parallelism) — THE predicate for 'is the codec
    live', consumed by both ``_step_factory_args`` (which nulls the
    cconfig the growers trace) and ``_effective_wire_key`` (the resume
    guard), so the two can never drift apart."""
    return (not mesh_present
            or config.parallelism in ("feature_parallel",
                                      "voting_parallel"))


def _mesh_world_size(mesh: Optional[Mesh]) -> int:
    """Device count of a fit's mesh (1 with no mesh) — the ONE
    world-size derivation for both the resume-time comparison and the
    checkpoint stamp, so the two can never read differently-computed
    values."""
    if mesh is None:
        return 1
    return int(np.prod([mesh.shape[a] for a in mesh.axis_names]))


def _effective_wire_key(config: "BoostingConfig", mesh: Optional[Mesh]):
    """The histogram-psum wire a fit ACTUALLY uses, as a comparable key:
    ``None`` for the flat f32 wire (no codec, or
    :func:`_hist_psum_nulled`), else ``(compression, min_size, chunk)``
    with chunk zeroed for non-int8 codecs (bf16 never chunks) — plus
    the RESOLVED planner routing as a 4th element when it is anything
    but certainly-flat (ISSUE 14: a hierarchical route quantizes
    intra-host SUMS where flat quantizes per-rank payloads — different
    histogram numerics, so a routing toggle against an existing
    checkpoint refuses exactly like a codec toggle; 'auto' on unknown
    topology resolves flat and keeps pre-planner 3-element keys
    comparing equal).  DL-only fields (error_feedback/sharded_update/
    manual) never enter the key."""
    cc = resolve_collective_config(config.collective_compression)
    if cc is None or _hist_psum_nulled(config, mesh is not None):
        return None
    from ...parallel.planner import get_planner
    routing = get_planner().resolved_routing(
        cc, world=_mesh_world_size(mesh))
    if not cc.compresses and routing == "flat":
        return None
    key = ((cc.compression, cc.min_size,
            cc.chunk if cc.compression == "int8" else 0)
           if cc.compresses else ("none", 0, 0))
    if routing != "flat":
        key = key + (routing,)
    return key


def _latest_checkpoint(directory: str) -> Optional[Booster]:
    import os
    import re as _re
    if not os.path.isdir(directory):
        return None
    found = []
    for name in os.listdir(directory):
        m = _re.match(r"iter_(\d+)\.json$", name)
        if m:
            found.append((int(m.group(1)), name))
    if not found:
        return None
    _, name = max(found)
    with open(os.path.join(directory, name)) as f:
        return Booster.from_string(f.read())


def _write_checkpoint(directory: str, booster: Booster,
                      keep: int = 3) -> None:
    import os
    import re as _re

    from ...resilience.faults import get_faults
    os.makedirs(directory, exist_ok=True)
    n = booster.num_trees // max(booster.num_class, 1)
    path = os.path.join(directory, f"iter_{n:08d}.json")
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(booster.to_dict(), f)
    # a SIGKILL between write and publish must leave only the tmp file,
    # which _latest_checkpoint never matches — resume sees the prior step
    get_faults().kill_point("gbdt.checkpoint.pre_publish", iteration=n)
    os.replace(tmp, path)
    # the published step is this rank's durable position: report it on
    # the heartbeat channel so the gang supervisor's verdicts (and the
    # elastic-resume recovery clock) carry real training progress
    from ...parallel.heartbeat import beat
    from ...telemetry.flight import record as _flight_record
    beat(step=n)
    _flight_record("checkpoint", step=n, path=path)
    get_faults().kill_point("gbdt.checkpoint", iteration=n)
    matches = (_re.match(r"iter_(\d+)\.json$", x)
               for x in os.listdir(directory))
    steps = sorted(int(m.group(1)) for m in matches if m)
    for old in steps[:-keep]:
        try:
            os.remove(os.path.join(directory, f"iter_{old:08d}.json"))
        except OSError:
            pass


def _available_host_bytes() -> int:
    """Best-effort available host memory (MemAvailable, then sysconf),
    0 when neither source exists."""
    import os
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    try:
        return os.sysconf("SC_AVPHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")
    except (AttributeError, OSError, ValueError):
        return 0


def _advanced_mask_budget_bytes(config: "BoostingConfig") -> int:
    """Byte budget for the advanced-monotone (M, M, F) overlap masks.

    Priority: ``pass_through={"advanced_mask_bytes": ...}`` kwarg, then
    the ``SYNAPSEML_TPU_ADV_MONO_MASK_BYTES`` env var (both taken
    verbatim), then a quarter of the host's available memory clamped to
    [1 GiB (the historical fixed guard), 8 GiB] — the mask estimate
    excludes XLA's compile/temp headroom, so the auto budget stays well
    inside even a big host and anything larger must be opted into."""
    import os
    override = config.pass_through.get(
        "advanced_mask_bytes",
        os.environ.get("SYNAPSEML_TPU_ADV_MONO_MASK_BYTES"))
    if override is not None:
        return int(float(override))
    return min(max(1 << 30, _available_host_bytes() // 4), 8 << 30)


def _placeholder_mapper(m: BinMapper) -> bool:
    return bool(np.all(m.num_bins <= 1)) and bool(np.all(np.isinf(m.upper_bounds)))


def _replay_margin(b: Booster, X: np.ndarray) -> np.ndarray:
    """Warm-start margin re-based in the TRAINING accumulation order.

    The train loop advances scores one f32 add per tree
    (``scores + leaf_value[node_id]``); ``predict_margin``'s fused
    traversal reassociates the tree sum, which drifts by ulps and makes
    an otherwise-deterministic gbdt/goss resume diverge from the
    uninterrupted run on near-tie splits.  Replaying per-tree leaf values
    sequentially in f32 reproduces training's exact rounding, so the
    resumed run continues bit-identically.  dart/rf reweight trees at
    predict time — their resume is documented-approximate, use the fused
    path."""
    if b.config.boosting_type not in ("gbdt", "goss") \
            or any(w != 1.0 for w in b.tree_weights):
        return b.predict_margin(X)
    _, leaves = b.predict_margin(X, return_leaves=True)
    n = len(X)
    K = max(b.num_class, 1)
    cols = []
    for k in range(K):
        base = b.init_score[min(k, len(b.init_score) - 1)]
        m = np.full(n, np.float32(base), np.float32)
        ids = leaves[k]                          # (T_k, n) leaf node ids
        ktrees = [t for t, kc in zip(b.trees, b.tree_class) if kc == k] \
            if K > 1 else b.trees
        for t, tree in enumerate(ktrees):
            m = m + np.asarray(tree.leaf_value, np.float32)[ids[t]]
        cols.append(m)
    return cols[0] if K == 1 else np.stack(cols, axis=1)


def train(X: np.ndarray, y: np.ndarray, config: BoostingConfig,
          sample_weight: Optional[np.ndarray] = None,
          valid: Optional[Tuple[np.ndarray, np.ndarray, Optional[np.ndarray]]] = None,
          feature_names: Optional[Sequence[str]] = None,
          mesh: Optional[Mesh] = None,
          init_model: Optional[Booster] = None,
          callbacks: Optional[Sequence[Callable]] = None,
          group: Optional[np.ndarray] = None,
          valid_group: Optional[np.ndarray] = None,
          checkpoint_dir: Optional[str] = None,
          checkpoint_interval: int = 0,
          step_profiler=None,
          ) -> Tuple[Booster, List[EvalRecord]]:
    """Full training run (trainOneDataBatch analogue, LightGBMBase.scala:393).

    ``checkpoint_dir`` + ``checkpoint_interval`` enable STEP-LEVEL
    checkpoint/resume (beyond the reference, whose only resume unit is the
    numBatches warm-start fold, LightGBMBase.scala:38-59): every N
    iterations the partial booster is written atomically; a later call
    with the same dir resumes from the newest file and trains only the
    remaining iterations.  Resume re-bases scores from the saved model, so
    unbagged gbdt/goss runs continue on the identical tree sequence;
    bagged runs continue with a fresh subsample stream and dart runs
    freeze the carried trees at their checkpointed weights with a fresh
    drop stream over the new trees (both the documented-approximate
    semantics of the reference's warm start, LightGBMBase.scala:38-59).

    When ``mesh`` is given, rows are sharded over its ``data`` axis and each
    iteration's histograms ride one psum — the entire distributed story.

    ``X`` may be a numpy matrix OR a chunked source (anything with
    ``num_rows``/``num_features``/``iter_chunks``/``sample_rows`` — e.g.
    :class:`~synapseml_tpu.io.colstore.ChunkedColumnSource`): then features
    stream from disk in micro-batches into the device-resident binned
    matrix and host memory stays O(chunk) — the StreamingPartitionTask
    ingestion model (StreamingPartitionTask.scala:101-422).  With a source
    carrying a label column, ``y=None`` reads labels from it.

    ``step_profiler`` (a :class:`~synapseml_tpu.telemetry.gangplane.
    StepProfiler`) decomposes each boosting iteration's wall time into
    data (mask/bag prep) / compute (tree grow + download) / collective /
    other (eval, checkpoint) segments.  Profiling forces the eager host
    path — the fused ``lax.scan`` dispatch admits no per-iteration
    boundary to time.
    """
    import time as _time
    measures = InstrumentationMeasures()
    _t0 = _time.perf_counter()
    # ``checkpoint_dir`` also accepts a core.checkpoint.CheckpointManager
    # (anything carrying ``.directory``): preemption-tolerant callers hand
    # the same manager to every trainer and the booster writes its
    # iteration checkpoints into its directory
    if checkpoint_dir is not None and not isinstance(checkpoint_dir, str):
        checkpoint_dir = getattr(checkpoint_dir, "directory", checkpoint_dir)
    if checkpoint_dir and checkpoint_interval > 0:
        # dart resume uses the warm-start (init_model) semantics LightGBM
        # itself documents as APPROXIMATE: the carried trees are frozen
        # at their checkpointed weights (they re-based the score margin)
        # and the fresh run's drop/normalize stream applies only to the
        # trees grown after resume.  Exact continuation is impossible —
        # later drops reweight EARLIER trees, so the uninterrupted
        # drop/normalize sequence cannot be replayed from a prefix — and
        # the reference's own numBatches warm start has the same
        # stated-approximate behavior (LightGBMBase.scala:38-59).
        resumed = _latest_checkpoint(checkpoint_dir)
        if resumed is not None:
            # codec guard (the DL _CheckpointLoop's counterpart): the
            # remaining trees would grow on a different histogram wire
            # than the carried ones — bit-exact with neither clean run —
            # so a collective_compression toggle against an existing
            # checkpoint fails loudly instead of silently changing the
            # numerics mid-model.  The key is the EFFECTIVE wire, not
            # the declared config: only the fields the histogram psum
            # reads (codec, min_size, int8 chunk — error_feedback/
            # sharded_update/manual are DL-only, bf16 never chunks),
            # nulled where the psum itself is nulled (_step_factory_args:
            # no mesh, feature/voting parallelism) — so a topology change
            # like gang-fit → single-device-resume flips the key even
            # under an unchanged config, and a single-device fit that
            # declared a (documented-ignored) codec resumes freely.
            # Checkpoints carry the writer's key (stamped below) because
            # mesh-ness is a train() arg the config alone cannot encode.
            saved_pt = resumed.config.pass_through or {}
            if "_codec_wire_key" in saved_pt:
                saved_cc = saved_pt["_codec_wire_key"]
                saved_cc = tuple(saved_cc) if saved_cc is not None else None
            else:
                # unstamped checkpoint: the codec fields did not exist
                # when it was written, so it trained on the f32 wire
                saved_cc = None
            cur_cc = _effective_wire_key(config, mesh)
            if saved_cc != cur_cc:
                raise ValueError(
                    f"checkpoint at {checkpoint_dir} was trained with "
                    f"collective_compression wire {saved_cc!r} but this "
                    f"fit requests {cur_cc!r}; resuming would grow the "
                    "remaining trees under different histogram numerics "
                    "— use a fresh checkpoint_dir or keep the codec")
            # same contract for the ingest dtype: trees grown on bf16
            # g/h are not bit-compatible with f32-ingest continuation
            # (an unstamped checkpoint predates fused ingest = f32)
            saved_fused = bool(saved_pt.get("_fused_ingest", False))
            cur_fused = _fused_ingest_on(config)
            if saved_fused != cur_fused:
                raise ValueError(
                    f"checkpoint at {checkpoint_dir} was trained with "
                    f"fused_ingest={saved_fused} but this fit requests "
                    f"{cur_fused}; resuming would grow the remaining "
                    "trees under a different histogram ingest dtype — "
                    "use a fresh checkpoint_dir or keep the knob "
                    "(fused_ingest=False resumes pre-fused checkpoints)")
            # world size is deliberately NOT part of the refusal key: an
            # elastic gang resize resumes an N-rank checkpoint on M ranks
            # (rows re-pad and re-shard over the new mesh below; the
            # histogram psum is a sum over ALL rows, so the partition is
            # not model state).  The stamped writer size is
            # informational — a resized resume is recorded, never
            # refused, as long as the effective wire matches.
            cur_ws = _mesh_world_size(mesh)
            saved_ws = saved_pt.get("_fit_world_size")
            if saved_ws is not None and int(saved_ws) != cur_ws:
                from ...resilience.faults import get_faults
                from ...telemetry.flight import record as _flight_rec
                get_faults().note("gbdt.resize_resume",
                                  saved=int(saved_ws), current=cur_ws)
                _flight_rec("resize_resume", trainer="gbdt",
                            saved_shards=int(saved_ws),
                            current_shards=cur_ws)
            done = resumed.num_trees // max(resumed.num_class, 1)
            if done >= config.num_iterations:
                return resumed, []
            config = dataclasses.replace(
                config, num_iterations=config.num_iterations - done)
            init_model = resumed
        # stamp THIS fit's effective wire into the config the written
        # checkpoints carry (the guard above reads it back; JSON
        # round-trips the tuple as a list), plus the writer's device
        # count for resize observability
        key = _effective_wire_key(config, mesh)
        config = dataclasses.replace(config, pass_through={
            **config.pass_through,
            "_codec_wire_key": list(key) if key is not None else None,
            "_fused_ingest": _fused_ingest_on(config),
            "_fit_world_size": _mesh_world_size(mesh)})
    source = X if hasattr(X, "iter_chunks") else None
    if source is not None:
        n, F = source.num_rows, source.num_features
        if y is None:
            y = source.read_labels()
            if y is None:
                raise ValueError("streaming train with y=None needs the "
                                 "source to carry a label_col")
        if sample_weight is None:
            sample_weight = source.read_weights()
    else:
        X = np.ascontiguousarray(X, np.float32)
        n, F = X.shape

    if config.two_level_hist not in ("auto", "on", "off", True, False):
        raise ValueError(
            f"two_level_hist={config.two_level_hist!r}: must be 'auto', "
            "'on', or 'off'")
    # fail fast on a bad codec string / ingest knob, before
    # binning/compiles start
    resolve_collective_config(config.collective_compression)
    _fused_ingest_on(config)

    if config.monotone_constraints and any(config.monotone_constraints):
        if config.monotone_constraints_method not in ("basic",
                                                      "intermediate",
                                                      "advanced"):
            raise ValueError(
                f"monotone_constraints_method="
                f"{config.monotone_constraints_method!r}: must be 'basic', "
                "'intermediate' or 'advanced'")
        if len(config.monotone_constraints) != F:
            raise ValueError(
                f"monotone_constraints has "
                f"{len(config.monotone_constraints)} entries for {F} "
                "features")
        if any(int(c) not in (-1, 0, 1) for c in config.monotone_constraints):
            raise ValueError("monotone_constraints entries must be -1, 0, "
                             "or 1")
        cats = set(config.categorical_feature or [])
        if any(int(c) != 0 and i in cats
               for i, c in enumerate(config.monotone_constraints)):
            raise ValueError("monotone constraints on categorical features "
                             "are not meaningful (category-subset splits "
                             "have no direction)")
        if config.monotone_constraints_method == "advanced":
            # the advanced refresh materializes (M, M, F) overlap masks
            # (bool + int32 reductions, ~5 bytes/entry) inside the jitted
            # per-wave refresh — guard the O(M^2 F) memory here so a
            # config that cannot fit fails fast instead of OOMing or
            # stalling compilation mid-train.  The budget scales with the
            # host's available memory (not a fixed 1 GiB), so big hosts
            # degrade to slow instead of refusing (ADVICE r5 item 2);
            # SYNAPSEML_TPU_ADV_MONO_MASK_BYTES or
            # pass_through={"advanced_mask_bytes": ...} overrides it
            from .trainer import max_nodes
            m_nodes = max_nodes(config.num_leaves)
            adv_bytes = 5 * m_nodes * m_nodes * F
            budget = _advanced_mask_budget_bytes(config)
            if adv_bytes > budget:
                raise ValueError(
                    f"monotone_constraints_method='advanced' with "
                    f"num_leaves={config.num_leaves} and {F} features "
                    f"needs ~{adv_bytes / 2**30:.1f} GiB of (M, M, F) "
                    f"constraint masks per refresh (M={m_nodes} nodes), "
                    f"over this host's {budget / 2**30:.1f} GiB budget; "
                    "use monotone_constraints_method='intermediate' "
                    "(a provable superset of the advanced constraint "
                    "set) for models this size, or raise the budget via "
                    "SYNAPSEML_TPU_ADV_MONO_MASK_BYTES / "
                    "pass_through={'advanced_mask_bytes': ...}")

    # distributed lambdarank: pack WHOLE groups onto shards up front (the
    # reference's query-rows-share-a-partition rule); rows permute into
    # per-shard slabs padded to a common length, lambdas stay shard-local
    lr_pack = None
    lr_stream_perm = None
    if (config.objective == "lambdarank" and mesh is not None
            and config.parallelism != "feature_parallel"):
        # data_parallel AND voting_parallel shard ROWS, so whole groups
        # pack onto shards and lambdas compute shard-locally.
        # feature_parallel REPLICATES rows, so it skips the packing and
        # uses the plain in-memory objective on every rank
        if group is None:
            raise ValueError("lambdarank requires group sizes (groupCol)")
        from .pallas_hist import hist_pad_multiple
        from .ranking import pack_groups_for_shards
        _shards = mesh.shape[DATA_AXIS]
        _B = config.max_bin + 1
        _unit = (hist_pad_multiple()
                 if (jax.default_backend() == "tpu" and _B <= 512
                     and _B % 8 == 0) else 1)
        perm, _sq, _smask, _L = pack_groups_for_shards(
            np.asarray(group), _shards, _unit, max_group_size=128)
        _valid = (perm >= 0)
        pc = np.maximum(perm, 0)
        if source is not None:
            # streamed ranking: labels/weights permute on HOST (tiny);
            # the binned matrix streams to device in SOURCE order and
            # permutes into the per-shard group slabs ON DEVICE after
            # assembly — whole groups land on one shard exactly like the
            # in-memory path, host memory stays O(chunk)
            lr_stream_perm = (pc, _valid, n)      # n = source row count
        else:
            X = X[pc]
            X[~_valid] = np.nan    # pads must not shift the bin quantiles
        y = np.asarray(y)[pc] * _valid
        sw = (np.asarray(sample_weight, np.float32)[pc]
              if sample_weight is not None
              else np.ones(len(pc), np.float32))
        sample_weight = (sw * _valid).astype(np.float32)
        n = len(pc)
        lr_pack = (_sq, _smask, _L, _valid)
    K = config.num_class if config.objective in ("multiclass", "multiclassova") else 1
    feature_names = list(feature_names) if feature_names else [f"f{i}" for i in range(F)]
    rng = np.random.default_rng(config.seed)

    # -- binning (calculateRowStatistics analogue) -------------------------
    # imported LightGBM models carry a placeholder mapper (all-inf bounds);
    # warm-starting from one must fit a REAL mapper or every row would land
    # in bin 1 and the new trees would be stumps
    if init_model is not None and not _placeholder_mapper(init_model.bin_mapper):
        mapper = init_model.bin_mapper
    elif source is not None:
        # streamed samples carry no aligned labels: categorical bins order
        # by value instead of target statistic (documented fallback)
        mapper = fit_bin_mapper(
            source.sample_rows(config.bin_sample_count, config.seed),
            config.max_bin, sample_count=config.bin_sample_count,
            seed=config.seed,
            categorical_features=config.categorical_feature)
    else:
        mapper = fit_bin_mapper(X, config.max_bin,
                                sample_count=config.bin_sample_count,
                                seed=config.seed,
                                categorical_features=config.categorical_feature,
                                y=np.asarray(y, np.float64))
    measures.binning_s = _time.perf_counter() - _t0
    _t_prep = _time.perf_counter()

    # -- labels / weights --------------------------------------------------
    w = np.ones(n, np.float32) if sample_weight is None else \
        np.asarray(sample_weight, np.float32).copy()
    w_scaled = False
    if config.objective == "binary":
        yb = (np.asarray(y) > 0).astype(np.float32)
        if config.is_unbalance or config.scale_pos_weight != 1.0:
            pos = max(float(yb.sum()), 1.0)
            neg = max(float(n - yb.sum()), 1.0)
            spw = (neg / pos) if config.is_unbalance else config.scale_pos_weight
            w = np.where(yb > 0, w * spw, w).astype(np.float32)
            w_scaled = True
        labels_np = yb
    elif K > 1:
        labels_np = np.asarray(y, np.float32)
    else:
        labels_np = np.asarray(y, np.float32)

    # -- init score (boost_from_average) -----------------------------------
    if init_model is not None:
        if config.boosting_type == "rf":
            # rf trees are INDEPENDENT fits at the constant init margin —
            # continued training must not boost from the ensemble margin
            # (and must not pay a full carried-model prediction pass only
            # to discard it)
            base_margin = None
        elif source is not None:
            base_margin = np.concatenate(
                [init_model.predict_margin(cx)
                 for cx, _, _ in source.iter_chunks()])
        else:
            base_margin = _replay_margin(init_model, X)
        init_sc = init_model.init_score
    elif (config.boost_from_average
          and config.objective not in ("multiclass", "multiclassova")):
        s0 = initial_score(config.objective, labels_np, w)
        init_sc = np.full(K, s0, np.float32)
        base_margin = None                 # constant margin built on device
    else:
        init_sc = np.zeros(K, np.float32)
        base_margin = None

    # -- padding + device placement ---------------------------------------
    # pallas kernel constraints: B must be sublane-aligned and the one-hot
    # working set must fit VMEM; otherwise scatter fallback
    B_total = config.max_bin + 1
    pallas_candidate = (jax.default_backend() == "tpu"
                        and B_total <= 512 and B_total % 8 == 0)
    shards = mesh.shape[DATA_AXIS] if mesh is not None else 1
    featpar = config.parallelism == "feature_parallel" and mesh is not None
    use_pallas = pallas_candidate
    uses_fused = (config.growth_policy == "depthwise" and not featpar
                  and config.parallelism != "voting_parallel")
    if pallas_candidate and uses_fused:
        # the fused route+hist kernel keeps its whole accumulator VMEM-
        # resident, which scales with F — wide matrices fall back to the
        # scatter path (EFB re-gates on the bundled width below)
        from .pallas_hist import fused_geometry
        use_pallas = fused_geometry(
            F, B_total, default_n_slots(config.num_leaves)) is not None
    # feature_parallel replicates ROWS and shards FEATURES: rows pad only
    # for the pallas chunk, features pad to the rank count
    row_shards = 1 if featpar else shards
    pad_unit = row_shards
    if pallas_candidate:       # pad for the kernel even if EFB re-gates
        from .pallas_hist import hist_pad_multiple
        pad_unit = row_shards * hist_pad_multiple()
    Fp = F
    if featpar:
        Fp = F + (-F) % shards
    pad = (-n) % pad_unit
    if pad:
        labels_np = np.concatenate([labels_np, np.zeros(pad, labels_np.dtype)])
        if sample_weight is not None or w_scaled:
            w = np.concatenate([w, np.zeros(pad, np.float32)])
    N = n + pad

    def put(xx, ndim):
        if mesh is None:
            return jnp.asarray(xx)
        if featpar:                       # rows replicated on every rank
            return jax.device_put(xx, replicated(mesh))
        return jax.device_put(xx, batch_sharding(mesh, ndim))

    def dev_fill(fill, shape):
        """Constant arrays are built ON the chip — no host→device traffic
        (the link behind the driver tunnel runs ~20 MB/s)."""
        if mesh is None:
            return jnp.full(shape, fill, jnp.float32)
        sh = replicated(mesh) if featpar else batch_sharding(mesh, len(shape))
        return jax.jit(lambda: jnp.full(shape, fill, jnp.float32),
                       out_shardings=sh)()

    if config.two_level_hist == "auto":
        # resolve here, where BOTH the global row count (the grower only
        # sees shard-local rows, which would scale the documented 500k
        # threshold with device count) and the pallas decision are known:
        # on the XLA scatter fallback two-level only ADDS work (fine
        # hists get built then pooled) while coarsening non-top-K splits,
        # so auto requires a pallas grower that implements it — the
        # fused depthwise path, or the single-device/data-parallel
        # lossguide path (per-tile nodes kernel).  feature/voting
        # parallel growers ignore two_level, so auto must stay "off"
        # there (a stale "on" would also fork the GrowthParams jit key
        # for an identical program).  Must resolve BEFORE the
        # warm-compile thread below — GrowthParams is the jit/lru cache
        # key, so a thread warming the 'auto' config would compile a
        # program the run never uses.  (The EFB re-gate further down can
        # only flip use_pallas when enable_bundle is set, and EFB
        # structurally disables two-level in the grower anyway.)
        from .trainer import TWO_LEVEL_MIN_ROWS
        _tl_lossguide = (config.growth_policy == "lossguide"
                         and not featpar
                         and config.parallelism != "voting_parallel")
        _tl_resolved = ("on" if (n >= TWO_LEVEL_MIN_ROWS and use_pallas
                                 and (uses_fused or _tl_lossguide))
                        else "off")
        if _tl_resolved == "on":
            # 'auto' flipping to coarse-then-refine CHANGES split-search
            # semantics (non-top-K features split only on coarse-bin
            # boundaries) — say so once, visibly, so a user can tell
            # which semantics produced a model (ADVICE r5 item 1)
            _logging.getLogger("synapseml_tpu.gbdt").info(
                "two_level_hist='auto' resolved to 'on' (%d rows >= %d, "
                "pallas grower): histograms build coarse and only the top "
                "%d features refine at full resolution; set "
                "two_level_hist='off' for exact full-resolution splits",
                n, TWO_LEVEL_MIN_ROWS, config.refine_features)
        config = dataclasses.replace(config, two_level_hist=_tl_resolved)
    # set on EVERY fit (not just the 'auto' branch), else an explicit
    # 'on'/'off' fit would leave the previous fit's resolution standing;
    # unlabeled on purpose — a per-policy label would leave the OTHER
    # policy's series stale across fits.  Guarded: telemetry must never
    # break training (same contract as _publish_measures/_tl_gauge).
    try:
        _telemetry.get_registry().gauge(
            "gbdt_two_level_resolved",
            "1 when the current fit's two_level_hist (after 'auto' "
            "resolution) requests coarse-then-refine histograms").set(
                1.0 if config.two_level_hist in ("on", True) else 0.0)
    except Exception:
        pass

    # -- compile/transfer overlap ------------------------------------------
    # the jitted step's first compile (cold: tens of seconds, warm cache:
    # seconds) and the host-side binning + u8 upload are independent; warm
    # the step on a helper thread with zero-dummies of the final shapes so
    # the wall clock pays max(compile, binning+upload), not the sum.
    # _make_step is lru-cached, so the real construction below returns the
    # SAME jitted callable the thread compiled.  Restricted to the plain
    # single-device path (sharded dummies would need placement logic, and
    # EFB/lambdarank only learn their shapes after binning).
    _warm_thread = None
    if (use_pallas and mesh is None and K == 1 and not config.enable_bundle
            and config.objective != "lambdarank" and n >= 200_000):
        _wargs, _wkw = _step_factory_args(config, K, mesh, featpar,
                                          use_pallas, num_features=F)
        # warm the program the run will actually use: the scanned
        # whole-run program for fire-and-forget fits, else the one-step
        _w_scan_ok = (not (config.boosting_type == "dart" or valid is not None
                           or callbacks or step_profiler is not None
                           or (checkpoint_dir and checkpoint_interval > 0))
                      and config.feature_fraction >= 1.0
                      and config.num_iterations >= SCAN_CHUNK)
        if _w_scan_ok:
            _wrun = _make_scan(_wargs, tuple(sorted(_wkw.items())),
                               config.bagging_freq, config.seed,
                               config.boosting_type == "rf")
        else:
            _wstep = _make_step(*_wargs, **_wkw)
        _w_ub_cols = mapper.upper_bounds.shape[1]

        def _warm_compile():
            try:
                zf32 = functools.partial(jnp.zeros, dtype=jnp.float32)
                _cargs = (jnp.zeros((F, N), jnp.int32), zf32(N), zf32(N),
                          jnp.ones(N, jnp.float32))
                _ctail = (jnp.ones(F, bool),
                          jnp.zeros((F, _w_ub_cols), jnp.float32),
                          jnp.full(F, config.max_bin + 1, jnp.int32),
                          None)
                if _w_scan_ok:
                    # a real (junk-data) call: only the dispatch path
                    # populates jit's executable cache, and one SCAN_CHUNK
                    # of empty trees is ~1 s of device time overlapped
                    # with binning
                    out = _wrun(*_cargs, jnp.ones(N, jnp.float32),
                                jax.random.PRNGKey(0), _ctail[0], _ctail[1],
                                _ctail[2], _ctail[3], zf32(N),
                                jnp.zeros((), jnp.int32))
                else:
                    out = _wstep(*_cargs, (jnp.ones(N, jnp.float32),
                                 jax.random.PRNGKey(0)), _ctail[0],
                                 jax.random.PRNGKey(1), _ctail[1],
                                 _ctail[2], _ctail[3])
                jax.block_until_ready(out[1])
            except Exception:
                pass           # warming is best-effort; the loop compiles

        import threading as _threading
        _warm_thread = _threading.Thread(target=_warm_compile, daemon=True)
        _warm_thread.start()

    # host-bin to the narrowest integer type (native multithreaded search)
    # and upcast/transpose on device: ships 1-2 bytes/cell instead of 4 —
    # the upload, not the searchsorted, is the fixed cost that bounds short
    # training runs
    _t_bin2 = _time.perf_counter()

    def bin_host(mat):
        if mapper.has_categorical:
            # categorical LUTs live in the python mapper; the native fast
            # path handles the numeric-only common case
            out = mapper.transform(mat)
            return out.astype(np.uint8 if mapper.max_bin <= 255
                              else np.uint16)
        if mapper.max_bin <= 255:
            from ...native import bin_columns_u8
            return bin_columns_u8(mat, mapper.upper_bounds, mapper.max_bin)
        return mapper.transform(mat).astype(np.uint16)

    # exclusive feature bundling: fit on a binned sample, then every
    # chunk/matrix flows through the bundle remap before device upload.
    # feature_parallel fits ONE BUNDLER PER RANK SLICE (bundles never
    # cross rank boundaries, so vertical sharding and bundling compose);
    # every rank's bundled block pads to the widest rank's bundle count
    # so the sharded matrix stays rectangular
    bundler = None
    rank_bundlers = None
    Fsl = Fp // shards if featpar else 0
    # ONE padded num_bins vector (pad features: 1 bin, never split) and ONE
    # column padder — the route tables, bundler fits, chunk binning and the
    # device num_bins below must all agree on the padding convention
    _nb_pad = mapper.num_bins if Fp == F else np.concatenate(
        [mapper.num_bins, np.ones(Fp - F, mapper.num_bins.dtype)])

    def _pad_cols_to_fp(mat):
        if Fp == F:
            return mat
        return np.concatenate(
            [mat, np.zeros((len(mat), Fp - F), mat.dtype)], axis=1)

    if config.enable_bundle:
        if init_model is not None and init_model.bundler is not None \
                and not featpar:
            bundler = init_model.bundler
        else:
            if source is not None:
                sample_mat = source.sample_rows(
                    min(config.bin_sample_count, 50_000), config.seed)
            else:
                take = min(n, 50_000)
                sample_mat = X[:take]
            sample_b = bin_host(np.ascontiguousarray(sample_mat, np.float32))
            if featpar:
                sample_b = _pad_cols_to_fp(sample_b)
                rank_bundlers = [
                    FeatureBundler.fit(
                        sample_b[:, r * Fsl:(r + 1) * Fsl],
                        _nb_pad[r * Fsl:(r + 1) * Fsl],
                        max_total_bins=config.max_bin + 1,
                        max_conflict_rate=config.max_conflict_rate)
                    for r in range(shards)]
            else:
                bundler = FeatureBundler.fit(
                    sample_b, mapper.num_bins,
                    max_total_bins=config.max_bin + 1,
                    max_conflict_rate=config.max_conflict_rate)
    Fb_rank = (max(b.num_bundles for b in rank_bundlers)
               if rank_bundlers else 0)

    if (bundler is not None and pallas_candidate and uses_fused
            and not use_pallas):
        # bundling shrank the feature axis: the fused kernel may fit now
        from .pallas_hist import fused_geometry
        use_pallas = fused_geometry(
            bundler.num_bundles, B_total,
            default_n_slots(config.num_leaves)) is not None


    def bin_eff(mat):
        b = bin_host(mat)
        if rank_bundlers is not None:
            b = _pad_cols_to_fp(b)
            parts = []
            for r, br in enumerate(rank_bundlers):
                t = br.transform(b[:, r * Fsl:(r + 1) * Fsl])
                if t.shape[1] < Fb_rank:
                    t = np.concatenate(
                        [t, np.zeros((len(t), Fb_rank - t.shape[1]),
                                     t.dtype)], axis=1)
                parts.append(t)
            return np.concatenate(parts, axis=1)
        return bundler.transform(b) if bundler is not None else b

    if mesh is None:
        bins_spec = None
    elif featpar:
        bins_spec = NamedSharding(mesh, P(DATA_AXIS, None))   # F sharded
    else:
        bins_spec = NamedSharding(mesh, P(None, DATA_AXIS))   # N sharded

    def put_bins(mat):
        """Upload a host (rows, F) small-int block.  Feature-parallel pads
        the feature axis on HOST and ships each rank only its own feature
        slice (P(None, data)) — replicating the full matrix would multiply
        both link traffic and HBM by the rank count."""
        if featpar:
            if rank_bundlers is None:
                # (the EFB path pads + bundles inside bin_eff already)
                mat = _pad_cols_to_fp(mat)
            return jax.device_put(mat, NamedSharding(mesh, P(None, DATA_AXIS)))
        return put(mat, 2)

    def finish_bins(stacked_dev):
        """(N, Fp) small-int device array → (Fp, N) int32 with the mode's
        sharding (for feature-parallel the transpose is shard-local)."""
        def fn(b):
            out = b.astype(jnp.int32).T
            if bins_spec is not None:
                out = jax.lax.with_sharding_constraint(out, bins_spec)
            return out
        return jax.jit(fn)(stacked_dev)

    # micro-batch push (StreamingPartitionTask analogue) for BOTH sources:
    # each chunk is binned and shipped independently (device_put is async,
    # so chunk k's bytes ride the tunnel while chunk k+1 bins on the host —
    # the fixed cost pays ~max(binning, upload) instead of their sum); the
    # full matrix exists only on DEVICE, assembled by one concatenate, so
    # streamed host peak stays O(chunk).  Row-sharded uploads require a row
    # count divisible by the shard count: a host-side carry re-chunks
    # arbitrary chunk/tail sizes to shard multiples, and the remainder
    # merges into the pad block (n + pad is a shard multiple by
    # construction, so the combined tail always divides evenly).
    if source is not None:
        chunk_iter = (cx for cx, _, _ in source.iter_chunks())
    else:
        crows = max(row_shards, 131_072 // row_shards * row_shards)
        chunk_iter = (X[lo:lo + crows] for lo in range(0, n, crows))
    bin_dt = np.uint8 if mapper.max_bin <= 255 else np.uint16
    # streamed ranking permutes AFTER assembly: the stream's own tail pad
    # only needs shard divisibility for the source row count
    stream_pad = pad if lr_stream_perm is None \
        else (-lr_stream_perm[2]) % row_shards
    dev_chunks = []
    carry = None
    for cx in chunk_iter:
        b = bin_eff(cx)
        if carry is not None and len(carry):
            b = np.concatenate([carry, b])
        keep = len(b) - len(b) % row_shards
        carry = b[keep:].copy()    # view would pin the whole chunk
        if keep:
            dev_chunks.append(put_bins(b[:keep]))
    tail_rows = (len(carry) if carry is not None else 0) + stream_pad
    if tail_rows:
        if rank_bundlers is not None:
            pad_f = shards * Fb_rank
        elif bundler is not None:
            pad_f = bundler.num_bundles
        else:
            pad_f = F
        tail = np.zeros((tail_rows, pad_f), bin_dt)
        if carry is not None and len(carry):
            tail[:len(carry)] = carry
        dev_chunks.append(put_bins(tail))
    if len(dev_chunks) > 1:
        stacked = jax.jit(lambda *cs: jnp.concatenate(cs))(*dev_chunks)
    else:
        stacked = dev_chunks[0]
    bins_t = finish_bins(stacked)
    del dev_chunks, stacked
    if lr_stream_perm is not None:
        # device-side whole-group packing: gather source-order columns
        # into the per-shard slabs; pad slots get the NaN row's bins
        # (bin 0 per feature, through the bundler when EFB is on) so the
        # packed matrix is bit-identical to the in-memory path's
        pc_h, valid_h, _n_src = lr_stream_perm
        pad_bins = bin_eff(np.full((1, F), np.nan, np.float32))[0]
        pc_d = jnp.asarray(pc_h.astype(np.int32))
        valid_d = jnp.asarray(valid_h)
        pad_d = jnp.asarray(pad_bins.astype(np.int32))

        def _pack(b):
            out = jnp.where(valid_d[None, :], jnp.take(b, pc_d, axis=1),
                            pad_d[:, None])
            if bins_spec is not None:
                out = jax.lax.with_sharding_constraint(out, bins_spec)
            return out
        bins_t = jax.jit(_pack)(bins_t)
    measures.binning_s += _time.perf_counter() - _t_bin2
    labels = put(labels_np, 1)
    if sample_weight is None and not w_scaled:
        weights = dev_fill(1.0, (N,))
    else:
        weights = put(w, 1)
    if init_model is not None and base_margin is not None:
        if pad:
            shp = (pad,) if base_margin.ndim == 1 else (pad, K)
            base_margin = np.concatenate(
                [base_margin, np.zeros(shp, np.float32)])
        scores = put(base_margin.astype(np.float32), base_margin.ndim)
    else:
        scores = dev_fill(float(init_sc[0]), (N,) if K == 1 else (N, K))
    init_scores_dev = scores            # rf resets to this every iteration
    # split search, thresholds and trees live in ORIGINAL feature space
    # even under EFB (bundling only compresses histogram construction —
    # the LightGBM scheme), so bounds/bin counts are always the mapper's
    ub_np = mapper.upper_bounds
    nb_np = mapper.num_bins
    bundle_map_dev = None
    if rank_bundlers is not None:
        # per-rank route tables stacked on the ORIGINAL feature axis and
        # sharded like bounds/nbins — each rank sees its own tables, whose
        # col/gather_src indices point into its own padded bundled slice
        maps = [br.route_tables(_nb_pad[r * Fsl:(r + 1) * Fsl], B_total)
                for r, br in enumerate(rank_bundlers)]
        bundle_map_dev = {}
        for k in maps[0]:
            stacked = np.concatenate([m[k] for m in maps], axis=0)
            spec = P(DATA_AXIS, None) if stacked.ndim == 2 else P(DATA_AXIS)
            bundle_map_dev[k] = jax.device_put(
                jnp.asarray(stacked.astype(np.int32)),
                NamedSharding(mesh, spec))
    elif bundler is not None:
        bm = bundler.route_tables(mapper.num_bins, B_total)
        bundle_map_dev = {k: jnp.asarray(v.astype(np.int32))
                          for k, v in bm.items()}
        if mesh is not None:
            bundle_map_dev = {k: jax.device_put(v, replicated(mesh))
                              for k, v in bundle_map_dev.items()}
    if Fp != F:                         # padded features: 1 bin, never split
        ub_np = np.concatenate(
            [ub_np, np.full((Fp - F, ub_np.shape[1]), np.inf, np.float32)])
        nb_np = _nb_pad.astype(np.int32)
    upper_bounds = jnp.asarray(ub_np)
    num_bins = jnp.asarray(nb_np)
    if mesh is not None:
        fp_sh = (NamedSharding(mesh, P(DATA_AXIS, None)) if featpar
                 else replicated(mesh))
        fp_sh1 = (NamedSharding(mesh, P(DATA_AXIS)) if featpar
                  else replicated(mesh))
        upper_bounds = jax.device_put(upper_bounds, fp_sh)
        num_bins = jax.device_put(num_bins, fp_sh1)

    # -- objective ---------------------------------------------------------
    objective_fn = None            # non-lambdarank: _step_factory_args builds it
    if config.objective == "lambdarank":
        if group is None:
            raise ValueError("lambdarank requires group sizes (groupCol)")
        from .ranking import (build_group_index, make_lambdarank_objective,
                              make_lambdarank_objective_sharded)
        lg_arr = (np.asarray(config.label_gain, np.float32)
                  if config.label_gain else None)
        if lr_pack is not None:
            _sq, _smask, _L, _ = lr_pack
            objective_fn = make_lambdarank_objective_sharded(
                _sq, _smask, n_rows_local=_L, axis_name=DATA_AXIS,
                sigma=1.0, max_position=config.max_position,
                label_gain=lg_arr)
        else:
            qidx, qmask = build_group_index(np.asarray(group))
            objective_fn = make_lambdarank_objective(
                qidx, qmask, n_rows=n + pad, sigma=1.0,
                max_position=config.max_position, label_gain=lg_arr)
    is_rf = config.boosting_type == "rf"
    is_dart = config.boosting_type == "dart"
    use_goss = config.boosting_type == "goss"
    lr = 1.0 if is_rf else config.learning_rate

    # the histogram kernels see the BUNDLED / per-rank feature width, and
    # the tuned-chunk consult keys on exactly that width (a mismatched
    # geometry falls back to the ladder default).  Must mirror the warm-
    # compile call above (plain path: width == F) or the lru cache forks.
    if rank_bundlers:
        _hist_F = Fb_rank
    elif bundler is not None:
        _hist_F = bundler.num_bundles
    elif featpar:
        _hist_F = Fp // shards
    else:
        _hist_F = F
    _sargs, _skw = _step_factory_args(config, K, mesh, featpar, use_pallas,
                                      objective_fn=objective_fn,
                                      num_features=_hist_F)
    # lambdarank's objective closes over per-dataset arrays: a cache entry
    # would both never hit again and pin the arrays — bypass the cache
    make = (_make_step.__wrapped__ if config.objective == "lambdarank"
            else _make_step)
    step = make(*_sargs, **_skw)

    # -- validation setup (validationIndicatorCol analogue) ----------------
    have_valid = valid is not None
    if have_valid:
        Xv, yv, wv = valid
        Xv = np.ascontiguousarray(Xv, np.float32)
        binned_v = jnp.asarray(np.ascontiguousarray(
            bin_host(Xv).astype(np.int32).T))
        yv = (np.asarray(yv) > 0).astype(np.float32) if config.objective == "binary" \
            else np.asarray(yv, np.float32)
        # contributions accumulate separately from the init margin so rf can
        # average only the tree part
        valid_contrib = np.zeros((len(yv), K) if K > 1 else len(yv), np.float32)
        if init_model is not None:
            # warm start: eval margins must include the carried-over trees
            valid_init = init_model.predict_margin(Xv).astype(np.float32)
        else:
            valid_init = init_sc[0] if K == 1 else init_sc[None, :]
        metric_name = config.metric or metrics_mod.default_metric(config.objective, K)
        if metric_name.startswith("ndcg"):
            if valid_group is None:
                raise ValueError("ndcg eval requires valid_group sizes")
            ndcg_fn = metrics_mod.ndcg_at(config.max_position)
            metric_fn = lambda yy, mm, ww: ndcg_fn(yy, mm, valid_group, ww)  # noqa: E731
            larger_better = True
        else:
            metric_fn, larger_better = metrics_mod.METRICS.get(
                metric_name, metrics_mod.METRICS["l2"])


    measures.data_prep_s = _time.perf_counter() - _t_prep
    _t_train = _time.perf_counter()
    trees: List[Tree] = []
    tree_class: List[int] = []
    tree_weights: List[float] = []
    eval_history: List[EvalRecord] = []
    best_val = None
    best_iter = -1
    rounds_no_improve = 0

    # continued training picks the bag/key streams up where the carried
    # model left off: replaying iteration indices from 0 would hand a
    # resumed rf the SAME subsamples (and, at the constant init margin,
    # the IDENTICAL trees) it already has
    prior_iters = (len(init_model.trees) // max(K, 1)
                   if init_model is not None else 0)
    if prior_iters and config.feature_fraction < 1.0:
        k = max(1, int(round(F * config.feature_fraction)))
        for _ in range(prior_iters):      # fast-forward the host stream
            rng.choice(F, k, replace=False)

    rf_denominator = 0
    bag = np.ones(N, np.float32)
    if lr_pack is not None:
        bag = lr_pack[3].astype(np.float32)     # pad rows interspersed
    if pad:
        bag[n:] = 0.0
    # tunnel/PCIe round trips dominate small-step training: dart, per-iter
    # validation and callbacks need each tree on the host DURING the loop;
    # everything else runs fully async — device-resident masks are hoisted
    # and tree downloads deferred until after the last dispatch
    ckpt_every = (checkpoint_interval
                  if checkpoint_dir and checkpoint_interval > 0 else 0)
    eager_host = (is_dart or have_valid or bool(callbacks)
                  or bool(ckpt_every) or step_profiler is not None)
    pending_stacks: List[Tuple[Tree, List[float]]] = []
    base_bag_dev = jnp.asarray(bag)     # pad-row mask, uploaded once
    bag_root_key = jax.random.PRNGKey(config.bagging_seed)
    # fire-and-forget runs collapse the whole boosting loop into ONE
    # on-device lax.scan dispatch (_make_scan) — per-iteration Python
    # dispatch costs ~36 ms/tree through the tunnel; feature_fraction
    # draws its mask from the host rng each iteration so it stays looped
    use_scan = not eager_host and config.feature_fraction >= 1.0

    fmask_dev = None
    rf_reset_scores = None
    # leaf-wise depth is bounded by num_leaves-1 splits; never truncate
    depth_hint = max(2, config.num_leaves)

    # dart under feature_parallel: rescoring traverses the SHARDED binned
    # matrix with owner-broadcast go-left masks (one psum per level, the
    # training routing pattern) instead of gathering columns
    _fp_tree_predict = None
    if featpar and is_dart:
        _bm_spec = ({"col": P(DATA_AXIS), "lo": P(DATA_AXIS),
                     "hi": P(DATA_AXIS), "default_bin": P(DATA_AXIS),
                     "gather_src": P(DATA_AXIS, None)}
                    if bundle_map_dev is not None else None)
        from .trainer import predict_binned_tree_featpar as _fp_body

        def _mk_fp_predict():
            in_specs = [P(DATA_AXIS, None), P()]
            if _bm_spec is not None:
                in_specs.append(_bm_spec)

            def inner(bl, tree, *bm):
                return _fp_body(bl, tree, depth_hint, B_total, DATA_AXIS,
                                bundle_map=bm[0] if bm else None)

            sm = jax.shard_map(inner, mesh=mesh, in_specs=tuple(in_specs),
                               out_specs=P())
            if _bm_spec is not None:
                return jax.jit(lambda b, t: sm(b, t, bundle_map_dev))
            return jax.jit(sm)
        _fp_tree_predict = _mk_fp_predict()

    def _dart_tree_predict(tree_dev):
        if _fp_tree_predict is not None:
            return _fp_tree_predict(bins_t, tree_dev)
        return _predict_binned_tree(bins_t, tree_dev, depth_hint,
                                    bundle_map_dev, B_total)

    if _warm_thread is not None:
        _warm_thread.join()

    scan_start = 0          # iterations handled by scanned dispatches
    n_scan_chunks = config.num_iterations // SCAN_CHUNK if use_scan else 0
    if n_scan_chunks:
        feature_mask = np.zeros(Fp, bool)
        feature_mask[:F] = True
        fmask_dev = jnp.asarray(feature_mask)
        if featpar:
            fmask_dev = jax.device_put(
                fmask_dev, NamedSharding(mesh, P(DATA_AXIS)))
        if config.objective == "lambdarank":
            scan_fn = _make_scan.__wrapped__(
                _sargs, tuple(sorted(_skw.items())),
                config.bagging_freq, config.seed, is_rf, cache_step=False)
        else:
            scan_fn = _make_scan(_sargs, tuple(sorted(_skw.items())),
                                 config.bagging_freq, config.seed, is_rf)
        chunk_stacks = []
        sc = scores
        for ci in range(n_scan_chunks):
            sc, tstacks = scan_fn(
                bins_t, sc, labels, weights, base_bag_dev, bag_root_key,
                fmask_dev, upper_bounds, num_bins, bundle_map_dev,
                init_scores_dev if is_rf else scores,
                jnp.asarray(prior_iters + ci * SCAN_CHUNK, jnp.int32))
            chunk_stacks.append(tstacks)
            if ci == 0:
                # first dispatch returns once compiled; execution is async
                # until the download below
                measures.compile_s = _time.perf_counter() - _t_train
        # ONE readback for every tree of every chunk: per-field np.asarray
        # pays a full tunnel round trip each (11 fields x chunks ~ seconds);
        # tree ints fit f32 exactly (ids < 2^7, counts <= N < 2^24)
        flat = np.asarray(_pack_flat(chunk_stacks))
        off = 0
        host_stacks = []
        for ts in chunk_stacks:
            fields = []
            for a in ts:
                n_el = int(np.prod(a.shape))
                fields.append(flat[off:off + n_el].reshape(a.shape)
                              .astype(np.dtype(a.dtype)))
                off += n_el
            host_stacks.append(fields)
        for all_fields in host_stacks:
            for i in range(SCAN_CHUNK):
                for k in range(K):
                    trees.append(Tree(*[a[i, k] for a in all_fields]))
                    tree_class.append(k)
                    tree_weights.append(1.0)
        if is_rf:
            rf_denominator = n_scan_chunks * SCAN_CHUNK
        scores = sc
        scan_start = n_scan_chunks * SCAN_CHUNK

    # the whole boosting loop runs under the profiler guard: an
    # escaping exception (e.g. an injected mid-checkpoint preemption)
    # must close the open step and restore the thread-local active
    # profiler, or later collectives on this thread would keep
    # accumulating into a dead profiler's abandoned step
    try:
        for it in range(scan_start, config.num_iterations):
            if step_profiler is not None:
                step_profiler.step_begin(it)
            # bagging (bagging_fraction/freq semantics): the mask is drawn on
            # device from this key; reusing a key across freq iterations
            # reproduces the persist-until-refresh behavior
            bag_key = jax.random.fold_in(
                bag_root_key, (prior_iters + it) // max(config.bagging_freq, 1))
            if config.feature_fraction < 1.0:
                k = max(1, int(round(F * config.feature_fraction)))
                feature_mask = np.zeros(Fp, bool)  # padded features stay off
                feature_mask[rng.choice(F, k, replace=False)] = True
                fmask_dev = None
            elif fmask_dev is None:
                feature_mask = np.zeros(Fp, bool)
                feature_mask[:F] = True
            if fmask_dev is None:
                fmask_dev = jnp.asarray(feature_mask)
                if featpar:
                    fmask_dev = jax.device_put(
                        fmask_dev, NamedSharding(mesh, P(DATA_AXIS)))

            # dart: drop trees, rebase scores
            dropped: List[int] = []
            if is_dart and trees and rng.random() >= config.skip_drop:
                drop_mask = rng.random(len(trees)) < config.drop_rate
                dropped = list(np.nonzero(drop_mask)[0][:config.max_drop])
                for d in dropped:
                    contrib = (_dart_tree_predict(_to_device_tree(trees[d]))
                               * tree_weights[d])
                    scores = _sub_scores(scores, contrib, tree_class[d], K)

            # mask to 32 bits so looped and scanned runs derive identical keys
            # even under jax_enable_x64 (the scan's seed_base is masked too)
            key = jax.random.PRNGKey(
                (config.seed * 100003 + prior_iters + it) & 0xffffffff)
            if step_profiler is not None:
                step_profiler.mark("data")
                if step_profiler.capture_xla:
                    step_profiler.capture_cost(
                        "gbdt_step", step, bins_t, scores, labels, weights,
                        (base_bag_dev, bag_key), fmask_dev, key,
                        upper_bounds, num_bins, bundle_map_dev,
                        items=N // max(row_shards, 1))   # per-device rows
            tstack, new_scores = step(bins_t, scores, labels, weights,
                                      (base_bag_dev, bag_key), fmask_dev,
                                      key, upper_bounds, num_bins,
                                      bundle_map_dev)
            if eager_host:
                # the host-side download synchronizes, so the compute mark
                # below times the executed tree grow, not just its dispatch
                new_trees = [Tree(*[np.asarray(a[k]) for a in tstack])
                             for k in range(K)]
            else:
                new_trees = None                  # downloaded after the loop
            if it == 0:
                jax.block_until_ready(new_scores)
                measures.compile_s = _time.perf_counter() - _t_train
            if step_profiler is not None:
                step_profiler.mark("compute")

            dropped_weight_changes = []
            if is_dart and dropped:
                # normalize: new trees weighted 1/(|D|+1); dropped scaled |D|/(|D|+1)
                ndrop = len(dropped)
                new_w = 1.0 / (ndrop + 1)
                factor = ndrop / (ndrop + 1)
                for k in range(K):
                    contrib = (_dart_tree_predict(_to_device_tree(new_trees[k]))
                               * new_w)
                    scores = _add_scores(scores, contrib, k, K)
                for d in dropped:
                    old_w = tree_weights[d]
                    tree_weights[d] = old_w * factor
                    dropped_weight_changes.append((d, old_w))
                    contrib = (_dart_tree_predict(_to_device_tree(trees[d]))
                               * tree_weights[d])
                    scores = _add_scores(scores, contrib, tree_class[d], K)
                weights_new = [new_w] * K
            else:
                scores = new_scores
                weights_new = [1.0] * K

            if eager_host:
                for k in range(K):
                    trees.append(new_trees[k])
                    tree_class.append(k)
                    tree_weights.append(weights_new[k])
            else:
                pending_stacks.append((tstack, weights_new))
            if is_rf:
                rf_denominator += 1
                # rf: gradients always at init margin → reset scores (the
                # reset array is device-resident once, reused every iteration)
                if rf_reset_scores is None:
                    rf_reset_scores = init_scores_dev
                scores = rf_reset_scores

            # validation eval + early stopping (TrainUtils.scala:143-169)
            if have_valid:
                _t_eval = _time.perf_counter()
                # incremental: new trees, plus weight deltas of dart-dropped trees
                for k in range(K):
                    contrib = np.asarray(_predict_binned_tree(
                        binned_v, _to_device_tree(new_trees[k]), depth_hint))
                    if K == 1:
                        valid_contrib += contrib * weights_new[0]
                    else:
                        valid_contrib[:, k] += contrib * weights_new[k]
                for d, old_w in dropped_weight_changes:
                    contrib = np.asarray(_predict_binned_tree(
                        binned_v, _to_device_tree(trees[d]), depth_hint))
                    delta_w = tree_weights[d] - old_w
                    if K == 1:
                        valid_contrib += contrib * delta_w
                    else:
                        valid_contrib[:, tree_class[d]] += contrib * delta_w
                if is_rf:
                    # the final rf model averages over ALL trees (carried +
                    # new): un-average the carried model's margin and re-pool
                    base_ = (init_sc[0] if K == 1
                             else np.asarray(init_sc)[None, :])
                    old_sum = (valid_init - base_) * prior_iters
                    vm = base_ + ((old_sum + valid_contrib)
                                  / max(prior_iters + rf_denominator, 1))
                else:
                    vm = valid_init + valid_contrib
                val = metric_fn(yv, vm, wv)
                eval_history.append(EvalRecord(it, metric_name, val))
                improved = (best_val is None
                            or (val > best_val if larger_better else val < best_val))
                if improved:
                    best_val, best_iter, rounds_no_improve = val, it, 0
                else:
                    rounds_no_improve += 1
                    if (config.early_stopping_round > 0
                            and rounds_no_improve >= config.early_stopping_round):
                        measures.eval_s += _time.perf_counter() - _t_eval
                        break
                measures.eval_s += _time.perf_counter() - _t_eval
            if callbacks:
                for cb in callbacks:
                    cb(it, trees, eval_history)
            if ckpt_every and (it + 1) % ckpt_every == 0:
                pre_t, pre_c, pre_w = (
                    (init_model.trees, init_model.tree_class,
                     init_model.tree_weights) if init_model else ([], [], []))
                _write_checkpoint(checkpoint_dir, Booster(
                    pre_t + trees, pre_c + tree_class, pre_w + tree_weights,
                    K, config.objective, init_sc, mapper, feature_names,
                    config, bundler=bundler))
            if step_profiler is not None:
                step_profiler.step_end()      # eval + checkpoint → "other"
    finally:
        if step_profiler is not None:
            step_profiler.finish()    # early-stop break / exception path

    # deferred mode: one sync for the whole run, then download every tree in
    # ONE transfer per field (T, K, M) — per-stack downloads pay a tunnel/PCIe
    # round trip each, which dominates small-tree training
    if pending_stacks:
        # one jitted computation for ALL fields: stacking field-by-field in
        # eager ops compiles 11 tiny XLA programs (~13 s on a cold cache);
        # a single fused stack compiles once
        stacked = jax.jit(
            lambda ts: Tree(*[jnp.stack([getattr(t, f) for t in ts])
                              for f in Tree._fields]))(
            [t for t, _ in pending_stacks])
        all_fields = [np.asarray(a) for a in stacked]
        for i, (_, per_class_weights) in enumerate(pending_stacks):
            for k in range(K):
                trees.append(Tree(*[a[i, k] for a in all_fields]))
                tree_class.append(k)
                tree_weights.append(per_class_weights[k])
    measures.training_s = _time.perf_counter() - _t_train
    measures.iterations = len(trees) // max(K, 1)  # this fit only — before
    if init_model is not None:                     # the warm-start fold-in
        # continued training: carry previous trees forward (modelString
        # warm-start fold-in, LightGBMBase.scala:38-59)
        trees = init_model.trees + trees
        tree_class = init_model.tree_class + tree_class
        tree_weights = init_model.tree_weights + tree_weights
    measures.total_s = _time.perf_counter() - _t0
    _publish_measures(measures, config, n_rows=n, n_features=F)
    booster = Booster(trees, tree_class, tree_weights, K, config.objective,
                      init_sc, mapper, feature_names, config,
                      best_iteration=best_iter, bundler=bundler)
    booster.measures = measures
    return booster, eval_history


#: per-phase wall-clock buckets: sub-second phases through multi-minute fits
_PHASE_BUCKETS = (0.01, 0.05, 0.1, 0.5, 1.0, 2.0, 5.0, 10.0, 30.0, 60.0,
                  120.0, 300.0, 600.0)


def _publish_measures(measures: "InstrumentationMeasures",
                      config: "BoostingConfig", n_rows: int,
                      n_features: int) -> None:
    """Mirror one fit's InstrumentationMeasures into the process
    telemetry: a per-phase histogram (the round-over-round "which boost
    phase regressed" answer), an iteration counter, the resolved
    two-level-mode gauge, and one retrospective ``gbdt.train`` span."""
    try:
        reg = _telemetry.get_registry()
        hist = reg.histogram(
            "gbdt_phase_seconds", "per-phase wall clock of gbdt fits",
            ("phase",), buckets=_PHASE_BUCKETS)
        for phase, secs in (("binning", measures.binning_s),
                            ("data_prep", measures.data_prep_s),
                            ("compile", measures.compile_s),
                            ("training", measures.training_s),
                            ("eval", measures.eval_s),
                            ("total", measures.total_s)):
            hist.observe(secs, phase=phase)
        reg.counter("gbdt_iterations_total",
                    "boosting iterations trained").inc(
                        max(measures.iterations, 0))
        reg.gauge("gbdt_two_level_active",
                  "1 when the finished fit trained with coarse-then-"
                  "refine histograms", ()).set(
                      1.0 if config.two_level_hist in ("on", True) else 0.0)
        _telemetry.get_tracer().record(
            "gbdt.train", measures.total_s, rows=n_rows,
            features=n_features, objective=config.objective,
            two_level=str(config.two_level_hist),
            **{k: round(v, 4) for k, v in measures.as_dict().items()
               if isinstance(v, float)})
    except Exception:    # telemetry must never break training
        pass


def _to_device_tree(t: Tree) -> Tree:
    return Tree(*[jnp.asarray(a) for a in t])


def _sub_scores(scores, contrib, k, K):
    if K == 1:
        return scores - contrib
    return scores.at[:, k].add(-contrib)


def _add_scores(scores, contrib, k, K):
    if K == 1:
        return scores + contrib
    return scores.at[:, k].add(contrib)
