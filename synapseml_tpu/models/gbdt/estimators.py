"""GBDT pipeline estimators: classifier / regressor / ranker.

The user-facing API of the boosting engine, mirroring the reference's
``LightGBMClassifier/LightGBMRegressor/LightGBMRanker`` estimator/model
pairs and their param surface (reference: lightgbm/.../LightGBMClassifier.scala:27-211,
LightGBMRegressor.scala, LightGBMRanker.scala, params/LightGBMParams.scala:1-621).

Key re-designs for TPU:
- ``fit`` trains via the jitted histogram grower over a device mesh
  (data-parallel psum) instead of barrier-mode ``mapPartitions`` + native
  allreduce (LightGBMBase.scala:584-599);
- ``transform`` scores whole column batches with one XLA traversal instead
  of one JNI call per row (LightGBMClassifier.scala:119-166 per-row UDFs);
- ``numBatches`` folds warm-started training over row batches like
  LightGBMBase.scala:44-59;
- ``validationIndicatorCol`` carves the validation rows out of the input
  frame exactly like the reference (LightGBMBase.scala:403-407).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax

from ...core.dataset import Dataset, find_unused_column_name
from ...core.params import (BoolParam, DictParam, FloatParam, IntParam,
                            ListParam, Params, PyObjectParam, StringParam,
                            UDFParam)
from ...core.pipeline import Estimator, Model
from ...parallel.mesh import DATA_AXIS, data_parallel_mesh
from .booster import Booster, BoostingConfig, train


class GBDTParams(Params):
    """Shared boosting params (reference: params/LightGBMParams.scala)."""
    featuresCol = StringParam(doc="features vector column", default="features")
    labelCol = StringParam(doc="label column", default="label")
    weightCol = StringParam(doc="sample weight column")
    predictionCol = StringParam(doc="prediction output column", default="prediction")
    validationIndicatorCol = StringParam(
        doc="bool column marking validation rows (LightGBMBase.scala:403)")
    numIterations = IntParam(doc="number of boosting iterations", default=100)
    learningRate = FloatParam(doc="shrinkage rate", default=0.1)
    numLeaves = IntParam(doc="max leaves per tree", default=31)
    maxDepth = IntParam(doc="max tree depth (<=0: unlimited)", default=-1)
    minDataInLeaf = IntParam(doc="min rows per leaf", default=20)
    minSumHessianInLeaf = FloatParam(doc="min hessian sum per leaf", default=1e-3)
    lambdaL1 = FloatParam(doc="L1 regularization", default=0.0)
    lambdaL2 = FloatParam(doc="L2 regularization", default=0.0)
    minGainToSplit = FloatParam(doc="min split gain", default=0.0)
    maxBin = IntParam(doc="max feature bins", default=255)
    binSampleCount = IntParam(doc="rows sampled for bin boundaries", default=200000)
    featureFraction = FloatParam(doc="per-tree feature subsample", default=1.0)
    baggingFraction = FloatParam(doc="row subsample fraction", default=1.0)
    baggingFreq = IntParam(doc="resample every k iterations", default=0)
    baggingSeed = IntParam(doc="bagging seed", default=3)
    boostingType = StringParam(doc="gbdt|rf|dart|goss", default="gbdt",
                               allowed=("gbdt", "rf", "dart", "goss"))
    topRate = FloatParam(doc="goss top-gradient keep rate", default=0.2)
    otherRate = FloatParam(doc="goss small-gradient sample rate", default=0.1)
    dropRate = FloatParam(doc="dart tree dropout rate", default=0.1)
    maxDrop = IntParam(doc="dart max dropped trees per iter", default=50)
    skipDrop = FloatParam(doc="dart skip-dropout probability", default=0.5)
    earlyStoppingRound = IntParam(doc="early stopping patience (0=off)", default=0)
    metric = StringParam(doc="eval metric name", default="")
    boostFromAverage = BoolParam(doc="init score from label mean", default=True)
    seed = IntParam(doc="master seed", default=0)
    verbosity = IntParam(doc="log verbosity", default=-1)
    numBatches = IntParam(
        doc="split data into k sequential warm-started batches "
            "(LightGBMBase.scala:44-59)", default=0)
    numShards = IntParam(
        doc="data-parallel shards over the device mesh; 0 = all local "
            "devices (partition→chip placement)", default=0)
    parallelism = StringParam(
        doc="data_parallel|voting_parallel|feature_parallel (the reference's "
            "tree_learner values, params/LightGBMParams.scala:24-26)",
        default="data_parallel",
        allowed=("data_parallel", "voting_parallel", "feature_parallel"))
    topK = IntParam(doc="voting-parallel top features per shard", default=20)
    enableBundle = BoolParam(
        doc="exclusive feature bundling: merge rarely-co-nonzero features "
            "into shared HISTOGRAM columns (sparse/one-hot densification; "
            "LightGBM enable_bundle). Trees stay in original feature "
            "space, so predict/SHAP/export work unchanged",
        default=False)
    maxConflictRate = FloatParam(doc="EFB allowed conflict fraction",
                                 default=0.0)
    categoricalSlotIndexes = ListParam(
        doc="feature-vector slots holding category codes "
            "(categoricalSlotIndexes parity, params/LightGBMParams.scala): "
            "binned in target-statistic order so bin-range splits act as "
            "category-subset splits")
    checkpointDir = StringParam(
        doc="iteration-checkpoint directory: training saves the partial "
            "booster every checkpointInterval iterations and a re-fit "
            "resumes from the newest one (step-level resume, beyond the "
            "reference's numBatches warm start)")
    checkpointInterval = IntParam(doc="save every N boosting iterations "
                                      "(0 = off)", default=0)
    checkpointManager = PyObjectParam(
        doc="core.checkpoint.CheckpointManager to write iteration "
            "checkpoints through (overrides checkpointDir) — the "
            "preemption-tolerant fit surface: re-fit with the same "
            "manager resumes from its latest step")
    monotoneConstraints = ListParam(
        doc="per-feature monotone direction {-1, 0, 1} "
            "(monotoneConstraints parity, params/LightGBMParams.scala:"
            "168-183): 1 forces predictions non-decreasing in the "
            "feature, -1 non-increasing")
    monotoneConstraintsMethod = StringParam(
        doc="constraint enforcement method (monotoneConstraintsMethod): "
            "'basic' (midpoint clamping), 'intermediate' (opposite-"
            "subtree extremes), 'advanced' (exact pairwise leaf-box "
            "constraints)", default="basic",
        allowed=("basic", "intermediate", "advanced"))
    monotonePenalty = FloatParam(
        doc="gain penalization for constrained-feature splits near the "
            "root (monotonePenalty): 1 forbids them at the root",
        default=0.0)
    passThroughArgs = DictParam(doc="extra engine params (ParamsStringBuilder "
                                    "pass-through analogue)")
    predictDisableShapeCheck = BoolParam(doc="skip feature-count check at "
                                             "predict", default=False)
    collectiveCompression = PyObjectParam(
        doc="wire codec for the data-parallel histogram allreduce: "
            "'none' (default) | 'bf16' | 'int8' | a "
            "parallel.compression.CollectiveConfig — int8 ships ~1/4 "
            "the bytes per histogram psum at a bounded split-quality "
            "cost (holdout parity pinned in tier-1); ignored for "
            "voting/feature parallelism and single-device fits")

    def _build_config(self, objective: str, num_class: int = 1) -> BoostingConfig:
        extra = self.passThroughArgs or {}
        cfg = BoostingConfig(
            objective=objective,
            boosting_type=self.boostingType,
            num_iterations=self.numIterations,
            learning_rate=self.learningRate,
            num_leaves=self.numLeaves,
            max_depth=self.maxDepth,
            min_data_in_leaf=self.minDataInLeaf,
            min_sum_hessian_in_leaf=self.minSumHessianInLeaf,
            lambda_l1=self.lambdaL1,
            lambda_l2=self.lambdaL2,
            min_gain_to_split=self.minGainToSplit,
            max_bin=self.maxBin,
            bin_sample_count=self.binSampleCount,
            feature_fraction=self.featureFraction,
            bagging_fraction=self.baggingFraction,
            bagging_freq=self.baggingFreq,
            bagging_seed=self.baggingSeed,
            seed=self.seed,
            num_class=num_class,
            boost_from_average=self.boostFromAverage,
            early_stopping_round=self.earlyStoppingRound,
            metric=self.metric,
            top_rate=self.topRate,
            other_rate=self.otherRate,
            drop_rate=self.dropRate,
            max_drop=self.maxDrop,
            skip_drop=self.skipDrop,
            parallelism=self.parallelism,
            top_k=self.topK,
            enable_bundle=self.enableBundle,
            max_conflict_rate=self.maxConflictRate,
            categorical_feature=[int(i) for i in self.categoricalSlotIndexes]
            if self.get("categoricalSlotIndexes") else None,
            monotone_constraints=[int(c) for c in self.monotoneConstraints]
            if self.get("monotoneConstraints") else None,
            monotone_constraints_method=self.monotoneConstraintsMethod,
            monotone_penalty=self.monotonePenalty,
            collective_compression=(self.get("collectiveCompression")
                                    or "none"),
        )
        for k, v in extra.items():
            if hasattr(cfg, k):
                setattr(cfg, k, v)
            else:
                cfg.pass_through[k] = v
        return cfg

    def _mesh(self, n_rows: int):
        shards = self.numShards
        if shards == 0:
            shards = min(len(jax.devices()), max(1, n_rows // 1024))
        if shards <= 1:
            return None
        return data_parallel_mesh(shards)

    def _features_matrix(self, ds: Dataset) -> np.ndarray:
        return ds.to_numpy([self.featuresCol])

    def _split_validation(self, ds: Dataset):
        """Carve out validation rows (LightGBMBase.scala:403-407)."""
        vcol = self.validationIndicatorCol
        if vcol and vcol in ds:
            mask = ds[vcol].astype(bool)
            return ds.filter(~mask), ds.filter(mask)
        return ds, None


class GBDTModelBase(Model):
    featuresCol = StringParam(doc="features vector column", default="features")
    predictionCol = StringParam(doc="prediction output column", default="prediction")
    leafPredictionCol = StringParam(doc="per-tree leaf index output column")
    featuresShapCol = StringParam(doc="per-feature contribution output column")
    numIterationsUsed = IntParam(doc="trees used at predict (-1: all)", default=-1)
    predictDisableShapeCheck = BoolParam(doc="skip feature-count check",
                                         default=False)
    boosterModel = PyObjectParam(doc="trained booster")

    @property
    def booster(self) -> Booster:
        return self.boosterModel

    @property
    def training_measures(self):
        """Per-phase wall-clock instrumentation of the fit that produced
        this model (reference: getAllTrainingMeasures on the estimator,
        LightGBMPerformance.scala:90-111); None for deserialized models."""
        return getattr(self.booster, "measures", None)

    def get_feature_importances(self, importance_type: str = "split") -> List[float]:
        return list(self.booster.feature_importance(importance_type))

    def get_booster_num_trees(self) -> int:
        return self.booster.num_trees

    def get_model_string(self) -> str:
        """saveNativeModel analogue (LightGBMBooster.saveToString)."""
        return self.booster.to_string()

    def _check_features(self, X: np.ndarray):
        expected = self.booster.bin_mapper.num_features
        if not self.predictDisableShapeCheck and X.shape[1] != expected:
            raise ValueError(f"feature count {X.shape[1]} != model's {expected}")

    def _maybe_add_leaves(self, ds: Dataset, X: np.ndarray) -> Dataset:
        if self.leafPredictionCol:
            leaves = self.booster.predict_leaf(X).astype(np.float64)
            ds = ds.with_column(self.leafPredictionCol, list(leaves))
        if self.featuresShapCol:
            shap = self.booster.predict_contrib(X)
            ds = ds.with_column(self.featuresShapCol, list(shap))
        return ds


class GBDTClassifier(GBDTParams, Estimator):
    """LightGBMClassifier analogue (reference: LightGBMClassifier.scala:27)."""
    objective = StringParam(doc="binary|multiclass|multiclassova", default="binary",
                            allowed=("binary", "multiclass", "multiclassova"))
    probabilityCol = StringParam(doc="probability vector column", default="probability")
    rawPredictionCol = StringParam(doc="margin vector column", default="rawPrediction")
    isUnbalance = BoolParam(doc="auto-reweight positive class", default=False)
    scalePosWeight = FloatParam(doc="positive class weight", default=1.0)
    thresholds = ListParam(doc="per-class prediction thresholds")

    def _fit(self, ds: Dataset) -> "GBDTClassificationModel":
        train_ds, valid_ds = self._split_validation(ds)
        X = self._features_matrix(train_ds)
        y_raw = np.asarray(train_ds[self.labelCol], np.float64)
        w = train_ds[self.weightCol].astype(np.float32) if self.weightCol else None
        classes = np.unique(y_raw[~np.isnan(y_raw)])
        num_class = len(classes)
        # remap arbitrary label values to contiguous 0..K-1 class indices
        y = np.searchsorted(classes, y_raw).astype(np.float64)
        objective = self.objective
        if objective == "binary" and num_class > 2:
            objective = "multiclass"
        K = num_class if objective in ("multiclass", "multiclassova") else 1
        cfg = self._build_config(objective, max(K, 1))
        cfg.is_unbalance = self.isUnbalance
        cfg.scale_pos_weight = self.scalePosWeight

        valid = None
        if valid_ds is not None and valid_ds.num_rows > 0:
            yv_raw = np.asarray(valid_ds[self.labelCol], np.float64)
            valid = (self._features_matrix(valid_ds),
                     np.searchsorted(classes, yv_raw).astype(np.float64),
                     valid_ds[self.weightCol].astype(np.float32)
                     if self.weightCol else None)

        booster, history = _train_batched(
            X, y, cfg, w, valid, self.numBatches, self._mesh(len(X)),
            seed=self.seed,
            checkpoint_dir=(self.get("checkpointManager")
                            or self.get("checkpointDir")),
            checkpoint_interval=int(self.checkpointInterval))
        model = GBDTClassificationModel(
            boosterModel=booster,
            featuresCol=self.featuresCol,
            predictionCol=self.predictionCol,
            probabilityCol=self.probabilityCol,
            rawPredictionCol=self.rawPredictionCol,
            numClasses=max(num_class, 2),
            classLabels=[float(c) for c in classes],
        )
        if self.is_set("thresholds"):
            model.set("thresholds", self.thresholds)
        model._eval_history = history
        return model


class GBDTClassificationModel(GBDTModelBase):
    """LightGBMClassificationModel analogue; batched scoring."""
    probabilityCol = StringParam(doc="probability vector column", default="probability")
    rawPredictionCol = StringParam(doc="margin vector column", default="rawPrediction")
    numClasses = IntParam(doc="number of classes", default=2)
    classLabels = ListParam(doc="original label value per class index")
    thresholds = ListParam(doc="per-class prediction thresholds")

    def _transform(self, ds: Dataset) -> Dataset:
        X = ds.to_numpy([self.featuresCol])
        self._check_features(X)
        ni = self.numIterationsUsed
        margin = self.booster.predict_margin(X, None if ni < 0 else ni)
        proba = self.booster.to_proba(np.asarray(margin))
        if margin.ndim == 1:
            raw = np.stack([-margin, margin], axis=1)
        else:
            raw = margin
        if self.thresholds:
            scaled = proba / np.asarray(self.thresholds)[None, :]
            pred = np.argmax(scaled, axis=1).astype(np.float64)
        else:
            pred = np.argmax(proba, axis=1).astype(np.float64)
        if self.classLabels:
            pred = np.asarray(self.classLabels, np.float64)[pred.astype(int)]
        out = ds
        if self.rawPredictionCol:
            out = out.with_column(self.rawPredictionCol, list(raw.astype(np.float64)))
        if self.probabilityCol:
            out = out.with_column(self.probabilityCol, list(proba.astype(np.float64)))
        out = out.with_column(self.predictionCol, pred)
        return self._maybe_add_leaves(out, X)

    @staticmethod
    def load_native_model_from_string(s: str, **kw) -> "GBDTClassificationModel":
        """loadNativeModelFromString analogue (LightGBMClassifier.scala:196);
        accepts LightGBM text models and the internal JSON."""
        b = Booster.from_string(s)
        return GBDTClassificationModel(boosterModel=b,
                                       numClasses=max(b.num_class, 2), **kw)

    @staticmethod
    def load_native_model_from_file(path: str, **kw) -> "GBDTClassificationModel":
        """loadNativeModelFromFile analogue (LightGBMClassifier.scala:196)."""
        with open(path) as f:
            return GBDTClassificationModel.load_native_model_from_string(
                f.read(), **kw)


class GBDTRegressor(GBDTParams, Estimator):
    """LightGBMRegressor analogue."""
    objective = StringParam(
        doc="regression objective", default="regression",
        allowed=("regression", "regression_l1", "huber", "fair", "poisson",
                 "quantile", "mape", "gamma", "tweedie", "mse", "mae"))
    alpha = FloatParam(doc="huber/quantile alpha", default=0.9)
    tweedieVariancePower = FloatParam(doc="tweedie variance power", default=1.5)

    def _fit(self, ds: Dataset) -> "GBDTRegressionModel":
        train_ds, valid_ds = self._split_validation(ds)
        X = self._features_matrix(train_ds)
        y = np.asarray(train_ds[self.labelCol], np.float64)
        w = train_ds[self.weightCol].astype(np.float32) if self.weightCol else None
        cfg = self._build_config(self.objective)
        cfg.alpha = self.alpha
        cfg.tweedie_variance_power = self.tweedieVariancePower
        valid = None
        if valid_ds is not None and valid_ds.num_rows > 0:
            valid = (self._features_matrix(valid_ds),
                     np.asarray(valid_ds[self.labelCol], np.float64),
                     valid_ds[self.weightCol].astype(np.float32)
                     if self.weightCol else None)
        booster, history = _train_batched(
            X, y, cfg, w, valid, self.numBatches, self._mesh(len(X)),
            seed=self.seed,
            checkpoint_dir=(self.get("checkpointManager")
                            or self.get("checkpointDir")),
            checkpoint_interval=int(self.checkpointInterval))
        model = GBDTRegressionModel(
            boosterModel=booster,
            featuresCol=self.featuresCol,
            predictionCol=self.predictionCol,
        )
        model._eval_history = history
        return model


class GBDTRegressionModel(GBDTModelBase):
    def _transform(self, ds: Dataset) -> Dataset:
        X = ds.to_numpy([self.featuresCol])
        self._check_features(X)
        ni = self.numIterationsUsed
        pred = self.booster.predict_margin(X, None if ni < 0 else ni)
        if self.booster.objective in ("poisson", "gamma", "tweedie"):
            pred = np.exp(pred)
        out = ds.with_column(self.predictionCol, np.asarray(pred, np.float64))
        return self._maybe_add_leaves(out, X)

    @staticmethod
    def load_native_model_from_string(s: str, **kw) -> "GBDTRegressionModel":
        return GBDTRegressionModel(boosterModel=Booster.from_string(s), **kw)

    @staticmethod
    def load_native_model_from_file(path: str, **kw) -> "GBDTRegressionModel":
        with open(path) as f:
            return GBDTRegressionModel.load_native_model_from_string(f.read(), **kw)


class GBDTRanker(GBDTParams, Estimator):
    """LightGBMRanker analogue (lambdarank objective + groupCol)."""
    groupCol = StringParam(doc="query/group id column", default="query")
    maxPosition = IntParam(doc="NDCG truncation position", default=10)
    labelGain = ListParam(doc="relevance gain per label level")
    evalAt = ListParam(doc="NDCG eval positions", default=[1, 3, 5, 10])

    def _fit(self, ds: Dataset) -> "GBDTRankerModel":
        train_ds, valid_ds = self._split_validation(ds)
        # group-contiguous layout required: stable-sort by group id
        train_ds = train_ds.sort(self.groupCol)
        X = self._features_matrix(train_ds)
        y = np.asarray(train_ds[self.labelCol], np.float64)
        w = train_ds[self.weightCol].astype(np.float32) if self.weightCol else None
        gids = train_ds[self.groupCol]
        _, counts = np.unique(gids, return_counts=True)
        cfg = self._build_config("lambdarank")
        cfg.max_position = self.maxPosition
        if self.labelGain:
            cfg.label_gain = list(self.labelGain)
        valid = None
        vgroups = None
        if valid_ds is not None and valid_ds.num_rows > 0:
            valid_ds = valid_ds.sort(self.groupCol)
            _, vgroups = np.unique(valid_ds[self.groupCol], return_counts=True)
            valid = (self._features_matrix(valid_ds),
                     np.asarray(valid_ds[self.labelCol], np.float64),
                     valid_ds[self.weightCol].astype(np.float32)
                     if self.weightCol else None)
        booster, history = train(
            X, y, cfg, sample_weight=w, valid=valid,
            mesh=self._mesh(len(X)),   # whole groups pack onto shards
            group=counts, valid_group=vgroups,
            checkpoint_dir=(self.get("checkpointManager")
                            or self.get("checkpointDir")),
            checkpoint_interval=int(self.checkpointInterval))
        model = GBDTRankerModel(
            boosterModel=booster,
            featuresCol=self.featuresCol,
            predictionCol=self.predictionCol,
        )
        model._eval_history = history
        return model


class GBDTRankerModel(GBDTModelBase):
    @staticmethod
    def load_native_model_from_string(s: str, **kw) -> "GBDTRankerModel":
        return GBDTRankerModel(boosterModel=Booster.from_string(s), **kw)

    @staticmethod
    def load_native_model_from_file(path: str, **kw) -> "GBDTRankerModel":
        with open(path) as f:
            return GBDTRankerModel.load_native_model_from_string(f.read(), **kw)

    def _transform(self, ds: Dataset) -> Dataset:
        X = ds.to_numpy([self.featuresCol])
        self._check_features(X)
        ni = self.numIterationsUsed
        pred = self.booster.predict_margin(X, None if ni < 0 else ni)
        out = ds.with_column(self.predictionCol, np.asarray(pred, np.float64))
        return self._maybe_add_leaves(out, X)


def _train_batched(X, y, cfg, w, valid, num_batches: int, mesh, seed: int,
                   checkpoint_dir=None, checkpoint_interval=0):
    """numBatches fold-over warm start (LightGBMBase.scala:44-59)."""
    if num_batches and num_batches > 1:
        if checkpoint_dir:
            raise ValueError(
                "checkpointDir cannot combine with numBatches > 1: the "
                "batch fold is itself a warm-start sequence — checkpoint "
                "single-batch training instead")
        n = len(X)
        idx = np.array_split(np.arange(n), num_batches)
        booster = None
        history = []
        for part in idx:
            booster, h = train(X[part], y[part], cfg,
                               sample_weight=None if w is None else w[part],
                               valid=valid, mesh=mesh, init_model=booster)
            history.extend(h)
        return booster, history
    return train(X, y, cfg, sample_weight=w, valid=valid, mesh=mesh,
                 checkpoint_dir=checkpoint_dir,
                 checkpoint_interval=checkpoint_interval)
