"""Leaf-wise histogram tree growth, fully inside ``jit``.

This replaces LightGBM's native C++ tree learner (reference: the black box
behind LGBM_BoosterUpdateOneIter, booster/LightGBMBooster.scala:359; per-iter
histogram build + cross-machine allreduce + split).  The TPU formulation:

- **Static shapes everywhere**: exactly ``num_leaves-1`` split iterations in
  a ``lax.fori_loop``; zero-gain iterations are no-ops guarded by
  ``lax.cond``.  Histograms live in a slot-reused buffer of ``num_leaves+1``
  slots (a split's left child reuses the parent's slot, the right child
  takes a fresh one) so memory stays O(num_leaves · F · B).
- **Histogram subtraction**: only the left child's histogram is built by
  scatter-add; the right child's is parent − left (LightGBM's classic
  optimization, here it also halves scatter traffic).
- **Data-parallel = one psum**: rows are sharded over the mesh ``data``
  axis; passing ``axis_name`` makes every histogram build and root-stat
  reduction a ``lax.psum`` — the entire replacement for the reference's
  driver-socket rendezvous + native allreduce ring
  (NetworkManager.scala:55-205).  The growth loop itself is replicated and
  deterministic on every rank.
- **Missing values**: NaN maps to bin 0 and always routes left (a fixed
  default-left policy).

Split gain follows LightGBM: with G/H the child gradient/hessian sums,
``score(G,H) = T(G)^2 / (H + λ2)`` where T is the L1 soft-threshold, and
``gain = score(GL,HL) + score(GR,HR) - score(G,H)``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ... import telemetry as _telemetry
from ...parallel.planner import planned_psum as _c_planned_psum


def _tl_gauge(grower: str, active: bool) -> None:
    """Record the FINAL per-program two-level decision (the growers apply
    structural exclusions train() cannot see — EFB, monotone, voting,
    VMEM fit), so the gauge answers "which split-search semantics is this
    program actually using".  Runs at trace/step-construction time."""
    try:
        _telemetry.get_registry().gauge(
            "gbdt_two_level_grower_active",
            "1 when the grower program traced with coarse-then-refine "
            "histograms, by growth policy", ("grower",)).set(
                1.0 if active else 0.0, grower=grower)
    except Exception:
        pass


class GrowthParams(NamedTuple):
    """Static growth hyperparameters (hashable → part of the jit key)."""
    num_leaves: int = 31
    max_depth: int = -1               # <=0: unlimited (bounded by num_leaves)
    min_data_in_leaf: float = 20.0
    min_sum_hessian_in_leaf: float = 1e-3
    lambda_l1: float = 0.0
    lambda_l2: float = 0.0
    min_gain_to_split: float = 0.0
    total_bins: int = 256             # B (incl. missing bin 0)
    voting_k: int = 0                 # >0: voting-parallel with this top-k
    #: per-feature {-1, 0, +1} (None: unconstrained) — LightGBM's
    #: ``monotone_constraints`` (params/LightGBMParams.scala:168-183);
    #: the "basic" method: violating splits are discarded, child outputs
    #: are clamped to bounds propagated down the tree
    monotone_constraints: Optional[Tuple[int, ...]] = None
    #: gain penalization for splits on constrained features near the root
    #: (LightGBM ``monotone_penalty``, BaseTrainParams.scala:128-130)
    monotone_penalty: float = 0.0
    #: "basic" (midpoint bound propagation) | "intermediate" (bounds from
    #: the opposite sibling SUBTREE's current extreme outputs, recomputed
    #: over the whole tree each wave — much less constraining, LightGBM's
    #: recommended upgrade) | "advanced" (the exact minimal pairwise
    #: constraint set over ordered-and-overlapping leaf boxes — see
    #: :func:`_advanced_bounds`; provably no tighter than intermediate)
    monotone_method: str = "basic"
    #: two-level histograms for wide-bin depthwise growth: "off" | "auto"
    #: (on for N >= TWO_LEVEL_MIN_ROWS; N is shard-local here — train()
    #: resolves "auto" from the GLOBAL row count before building steps)
    #: | "on".  Histograms build and store at COARSE
    #: (bin >> TWO_LEVEL_SHIFT) resolution; the top ``refine_k`` features
    #: — chosen ONCE per tree from the root's coarse per-feature gains —
    #: are refined at full resolution every wave (left children built,
    #: right children by fine subtraction) and each split picks the
    #: better of the refined fine candidates and the unrefined
    #: coarse-boundary candidates.  The 255-bin one-hot build — the
    #: measured VPU bottleneck of the level pass — shrinks 2^shift; split
    #: quality is preserved unless a feature outside the root-chosen
    #: top-K beats every refined feature only on a sub-coarse-boundary
    #: cut (each coarse boundary IS a fine split, so coarse candidates
    #: remain exact lower bounds)
    two_level: str = "off"
    #: features refined at full resolution when two-level is on
    refine_k: int = 0
    #: tuned rows-per-chunk for the Pallas histogram kernels (0 = the
    #: ``_tile_for`` ladder default).  Set from the ``gbdt_hist_chunk``
    #: tuning-table winner by ``BoostingConfig.growth_params()`` —
    #: part of this NamedTuple (and therefore the jit static key) so a
    #: tuned geometry compiles its own program instead of silently
    #: reusing the default's
    hist_chunk: int = 0


class Tree(NamedTuple):
    """Flat tree arrays; node 0 is the root. -1 children ⇒ leaf."""
    split_feature: jnp.ndarray        # (MAX_NODES,) int32
    split_bin: jnp.ndarray            # (MAX_NODES,) int32 (go left if bin<=)
    threshold: jnp.ndarray            # (MAX_NODES,) f32 raw-value threshold
    split_gain: jnp.ndarray           # (MAX_NODES,) f32 (0 for leaves)
    left_child: jnp.ndarray           # (MAX_NODES,) int32
    right_child: jnp.ndarray          # (MAX_NODES,) int32
    leaf_value: jnp.ndarray           # (MAX_NODES,) f32 (already shrunk)
    node_value: jnp.ndarray           # (MAX_NODES,) f32 output at every node
    num_nodes: jnp.ndarray            # () int32
    default_left: jnp.ndarray         # (MAX_NODES,) bool — missing routing
                                      # per node (training always emits
                                      # True; imported models may not)
    node_count: jnp.ndarray           # (MAX_NODES,) f32 — rows covering
                                      # each node (TreeSHAP cover weights)
    missing_zero: jnp.ndarray         # (MAX_NODES,) bool — LightGBM
                                      # missing_type=Zero: |x|<=1e-35 (and
                                      # NaN) routes by default_left at this
                                      # node; training emits all-False


def max_nodes(num_leaves: int) -> int:
    return 2 * num_leaves


def _soft_threshold(g, l1):
    return jnp.sign(g) * jnp.maximum(jnp.abs(g) - l1, 0.0)


def _leaf_score(g, h, l1, l2):
    t = _soft_threshold(g, l1)
    return t * t / (h + l2 + 1e-32)


def _leaf_output(g, h, l1, l2):
    return -_soft_threshold(g, l1) / (h + l2 + 1e-32)


def _build_hist(bins_t, flat_bins, grad, hess, mask, F, B, use_pallas,
                vals8=None, scales=None, hist_shift=0, hist_chunk=0):
    """Histogram for masked rows → (F*Bh, 3) f32 [grad, hess, count]
    (Bh = coarse width when ``hist_shift`` > 0 — the leaf-wise grower's
    two-level coarse build).

    ``mask`` is the row weight (bag/GOSS amplification); the count channel
    counts rows with mask>0 exactly once so GOSS amplification never
    inflates leaf counts.  On TPU the Pallas MXU kernel builds it
    (pallas_hist.py); elsewhere an XLA scatter-add over the precomputed
    flattened bin ids ``flat_bins`` (F, N).

    ``vals8``/``scales``: per-TREE int8 limb quantization from
    :func:`prep_hist_vals` (already weighted by the tree's row mask).
    Passing them keeps the quantization scale identical across every
    histogram of the tree — node-local scales would round differently
    from the depthwise grower's global scale and flip near-tie splits;
    ``mask`` then only selects node membership."""
    if use_pallas:
        from .pallas_hist import build_hist_nodes_pallas, coarse_bins
        assert vals8 is not None, "pallas path requires per-tree vals8/scales"
        slot = jnp.where(mask > 0, 0, -1).astype(jnp.int32)
        Bh = coarse_bins(B, hist_shift) if hist_shift else B
        return build_hist_nodes_pallas(
            bins_t, slot, vals8, scales, 1, B, hist_shift=hist_shift,
            interpret=(use_pallas == "interpret"),
            hist_chunk=hist_chunk)[0].reshape(F * Bh, 3)
    upd = _hist_updates(grad, hess, mask)                                 # (N,3)
    upd = jnp.broadcast_to(upd[None, :, :], (F,) + upd.shape)             # (F,N,3)
    hist = jnp.zeros((F * B, 3), jnp.float32)
    hist = hist.at[flat_bins].add(upd.astype(jnp.float32))
    if hist_shift:
        from .pallas_hist import coarse_bins
        Bh = coarse_bins(B, hist_shift)
        hist = _pool_coarse(hist.reshape(F, B, 3), Bh,
                            hist_shift).reshape(F * Bh, 3)
    return hist


def _mono_penalty_factor(node_depth, penalty: float):
    """LightGBM's ComputeMonotoneSplitGainPenalty: 1 forbids constrained
    splits at the root, higher values reach deeper."""
    eps = 1e-10
    d = node_depth.astype(jnp.float32)
    if penalty <= 1.0:
        fac = 1.0 - penalty / jnp.exp2(d) + eps
    else:
        fac = 1.0 - jnp.exp2(jnp.float32(penalty) - 1.0 - d) + eps
    return jnp.where(jnp.float32(penalty) >= d + 1.0, eps, fac)


def _obj2(g, h, w, l1, l2):
    """2× the objective reduction at leaf output ``w`` — equals
    :func:`_leaf_score` when ``w`` is the unclamped optimum, so constrained
    gains degrade exactly to the unconstrained formula when no bound
    binds."""
    return -(2.0 * g * w + (h + l2) * w * w + 2.0 * l1 * jnp.abs(w))


def _gain_matrix(hist, sum_g, sum_h, sum_c, num_bins, feature_mask,
                 node_depth, p: GrowthParams, node_lo=None, node_hi=None,
                 mono_c=None):
    """Split-gain matrix (F, B) with invalid candidates at -inf, plus the
    cumulative left sums as three (F, B) channel arrays (gl, hl, cl)
    the winner's child stats read from.

    Split at bin b sends bins<=b left, b ∈ [0, B-2].

    With ``mono_c`` ((F,) int32 in {-1,0,1}) and the node's output bounds
    ``node_lo``/``node_hi``, gains come from CLAMPED child outputs, splits
    whose clamped outputs violate the feature's direction are discarded,
    and constrained-feature gains are penalized by depth
    (``monotone_penalty``) — the LightGBM "basic" method.
    """
    F, B, _ = hist.shape
    # unpack channels BEFORE any arithmetic: (..., B, 3) puts 3 in the
    # lane dim, so every op on it touches 128/3 ≈ 43x its logical bytes in
    # (8, 128)-tiled physical layout — slicing pays that once and the
    # scans/gains below run on clean (..., F, B) arrays (measured
    # ~14 ms/tree of split search at B=256 before this reshuffle)
    gch, hch, cch = hist[..., 0], hist[..., 1], hist[..., 2]
    # prefix sums over the bin axis via log-depth associative scan:
    # jnp.cumsum lowers to an O(B^2)-work reduce-window on TPU, and a
    # triangular-matmul formulation reassociates sums differently per
    # batch shape, so the two growers' near-tie splits diverge — the
    # scan's fixed pairwise tree is both O(B log B) and
    # batch-shape-independent
    gl = lax.associative_scan(jnp.add, gch, axis=-1)     # (F, B)
    hl = lax.associative_scan(jnp.add, hch, axis=-1)
    cl = lax.associative_scan(jnp.add, cch, axis=-1)
    gr, hr, cr = sum_g - gl, sum_h - hl, sum_c - cl
    if mono_c is None:
        gain = (_leaf_score(gl, hl, p.lambda_l1, p.lambda_l2)
                + _leaf_score(gr, hr, p.lambda_l1, p.lambda_l2)
                - _leaf_score(sum_g, sum_h, p.lambda_l1, p.lambda_l2))
    else:
        wl = jnp.clip(_leaf_output(gl, hl, p.lambda_l1, p.lambda_l2),
                      node_lo, node_hi)
        wr = jnp.clip(_leaf_output(gr, hr, p.lambda_l1, p.lambda_l2),
                      node_lo, node_hi)
        wp = jnp.clip(_leaf_output(sum_g, sum_h, p.lambda_l1, p.lambda_l2),
                      node_lo, node_hi)
        gain = (_obj2(gl, hl, wl, p.lambda_l1, p.lambda_l2)
                + _obj2(gr, hr, wr, p.lambda_l1, p.lambda_l2)
                - _obj2(sum_g, sum_h, wp, p.lambda_l1, p.lambda_l2))
        cvec = mono_c[:, None]
        viol = (((cvec == 1) & (wl > wr)) | ((cvec == -1) & (wl < wr)))
        gain = jnp.where(viol, -jnp.inf, gain)
        if p.monotone_penalty > 0.0:
            fac = _mono_penalty_factor(node_depth, p.monotone_penalty)
            gain = jnp.where(cvec != 0, gain * fac, gain)
    bins_idx = jnp.arange(B)[None, :]
    valid = ((cl >= p.min_data_in_leaf) & (cr >= p.min_data_in_leaf)
             & (hl >= p.min_sum_hessian_in_leaf)
             & (hr >= p.min_sum_hessian_in_leaf)
             & (bins_idx < (num_bins[:, None] + 1) - 1)   # inside feature's bin range
             & (bins_idx < B - 1)
             & feature_mask[:, None])
    if p.max_depth > 0:
        valid = valid & (node_depth < p.max_depth)
    return jnp.where(valid, gain, -jnp.inf), (gl, hl, cl)


def _best_split(hist, sum_g, sum_h, sum_c, num_bins, feature_mask,
                node_depth, p: GrowthParams, node_lo=None, node_hi=None,
                mono_c=None):
    """Best (gain, feature, bin, left-sums) from a node histogram (F, B, 3)."""
    F, B, _ = hist.shape
    gain, cum = _gain_matrix(hist, sum_g, sum_h, sum_c, num_bins,
                             feature_mask, node_depth, p, node_lo, node_hi,
                             mono_c)
    flat = jnp.argmax(gain)
    bf, bb = flat // B, flat % B
    bgain = gain[bf, bb]
    gl, hl, cl = cum
    return bgain, bf.astype(jnp.int32), bb.astype(jnp.int32), \
        gl[bf, bb], hl[bf, bb], cl[bf, bb]


# -- two-level (coarse-then-refine) histograms ------------------------------
#
# At max_bin=255 the level pass is bounded by the VPU one-hot build
# (measured: the int8 matmul runs at ~122 Tmac/s while the (ft·B, C)
# one-hot construction costs ~1.5x the matmul and the step time equals
# the max of the two).  Two-level growth builds the per-wave histograms
# at COARSE (bin >> TWO_LEVEL_SHIFT) resolution — 2^shift less
# one-hot work and matmul, equally smaller split scans and histogram
# state — then refines only
# a top-K feature subset, chosen ONCE per tree from the ROOT's coarse
# per-feature gains, with ONE narrow full-resolution pass per wave (left
# children only; right children by subtraction from the parent's stored
# fine-K histograms — a per-wave adaptive set would need both children
# built fresh at 2S lanes, which was measured to eat the coarse win).
# Every coarse boundary is itself a fine split, so unrefined features
# keep exact (if coarser) candidates; the tradeoff is only that a
# feature outside the root-chosen top-K cannot win on a
# sub-coarse-boundary cut.

#: rows below which "auto" two-level stays off (small data gains nothing
#: and exactness-vs-255-bins matters more in tests)
TWO_LEVEL_MIN_ROWS = 500_000
#: coarse level is bin >> this shift (255-bin fine -> 32-bin coarse;
#: measured on chip: shift 3 cuts the coarse pass ~17% vs shift 2 with
#: holdout AUC unchanged — the refined top-K carries fine resolution and
#: the 32-bin coarse fallback still bounds every unrefined feature)
TWO_LEVEL_SHIFT = 3


def _pool_coarse(hist, Bc: int, shift: int):
    """Fine (..., B, 3) f32 histograms → coarse (..., Bc, 3) by summing
    the ``1 << shift`` fine bins sharing each coarse index — the XLA-path
    counterpart of the pallas kernel's in-kernel coarse build."""
    B = hist.shape[-2]
    g = 1 << shift
    pad = Bc * g - B
    h = jnp.pad(hist, [(0, 0)] * (hist.ndim - 2) + [(0, pad), (0, 0)])
    return h.reshape(h.shape[:-2] + (Bc, g, 3)).sum(-2)


def _tl_coarse_gains(c_hists, sum_g, sum_h, sum_c, depth, lo, hi,
                     num_bins_c, feature_mask, p: GrowthParams):
    """Batched coarse gain matrices for two-level selection.

    → (gains (S', F, Bc), cum 3-tuple of (S', F, Bc), per-feature max
    gains (S', F))."""
    def one(h, g, hh, c, d, l, u):
        return _gain_matrix(h, g, hh, c, num_bins_c, feature_mask, d, p,
                            l, u, None)
    cg, ccum = jax.vmap(one)(c_hists, sum_g, sum_h, sum_c, depth, lo, hi)
    return cg, ccum, jnp.max(cg, axis=-1)


def _tl_final_pick(cg, ccum, f_hists, topk, sum_g, sum_h, sum_c, depth,
                   lo, hi, num_bins, feature_mask, p: GrowthParams,
                   shift: int):
    """Merge the refined fine candidates with the unrefined coarse
    candidates → per-node best split in FINE bin space.

    ``cg``/``ccum``: coarse gains and cumulative left sums from
    :func:`_tl_coarse_gains`; ``f_hists`` (S', K, B, 3): full-resolution
    histograms of the ``topk`` features.  A coarse candidate at coarse bin
    c maps to the fine boundary ``(c+1)·2^shift - 1`` (the rows ≤ that
    fine bin are exactly the rows ≤ c at coarse resolution, so the coarse
    cum sums are exact for the mapped split)."""
    Sp, F, Bc = cg.shape
    B = f_hists.shape[-2]
    rows = jnp.arange(Sp)
    # coarse candidates exclude the refined features (they compete at
    # fine resolution instead)
    cg = cg.at[:, topk, :].set(-jnp.inf)
    flat = jnp.argmax(cg.reshape(Sp, -1), axis=-1)
    cf, cc = flat // Bc, flat % Bc
    cgain = cg[rows, cf, cc]
    cgl = ccum[0][rows, cf, cc]
    chl = ccum[1][rows, cf, cc]
    ccl = ccum[2][rows, cf, cc]
    step = 1 << shift
    cbin = jnp.minimum(cc * step + step - 1, num_bins[cf] - 1)

    nbk = num_bins[topk]
    fmk = feature_mask[topk]

    def one(h, g, hh, c, d, l, u):
        return _gain_matrix(h, g, hh, c, nbk, fmk, d, p, l, u, None)
    fg, fcum = jax.vmap(one)(f_hists, sum_g, sum_h, sum_c, depth, lo, hi)
    fflat = jnp.argmax(fg.reshape(Sp, -1), axis=-1)
    fk, fb = fflat // B, fflat % B
    fgain = fg[rows, fk, fb]
    fgl = fcum[0][rows, fk, fb]
    fhl = fcum[1][rows, fk, fb]
    fcl = fcum[2][rows, fk, fb]

    use_f = fgain >= cgain
    return (jnp.where(use_f, fgain, cgain),
            jnp.where(use_f, topk[fk], cf).astype(jnp.int32),
            jnp.where(use_f, fb, cbin).astype(jnp.int32),
            jnp.where(use_f, fgl, cgl),
            jnp.where(use_f, fhl, chl),
            jnp.where(use_f, fcl, ccl))


def _tl_root_pick(root_hist, root_g, root_h, root_c, num_bins, num_bins_c,
                  feature_mask, p: GrowthParams, shift: int, K: int,
                  bins_t, B: int, use_pallas, build_fine_root, ar):
    """Shared two-level ROOT setup for both growers: coarse gains → the
    per-tree top-K feature set → gathered/prepared refined-feature
    layouts → root fine histograms → merged root pick.

    ``build_fine_root(bins_kp) -> (1, K, B, 3)`` is the grower-specific
    fine build (fused-path tiles vs flat XLA ids both prepared here).
    → (topk, sel_k, bins_kp, root_fine, (bg, bf, bb, bgl, bhl, bcl))."""
    z1 = jnp.zeros((1,), jnp.int32)
    ninf1 = jnp.full((1,), -jnp.inf)
    inf1 = jnp.full((1,), jnp.inf)
    cg0, ccum0, fgain0 = _tl_coarse_gains(
        root_hist[None], root_g[None], root_h[None], root_c[None],
        z1, ninf1, inf1, num_bins_c, feature_mask, p)
    topk = lax.top_k(fgain0[0], K)[1].astype(jnp.int32)
    # gather + layout the K refined feature rows ONCE per tree (a
    # contiguous feature-axis row copy, NOT the pathological per-row
    # gather); the split loops close over the result
    sel_k = jnp.take(bins_t, topk, axis=0)
    if use_pallas:
        from .pallas_hist import prepare_feature_tiles
        bins_kp = prepare_feature_tiles(sel_k, B, K)
    else:
        bins_kp = sel_k + (jnp.arange(K, dtype=jnp.int32) * B)[:, None]
    root_fine = ar(build_fine_root(bins_kp))               # (1, K, B, 3)
    rbest = _tl_final_pick(cg0, ccum0, root_fine, topk,
                           root_g[None], root_h[None], root_c[None],
                           z1, ninf1, inf1, num_bins, feature_mask,
                           p, shift)
    return topk, sel_k, bins_kp, root_fine, tuple(x[0] for x in rbest)


def _mono_vec(p: GrowthParams, F: int):
    """(F,) int32 constraint vector padded/truncated to the feature count
    this grower sees (pallas feature padding adds unconstrained columns),
    or None when unconstrained."""
    if p.monotone_constraints is None or not any(p.monotone_constraints):
        return None
    c = tuple(p.monotone_constraints)[:F]
    c = c + (0,) * (F - len(c))
    return jnp.asarray(c, jnp.int32)


def _mono_child_bounds(cf, lo, hi, wl, wr):
    """Child output bounds after splitting on a feature with constraint
    ``cf`` (basic method): the clamped child outputs' midpoint caps the
    violating side; unconstrained split features pass bounds through."""
    mid = 0.5 * (wl + wr)
    l_lo = jnp.where(cf == -1, jnp.maximum(lo, mid), lo)
    l_hi = jnp.where(cf == 1, jnp.minimum(hi, mid), hi)
    r_lo = jnp.where(cf == 1, jnp.maximum(lo, mid), lo)
    r_hi = jnp.where(cf == -1, jnp.minimum(hi, mid), hi)
    return l_lo, l_hi, r_lo, r_hi


def _intermediate_bounds(split_feature, left_child, right_child,
                         raw_value, mono_c, n_iters: int = 0):
    """Intermediate-method bounds: a constrained split bounds each child
    SUBTREE by the opposite subtree's extreme leaf outputs (LightGBM's
    IntermediateLeafConstraints semantics) instead of the midpoint.

    Implementation: the constraint set is materialized as explicit pairs
    — for a split at node a on feature f with c=+1, every node of L(a)
    is <= every LEAF of R(a) and every node of R(a) is >= every LEAF of
    L(a) (extremes range over leaves, matching the old scan formulation)
    — then projected through :func:`_project_pairs`, which is exact and
    convergent where the old clip-raw iteration oscillated on
    conflicting raw values.  ``n_iters`` is kept for call-site
    compatibility and ignored.

    Returns (lo, hi, clamped_value), each (M,)."""
    del n_iters
    M = split_feature.shape[0]
    leaf = left_child < 0

    # desc[a, i]: node i lies in a's subtree (children carry higher
    # indices than parents in every grower here, so one backward walk)
    def back(k, desc):
        j = M - 1 - k
        l = jnp.maximum(left_child[j], 0)
        r = jnp.maximum(right_child[j], 0)
        internal = left_child[j] >= 0
        row = jnp.zeros(M, jnp.bool_).at[j].set(True)
        row = row | (jnp.where(internal, desc[l] | desc[r],
                               jnp.zeros(M, jnp.bool_)))
        return desc.at[j].set(row)

    desc = lax.fori_loop(0, M, back, jnp.zeros((M, M), jnp.bool_))

    internal = left_child >= 0
    inL = jnp.where(internal[:, None],
                    desc[jnp.maximum(left_child, 0)], False)    # (M, M)
    inR = jnp.where(internal[:, None],
                    desc[jnp.maximum(right_child, 0)], False)
    c = jnp.where(internal, mono_c[jnp.maximum(split_feature, 0)], 0)
    # side that must stay LOW / HIGH at each constrained split
    low_side = jnp.where((c == 1)[:, None], inL,
                         jnp.where((c == -1)[:, None], inR, False))
    high_side = jnp.where((c == 1)[:, None], inR,
                          jnp.where((c == -1)[:, None], inL, False))
    # P[i, j]: val_i <= val_j with j leaf; Q[i, j]: val_i >= val_j, j leaf
    f32 = jnp.float32
    P = (low_side.T.astype(f32)
         @ (high_side & leaf[None, :]).astype(f32)) > 0
    Q = (high_side.T.astype(f32)
         @ (low_side & leaf[None, :]).astype(f32)) > 0
    return _project_pairs(P, Q, raw_value, leaf)


def _project_pairs(P, Q, raw_value, leaf):
    """Feasible monotone assignment + bounds from explicit constraints.

    ``P[i, j]``: ``val_i <= val_j``; ``Q[i, j]``: ``val_i >= val_j`` —
    in both, j is a LEAF (i may be any node).  Leaves take
    ``(L + U) / 2`` with ``L_i = max(raw_i, max raw over transitive-
    closure predecessors)`` and ``U_i = min(raw_i, min raw over closure
    successors)``: L and U are each non-decreasing along every
    constraint edge, so their average is feasible BY CONSTRUCTION and
    equals raw wherever raw is already feasible — unlike the previous
    clip-raw-to-current-bounds iteration, which oscillated with period 2
    on conflicting raw values and, at an even iteration count, handed
    the raw violating values straight back.  Internal nodes clamp to the
    bounds the final leaf values imply (they never feed back).

    Returns (lo, hi, val), each (M,)."""
    M = raw_value.shape[0]
    leaf_pairs = P & leaf[:, None]
    f32 = jnp.float32

    def sq(le, _):
        return (le | ((le.astype(f32) @ le.astype(f32)) > 0)), None

    rounds = max(int(np.ceil(np.log2(max(M, 2)))), 1)
    close, _ = lax.scan(sq, leaf_pairs, None, length=rounds)
    L = jnp.maximum(raw_value, jnp.max(
        jnp.where(close.T, raw_value[None, :], -jnp.inf), axis=1))
    U = jnp.minimum(raw_value, jnp.min(
        jnp.where(close, raw_value[None, :], jnp.inf), axis=1))
    vleaf = jnp.where(leaf, 0.5 * (L + U), raw_value)
    # per-node bounds from the FINAL leaf values — what split search and
    # internal-node clamping consume
    hi = jnp.min(jnp.where(P, vleaf[None, :], jnp.inf), axis=1)
    lo = jnp.max(jnp.where(Q, vleaf[None, :], -jnp.inf), axis=1)
    val = jnp.where(leaf, vleaf, jnp.clip(raw_value, lo, hi))
    return lo, hi, val


def _advanced_bounds(split_feature, split_bin, left_child, right_child,
                     raw_value, mono_c, total_bins: int, n_iters: int = 6):
    """Advanced-method bounds: the EXACT minimal constraint set for
    single-tree monotonicity.

    ``val_i <= val_j`` is required iff leaves i and j are ORDERED on a
    constrained feature f (i's bin box strictly left of j's) and their
    boxes OVERLAP on every other feature — precisely the pairs some input
    pair x <= x' (differing only in f) can land in, so the set is both
    necessary and sufficient.  Intermediate's opposite-subtree extremes
    are a SUPERSET of these pairs (it also constrains non-overlapping
    boxes), which is why advanced is provably no tighter than
    intermediate; LightGBM's own ``advanced`` pursues the same relaxation
    via threshold-dependent per-leaf constraints
    (reference surfaces the method string only:
    params/LightGBMParams.scala:168-183).  O(M^2 F) memory — fine for
    monotone-model sizes; reject upstream if it ever is not.

    Returns (lo, hi, clamped_value), each (M,); internal nodes clamp to
    the bounds the final leaf values imply."""
    del n_iters                      # _project_pairs is exact, not iterative
    M = split_feature.shape[0]
    F = mono_c.shape[0]
    JUNK = M

    # per-node bin boxes (lo, hi] by a root->children walk (children carry
    # higher indices than parents in every grower here); categorical
    # features use target-ordered bins, so their splits are interval
    # splits too and the box walk stays exact
    lo0 = jnp.full((M + 1, F), -1, jnp.int32)
    hi0 = jnp.full((M + 1, F), total_bins - 1, jnp.int32)

    def fwd(j, boxes):
        lo, hi = boxes
        lraw, rraw = left_child[j], right_child[j]
        internal = lraw >= 0
        l = jnp.where(internal, lraw, JUNK)
        r = jnp.where(internal, rraw, JUNK)
        f = jnp.maximum(split_feature[j], 0)
        b = split_bin[j]
        lhi = hi[j].at[f].set(jnp.minimum(hi[j, f], b))
        rlo = lo[j].at[f].set(jnp.maximum(lo[j, f], b))
        lo = lo.at[l].set(lo[j]).at[r].set(rlo)
        hi = hi.at[l].set(lhi).at[r].set(hi[j])
        return lo, hi

    lo, hi = lax.fori_loop(0, M, fwd, (lo0, hi0))
    lo, hi = lo[:M], hi[:M]

    leaf = left_child < 0
    # boxes (lo, hi] intersect iff lo_i < hi_j and lo_j < hi_i
    ov = ((lo[:, None, :] < hi[None, :, :])
          & (lo[None, :, :] < hi[:, None, :]))          # (M, M, F)
    n_ov = jnp.sum(ov.astype(jnp.int32), axis=-1)       # (M, M)
    # overlap on every feature EXCEPT f
    ov_exc = (n_ov[:, :, None] - ov.astype(jnp.int32)) == (F - 1)
    ordered = hi[:, None, :] <= lo[None, :, :]          # i left of j on f
    # any-node-to-LEAF constraint masks for _project_pairs (internal
    # nodes get bounds from the leaf values but never feed back)
    inc_f = ov_exc & (mono_c[None, None, :] == 1)
    dec_f = ov_exc & (mono_c[None, None, :] == -1)
    # val_i <= val_j: i left of j on a +1 feature, or right of j on a -1
    P_any = (jnp.any(ordered & inc_f, axis=-1)
             | jnp.any(ordered.transpose(1, 0, 2) & dec_f, axis=-1))
    # val_i >= val_j: the mirrored directions
    Q_any = (jnp.any(ordered.transpose(1, 0, 2) & inc_f, axis=-1)
             | jnp.any(ordered & dec_f, axis=-1))
    return _project_pairs(P_any & leaf[None, :], Q_any & leaf[None, :],
                          raw_value, leaf)


def _tree_bounds(split_feature, split_bin, left_child, right_child,
                 raw_value, mono_c, p: "GrowthParams", n_iters: int = 4):
    """Whole-tree bounds refresh for the method in ``p.monotone_method``
    (``intermediate`` or ``advanced``) → (lo, hi, clamped_value)."""
    if p.monotone_method == "advanced":
        return _advanced_bounds(split_feature, split_bin, left_child,
                                right_child, raw_value, mono_c,
                                p.total_bins, n_iters=max(n_iters, 6))
    return _intermediate_bounds(split_feature, left_child, right_child,
                                raw_value, mono_c, n_iters=n_iters)


def _refresh_intermediate(s, mono_c, p: "GrowthParams"):
    """Replace a grower state's node bounds with whole-tree-refresh
    bounds (intermediate or advanced method) recomputed over the whole
    current tree."""
    raw = _leaf_output(s["sum_g"], s["sum_h"], p.lambda_l1, p.lambda_l2)
    lo, hi, _ = _tree_bounds(s["split_feature"], s["split_bin"],
                             s["left_child"], s["right_child"], raw,
                             mono_c, p)
    return dict(s, node_lo=lo, node_hi=hi)


def _mono_node_bounds(mono_cf, p_lo, p_hi, lg, lh, rg, rh, p):
    """One split's child bounds: pass-through when unconstrained
    (``mono_cf`` None), else clamp the children's leaf outputs to the
    parent bounds and cap the violating side at their midpoint — the ONE
    place the basic-method propagation lives for all three growers."""
    if mono_cf is None:
        return p_lo, p_hi, p_lo, p_hi
    wl = jnp.clip(_leaf_output(lg, lh, p.lambda_l1, p.lambda_l2),
                  p_lo, p_hi)
    wr = jnp.clip(_leaf_output(rg, rh, p.lambda_l1, p.lambda_l2),
                  p_lo, p_hi)
    return _mono_child_bounds(mono_cf, p_lo, p_hi, wl, wr)


def _best_split_voting(local_hist, sum_g, sum_h, sum_c, num_bins,
                       feature_mask, node_depth, p: GrowthParams,
                       axis_name: str, node_lo=None, node_hi=None,
                       mono_c=None):
    """Voting-parallel split selection (LightGBM ``voting_parallel`` / the
    PV-Tree algorithm; reference surfaces it as the ``parallelism`` param,
    params/LightGBMParams.scala:25, topK LightGBMBase.scala:251).

    Each rank keeps its histograms LOCAL and: (1) ranks features by local
    best gain and votes for its top-k; (2) votes ride one tiny psum and the
    global top-2k features are selected identically on every rank; (3) only
    those 2k features' histograms are psum'd — O(2k·B) instead of O(F·B)
    ICI traffic — and the true global best split is chosen among them.
    ``sum_g/h/c`` must be the node's GLOBAL stats.
    """
    F, B, _ = local_hist.shape
    k = min(p.voting_k, F)
    sel_n = min(2 * k, F)

    # (1) local view: gains against local node stats (the local root/leaf
    # sums live in every feature's bins; feature 0 spans all rows)
    lsum = jnp.sum(local_hist[0], axis=0)            # (3,)
    lgain, _ = _gain_matrix(local_hist, lsum[0], lsum[1], lsum[2],
                            num_bins, feature_mask, node_depth, p,
                            node_lo, node_hi, mono_c)
    per_feat = jnp.max(lgain, axis=1)                # (F,)
    _, local_top = lax.top_k(per_feat, k)
    votes = jnp.zeros(F, jnp.float32).at[local_top].add(
        jnp.where(per_feat[local_top] > -jnp.inf, 1.0, 0.0))
    votes = lax.psum(votes, axis_name)

    # (2) deterministic global top-2k: votes desc, feature index asc
    # (exact in f32 while votes·(F+1)+F < 2^24)
    score = votes * jnp.float32(F + 1) + jnp.arange(F - 1, -1, -1,
                                                    dtype=jnp.float32)
    _, sel = lax.top_k(score, sel_n)
    sel = sel.astype(jnp.int32)

    # (3) aggregate only the voted features; pick the global best among them
    glob = lax.psum(local_hist[sel], axis_name)      # (sel_n, B, 3)
    ggain, cum = _gain_matrix(glob, sum_g, sum_h, sum_c, num_bins[sel],
                              feature_mask[sel], node_depth, p,
                              node_lo, node_hi,
                              None if mono_c is None else mono_c[sel])
    flat = jnp.argmax(ggain)
    bi, bb = flat // B, flat % B
    gl, hl, cl = cum
    return ggain[bi, bb], sel[bi], bb.astype(jnp.int32), \
        gl[bi, bb], hl[bi, bb], cl[bi, bb]


@functools.partial(jax.jit, static_argnames=("p", "axis_name", "use_pallas",
                                             "cconfig"))
def grow_tree(bins_t: jnp.ndarray,          # (F, N) int32 (transposed bins)
              grad: jnp.ndarray,            # (N,) f32 (0 for pad rows)
              hess: jnp.ndarray,            # (N,) f32 (0 for pad rows)
              row_valid: jnp.ndarray,       # (N,) f32 bag-weight ∈ {0,1} or GOSS weight
              feature_mask: jnp.ndarray,    # (F,) bool — feature_fraction mask
              upper_bounds: jnp.ndarray,    # (F, B-1) f32 raw bin bounds
              num_bins: jnp.ndarray,        # (F,) int32
              learning_rate: float,
              p: GrowthParams,
              axis_name: Optional[str] = None,
              use_pallas: bool = False,
              bundle_map: Optional[dict] = None,
              cconfig=None,
              ) -> Tuple[Tree, jnp.ndarray]:
    """Grow one tree; returns (tree, per-row leaf node ids).

    When ``axis_name`` is set the function must run inside shard_map over
    that axis; histograms and root stats are psum'd so every rank grows the
    identical tree from its row shard.

    ``bundle_map`` (EFB): ``bins_t`` holds BUNDLED columns but split
    search, routing and the emitted tree all live in ORIGINAL feature
    space — histograms unbundle before each pick, splits route through
    :func:`_slot_route_params`.

    ``cconfig`` (a :class:`~synapseml_tpu.parallel.compression.
    CollectiveConfig`, static): puts the per-split histogram allreduce —
    THE data-parallel bandwidth hog — on a quantized wire.  Stateless
    per histogram; every rank still decodes identical bytes, so the
    identical-tree invariant holds.
    """
    F, N = bins_t.shape
    B = p.total_bins
    L = p.num_leaves
    M = max_nodes(L)

    # voting-parallel keeps histograms local and aggregates only the voted
    # features inside _best_split_voting; full data-parallel psums every
    # histogram as it is built
    voting = p.voting_k > 0 and axis_name is not None
    F_search = num_bins.shape[0]           # ORIGINAL feature count
    mono_c = _mono_vec(p, F_search)

    # two-level (coarse-then-refine) histograms for strict leaf-wise
    # growth: same scheme as the depthwise grower (module comment above
    # _pool_coarse) — per-split coarse build + root-chosen fine-K refine;
    # the per-tile nodes kernel needs no extra VMEM gate (its scratch is
    # bounded by the ft cap regardless of K)
    from .pallas_hist import coarse_bins
    tl = (p.refine_k > 0 and p.two_level != "off"
          and bundle_map is None and mono_c is None and not voting
          and B >= 128 and F > p.refine_k
          and (p.two_level == "on" or N >= TWO_LEVEL_MIN_ROWS))
    _tl_gauge("lossguide", tl)
    SH = TWO_LEVEL_SHIFT
    Bc = coarse_bins(B, SH)
    Bh = Bc if tl else B                   # stored-histogram width
    K = p.refine_k
    num_bins_c = -(-num_bins // (1 << SH))

    def ar(x):
        # routed through the planner dispatch so the histogram
        # allreduce — THE data-parallel hot collective — shows up in
        # collective_{calls,bytes}_total (recorded per traced program)
        # AND takes the topology-planned route: with a compression
        # config the wire rides the quantized reduce-scatter +
        # all-gather (or the two-level hierarchical form on a known
        # multi-host topology); without one this traces exactly the
        # bare f32 psum it always did
        if not axis_name or voting:
            return x
        return _c_planned_psum(
            x, axis_name, cconfig,
            op="gbdt_hist_psum" if cconfig is not None else "psum")

    def unb(hist3, g, h, c):
        if bundle_map is None:
            return hist3
        return _unbundle_hists(hist3, bundle_map["gather_src"],
                               jnp.stack([g, h, c], -1))

    if voting:
        def pick(hist3, g, h, c, depth, lo, hi):
            if bundle_map is not None:
                # unbundle the LOCAL histograms before voting: gather and
                # residual are linear, so the selective psum of unbundled
                # columns equals unbundling the psum — votes and the
                # aggregated gains both live in ORIGINAL feature space.
                # The local node totals come from bundled column 0, whose
                # bins cover every row of the node exactly once
                ltot = jnp.sum(hist3[0], axis=0)
                hist3 = _unbundle_hists(hist3, bundle_map["gather_src"],
                                        ltot)
            return _best_split_voting(hist3, g, h, c, num_bins, feature_mask,
                                      depth, p, axis_name, lo, hi, mono_c)
    else:
        def pick(hist3, g, h, c, depth, lo, hi):
            return _best_split(unb(hist3, g, h, c), g, h, c, num_bins,
                               feature_mask, depth, p, lo, hi, mono_c)

    flat_bins = None
    vals8 = scales = None
    bins_pl = bins_t
    if not use_pallas:
        flat_bins = bins_t + (jnp.arange(F, dtype=jnp.int32) * B)[:, None]
    else:
        from .pallas_hist import prep_hist_vals, prepare_feature_tiles
        vals8, scales = prep_hist_vals(grad, hess, row_valid)
        # (G, ft, N) tile reshape ONCE per tree, not per split (the
        # reshape materializes a copy; see prepare_feature_tiles)
        bins_pl = prepare_feature_tiles(bins_t, B, F)

    # root
    root_hist = ar(_build_hist(bins_pl, flat_bins, grad, hess,
                               row_valid, F, B, use_pallas,
                               vals8, scales,
                               hist_shift=(SH if tl else 0),
                               hist_chunk=p.hist_chunk)
                   ).reshape(F, Bh, 3)
    root_stats = jnp.sum(root_hist[0], axis=0)
    if voting:
        root_stats = lax.psum(root_stats, axis_name)
    root_g, root_h, root_c = root_stats[0], root_stats[1], root_stats[2]

    topk = None
    root_fine = None
    if tl:
        def build_fine_k(bkp, mask):
            """(1, K, B, 3) fine histograms of the refined features for
            the masked rows."""
            if use_pallas:
                from .pallas_hist import build_hist_nodes_pallas
                slot = jnp.where(mask > 0, 0, -1).astype(jnp.int32)
                return build_hist_nodes_pallas(
                    bkp, slot, vals8, scales, 1, B,
                    interpret=(use_pallas == "interpret"),
                    hist_chunk=p.hist_chunk)
            return _build_hist_nodes_xla(
                bkp, grad, hess, mask,
                jnp.where(mask > 0, 0, -1).astype(jnp.int32), 1, K, B)

        topk, sel_k, bins_kp, root_fine, rbest0 = _tl_root_pick(
            root_hist, root_g, root_h, root_c, num_bins, num_bins_c,
            feature_mask, p, SH, K, bins_t, B, use_pallas,
            lambda bkp: build_fine_k(bkp, row_valid), ar)

    # per-node state
    zi = jnp.zeros(M, jnp.int32)
    zf = jnp.zeros(M, jnp.float32)
    state = dict(
        node_id=jnp.zeros(N, jnp.int32),
        hist=jnp.zeros((L + 1, F * Bh, 3), jnp.float32).at[0].set(
            root_hist.reshape(F * Bh, 3)),
        slot=zi,                                   # node -> hist slot
        sum_g=zf.at[0].set(root_g),
        sum_h=zf.at[0].set(root_h),
        sum_c=zf.at[0].set(root_c),
        depth=zi,
        best_gain=jnp.full(M, -jnp.inf, jnp.float32),
        best_feat=zi, best_bin=zi,
        best_gl=zf, best_hl=zf, best_cl=zf,
        active=jnp.zeros(M, jnp.bool_).at[0].set(True),
        split_feature=jnp.full(M, -1, jnp.int32),
        split_bin=zi,
        split_gain=zf,
        threshold=zf,
        left_child=jnp.full(M, -1, jnp.int32),
        right_child=jnp.full(M, -1, jnp.int32),
        num_nodes=jnp.ones((), jnp.int32),
        next_slot=jnp.ones((), jnp.int32),
        node_lo=jnp.full(M, -jnp.inf, jnp.float32),
        node_hi=jnp.full(M, jnp.inf, jnp.float32),
    )
    if tl:
        state["hist_f"] = jnp.zeros((L + 1, K * B, 3), jnp.float32).at[
            0].set(root_fine[0].reshape(K * B, 3))
        bg, bf_, bb, bgl, bhl, bcl = rbest0
    else:
        bg, bf_, bb, bgl, bhl, bcl = pick(root_hist, root_g, root_h,
                                          root_c,
                                          jnp.zeros((), jnp.int32),
                                          -jnp.inf, jnp.inf)
    state["best_gain"] = state["best_gain"].at[0].set(bg)
    state["best_feat"] = state["best_feat"].at[0].set(bf_)
    state["best_bin"] = state["best_bin"].at[0].set(bb)
    state["best_gl"] = state["best_gl"].at[0].set(bgl)
    state["best_hl"] = state["best_hl"].at[0].set(bhl)
    state["best_cl"] = state["best_cl"].at[0].set(bcl)

    def do_split(s):
        gains = jnp.where(s["active"], s["best_gain"], -jnp.inf)
        leaf = jnp.argmax(gains).astype(jnp.int32)
        feat, sbin = s["best_feat"][leaf], s["best_bin"][leaf]
        l_id = s["num_nodes"]
        r_id = s["num_nodes"] + 1

        in_leaf = s["node_id"] == leaf
        col_s, t1_s, lo_s, hi_s, df_s = _slot_route_params(
            feat, sbin, B, bundle_map)
        go_left = _route_left(bins_t[col_s, :], t1_s, lo_s, hi_s, df_s)
        new_node_id = jnp.where(in_leaf, jnp.where(go_left, l_id, r_id),
                                s["node_id"])

        # left child hist by one device pass, right by subtraction
        lmask = (new_node_id == l_id).astype(jnp.float32) * row_valid
        l_hist = ar(_build_hist(bins_pl, flat_bins, grad, hess, lmask, F, B,
                                use_pallas, vals8, scales,
                                hist_shift=(SH if tl else 0),
                                hist_chunk=p.hist_chunk))
        parent_slot = s["slot"][leaf]
        r_hist = s["hist"][parent_slot] - l_hist
        r_slot = s["next_slot"]
        hist = s["hist"].at[parent_slot].set(l_hist).at[r_slot].set(r_hist)

        lg, lh, lc = s["best_gl"][leaf], s["best_hl"][leaf], s["best_cl"][leaf]
        rg, rh, rc = s["sum_g"][leaf] - lg, s["sum_h"][leaf] - lh, s["sum_c"][leaf] - lc
        cdepth = s["depth"][leaf] + 1

        p_lo, p_hi = s["node_lo"][leaf], s["node_hi"][leaf]
        l_lo, l_hi, r_lo, r_hi = _mono_node_bounds(
            None if mono_c is None else mono_c[feat],
            p_lo, p_hi, lg, lh, rg, rh, p)

        hist_f = None
        if tl:
            lf = ar(build_fine_k(bins_kp, lmask))[0].reshape(K * B, 3)
            rf = s["hist_f"][parent_slot] - lf
            hist_f = (s["hist_f"].at[parent_slot].set(lf)
                      .at[r_slot].set(rf))
            c_hists = jnp.stack([l_hist, r_hist]).reshape(2, F, Bh, 3)
            f_hists = jnp.stack([lf, rf]).reshape(2, K, B, 3)
            cgm, ccum, _ = _tl_coarse_gains(
                c_hists, jnp.stack([lg, rg]), jnp.stack([lh, rh]),
                jnp.stack([lc, rc]), jnp.stack([cdepth, cdepth]),
                jnp.stack([l_lo, r_lo]), jnp.stack([l_hi, r_hi]),
                num_bins_c, feature_mask, p)
            cb = _tl_final_pick(
                cgm, ccum, f_hists, topk, jnp.stack([lg, rg]),
                jnp.stack([lh, rh]), jnp.stack([lc, rc]),
                jnp.stack([cdepth, cdepth]), jnp.stack([l_lo, r_lo]),
                jnp.stack([l_hi, r_hi]), num_bins, feature_mask, p, SH)
            (lbg, rbg), (lbf, rbf), (lbb, rbb) = cb[0], cb[1], cb[2]
            (lbgl, rbgl), (lbhl, rbhl), (lbcl, rbcl) = cb[3], cb[4], cb[5]
        else:
            lbg, lbf, lbb, lbgl, lbhl, lbcl = pick(
                l_hist.reshape(F, B, 3), lg, lh, lc, cdepth, l_lo, l_hi)
            rbg, rbf, rbb, rbgl, rbhl, rbcl = pick(
                r_hist.reshape(F, B, 3), rg, rh, rc, cdepth, r_lo, r_hi)

        thr = jnp.where(sbin >= 1, upper_bounds[feat, jnp.maximum(sbin - 1, 0)],
                        -jnp.inf)

        return dict(
            node_id=new_node_id,
            hist=hist,
            slot=s["slot"].at[l_id].set(parent_slot).at[r_id].set(r_slot),
            sum_g=s["sum_g"].at[l_id].set(lg).at[r_id].set(rg),
            sum_h=s["sum_h"].at[l_id].set(lh).at[r_id].set(rh),
            sum_c=s["sum_c"].at[l_id].set(lc).at[r_id].set(rc),
            depth=s["depth"].at[l_id].set(cdepth).at[r_id].set(cdepth),
            best_gain=s["best_gain"].at[l_id].set(lbg).at[r_id].set(rbg),
            best_feat=s["best_feat"].at[l_id].set(lbf).at[r_id].set(rbf),
            best_bin=s["best_bin"].at[l_id].set(lbb).at[r_id].set(rbb),
            best_gl=s["best_gl"].at[l_id].set(lbgl).at[r_id].set(rbgl),
            best_hl=s["best_hl"].at[l_id].set(lbhl).at[r_id].set(rbhl),
            best_cl=s["best_cl"].at[l_id].set(lbcl).at[r_id].set(rbcl),
            active=s["active"].at[leaf].set(False).at[l_id].set(True)
                   .at[r_id].set(True),
            split_feature=s["split_feature"].at[leaf].set(feat),
            split_bin=s["split_bin"].at[leaf].set(sbin),
            split_gain=s["split_gain"].at[leaf].set(s["best_gain"][leaf]),
            threshold=s["threshold"].at[leaf].set(thr),
            left_child=s["left_child"].at[leaf].set(l_id),
            right_child=s["right_child"].at[leaf].set(r_id),
            num_nodes=s["num_nodes"] + 2,
            next_slot=s["next_slot"] + 1,
            node_lo=s["node_lo"].at[l_id].set(l_lo).at[r_id].set(r_lo),
            node_hi=s["node_hi"].at[l_id].set(l_hi).at[r_id].set(r_hi),
            **({"hist_f": hist_f} if tl else {}),
        )

    def maybe_intermediate_split(s):
        out = do_split(s)
        if mono_c is None or p.monotone_method not in ("intermediate",
                                                       "advanced"):
            return out
        # intermediate: bounds come from the OPPOSITE subtree's extremes
        # over the whole current tree; the fresh children re-pick under
        # the refreshed (looser) bounds
        out = _refresh_intermediate(out, mono_c, p)
        l_id, r_id = out["num_nodes"] - 2, out["num_nodes"] - 1
        for cid in (l_id, r_id):
            chist = out["hist"][out["slot"][cid]].reshape(F, B, 3)
            cbg, cbf, cbb, cbgl, cbhl, cbcl = pick(
                chist, out["sum_g"][cid], out["sum_h"][cid],
                out["sum_c"][cid], out["depth"][cid],
                out["node_lo"][cid], out["node_hi"][cid])
            out["best_gain"] = out["best_gain"].at[cid].set(cbg)
            out["best_feat"] = out["best_feat"].at[cid].set(cbf)
            out["best_bin"] = out["best_bin"].at[cid].set(cbb)
            out["best_gl"] = out["best_gl"].at[cid].set(cbgl)
            out["best_hl"] = out["best_hl"].at[cid].set(cbhl)
            out["best_cl"] = out["best_cl"].at[cid].set(cbcl)
        return out

    def body(_, s):
        gains = jnp.where(s["active"], s["best_gain"], -jnp.inf)
        can_split = jnp.max(gains) > p.min_gain_to_split
        return lax.cond(can_split, maybe_intermediate_split, lambda x: x, s)

    state = lax.fori_loop(0, L - 1, body, state)

    node_value = _leaf_output(state["sum_g"], state["sum_h"],
                              p.lambda_l1, p.lambda_l2)
    if mono_c is not None:
        if p.monotone_method in ("intermediate", "advanced"):
            _, _, node_value = _tree_bounds(
                state["split_feature"], state["split_bin"],
                state["left_child"], state["right_child"], node_value,
                mono_c, p, n_iters=6)
        else:
            node_value = jnp.clip(node_value, state["node_lo"],
                                  state["node_hi"])
    node_value = learning_rate * node_value
    leaf_value = jnp.where(state["left_child"] < 0, node_value, 0.0)

    tree = Tree(split_feature=state["split_feature"],
                split_bin=state["split_bin"],
                threshold=state["threshold"],
                split_gain=state["split_gain"],
                left_child=state["left_child"],
                right_child=state["right_child"],
                leaf_value=leaf_value,
                node_value=node_value,
                num_nodes=state["num_nodes"],
                default_left=jnp.ones(M, jnp.bool_),
                node_count=state["sum_c"],
                missing_zero=jnp.zeros(M, jnp.bool_))
    return tree, state["node_id"]


# -- depth-level growth ------------------------------------------------------
#
# The leaf-wise grower above launches one full-data histogram pass per split
# (num_leaves-1 sequential passes per tree).  The depth-level grower selects
# up to ``n_slots`` best leaves per wave (gain-ordered, budget-capped — the
# depthwise/lossguide hybrid used by accelerator GBDT implementations) and
# builds ALL their left-child histograms in ONE data pass, with the node
# assignment folded into the matmul lane dimension (pallas_hist.py,
# build_hist_nodes_pallas).  Right children come from histogram subtraction
# as before.  Typical tree cost: 1 root pass + ceil(log2-ish) wave passes
# (≈6 for 31 leaves) instead of 31.


def _hist_updates(grad, hess, mask):
    """(N, 3) [g·m, h·m, count] histogram update values.

    On TPU the values compute in the INGEST dtype (bf16 under fused
    ingest — grad's dtype decides) so the producer chain feeding the
    scatter/kernel stays narrow and scatter input fusion materializes
    the narrow buffer; accumulation is always f32.  On other backends
    the products promote straight to f32 — XLA:CPU materializes the
    scatter's f32 updates operand regardless, and a bf16 intermediate
    would only ADD a buffer (measured +2.3% bytes on the bench shape;
    same backend-quirk class as the CPU donation guard in
    models/dl/training.py)."""
    if jax.default_backend() == "tpu":
        count = (mask > 0).astype(grad.dtype)
        m = mask.astype(grad.dtype)
        return jnp.stack([grad * m, hess * m, count], axis=-1)
    count = (mask > 0).astype(jnp.float32)
    return jnp.stack([grad * mask, hess * mask, count], axis=-1)


def _build_hist_nodes_xla(flat_bins, grad, hess, mask, slot, n_slots, F, B):
    """XLA scatter fallback: (n_slots, F, B, 3) node-batched histograms.
    Rows with slot -1 scatter into a junk slot that is dropped."""
    s = jnp.where(slot >= 0, slot, n_slots)
    ids = flat_bins + (s * (F * B))[None, :]                  # (F, N)
    upd = _hist_updates(grad, hess, mask)                         # (N,3)
    upd = jnp.broadcast_to(upd[None, :, :], (F,) + upd.shape)     # (F,N,3)
    hist = jnp.zeros(((n_slots + 1) * F * B, 3), jnp.float32)
    hist = hist.at[ids].add(upd.astype(jnp.float32))
    return hist.reshape(n_slots + 1, F, B, 3)[:n_slots]


def _build_hist_nodes(bins_t, flat_bins, vals8, scales, grad, hess, mask,
                      slot, n_slots, F, B, use_pallas, hist_chunk=0):
    """``bins_t`` may be the flat (F, N) matrix OR the pre-reshaped
    (G, ft, N) tile layout (prepare_feature_tiles, F == G*ft always) —
    growers hoist the reshape out of their loops because it materializes
    a copy."""
    if use_pallas:
        from .pallas_hist import build_hist_nodes_pallas
        return build_hist_nodes_pallas(bins_t, slot, vals8, scales, n_slots,
                                       B,
                                       interpret=(use_pallas == "interpret"),
                                       hist_chunk=hist_chunk)
    return _build_hist_nodes_xla(flat_bins, grad, hess, mask, slot,
                                 n_slots, F, B)


def _slot_route_params(feat, tbin, B, bundle_map):
    """Universal routing params for splits chosen on ORIGINAL features.

    Returns (col, t1, rlo, rhi, dflt): rows of column ``col`` go left iff
    ``x in (rlo, rhi] ? x <= t1 : dflt``.  Plain training routes the
    feature's own column with the full range, so the condition degrades to
    ``x <= tbin``; under EFB the split feature's BUNDLED range maps the
    original-bin threshold onto the bundled column (rank(b) = b +
    (b < default) — binning.py FeatureBundler.route_tables), and
    out-of-range rows (feature at its default bin) take the default-bin
    direction.  One formula, so the pallas kernel and every XLA routing
    path stay identical between plain and EFB training."""
    if bundle_map is None:
        return (feat, tbin, jnp.full_like(feat, -1),
                jnp.full_like(feat, B), jnp.ones_like(feat))
    col = bundle_map["col"][feat]
    lo = bundle_map["lo"][feat]
    hi = bundle_map["hi"][feat]
    d = bundle_map["default_bin"][feat]
    t1 = lo + tbin + (tbin < d).astype(tbin.dtype)
    dflt = (d <= tbin).astype(jnp.int32)
    return col, t1, lo, hi, dflt


def _route_left(xb, t1, rlo, rhi, dflt):
    in_range = (xb > rlo) & (xb <= rhi)
    return jnp.where(in_range, xb <= t1, dflt != 0)


def _unbundle_hists(hists, gather_src, tot):
    """Bundled histograms (..., Fb, Bb, 3) → ORIGINAL-feature histograms
    (..., F, B, 3) by static gather; a feature's DEFAULT bin carries the
    residual node mass (rows default in f sit at bundled bin 0 or inside
    other features' ranges).  Exact for exclusive bundles — which is why
    EFB training grows the BIT-IDENTICAL tree to unbundled training while
    the data pass stays compressed (the LightGBM scheme: EFB accelerates
    histogram construction, trees never leave original feature space).

    ``tot``: node totals (..., 3) [grad, hess, count]."""
    lead = hists.shape[:-3]
    F, B = gather_src.shape
    flat = hists.reshape(lead + (-1, 3))
    V = jnp.take(flat, jnp.maximum(gather_src, 0).reshape(-1), axis=-2)
    V = V.reshape(lead + (F, B, 3))
    V = jnp.where((gather_src >= 0)[..., None], V, 0.0)
    resid = tot[..., None, None, :] - jnp.sum(V, axis=-2, keepdims=True)
    return jnp.where((gather_src == -2)[..., None], resid, V)


def default_n_slots(num_leaves: int) -> int:
    """Node slots per wave: 16 slots × 8 value channels = the full 128-lane
    MXU tile; fewer when the leaf budget is smaller."""
    return max(1, min(16, num_leaves - 1))


@functools.partial(jax.jit, static_argnames=("p", "axis_name", "use_pallas",
                                             "n_slots", "cconfig"))
def grow_tree_depthwise(bins_t: jnp.ndarray,     # (F, N) int32
                        grad: jnp.ndarray,       # (N,) f32
                        hess: jnp.ndarray,       # (N,) f32
                        row_valid: jnp.ndarray,  # (N,) f32 bag/GOSS weight
                        feature_mask: jnp.ndarray,   # (F,) bool
                        upper_bounds: jnp.ndarray,   # (F, B-1) f32
                        num_bins: jnp.ndarray,       # (F,) int32
                        learning_rate: float,
                        p: GrowthParams,
                        axis_name: Optional[str] = None,
                        use_pallas: bool = False,
                        n_slots: int = 16,
                        bundle_map: Optional[dict] = None,
                        cconfig=None,
                        ) -> Tuple[Tree, jnp.ndarray]:
    """Grow one tree wave-by-wave; returns (tree, per-row leaf node ids).

    Semantics match :func:`grow_tree` except for the order leaves are split
    in: within a wave all selected leaves split simultaneously, so when the
    leaf budget runs out mid-wave the marginal leaves may differ from strict
    best-first order.  Split decisions per node are identical.

    ``cconfig``: quantized wire for the per-wave histogram psum — see
    :func:`grow_tree`.
    """
    from .pallas_hist import prep_hist_vals

    F, N = bins_t.shape
    B = p.total_bins
    L = p.num_leaves
    M = max_nodes(L)
    S = n_slots
    JUNK = M - 1              # node index never reached (num_nodes <= M-1)
    HJUNK = L                 # hist-buffer junk slot
    rows = jnp.arange(N)

    def ar(x):
        # same planner dispatch as grow_tree's: planned route when a
        # config is in play, the bare f32 psum trace otherwise
        if not axis_name:
            return x
        return _c_planned_psum(
            x, axis_name, cconfig,
            op="gbdt_hist_psum" if cconfig is not None else "psum")

    vals8, scales = (prep_hist_vals(grad, hess, row_valid) if use_pallas
                     else (None, None))
    flat_bins = None
    bins_pl = bins_t
    if not use_pallas:
        flat_bins = bins_t + (jnp.arange(F, dtype=jnp.int32) * B)[:, None]
    else:
        # the (G, ft, N) tile reshape materializes a copy (ft < 8 pads
        # sublanes): done ONCE per tree here — inside the wave loop's
        # cond XLA re-materializes it every level (~2.7 ms/tree @B=256)
        from .pallas_hist import prepare_feature_tiles
        bins_pl = prepare_feature_tiles(bins_t, B, F)

    def build(slot):
        return ar(_build_hist_nodes(bins_pl, flat_bins, vals8, scales, grad,
                                    hess, row_valid, slot, S, F, B,
                                    use_pallas, hist_chunk=p.hist_chunk))

    F_search = num_bins.shape[0]           # ORIGINAL feature count
    mono_c = _mono_vec(p, F_search)

    # two-level (coarse-then-refine) histograms: see the module comment
    # above _pool_coarse.  Structural exclusions keep every exactness-
    # pinned path (EFB bit-identity, monotone refresh re-picks) at full
    # resolution; "auto" additionally requires big data so small-data
    # tests keep exact-255 semantics
    from .pallas_hist import coarse_bins, fused_refine_fits
    tl = (p.refine_k > 0 and p.two_level != "off"
          and bundle_map is None and mono_c is None
          and B >= 128 and F > p.refine_k
          and (p.two_level == "on" or N >= TWO_LEVEL_MIN_ROWS)
          # the fused pass carries the K refined features' full-res
          # scratch/accumulator in VMEM — an uncapped refine_features
          # falls back to full-resolution growth instead of failing at
          # Mosaic compile time
          and (not use_pallas
               or fused_refine_fits(F, B, S, TWO_LEVEL_SHIFT,
                                    p.refine_k)))
    _tl_gauge("depthwise", tl)
    SH = TWO_LEVEL_SHIFT
    Bc = coarse_bins(B, SH)
    Bh = Bc if tl else B                   # stored-histogram width
    K = p.refine_k
    num_bins_c = -(-num_bins // (1 << SH))

    def unb(hists, g, h, c):
        if bundle_map is None:
            return hists
        return _unbundle_hists(hists, bundle_map["gather_src"],
                               jnp.stack([g, h, c], -1))

    pick = functools.partial(_best_split, num_bins=num_bins,
                             feature_mask=feature_mask, p=p, mono_c=mono_c)
    vpick = jax.vmap(lambda h, g, hh, c, d, lo, hi: pick(
        h, g, hh, c, node_depth=d, node_lo=lo, node_hi=hi))

    def build_fine_k(bins_kp, slot_vec, n_slots_):
        """Full-resolution histograms of the refined features for the
        two-level refine pass.  ``bins_kp`` is the PRE-GATHERED and
        pre-tiled (pallas) / pre-flattened (XLA) K-feature bin matrix —
        prepared once per tree right after the root picks ``topk`` so the
        wave loop never re-materializes the copy (XLA cannot hoist it out
        of while_loop)."""
        if use_pallas:
            from .pallas_hist import build_hist_nodes_pallas
            return build_hist_nodes_pallas(
                bins_kp, slot_vec, vals8, scales, n_slots_, B,
                interpret=(use_pallas == "interpret"),
                hist_chunk=p.hist_chunk)
        return _build_hist_nodes_xla(bins_kp, grad, hess, row_valid,
                                     slot_vec, n_slots_, K, B)

    # root: one batched pass with every row in slot 0.  On the pallas path
    # this rides the FUSED kernel with a degenerate all-left split of leaf 0
    # (t1=B → every row left, child id 0 → node ids unchanged): the fused
    # kernel computes its slot mask once per chunk instead of once per
    # (feature-tile, chunk) step, measured ~25% faster than the nodes
    # kernel for the same histograms
    if use_pallas:
        from .pallas_hist import fused_geometry, route_and_hist_pallas
    if use_pallas and fused_geometry(F, B, S) is not None:
        jv = jnp.full((S,), JUNK, jnp.int32)
        _, root_hists = route_and_hist_pallas(
            bins_pl, jnp.zeros(N, jnp.int32), jv.at[0].set(0),
            jnp.take(bins_t, jnp.zeros(S, jnp.int32), axis=0),
            jnp.full((S,), B, jnp.int32),
            jnp.full((S,), -1, jnp.int32), jnp.full((S,), B, jnp.int32),
            jnp.ones(S, jnp.int32), jnp.zeros(S, jnp.int32),
            jnp.zeros(S, jnp.int32), vals8, scales, S, B,
            hist_shift=(SH if tl else 0),
            interpret=(use_pallas == "interpret"),
            hist_chunk=p.hist_chunk)
        root_hist = ar(root_hists)[0]                      # (F, Bh, 3)
    else:
        root_hist = build(jnp.zeros(N, jnp.int32))[0]      # (F, B, 3)
        if tl:
            root_hist = _pool_coarse(root_hist, Bc, SH)
    root_stats = jnp.sum(root_hist[0], axis=0)
    root_g, root_h, root_c = root_stats[0], root_stats[1], root_stats[2]

    zi = jnp.zeros(M, jnp.int32)
    zf = jnp.zeros(M, jnp.float32)
    topk = None
    root_fine = None
    if tl:
        # the refined feature set is chosen ONCE per tree from the ROOT's
        # coarse per-feature gains: a fixed set lets every wave refine
        # LEFT children only (S slot lanes, the full 128-lane tile) and
        # derive right-child fine histograms by subtraction from the
        # parent's stored fine-K histograms — a per-wave adaptive set
        # needs both children built fresh (2S lanes), which doubles the
        # refine matmul and was measured to eat the coarse pass's win
        rslot0 = jnp.where(row_valid > 0, 0, -1).astype(jnp.int32)
        topk, sel_k, bins_kp, root_fine, rbest0 = _tl_root_pick(
            root_hist, root_g, root_h, root_c, num_bins, num_bins_c,
            feature_mask, p, SH, K, bins_t, B, use_pallas,
            lambda bkp: build_fine_k(bkp, rslot0, 1), ar)
        bg, bf_, bb, bgl, bhl, bcl = rbest0
    else:
        bg, bf_, bb, bgl, bhl, bcl = pick(
            unb(root_hist, root_g, root_h, root_c),
            root_g, root_h, root_c,
            node_depth=jnp.zeros((), jnp.int32),
            node_lo=-jnp.inf, node_hi=jnp.inf)
    state = dict(
        node_id=jnp.zeros(N, jnp.int32),
        hist=jnp.zeros((L + 2, F * Bh, 3), jnp.float32).at[0].set(
            root_hist.reshape(F * Bh, 3)),
        slot=zi,
        sum_g=zf.at[0].set(root_g),
        sum_h=zf.at[0].set(root_h),
        sum_c=zf.at[0].set(root_c),
        depth=zi,
        best_gain=jnp.full(M, -jnp.inf, jnp.float32).at[0].set(bg),
        best_feat=zi.at[0].set(bf_), best_bin=zi.at[0].set(bb),
        best_gl=zf.at[0].set(bgl), best_hl=zf.at[0].set(bhl),
        best_cl=zf.at[0].set(bcl),
        active=jnp.zeros(M, jnp.bool_).at[0].set(True),
        split_feature=jnp.full(M, -1, jnp.int32),
        split_bin=zi,
        split_gain=zf,
        threshold=zf,
        left_child=jnp.full(M, -1, jnp.int32),
        right_child=jnp.full(M, -1, jnp.int32),
        num_nodes=jnp.ones((), jnp.int32),
        next_slot=jnp.ones((), jnp.int32),
        node_lo=jnp.full(M, -jnp.inf, jnp.float32),
        node_hi=jnp.full(M, jnp.inf, jnp.float32),
    )
    if tl:
        state["hist_f"] = jnp.zeros((L + 2, K * B, 3), jnp.float32).at[
            0].set(root_fine[0].reshape(K * B, 3))

    def cond(s):
        leaves = (s["num_nodes"] + 1) // 2
        gains = jnp.where(s["active"], s["best_gain"], -jnp.inf)
        return (leaves < L) & (jnp.max(gains) > p.min_gain_to_split)

    def wave(s):
        gains = jnp.where(s["active"], s["best_gain"], -jnp.inf)
        tv, ti = lax.top_k(gains, S)                     # leaves to split
        budget = L - (s["num_nodes"] + 1) // 2
        jidx = jnp.arange(S, dtype=jnp.int32)
        valid = (tv > p.min_gain_to_split) & (jidx < budget)
        n_valid = jnp.sum(valid.astype(jnp.int32))
        parents = jnp.where(valid, ti, JUNK)

        # valid slots are packed first by top_k's sort, so child ids are
        # contiguous: left 2j, right 2j+1 past num_nodes
        l_ids = jnp.where(valid, s["num_nodes"] + 2 * jidx, JUNK)
        r_ids = jnp.where(valid, s["num_nodes"] + 2 * jidx + 1, JUNK)

        # route rows (new node id + histogram slot; JUNK parents match no
        # row) and build every selected leaf's left-child histogram in ONE
        # pass over the binned matrix — the fused kernel computes each
        # chunk's routing once and keeps it in VMEM for the histogram tiles
        rt_col, rt_t1, rt_lo, rt_hi, rt_df = _slot_route_params(
            s["best_feat"][parents], s["best_bin"][parents], B, bundle_map)
        leaves_after = (s["num_nodes"] + 1) // 2 + n_valid
        lf = None
        if use_pallas:
            from .pallas_hist import route_and_hist_pallas

            def fused_wave(_):
                out = route_and_hist_pallas(
                    bins_pl, s["node_id"], parents,
                    jnp.take(bins_t, rt_col, axis=0), rt_t1, rt_lo,
                    rt_hi, rt_df, l_ids, r_ids, vals8, scales, S, B,
                    hist_shift=(SH if tl else 0),
                    sel_k=(sel_k if tl else None),
                    interpret=(use_pallas == "interpret"),
                    hist_chunk=p.hist_chunk)
                # under tl the SAME pass also emits the refined features'
                # full-resolution left-child histograms (one bins read,
                # one routing, one slot-masked value build for both
                # levels — a separate refine pass cost ~2.8 ms/wave)
                return out if tl else out + (jnp.zeros(0, jnp.float32),)

            def route_only(_):
                # this wave fills the leaf budget: its child histograms can
                # never feed another split, so skip the one-hot pass (one of
                # five full-data passes per 31-leaf tree) and route in plain
                # XLA from the gathered split-column rows.  Child pick
                # stats (sum_g/h/c) come from the parent pick, not from
                # these histograms, so zeros are safe.
                sel = jnp.take(bins_t, rt_col, axis=0)
                inleaf = s["node_id"][None, :] == parents[:, None]   # (S, N)
                gl = _route_left(sel, rt_t1[:, None], rt_lo[:, None],
                                 rt_hi[:, None], rt_df[:, None])
                new = (jnp.sum(jnp.where(inleaf & gl, l_ids[:, None], 0), 0)
                       + jnp.sum(jnp.where(inleaf & ~gl, r_ids[:, None], 0), 0)
                       + jnp.where(jnp.any(inleaf, 0), 0, s["node_id"]))
                zf_ = (jnp.zeros((S, K, B, 3), jnp.float32) if tl
                       else jnp.zeros(0, jnp.float32))
                return new, jnp.zeros((S, F, Bh, 3), jnp.float32), zf_

            new_node_id, l_hists, lf = lax.cond(leaves_after >= L,
                                                route_only, fused_wave,
                                                None)
            l_hists = ar(l_hists)
            if tl:
                lf = ar(lf)
        else:
            slot_of_leaf = jnp.full(M, -1, jnp.int32).at[parents].set(
                jnp.where(valid, jidx, -1))
            rslot = slot_of_leaf[s["node_id"]]           # (N,)
            safe = jnp.maximum(rslot, 0)
            go_left = _route_left(bins_t[rt_col[safe], rows], rt_t1[safe],
                                  rt_lo[safe], rt_hi[safe], rt_df[safe])
            new_node_id = jnp.where(
                rslot >= 0,
                jnp.where(go_left, l_ids[rslot], r_ids[rslot]),
                s["node_id"])
            bslot = jnp.where(go_left, rslot, -1)
            l_hists = build(bslot)                       # (S, F, B, 3)
            if tl:
                l_hists = _pool_coarse(l_hists, Bc, SH)
        l_flat = l_hists.reshape(S, F * Bh, 3)
        pslot = jnp.where(valid, s["slot"][parents], HJUNK)
        r_flat = s["hist"][pslot] - l_flat
        r_slots = jnp.where(valid, s["next_slot"] + jidx, HJUNK)
        hist = s["hist"].at[pslot].set(l_flat).at[r_slots].set(r_flat)

        lg, lh, lc = (s["best_gl"][parents], s["best_hl"][parents],
                      s["best_cl"][parents])
        rg = s["sum_g"][parents] - lg
        rh = s["sum_h"][parents] - lh
        rc = s["sum_c"][parents] - lc
        cdepth = s["depth"][parents] + 1

        p_lo, p_hi = s["node_lo"][parents], s["node_hi"][parents]   # (S,)
        l_lo, l_hi, r_lo, r_hi = _mono_node_bounds(
            None if mono_c is None else mono_c[s["best_feat"][parents]],
            p_lo, p_hi, lg, lh, rg, rh, p)
        c_lo = jnp.concatenate([l_lo, r_lo])
        c_hi = jnp.concatenate([l_hi, r_hi])

        child_hists = jnp.concatenate(
            [l_flat.reshape(S, F, Bh, 3), r_flat.reshape(S, F, Bh, 3)])
        cg = jnp.concatenate([lg, rg])
        ch = jnp.concatenate([lh, rh])
        cc = jnp.concatenate([lc, rc])
        cd = jnp.concatenate([cdepth, cdepth])
        if tl:
            cgm, ccum, _ = _tl_coarse_gains(
                child_hists, cg, ch, cc, cd, c_lo, c_hi,
                num_bins_c, feature_mask, p)
            if lf is None:
                # XLA fallback: the fused kernel isn't in play, so the
                # refine histograms need their own (budget-gated) build
                lslot = (jnp.full(M, -1, jnp.int32)
                         .at[l_ids].set(jidx).at[JUNK].set(-1))

                def fine(_):
                    return build_fine_k(bins_kp, lslot[new_node_id], S)

                def fine_zeros(_):
                    # budget-filling wave: the children never split
                    # again — skip like the coarse route_only shortcut
                    # (zero hists fail min_data and pick -inf)
                    return jnp.zeros((S, K, B, 3), jnp.float32)

                lf = ar(lax.cond(leaves_after >= L, fine_zeros, fine,
                                 None))
            lf_flat = lf.reshape(S, K * B, 3)
            rf_flat = s["hist_f"][pslot] - lf_flat
            f_hists = jnp.concatenate([lf_flat.reshape(S, K, B, 3),
                                       rf_flat.reshape(S, K, B, 3)])
            cbg, cbf, cbb, cbgl, cbhl, cbcl = _tl_final_pick(
                cgm, ccum, f_hists, topk, cg, ch, cc, cd, c_lo, c_hi,
                num_bins, feature_mask, p, SH)
        else:
            cbg, cbf, cbb, cbgl, cbhl, cbcl = vpick(
                unb(child_hists, cg, ch, cc), cg, ch, cc, cd, c_lo, c_hi)

        cids = jnp.concatenate([l_ids, r_ids])           # (2S,)
        thr = jnp.where(s["best_bin"][parents] >= 1,
                        upper_bounds[s["best_feat"][parents],
                                     jnp.maximum(s["best_bin"][parents] - 1, 0)],
                        -jnp.inf)

        out = dict(
            node_id=new_node_id,
            hist=hist,
            slot=s["slot"].at[l_ids].set(pslot).at[r_ids].set(r_slots),
            sum_g=s["sum_g"].at[cids].set(cg),
            sum_h=s["sum_h"].at[cids].set(ch),
            sum_c=s["sum_c"].at[cids].set(cc),
            depth=s["depth"].at[cids].set(cd),
            best_gain=s["best_gain"].at[cids].set(cbg),
            best_feat=s["best_feat"].at[cids].set(cbf),
            best_bin=s["best_bin"].at[cids].set(cbb),
            best_gl=s["best_gl"].at[cids].set(cbgl),
            best_hl=s["best_hl"].at[cids].set(cbhl),
            best_cl=s["best_cl"].at[cids].set(cbcl),
            active=s["active"].at[parents].set(False).at[cids].set(True),
            split_feature=s["split_feature"].at[parents].set(
                jnp.where(valid, s["best_feat"][parents], -1)),
            split_bin=s["split_bin"].at[parents].set(s["best_bin"][parents]),
            split_gain=s["split_gain"].at[parents].set(
                jnp.where(valid, s["best_gain"][parents], 0.0)),
            threshold=s["threshold"].at[parents].set(thr),
            left_child=s["left_child"].at[parents].set(l_ids),
            right_child=s["right_child"].at[parents].set(r_ids),
            num_nodes=s["num_nodes"] + 2 * n_valid,
            next_slot=s["next_slot"] + n_valid,
            node_lo=s["node_lo"].at[cids].set(c_lo),
            node_hi=s["node_hi"].at[cids].set(c_hi),
        )
        if tl:
            out["hist_f"] = (s["hist_f"].at[pslot].set(lf_flat)
                             .at[r_slots].set(rf_flat))
        if mono_c is not None and p.monotone_method in ("intermediate",
                                                        "advanced"):
            # whole-tree refresh (opposite-subtree extremes, or the exact
            # pairwise set for advanced); this wave's children re-pick
            # under the refreshed (looser-than-midpoint) bounds
            out = _refresh_intermediate(out, mono_c, p)
            cbg2, cbf2, cbb2, cbgl2, cbhl2, cbcl2 = vpick(
                unb(child_hists, cg, ch, cc), cg, ch, cc, cd,
                out["node_lo"][cids], out["node_hi"][cids])
            out["best_gain"] = out["best_gain"].at[cids].set(cbg2)
            out["best_feat"] = out["best_feat"].at[cids].set(cbf2)
            out["best_bin"] = out["best_bin"].at[cids].set(cbb2)
            out["best_gl"] = out["best_gl"].at[cids].set(cbgl2)
            out["best_hl"] = out["best_hl"].at[cids].set(cbhl2)
            out["best_cl"] = out["best_cl"].at[cids].set(cbcl2)
        # the junk row absorbed every masked-out write; scrub it
        out["active"] = out["active"].at[JUNK].set(False)
        out["best_gain"] = out["best_gain"].at[JUNK].set(-jnp.inf)
        out["split_feature"] = out["split_feature"].at[JUNK].set(-1)
        out["left_child"] = out["left_child"].at[JUNK].set(-1)
        out["right_child"] = out["right_child"].at[JUNK].set(-1)
        return out

    state = lax.while_loop(cond, wave, state)

    node_value = _leaf_output(state["sum_g"], state["sum_h"],
                              p.lambda_l1, p.lambda_l2)
    if mono_c is not None:
        if p.monotone_method in ("intermediate", "advanced"):
            _, _, node_value = _tree_bounds(
                state["split_feature"], state["split_bin"],
                state["left_child"], state["right_child"], node_value,
                mono_c, p, n_iters=6)
        else:
            node_value = jnp.clip(node_value, state["node_lo"],
                                  state["node_hi"])
    node_value = learning_rate * node_value
    leaf_value = jnp.where(state["left_child"] < 0, node_value, 0.0)
    tree = Tree(split_feature=state["split_feature"],
                split_bin=state["split_bin"],
                threshold=state["threshold"],
                split_gain=state["split_gain"],
                left_child=state["left_child"],
                right_child=state["right_child"],
                leaf_value=leaf_value,
                node_value=node_value,
                num_nodes=state["num_nodes"],
                default_left=jnp.ones(M, jnp.bool_),
                node_count=state["sum_c"],
                missing_zero=jnp.zeros(M, jnp.bool_))
    return tree, state["node_id"]


# -- feature-parallel growth -------------------------------------------------
#
# LightGBM's ``tree_learner=feature_parallel`` (vertical partitioning; the
# reference only passes the string through to native code,
# params/BaseTrainParams.scala:99): every worker holds ALL rows but only a
# SLICE of the features.  Histograms never cross the interconnect — each
# rank scans its own feature columns, local best splits ride one tiny
# all-gather, and the winning split's owner broadcasts the row routing via
# a psum of owner-exclusive masks.  Communication per wave is O(S·N) bits
# + O(ranks·S) floats instead of O(F·B) histograms — the right trade when
# features outnumber rows.


@functools.partial(jax.jit, static_argnames=("p", "axis_name", "use_pallas",
                                             "n_slots"))
def grow_tree_feature_parallel(
        bins_t: jnp.ndarray,          # (F_local, N) int32 — THIS RANK's slice
        grad: jnp.ndarray,            # (N,) f32 replicated
        hess: jnp.ndarray,            # (N,) f32 replicated
        row_valid: jnp.ndarray,       # (N,) f32 replicated
        feature_mask: jnp.ndarray,    # (F_local,) bool
        upper_bounds: jnp.ndarray,    # (F_local, B-1) f32
        num_bins: jnp.ndarray,        # (F_local,) int32
        learning_rate: float,
        p: GrowthParams,
        axis_name: str,
        use_pallas: bool = False,
        n_slots: int = 16,
        bundle_map: Optional[dict] = None,
) -> Tuple[Tree, jnp.ndarray]:
    """Depth-level growth with the FEATURE axis sharded over ``axis_name``.

    Returns the identical tree on every rank; ``split_feature`` carries
    GLOBAL feature ids (rank · F_local + local id).  Semantics match
    :func:`grow_tree_depthwise` on the unsharded data.

    Under EFB, ``bins_t`` holds THIS RANK's bundled columns (each rank
    bundles its own slice, padded to a common width) and ``bundle_map``
    its route tables: local histograms unbundle before every pick, and
    the owner routes splits through the universal routing form — trees
    stay in ORIGINAL (global) feature space exactly like the other
    growers' EFB paths.
    """
    from .pallas_hist import prep_hist_vals

    FL, N = bins_t.shape              # bundled column count under EFB
    F_loc = num_bins.shape[0]         # ORIGINAL features on this rank
    B = p.total_bins
    L = p.num_leaves
    M = max_nodes(L)
    S = n_slots
    JUNK = M - 1
    rank = lax.axis_index(axis_name)

    vals8, scales = (prep_hist_vals(grad, hess, row_valid) if use_pallas
                     else (None, None))
    flat_bins = None
    bins_pl = bins_t
    if not use_pallas:
        flat_bins = bins_t + (jnp.arange(FL, dtype=jnp.int32) * B)[:, None]
    else:
        from .pallas_hist import prepare_feature_tiles
        bins_pl = prepare_feature_tiles(bins_t, B, FL)

    def build(slot):
        # LOCAL histograms only — the defining property of feature-parallel
        return _build_hist_nodes(bins_pl, flat_bins, vals8, scales, grad,
                                 hess, row_valid, slot, S, FL, B, use_pallas,
                                 hist_chunk=p.hist_chunk)

    # constraints come from the static tuple in p, so the GLOBAL vector is
    # available on every rank; each rank's gain pass slices its own span
    n_ranks = lax.axis_size(axis_name)
    mono_global = _mono_vec(p, F_loc * n_ranks)
    mono_local = (None if mono_global is None else
                  lax.dynamic_slice(mono_global, (rank * F_loc,), (F_loc,)))

    def pick_local(hist, g, h, c, depth, lo, hi):
        if bundle_map is not None:
            # unbundle this rank's LOCAL bundled histograms to its
            # original features before the gain pass (the same linearity
            # the voting pick leans on)
            hist = _unbundle_hists(hist, bundle_map["gather_src"],
                                   jnp.stack([g, h, c], -1))
        return _best_split(hist, g, h, c, num_bins, feature_mask, depth, p,
                           lo, hi, mono_local)

    def global_pick(hist_s, g, h, c, depth, lo, hi):
        """Per-node: local best over this rank's features, then a tiny
        all-gather picks the winner; returns global feature ids and the
        owner's raw-value threshold."""
        bg, bf_, bb, bgl, bhl, bcl = pick_local(hist_s, g, h, c, depth,
                                                lo, hi)
        thr = jnp.where(bb >= 1, upper_bounds[bf_, jnp.maximum(bb - 1, 0)],
                        -jnp.inf)
        packed = jnp.stack([bg, (rank * F_loc + bf_).astype(jnp.float32),
                            bb.astype(jnp.float32), bgl, bhl, bcl, thr])
        allp = lax.all_gather(packed, axis_name)           # (ranks, 7)
        win = jnp.argmax(allp[:, 0])
        wg, wf, wb, wgl, whl, wcl, wthr = (allp[win, i] for i in range(7))
        return (wg, wf.astype(jnp.int32), wb.astype(jnp.int32),
                wgl, whl, wcl, wthr)

    # root: stats directly from grad/hess (no rank owns every feature)
    root_g = jnp.sum(grad * row_valid)
    root_h = jnp.sum(hess * row_valid)
    root_c = jnp.sum((row_valid > 0).astype(jnp.float32))
    root_hist = build(jnp.zeros(N, jnp.int32))[0]

    zi = jnp.zeros(M, jnp.int32)
    zf = jnp.zeros(M, jnp.float32)
    bg, bf_, bb, bgl, bhl, bcl, bthr = global_pick(
        root_hist, root_g, root_h, root_c, jnp.zeros((), jnp.int32),
        -jnp.inf, jnp.inf)
    state = dict(
        node_id=jnp.zeros(N, jnp.int32),
        hist=jnp.zeros((L + 2, FL * B, 3), jnp.float32).at[0].set(
            root_hist.reshape(FL * B, 3)),
        slot=zi,
        sum_g=zf.at[0].set(root_g),
        sum_h=zf.at[0].set(root_h),
        sum_c=zf.at[0].set(root_c),
        depth=zi,
        best_gain=jnp.full(M, -jnp.inf, jnp.float32).at[0].set(bg),
        best_feat=zi.at[0].set(bf_), best_bin=zi.at[0].set(bb),
        best_gl=zf.at[0].set(bgl), best_hl=zf.at[0].set(bhl),
        best_cl=zf.at[0].set(bcl),
        best_thr=zf.at[0].set(bthr),
        active=jnp.zeros(M, jnp.bool_).at[0].set(True),
        split_feature=jnp.full(M, -1, jnp.int32),
        split_bin=zi,
        split_gain=zf,
        threshold=zf,
        left_child=jnp.full(M, -1, jnp.int32),
        right_child=jnp.full(M, -1, jnp.int32),
        num_nodes=jnp.ones((), jnp.int32),
        next_slot=jnp.ones((), jnp.int32),
        node_lo=jnp.full(M, -jnp.inf, jnp.float32),
        node_hi=jnp.full(M, jnp.inf, jnp.float32),
    )

    def cond(s):
        leaves = (s["num_nodes"] + 1) // 2
        gains = jnp.where(s["active"], s["best_gain"], -jnp.inf)
        return (leaves < L) & (jnp.max(gains) > p.min_gain_to_split)

    def wave(s):
        gains = jnp.where(s["active"], s["best_gain"], -jnp.inf)
        tv, ti = lax.top_k(gains, S)
        budget = L - (s["num_nodes"] + 1) // 2
        jidx = jnp.arange(S, dtype=jnp.int32)
        valid = (tv > p.min_gain_to_split) & (jidx < budget)
        n_valid = jnp.sum(valid.astype(jnp.int32))
        parents = jnp.where(valid, ti, JUNK)
        l_ids = jnp.where(valid, s["num_nodes"] + 2 * jidx, JUNK)
        r_ids = jnp.where(valid, s["num_nodes"] + 2 * jidx + 1, JUNK)

        # owner-exclusive routing: this rank contributes the go-left mask
        # only for slots whose winning feature lives in its slice; one psum
        # assembles every slot's mask on every rank.  Routing goes through
        # the universal form so plain and EFB splits share one path
        wf = s["best_feat"][parents]                        # (S,) global ids
        wb = s["best_bin"][parents]
        owner = wf // F_loc
        floc = jnp.clip(wf - rank * F_loc, 0, F_loc - 1)
        mine = (owner == rank) & valid
        col_s, t1_s, lo_s, hi_s, df_s = _slot_route_params(
            floc, wb, B, bundle_map)
        local_gl = _route_left(bins_t[col_s, :], t1_s[:, None],
                               lo_s[:, None], hi_s[:, None],
                               df_s[:, None])               # (S, N)
        gl_slots = lax.psum(
            jnp.where(mine[:, None], local_gl, False).astype(jnp.int8),
            axis_name) > 0                                  # (S, N) bool

        slot_of_leaf = jnp.full(M, -1, jnp.int32).at[parents].set(
            jnp.where(valid, jidx, -1))
        rslot = slot_of_leaf[s["node_id"]]                  # (N,)
        go_left = jnp.take_along_axis(
            gl_slots, jnp.clip(rslot, 0)[None, :], axis=0)[0]
        new_node_id = jnp.where(
            rslot >= 0,
            jnp.where(go_left, l_ids[rslot], r_ids[rslot]),
            s["node_id"])
        bslot = jnp.where(go_left, rslot, -1)

        l_hists = build(bslot)                              # (S, FL, B, 3)
        l_flat = l_hists.reshape(S, FL * B, 3)
        pslot = jnp.where(valid, s["slot"][parents], L)
        r_flat = s["hist"][pslot] - l_flat
        r_slots = jnp.where(valid, s["next_slot"] + jidx, L)
        hist = s["hist"].at[pslot].set(l_flat).at[r_slots].set(r_flat)

        lg = s["best_gl"][parents]
        lh = s["best_hl"][parents]
        lc = s["best_cl"][parents]
        rg = s["sum_g"][parents] - lg
        rh = s["sum_h"][parents] - lh
        rc = s["sum_c"][parents] - lc
        cdepth = s["depth"][parents] + 1

        p_lo, p_hi = s["node_lo"][parents], s["node_hi"][parents]   # (S,)
        l_lo, l_hi, r_lo, r_hi = _mono_node_bounds(
            None if mono_global is None else mono_global[wf],
            p_lo, p_hi, lg, lh, rg, rh, p)
        c_lo = jnp.concatenate([l_lo, r_lo])
        c_hi = jnp.concatenate([l_hi, r_hi])

        child_hists = jnp.concatenate(
            [l_flat.reshape(S, FL, B, 3), r_flat.reshape(S, FL, B, 3)])
        cg = jnp.concatenate([lg, rg])
        ch = jnp.concatenate([lh, rh])
        cc = jnp.concatenate([lc, rc])
        cd = jnp.concatenate([cdepth, cdepth])
        vg = jax.vmap(global_pick)(child_hists, cg, ch, cc, cd, c_lo, c_hi)
        cbg, cbf, cbb, cbgl, cbhl, cbcl, cbthr = vg

        cids = jnp.concatenate([l_ids, r_ids])
        out = dict(
            node_id=new_node_id,
            hist=hist,
            slot=s["slot"].at[l_ids].set(pslot).at[r_ids].set(r_slots),
            sum_g=s["sum_g"].at[cids].set(cg),
            sum_h=s["sum_h"].at[cids].set(ch),
            sum_c=s["sum_c"].at[cids].set(cc),
            depth=s["depth"].at[cids].set(cd),
            best_gain=s["best_gain"].at[cids].set(cbg),
            best_feat=s["best_feat"].at[cids].set(cbf),
            best_bin=s["best_bin"].at[cids].set(cbb),
            best_gl=s["best_gl"].at[cids].set(cbgl),
            best_hl=s["best_hl"].at[cids].set(cbhl),
            best_cl=s["best_cl"].at[cids].set(cbcl),
            best_thr=s["best_thr"].at[cids].set(cbthr),
            active=s["active"].at[parents].set(False).at[cids].set(True),
            split_feature=s["split_feature"].at[parents].set(
                jnp.where(valid, s["best_feat"][parents], -1)),
            split_bin=s["split_bin"].at[parents].set(s["best_bin"][parents]),
            split_gain=s["split_gain"].at[parents].set(
                jnp.where(valid, s["best_gain"][parents], 0.0)),
            threshold=s["threshold"].at[parents].set(s["best_thr"][parents]),
            left_child=s["left_child"].at[parents].set(l_ids),
            right_child=s["right_child"].at[parents].set(r_ids),
            num_nodes=s["num_nodes"] + 2 * n_valid,
            next_slot=s["next_slot"] + n_valid,
            node_lo=s["node_lo"].at[cids].set(c_lo),
            node_hi=s["node_hi"].at[cids].set(c_hi),
        )
        if mono_global is not None and p.monotone_method in ("intermediate",
                                                             "advanced"):
            # the whole-tree refresh runs REPLICATED: tree arrays and
            # sums are identical on every rank (splits are globally
            # agreed), and the constraint vector is the static global
            # tuple — so each rank recomputes the same bounds and the
            # re-pick goes through global_pick's all_gather like any
            # other pick
            out = _refresh_intermediate(out, mono_global, p)
            vg2 = jax.vmap(global_pick)(child_hists, cg, ch, cc, cd,
                                        out["node_lo"][cids],
                                        out["node_hi"][cids])
            cbg2, cbf2, cbb2, cbgl2, cbhl2, cbcl2, cbthr2 = vg2
            out["best_gain"] = out["best_gain"].at[cids].set(cbg2)
            out["best_feat"] = out["best_feat"].at[cids].set(cbf2)
            out["best_bin"] = out["best_bin"].at[cids].set(cbb2)
            out["best_gl"] = out["best_gl"].at[cids].set(cbgl2)
            out["best_hl"] = out["best_hl"].at[cids].set(cbhl2)
            out["best_cl"] = out["best_cl"].at[cids].set(cbcl2)
            out["best_thr"] = out["best_thr"].at[cids].set(cbthr2)
        out["active"] = out["active"].at[JUNK].set(False)
        out["best_gain"] = out["best_gain"].at[JUNK].set(-jnp.inf)
        out["split_feature"] = out["split_feature"].at[JUNK].set(-1)
        out["left_child"] = out["left_child"].at[JUNK].set(-1)
        out["right_child"] = out["right_child"].at[JUNK].set(-1)
        return out

    state = lax.while_loop(cond, wave, state)

    node_value = _leaf_output(state["sum_g"], state["sum_h"],
                              p.lambda_l1, p.lambda_l2)
    if mono_global is not None:
        if p.monotone_method in ("intermediate", "advanced"):
            _, _, node_value = _tree_bounds(
                state["split_feature"], state["split_bin"],
                state["left_child"], state["right_child"], node_value,
                mono_global, p, n_iters=6)
        else:
            node_value = jnp.clip(node_value, state["node_lo"],
                                  state["node_hi"])
    node_value = learning_rate * node_value
    leaf_value = jnp.where(state["left_child"] < 0, node_value, 0.0)
    tree = Tree(split_feature=state["split_feature"],
                split_bin=state["split_bin"],
                threshold=state["threshold"],
                split_gain=state["split_gain"],
                left_child=state["left_child"],
                right_child=state["right_child"],
                leaf_value=leaf_value,
                node_value=node_value,
                num_nodes=state["num_nodes"],
                default_left=jnp.ones(M, jnp.bool_),
                node_count=state["sum_c"],
                missing_zero=jnp.zeros(M, jnp.bool_))
    return tree, state["node_id"]


# -- prediction -------------------------------------------------------------

def predict_binned_tree_featpar(bins_local: jnp.ndarray,   # (FL, N) local
                                tree: Tree,                # replicated
                                depth_bound: int,
                                total_bins: int,
                                axis_name: str,
                                bundle_map: Optional[dict] = None):
    """One tree's leaf values over a FEATURE-SHARDED binned matrix — runs
    INSIDE shard_map.  Each traversal step's go-left mask is computed by
    the rank owning the split feature and broadcast with one psum (the
    same owner-exclusive pattern the feature-parallel grower's routing
    uses), so dart rescoring works without gathering the matrix.  Under
    EFB the owner routes through its local route tables (universal
    routing form)."""
    FL, N = bins_local.shape
    F_loc = (bundle_map["col"].shape[0] if bundle_map is not None else FL)
    rank = lax.axis_index(axis_name)
    rows = jnp.arange(N)

    def step(_, node):
        feat = tree.split_feature[node]                  # GLOBAL id
        is_leaf = feat < 0
        f = jnp.maximum(feat, 0)
        owner = f // F_loc
        floc = jnp.clip(f - rank * F_loc, 0, F_loc - 1)
        col, t1, rlo, rhi, dflt = _slot_route_params(
            floc, tree.split_bin[node], total_bins, bundle_map)
        gl_local = _route_left(bins_local[col, rows], t1, rlo, rhi, dflt)
        # int8 like the grower's routing psum: the owner-exclusive 0/1
        # mask sums to at most 1, and int32 would 4x the ICI traffic
        gl = lax.psum(jnp.where(owner == rank,
                                gl_local.astype(jnp.int8),
                                jnp.int8(0)),
                      axis_name) > 0
        child = jnp.where(gl, tree.left_child[node], tree.right_child[node])
        return jnp.where(is_leaf, node, child)

    leaf = lax.fori_loop(0, depth_bound, step, jnp.zeros(N, jnp.int32))
    return tree.leaf_value[leaf]


def _traverse(binned, tree: Tree, depth_bound: int):
    """Vectorized binned-feature traversal: (N, F) → leaf node id (N,)."""
    N = binned.shape[0]
    rows = jnp.arange(N)

    def step(_, node):
        feat = tree.split_feature[node]
        is_leaf = feat < 0
        f = jnp.maximum(feat, 0)
        go_left = binned[rows, f] <= tree.split_bin[node]
        child = jnp.where(go_left, tree.left_child[node], tree.right_child[node])
        return jnp.where(is_leaf, node, child)

    return lax.fori_loop(0, depth_bound, step,
                         jnp.zeros(N, jnp.int32))


@functools.partial(jax.jit, static_argnames=("depth_bound",))
def predict_binned(binned, tree: Tree, depth_bound: int):
    return tree.leaf_value[_traverse(binned, tree, depth_bound)]


@functools.partial(jax.jit, static_argnames=("depth_bound",))
def predict_binned_stacked(binned, trees_stacked: Tree, depth_bound: int):
    """Sum of all trees' outputs on BINNED features (N, F) — the predict
    path for EFB-bundled models, whose splits live in bin space (bundled
    thresholds have no raw-value meaning)."""
    N = binned.shape[0]
    rows = jnp.arange(N)

    def one_tree(carry, t: Tree):
        def step(_, node):
            feat = t.split_feature[node]
            is_leaf = feat < 0
            f = jnp.maximum(feat, 0)
            go_left = binned[rows, f] <= t.split_bin[node]
            child = jnp.where(go_left, t.left_child[node],
                              t.right_child[node])
            return jnp.where(is_leaf, node, child)

        leaf = lax.fori_loop(0, depth_bound, step, jnp.zeros(N, jnp.int32))
        return carry + t.leaf_value[leaf], leaf

    total, leaves = lax.scan(one_tree, jnp.zeros(N, jnp.float32),
                             trees_stacked)
    return total, leaves


@functools.partial(jax.jit, static_argnames=("depth_bound",))
def predict_raw_features(features, trees_stacked: Tree, depth_bound: int):
    """Sum of all trees' outputs on raw float features — the batched
    replacement for the reference's per-row JNI predict
    (LGBM_BoosterPredictForMatSingle, LightGBMBooster.scala:551).

    trees_stacked: a Tree whose arrays carry a leading tree axis (T, M).
    """
    N = features.shape[0]
    rows = jnp.arange(N)

    def one_tree(carry, t: Tree):
        def step(_, node):
            feat = t.split_feature[node]
            is_leaf = feat < 0
            f = jnp.maximum(feat, 0)
            x = features[rows, f]
            # LightGBM kZeroThreshold: missing_type=Zero treats |x|<=1e-35
            # (and NaN, which it coerces to 0) as missing
            missing = jnp.isnan(x) | (t.missing_zero[node]
                                      & (jnp.abs(x) <= 1e-35))
            go_left = jnp.where(missing, t.default_left[node],
                                x <= t.threshold[node])
            child = jnp.where(go_left, t.left_child[node], t.right_child[node])
            return jnp.where(is_leaf, node, child)

        leaf = lax.fori_loop(0, depth_bound, step, jnp.zeros(N, jnp.int32))
        return carry + t.leaf_value[leaf], leaf

    total, leaves = lax.scan(one_tree, jnp.zeros(N, jnp.float32), trees_stacked)
    return total, leaves   # leaves: (T, N) leaf indices (predict_leaf analogue)


def stack_trees(trees) -> Tree:
    return Tree(*[jnp.stack([getattr(t, f) for t in trees])
                  for f in Tree._fields])


def tree_depth(tree: Tree) -> int:
    """Host-side actual depth (for tight traversal bounds)."""
    lc = np.asarray(tree.left_child)
    rc = np.asarray(tree.right_child)
    depth = np.zeros(lc.shape, np.int32)
    out = 0
    for node in range(len(lc)):
        for child in (lc[node], rc[node]):
            if child >= 0:
                depth[child] = depth[node] + 1
                out = max(out, int(depth[child]))
    return out + 1
