"""LightGBM text model format: emit + parse.

The reference round-trips models through the native LightGBM model STRING
(reference: LightGBMBooster.saveToString booster/LightGBMBooster.scala:272-284;
LightGBMClassificationModel.loadNativeModelFromFile/String
LightGBMClassifier.scala:196-211).  This module speaks the same text format
so existing LightGBM models can be imported and our boosters exported to any
LightGBM runtime:

- ``tree`` header block: version/num_class/num_tree_per_iteration/
  max_feature_idx/objective/feature_names/average_output.
- Per-tree blocks ``Tree=i``: LightGBM node convention — internal nodes are
  indexed 0..num_leaves-2 and leaves appear as bitwise-complement indices
  (child < 0 ⇒ leaf ~child); splits are ``x <= threshold`` → left with the
  default-left/NaN flags packed into ``decision_type``.

Export folds per-tree weights (dart normalization, shrinkage already applied
by training) and the init score (into the first tree per class) into leaf
values, so a file's predictions equal ours with no side-channel: that is
also how LightGBM's own files behave (boost_from_average is baked in).
Imported models carry a placeholder bin mapper — raw-feature prediction
(`predict_margin`, `predict_contrib`) never consults bins.

Limitations: categorical splits (``num_cat > 0``) and linear-leaf models are
rejected explicitly; ``leaf_weight`` exports as zeros (our Tree keeps no
per-node hessian sums) while ``leaf_count``/``internal_count`` carry the
real covers (they feed exact TreeSHAP on both sides of a round trip).
"""

from __future__ import annotations

import re
from typing import Dict, List

import numpy as np

from .binning import BinMapper

#: decision_type flags (LightGBM: include/LightGBM/tree.h semantics)
_CATEGORICAL_MASK = 1
_DEFAULT_LEFT_MASK = 2
_MISSING_TYPE_ZERO = 1 << 2
_MISSING_TYPE_NAN = 2 << 2


def _fmt(v: float) -> str:
    return f"{float(v):.17g}"


def _objective_string(objective: str, num_class: int) -> str:
    if objective == "binary":
        return "binary sigmoid:1"
    if objective == "multiclass":
        return f"multiclass num_class:{num_class}"
    if objective == "multiclassova":
        return f"multiclassova num_class:{num_class} sigmoid:1"
    if objective in ("regression", "mse", "l2"):
        return "regression"
    return objective


def _parse_objective(s: str) -> Dict[str, object]:
    parts = s.split()
    name = parts[0] if parts else "regression"
    kv = dict(p.split(":", 1) for p in parts[1:] if ":" in p)
    num_class = int(kv.get("num_class", 1))
    if name == "regression_l2":
        name = "regression"
    return {"objective": name, "num_class": num_class}


# --------------------------------------------------------------------------
# export
# --------------------------------------------------------------------------

def _tree_block(tree, weight: float, bias: float, index: int,
                shrinkage: float) -> str:
    """One ``Tree=i`` section in LightGBM node numbering."""
    n_nodes = int(tree.num_nodes)
    lc = np.asarray(tree.left_child[:n_nodes])
    rc = np.asarray(tree.right_child[:n_nodes])
    internal = np.nonzero(lc >= 0)[0]
    leaves = np.nonzero(lc < 0)[0]
    int_idx = {int(n): i for i, n in enumerate(internal)}
    leaf_idx = {int(n): i for i, n in enumerate(leaves)}

    def child(c: int) -> int:
        c = int(c)
        return int_idx[c] if int(lc[c]) >= 0 else ~leaf_idx[c]

    lines = [f"Tree={index}",
             f"num_leaves={len(leaves)}",
             "num_cat=0"]
    leaf_vals = [float(tree.node_value[n]) * weight + bias for n in leaves]
    if len(internal):
        dl = np.asarray(tree.default_left[:n_nodes])
        mz = np.asarray(tree.missing_zero[:n_nodes])

        def dtype_of(n):
            missing = _MISSING_TYPE_ZERO if mz[n] else _MISSING_TYPE_NAN
            return (_DEFAULT_LEFT_MASK if dl[n] else 0) | missing

        lines += [
            "split_feature=" + " ".join(str(int(tree.split_feature[n]))
                                        for n in internal),
            "split_gain=" + " ".join(_fmt(tree.split_gain[n])
                                     for n in internal),
            "threshold=" + " ".join(_fmt(tree.threshold[n])
                                    for n in internal),
            "decision_type=" + " ".join(str(dtype_of(n)) for n in internal),
            "left_child=" + " ".join(str(child(lc[n])) for n in internal),
            "right_child=" + " ".join(str(child(rc[n])) for n in internal),
        ]
    counts = np.asarray(tree.node_count[:n_nodes])
    lines += [
        "leaf_value=" + " ".join(_fmt(v) for v in leaf_vals),
        "leaf_weight=" + " ".join("0" for _ in leaves),
        "leaf_count=" + " ".join(str(int(counts[n])) for n in leaves),
    ]
    if len(internal):
        lines += [
            "internal_value=" + " ".join(
                _fmt(float(tree.node_value[n]) * weight + bias)
                for n in internal),
            "internal_weight=" + " ".join("0" for _ in internal),
            "internal_count=" + " ".join(str(int(counts[n]))
                                         for n in internal),
        ]
    lines += ["is_linear=0", f"shrinkage={_fmt(shrinkage)}"]
    return "\n".join(lines) + "\n"


def booster_to_lgbm_string(booster) -> str:
    """Serialize a Booster to LightGBM's text model format
    (saveToString parity, LightGBMBooster.scala:272-284)."""
    K = booster.num_class
    F = booster.bin_mapper.num_features
    is_rf = booster.config.boosting_type == "rf"
    blocks: List[str] = []
    seen_class: Dict[int, bool] = {}
    for i, tree in enumerate(booster.trees):
        k = booster.tree_class[i]
        w = float(booster.tree_weights[i])
        # init score folds into leaf values: once per class for summed
        # models, into EVERY tree for averaged (rf) models so that
        # mean(leaves) keeps the full bias
        if is_rf:
            bias = float(booster.init_score[min(k, len(booster.init_score) - 1)])
        else:
            bias = 0.0
            if not seen_class.get(k):
                seen_class[k] = True
                bias = float(
                    booster.init_score[min(k, len(booster.init_score) - 1)])
        blocks.append(_tree_block(tree, w, bias, i,
                                  booster.config.learning_rate))

    header = ["tree", "version=v3",
              f"num_class={K}",
              f"num_tree_per_iteration={K}",
              "label_index=0",
              f"max_feature_idx={F - 1}",
              "objective=" + _objective_string(booster.objective, K),
              "feature_names=" + " ".join(booster.feature_names),
              "feature_infos=" + " ".join("[-1e+308:1e+308]"
                                          for _ in range(F))]
    if booster.config.boosting_type == "rf":
        header.append("average_output")
    body = "\n\n".join(blocks)
    header.append("tree_sizes=" + " ".join(str(len(b) + 1) for b in blocks))
    out = "\n".join(header) + "\n\n" + body + "\nend of trees\n"
    mono = booster.config.monotone_constraints
    if mono and any(mono):
        # LightGBM-style parameters section so constrained models survive
        # the round trip (LightGBM emits the full config here; we carry
        # the monotone settings, the ones that change predict semantics)
        out += ("\nparameters:\n"
                "[monotone_constraints: "
                + ",".join(str(int(c)) for c in mono) + "]\n"
                "[monotone_constraints_method: "
                + booster.config.monotone_constraints_method + "]\n"
                f"[monotone_penalty: {booster.config.monotone_penalty}]\n"
                "end of parameters\n")
    return out


# --------------------------------------------------------------------------
# import
# --------------------------------------------------------------------------

def _parse_block(text: str) -> Dict[str, str]:
    out = {}
    for line in text.splitlines():
        if "=" in line:
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def _tree_from_block(fields: Dict[str, str], max_leaves: int):
    from .trainer import Tree

    n_leaves = int(fields["num_leaves"])
    if int(fields.get("num_cat", "0") or 0) > 0:
        raise ValueError("categorical splits (num_cat>0) are not supported")
    if fields.get("is_linear", "0").strip() == "1":
        raise ValueError("linear-leaf trees (is_linear=1) are not supported")
    n_int = max(n_leaves - 1, 0)
    M = 2 * max_leaves
    split_feature = np.full(M, -1, np.int32)
    threshold = np.zeros(M, np.float32)
    split_gain = np.zeros(M, np.float32)
    left = np.full(M, -1, np.int32)
    right = np.full(M, -1, np.int32)
    node_value = np.zeros(M, np.float32)
    leaf_value = np.zeros(M, np.float32)
    default_left = np.ones(M, bool)
    node_count = np.zeros(M, np.float32)
    missing_zero = np.zeros(M, bool)

    def arr(key, dtype, n, default=None):
        if key not in fields:
            if default is not None:
                return np.full(n, default, dtype)
            raise ValueError(f"model string missing '{key}'")
        vals = fields[key].split()
        if len(vals) != n:
            raise ValueError(f"'{key}' has {len(vals)} values, expected {n}")
        return np.asarray([dtype(v) for v in vals])

    lv = arr("leaf_value", float, n_leaves)
    lcnt = arr("leaf_count", float, n_leaves, default=0.0)
    icnt = arr("internal_count", float, n_int, default=0.0)
    if n_int:
        sf = arr("split_feature", int, n_int)
        th = arr("threshold", float, n_int)
        sg = arr("split_gain", float, n_int, default=0.0)
        lc = arr("left_child", int, n_int)
        rc = arr("right_child", int, n_int)
        iv = arr("internal_value", float, n_int, default=0.0)
        dt = np.asarray(arr("decision_type", int, n_int,
                            default=_DEFAULT_LEFT_MASK | _MISSING_TYPE_NAN))
        if np.any(dt & _CATEGORICAL_MASK):
            raise ValueError("categorical decision_type is not supported")
        # missing_type bits 2-3: 0=None, 1=Zero, 2=NaN.  NaN missing (the
        # LightGBM float default) keeps the stored default direction.  For
        # None, LightGBM coerces NaN input to 0.0 — emulated exactly by
        # routing NaN where 0.0 would compare.  Zero missing (0.0 itself
        # treated as missing, |x| <= kZeroThreshold) rides the per-node
        # ``missing_zero`` flag on Tree.
        mtype = (dt >> 2) & 3

        def map_child(c: int) -> int:
            return int(c) if c >= 0 else n_int + (~int(c))

        for j in range(n_int):
            split_feature[j] = sf[j]
            threshold[j] = th[j]
            split_gain[j] = sg[j]
            left[j] = map_child(lc[j])
            right[j] = map_child(rc[j])
            node_value[j] = iv[j]
            node_count[j] = icnt[j]
            if ((dt[j] >> 2) & 3) == 0:          # None: NaN behaves as 0.0
                default_left[j] = bool(0.0 <= th[j])
            else:
                default_left[j] = bool(dt[j] & _DEFAULT_LEFT_MASK)
                missing_zero[j] = mtype[j] == 1
    for l in range(n_leaves):
        node_value[n_int + l] = lv[l]
        leaf_value[n_int + l] = lv[l]
        node_count[n_int + l] = lcnt[l]
    return Tree(split_feature=split_feature,
                split_bin=np.zeros(M, np.int32),
                threshold=threshold.astype(np.float32),
                split_gain=split_gain.astype(np.float32),
                left_child=left, right_child=right,
                leaf_value=leaf_value, node_value=node_value,
                num_nodes=np.asarray(n_int + n_leaves, np.int32),
                default_left=default_left,
                node_count=node_count,
                missing_zero=missing_zero)


def booster_from_lgbm_string(s: str):
    """Parse a LightGBM text model into a Booster
    (loadNativeModelFromString parity, LightGBMClassifier.scala:196-211)."""
    from .booster import Booster, BoostingConfig

    head, _, tail = s.partition("Tree=")
    if not tail:
        raise ValueError("not a LightGBM model string: no 'Tree=' block")
    header = _parse_block(head)
    obj = _parse_objective(header.get("objective", "regression"))
    K = max(int(header.get("num_tree_per_iteration", obj["num_class"])), 1)
    F = int(header.get("max_feature_idx", "0")) + 1
    feature_names = header.get("feature_names", "").split() or \
        [f"f{i}" for i in range(F)]
    is_rf = bool(re.search(r"^average_output\s*$", head, re.MULTILINE))

    tree_texts = ("Tree=" + tail).split("end of trees")[0]
    blocks = [b for b in re.split(r"\n(?=Tree=\d)", tree_texts) if b.strip()]
    parsed = [_parse_block(b) for b in blocks]
    max_leaves = max(int(p["num_leaves"]) for p in parsed)
    trees = [_tree_from_block(p, max_leaves) for p in parsed]

    objective = str(obj["objective"])
    mkw = {}
    mtc = re.search(r"\[monotone_constraints:\s*([^\]]*)\]", s)
    if mtc and mtc.group(1).strip():
        vals = [int(v) for v in re.split(r"[,\s]+", mtc.group(1).strip())
                if v]
        if any(vals):
            mkw["monotone_constraints"] = vals
    mmeth = re.search(r"\[monotone_constraints_method:\s*([^\]]*)\]", s)
    if mmeth and mmeth.group(1).strip():
        mkw["monotone_constraints_method"] = mmeth.group(1).strip()
    mpen = re.search(r"\[monotone_penalty:\s*([^\]]*)\]", s)
    if mpen:
        try:
            mkw["monotone_penalty"] = float(mpen.group(1))
        except ValueError:
            pass
    cfg = BoostingConfig(objective=objective,
                         boosting_type="rf" if is_rf else "gbdt",
                         num_class=K if K > 1 else 1,
                         num_leaves=max(max_leaves, 2), **mkw)
    mapper = BinMapper(upper_bounds=np.full((F, 255), np.inf, np.float32),
                       num_bins=np.ones(F, np.int32), max_bin=255)
    return Booster(trees=trees,
                   tree_class=[i % K for i in range(len(trees))],
                   tree_weights=[1.0] * len(trees),
                   num_class=K if K > 1 else 1,
                   objective=objective,
                   init_score=np.zeros(max(K, 1), np.float32),
                   bin_mapper=mapper,
                   feature_names=feature_names[:F],
                   config=cfg)
