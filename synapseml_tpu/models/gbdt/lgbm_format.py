"""LightGBM text model format: emit + parse.

The reference round-trips models through the native LightGBM model STRING
(reference: LightGBMBooster.saveToString booster/LightGBMBooster.scala:272-284;
LightGBMClassificationModel.loadNativeModelFromFile/String
LightGBMClassifier.scala:196-211).  This module speaks the same text format
so existing LightGBM models can be imported and our boosters exported to any
LightGBM runtime:

- ``tree`` header block: version/num_class/num_tree_per_iteration/
  max_feature_idx/objective/feature_names/average_output.
- Per-tree blocks ``Tree=i``: LightGBM node convention — internal nodes are
  indexed 0..num_leaves-2 and leaves appear as bitwise-complement indices
  (child < 0 ⇒ leaf ~child); splits are ``x <= threshold`` → left with the
  default-left/NaN flags packed into ``decision_type``.

Export folds per-tree weights (dart normalization, shrinkage already applied
by training) and the init score (into the first tree per class) into leaf
values, so a file's predictions equal ours with no side-channel: that is
also how LightGBM's own files behave (boost_from_average is baked in).
Imported models carry a placeholder bin mapper — raw-feature prediction
(`predict_margin`, `predict_contrib`) never consults bins.

Limitations: categorical splits (``num_cat > 0``) and linear-leaf models are
rejected explicitly; ``leaf_weight`` exports as zeros (our Tree keeps no
per-node hessian sums) while ``leaf_count``/``internal_count`` carry the
real covers (they feed exact TreeSHAP on both sides of a round trip).
"""

from __future__ import annotations

import re
from typing import Dict, List

import numpy as np

from .binning import BinMapper

#: decision_type flags (LightGBM: include/LightGBM/tree.h semantics)
_CATEGORICAL_MASK = 1
_DEFAULT_LEFT_MASK = 2
_MISSING_TYPE_ZERO = 1 << 2
_MISSING_TYPE_NAN = 2 << 2


def _fmt(v: float) -> str:
    return f"{float(v):.17g}"


def _objective_string(objective: str, num_class: int) -> str:
    if objective == "binary":
        return "binary sigmoid:1"
    if objective == "multiclass":
        return f"multiclass num_class:{num_class}"
    if objective == "multiclassova":
        return f"multiclassova num_class:{num_class} sigmoid:1"
    if objective in ("regression", "mse", "l2"):
        return "regression"
    return objective


def _parse_objective(s: str) -> Dict[str, object]:
    parts = s.split()
    name = parts[0] if parts else "regression"
    kv = dict(p.split(":", 1) for p in parts[1:] if ":" in p)
    num_class = int(kv.get("num_class", 1))
    if name == "regression_l2":
        name = "regression"
    return {"objective": name, "num_class": num_class}


# --------------------------------------------------------------------------
# export
# --------------------------------------------------------------------------

def _cat_rightset_bits(vals, bins, split_bin: int):
    """Bitset (uint32 words) of the category VALUES with bin > split_bin —
    our bin-space split sends bin <= t left, so the exported LightGBM
    in-set (which goes left there) is the COMPLEMENT with children
    swapped: unseen/missing categories then fall through LightGBM's
    not-in-set branch onto our left child, matching bin 0 <= t exactly."""
    right_vals = [int(v) for v, b in zip(vals, bins) if int(b) > split_bin]
    n_words = (max(right_vals) // 32 + 1) if right_vals else 1
    words = [0] * n_words
    for v in right_vals:
        words[v // 32] |= 1 << (v % 32)
    return words


def _tree_block(tree, weight: float, bias: float, index: int,
                shrinkage: float, cat_features: Dict = None) -> str:
    """One ``Tree=i`` section in LightGBM node numbering."""
    n_nodes = int(tree.num_nodes)
    lc = np.asarray(tree.left_child[:n_nodes])
    rc = np.asarray(tree.right_child[:n_nodes])
    internal = np.nonzero(lc >= 0)[0]
    leaves = np.nonzero(lc < 0)[0]
    int_idx = {int(n): i for i, n in enumerate(internal)}
    leaf_idx = {int(n): i for i, n in enumerate(leaves)}
    cat_features = cat_features or {}

    def child(c: int) -> int:
        c = int(c)
        return int_idx[c] if int(lc[c]) >= 0 else ~leaf_idx[c]

    # categorical nodes: bitset per node, children swapped (see
    # _cat_rightset_bits); cat_idx indexes cat_boundaries in node order
    is_cat = [int(tree.split_feature[n]) in cat_features for n in internal]
    cat_boundaries = [0]
    cat_words: List[int] = []
    cat_idx_of = {}
    for n, c in zip(internal, is_cat):
        if c:
            f = int(tree.split_feature[n])
            vals, bins = cat_features[f]
            words = _cat_rightset_bits(vals, bins,
                                       int(tree.split_bin[n]))
            cat_idx_of[int(n)] = len(cat_boundaries) - 1
            cat_words.extend(words)
            cat_boundaries.append(len(cat_words))

    lines = [f"Tree={index}",
             f"num_leaves={len(leaves)}",
             f"num_cat={len(cat_boundaries) - 1}"]
    leaf_vals = [float(tree.node_value[n]) * weight + bias for n in leaves]
    if len(internal):
        dl = np.asarray(tree.default_left[:n_nodes])
        mz = np.asarray(tree.missing_zero[:n_nodes])

        def dtype_of(n, cat):
            if cat:
                return _CATEGORICAL_MASK
            missing = _MISSING_TYPE_ZERO if mz[n] else _MISSING_TYPE_NAN
            return (_DEFAULT_LEFT_MASK if dl[n] else 0) | missing

        def thr_of(n, cat):
            return str(cat_idx_of[int(n)]) if cat \
                else _fmt(tree.threshold[n])

        lines += [
            "split_feature=" + " ".join(str(int(tree.split_feature[n]))
                                        for n in internal),
            "split_gain=" + " ".join(_fmt(tree.split_gain[n])
                                     for n in internal),
            "threshold=" + " ".join(thr_of(n, c)
                                    for n, c in zip(internal, is_cat)),
            "decision_type=" + " ".join(str(dtype_of(n, c))
                                        for n, c in zip(internal, is_cat)),
            # categorical children SWAP: the file's in-set-left is our
            # right child
            "left_child=" + " ".join(
                str(child(rc[n] if c else lc[n]))
                for n, c in zip(internal, is_cat)),
            "right_child=" + " ".join(
                str(child(lc[n] if c else rc[n]))
                for n, c in zip(internal, is_cat)),
        ]
        if len(cat_boundaries) > 1:
            lines += [
                "cat_boundaries=" + " ".join(str(b) for b in cat_boundaries),
                "cat_threshold=" + " ".join(str(w) for w in cat_words),
            ]
    counts = np.asarray(tree.node_count[:n_nodes])
    lines += [
        "leaf_value=" + " ".join(_fmt(v) for v in leaf_vals),
        "leaf_weight=" + " ".join("0" for _ in leaves),
        "leaf_count=" + " ".join(str(int(counts[n])) for n in leaves),
    ]
    if len(internal):
        lines += [
            "internal_value=" + " ".join(
                _fmt(float(tree.node_value[n]) * weight + bias)
                for n in internal),
            "internal_weight=" + " ".join("0" for _ in internal),
            "internal_count=" + " ".join(str(int(counts[n]))
                                         for n in internal),
        ]
    lines += ["is_linear=0", f"shrinkage={_fmt(shrinkage)}"]
    return "\n".join(lines) + "\n"


def booster_to_lgbm_string(booster) -> str:
    """Serialize a Booster to LightGBM's text model format
    (saveToString parity, LightGBMBooster.scala:272-284)."""
    K = booster.num_class
    F = booster.bin_mapper.num_features
    is_rf = booster.config.boosting_type == "rf"
    cat_features = booster.bin_mapper.cat_features or {}
    for f, (vals, _bins) in cat_features.items():
        bad = [v for v in vals
               if not float(v).is_integer() or v < 0 or v >= 1 << 21]
        if bad:
            raise ValueError(
                f"categorical feature {f}: LightGBM bitset thresholds "
                f"need non-negative integer categories < 2^21; got "
                f"{bad[:3]}")
    blocks: List[str] = []
    seen_class: Dict[int, bool] = {}
    for i, tree in enumerate(booster.trees):
        k = booster.tree_class[i]
        w = float(booster.tree_weights[i])
        # init score folds into leaf values: once per class for summed
        # models, into EVERY tree for averaged (rf) models so that
        # mean(leaves) keeps the full bias
        if is_rf:
            bias = float(booster.init_score[min(k, len(booster.init_score) - 1)])
        else:
            bias = 0.0
            if not seen_class.get(k):
                seen_class[k] = True
                bias = float(
                    booster.init_score[min(k, len(booster.init_score) - 1)])
        blocks.append(_tree_block(tree, w, bias, i,
                                  booster.config.learning_rate,
                                  cat_features))

    def feat_info(f: int) -> str:
        if f not in cat_features:
            return "[-1e+308:1e+308]"
        # categorical feature_infos: category values in BIN order (the
        # target-statistic order bins were assigned in) — LightGBM's own
        # categorical feature_infos form, and what lets an import rebuild
        # the bin-space LUT exactly.  An empty LUT (all-NaN fit column)
        # emits LightGBM's "none" token — an empty string would collapse
        # under whitespace split and misalign every later feature
        vals, bins = cat_features[f]
        if len(vals) == 0:
            return "none"
        by_bin = sorted(zip(bins, vals))
        return ":".join(str(int(v)) for _, v in by_bin)

    header = ["tree", "version=v3",
              f"num_class={K}",
              f"num_tree_per_iteration={K}",
              "label_index=0",
              f"max_feature_idx={F - 1}",
              "objective=" + _objective_string(booster.objective, K),
              "feature_names=" + " ".join(booster.feature_names),
              "feature_infos=" + " ".join(feat_info(f) for f in range(F))]
    if booster.config.boosting_type == "rf":
        header.append("average_output")
    body = "\n\n".join(blocks)
    header.append("tree_sizes=" + " ".join(str(len(b) + 1) for b in blocks))
    out = "\n".join(header) + "\n\n" + body + "\nend of trees\n"
    mono = booster.config.monotone_constraints
    if mono and any(mono):
        # LightGBM-style parameters section so constrained models survive
        # the round trip (LightGBM emits the full config here; we carry
        # the monotone settings, the ones that change predict semantics)
        out += ("\nparameters:\n"
                "[monotone_constraints: "
                + ",".join(str(int(c)) for c in mono) + "]\n"
                "[monotone_constraints_method: "
                + booster.config.monotone_constraints_method + "]\n"
                f"[monotone_penalty: {booster.config.monotone_penalty}]\n"
                "end of parameters\n")
    return out


# --------------------------------------------------------------------------
# import
# --------------------------------------------------------------------------

def _parse_block(text: str) -> Dict[str, str]:
    out = {}
    for line in text.splitlines():
        if "=" in line:
            k, v = line.split("=", 1)
            out[k.strip()] = v.strip()
    return out


def _bitset_values(words: List[int]) -> set:
    out = set()
    for wi, w in enumerate(words):
        w = int(w) & 0xffffffff
        while w:
            b = (w & -w).bit_length() - 1
            out.add(wi * 32 + b)
            w &= w - 1
    return out


def _tree_from_block(fields: Dict[str, str], max_leaves: int,
                     cat_luts: Dict = None):
    from .trainer import Tree

    n_leaves = int(fields["num_leaves"])
    num_cat = int(fields.get("num_cat", "0") or 0)
    if num_cat > 0 and not cat_luts:
        raise ValueError(
            "categorical splits (num_cat>0) need categorical "
            "feature_infos (colon-separated category lists) to rebuild "
            "the bin-space LUT")
    if fields.get("is_linear", "0").strip() == "1":
        raise ValueError("linear-leaf trees (is_linear=1) are not supported")
    n_int = max(n_leaves - 1, 0)
    M = 2 * max_leaves
    split_feature = np.full(M, -1, np.int32)
    threshold = np.zeros(M, np.float32)
    split_gain = np.zeros(M, np.float32)
    left = np.full(M, -1, np.int32)
    right = np.full(M, -1, np.int32)
    node_value = np.zeros(M, np.float32)
    leaf_value = np.zeros(M, np.float32)
    default_left = np.ones(M, bool)
    node_count = np.zeros(M, np.float32)
    missing_zero = np.zeros(M, bool)

    def arr(key, dtype, n, default=None):
        if key not in fields:
            if default is not None:
                return np.full(n, default, dtype)
            raise ValueError(f"model string missing '{key}'")
        vals = fields[key].split()
        if len(vals) != n:
            raise ValueError(f"'{key}' has {len(vals)} values, expected {n}")
        return np.asarray([dtype(v) for v in vals])

    split_bin = np.zeros(M, np.int32)
    lv = arr("leaf_value", float, n_leaves)
    lcnt = arr("leaf_count", float, n_leaves, default=0.0)
    icnt = arr("internal_count", float, n_int, default=0.0)
    if n_int:
        sf = arr("split_feature", int, n_int)
        th = arr("threshold", float, n_int)
        sg = arr("split_gain", float, n_int, default=0.0)
        lc = arr("left_child", int, n_int)
        rc = arr("right_child", int, n_int)
        iv = arr("internal_value", float, n_int, default=0.0)
        dt = np.asarray(arr("decision_type", int, n_int,
                            default=_DEFAULT_LEFT_MASK | _MISSING_TYPE_NAN))
        cat_nodes = (dt & _CATEGORICAL_MASK) != 0
        if np.any(cat_nodes):
            bounds = [int(v) for v in fields["cat_boundaries"].split()]
            words = [int(v) for v in fields["cat_threshold"].split()]
            for j in np.nonzero(cat_nodes)[0]:
                f = int(sf[j])
                if f not in (cat_luts or {}):
                    raise ValueError(
                        f"categorical split on feature {f} but its "
                        "feature_infos entry is not a category list")
                vals, bins = cat_luts[f]
                ci = int(th[j])
                in_set = _bitset_values(words[bounds[ci]:bounds[ci + 1]])
                # the file's in-set goes to ITS left; our convention is
                # bin <= t left with children swapped at export — so the
                # in-set must be a bin SUFFIX, t = min(in-set bins) - 1
                set_bins = sorted(int(b) for v, b in zip(vals, bins)
                                  if int(v) in in_set)
                nb = int(np.max(bins)) if len(bins) else 0
                if set_bins and (set_bins[0] + len(set_bins) - 1
                                 != set_bins[-1]
                                 or set_bins[-1] != nb):
                    raise ValueError(
                        "categorical bitset is not a contiguous suffix of "
                        "the target-ordered bins: arbitrary category "
                        "subsets (foreign LightGBM files) are not "
                        "representable in bin space — retrain here")
                t = (set_bins[0] - 1) if set_bins else nb
                split_bin[j] = t
                # swap children back: file-left (in-set) is our right
                lc[j], rc[j] = rc[j], lc[j]
                th[j] = float(t)       # hybrid traversal compares bins
        # missing_type bits 2-3: 0=None, 1=Zero, 2=NaN.  NaN missing (the
        # LightGBM float default) keeps the stored default direction.  For
        # None, LightGBM coerces NaN input to 0.0 — emulated exactly by
        # routing NaN where 0.0 would compare.  Zero missing (0.0 itself
        # treated as missing, |x| <= kZeroThreshold) rides the per-node
        # ``missing_zero`` flag on Tree.
        mtype = (dt >> 2) & 3

        def map_child(c: int) -> int:
            return int(c) if c >= 0 else n_int + (~int(c))

        for j in range(n_int):
            split_feature[j] = sf[j]
            threshold[j] = th[j]
            split_gain[j] = sg[j]
            left[j] = map_child(lc[j])
            right[j] = map_child(rc[j])
            node_value[j] = iv[j]
            node_count[j] = icnt[j]
            if ((dt[j] >> 2) & 3) == 0:          # None: NaN behaves as 0.0
                default_left[j] = bool(0.0 <= th[j])
            else:
                default_left[j] = bool(dt[j] & _DEFAULT_LEFT_MASK)
                missing_zero[j] = mtype[j] == 1
    for l in range(n_leaves):
        node_value[n_int + l] = lv[l]
        leaf_value[n_int + l] = lv[l]
        node_count[n_int + l] = lcnt[l]
    return Tree(split_feature=split_feature,
                split_bin=split_bin,
                threshold=threshold.astype(np.float32),
                split_gain=split_gain.astype(np.float32),
                left_child=left, right_child=right,
                leaf_value=leaf_value, node_value=node_value,
                num_nodes=np.asarray(n_int + n_leaves, np.int32),
                default_left=default_left,
                node_count=node_count,
                missing_zero=missing_zero)


def booster_from_lgbm_string(s: str):
    """Parse a LightGBM text model into a Booster
    (loadNativeModelFromString parity, LightGBMClassifier.scala:196-211)."""
    from .booster import Booster, BoostingConfig

    head, _, tail = s.partition("Tree=")
    if not tail:
        raise ValueError("not a LightGBM model string: no 'Tree=' block")
    header = _parse_block(head)
    obj = _parse_objective(header.get("objective", "regression"))
    K = max(int(header.get("num_tree_per_iteration", obj["num_class"])), 1)
    F = int(header.get("max_feature_idx", "0")) + 1
    feature_names = header.get("feature_names", "").split() or \
        [f"f{i}" for i in range(F)]
    is_rf = bool(re.search(r"^average_output\s*$", head, re.MULTILINE))

    # categorical feature_infos (colon-separated category values, in bin
    # order) rebuild the bin-space LUTs our categorical splits route by
    cat_luts: Dict[int, tuple] = {}
    infos = header.get("feature_infos", "").split()
    for f, info in enumerate(infos[:F]):
        # numerical infos are bracketed ranges; anything unbracketed (bar
        # LightGBM's "none") is a category list — a SINGLE category has no
        # colon yet must still rebuild its LUT
        if info and not info.startswith("[") and info != "none":
            vals_in_bin_order = [float(v) for v in info.split(":") if v]
            order = np.argsort(vals_in_bin_order, kind="stable")
            vals_sorted = np.asarray(vals_in_bin_order, np.float64)[order]
            bins_sorted = (np.asarray(order, np.int64) + 1).astype(np.int32)
            cat_luts[f] = (vals_sorted, bins_sorted)

    tree_texts = ("Tree=" + tail).split("end of trees")[0]
    blocks = [b for b in re.split(r"\n(?=Tree=\d)", tree_texts) if b.strip()]
    parsed = [_parse_block(b) for b in blocks]
    max_leaves = max(int(p["num_leaves"]) for p in parsed)
    trees = [_tree_from_block(p, max_leaves, cat_luts) for p in parsed]

    objective = str(obj["objective"])
    mkw = {}
    mtc = re.search(r"\[monotone_constraints:\s*([^\]]*)\]", s)
    if mtc and mtc.group(1).strip():
        vals = [int(v) for v in re.split(r"[,\s]+", mtc.group(1).strip())
                if v]
        if any(vals):
            mkw["monotone_constraints"] = vals
    mmeth = re.search(r"\[monotone_constraints_method:\s*([^\]]*)\]", s)
    if mmeth and mmeth.group(1).strip():
        mkw["monotone_constraints_method"] = mmeth.group(1).strip()
    mpen = re.search(r"\[monotone_penalty:\s*([^\]]*)\]", s)
    if mpen:
        try:
            mkw["monotone_penalty"] = float(mpen.group(1))
        except ValueError:
            pass
    cfg = BoostingConfig(objective=objective,
                         boosting_type="rf" if is_rf else "gbdt",
                         num_class=K if K > 1 else 1,
                         num_leaves=max(max_leaves, 2), **mkw)
    mapper = BinMapper(upper_bounds=np.full((F, 255), np.inf, np.float32),
                       num_bins=np.ones(F, np.int32), max_bin=255,
                       cat_features=cat_luts or None)
    return Booster(trees=trees,
                   tree_class=[i % K for i in range(len(trees))],
                   tree_weights=[1.0] * len(trees),
                   num_class=K if K > 1 else 1,
                   objective=objective,
                   init_score=np.zeros(max(K, 1), np.float32),
                   bin_mapper=mapper,
                   feature_names=feature_names[:F],
                   config=cfg)
