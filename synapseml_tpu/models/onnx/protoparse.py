"""Self-contained ONNX protobuf codec (no ``onnx`` package dependency).

The reference links ``onnx-protobuf`` and the ONNX Runtime JNI jar
(reference: build.sbt:420-421, deep-learning/.../ONNXUtils.scala:22-360).
This environment has neither the onnx wheel nor egress to fetch it, so we
read and write the ONNX ``ModelProto`` wire format directly: protobuf
encoding is a stable public format (tag = field_number << 3 | wire_type;
varint / 64-bit / length-delimited / 32-bit payloads), and the ONNX field
numbers are fixed by onnx.proto3.  Only the message subset needed for
graph execution is modelled.
"""

from __future__ import annotations

import struct
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Tuple, Union

import numpy as np

# -- ONNX TensorProto.DataType ------------------------------------------------
FLOAT, UINT8, INT8, UINT16, INT16, INT32, INT64 = 1, 2, 3, 4, 5, 6, 7
STRING, BOOL, FLOAT16, DOUBLE, UINT32, UINT64 = 8, 9, 10, 11, 12, 13
BFLOAT16 = 16

DTYPE_TO_NUMPY = {
    FLOAT: np.float32, UINT8: np.uint8, INT8: np.int8, UINT16: np.uint16,
    INT16: np.int16, INT32: np.int32, INT64: np.int64, BOOL: np.bool_,
    FLOAT16: np.float16, DOUBLE: np.float64, UINT32: np.uint32,
    UINT64: np.uint64,
}
NUMPY_TO_DTYPE = {np.dtype(v): k for k, v in DTYPE_TO_NUMPY.items()}


def numpy_to_elem_type(dtype) -> int:
    d = np.dtype(dtype)
    if str(d) == "bfloat16":
        return BFLOAT16
    try:
        return NUMPY_TO_DTYPE[d]
    except KeyError:
        raise TypeError(f"no ONNX elem_type for numpy dtype {d}") from None


# -- AttributeProto.AttributeType --------------------------------------------
A_FLOAT, A_INT, A_STRING, A_TENSOR, A_GRAPH = 1, 2, 3, 4, 5
A_FLOATS, A_INTS, A_STRINGS, A_TENSORS, A_GRAPHS = 6, 7, 8, 9, 10


# ============================================================================
# wire-format primitives
# ============================================================================

def _read_varint(buf: memoryview, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ValueError("malformed varint")


def _to_signed64(v: int) -> int:
    return v - (1 << 64) if v >= (1 << 63) else v


def _write_varint(out: bytearray, value: int) -> None:
    if value < 0:
        value += 1 << 64
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _iter_fields(data: Union[bytes, memoryview]) -> Iterator[Tuple[int, int, Any]]:
    """Yield (field_number, wire_type, payload) triples."""
    buf = memoryview(data)
    pos, end = 0, len(buf)
    while pos < end:
        tag, pos = _read_varint(buf, pos)
        fnum, wtype = tag >> 3, tag & 7
        if wtype == 0:
            val, pos = _read_varint(buf, pos)
        elif wtype == 1:
            val = bytes(buf[pos:pos + 8])
            pos += 8
        elif wtype == 2:
            ln, pos = _read_varint(buf, pos)
            val = buf[pos:pos + ln]
            pos += ln
        elif wtype == 5:
            val = bytes(buf[pos:pos + 4])
            pos += 4
        else:
            raise ValueError(f"unsupported wire type {wtype}")
        yield fnum, wtype, val


def _packed_or_single_i64(wtype: int, val, out: List[int]) -> None:
    if wtype == 0:
        out.append(_to_signed64(val))
    else:  # packed
        buf = memoryview(val)
        pos = 0
        while pos < len(buf):
            v, pos = _read_varint(buf, pos)
            out.append(_to_signed64(v))


def _packed_or_single_f32(wtype: int, val, out: List[float]) -> None:
    if wtype == 5:
        out.append(struct.unpack("<f", val)[0])
    else:
        out.extend(np.frombuffer(bytes(val), dtype="<f4").tolist())


def _packed_or_single_f64(wtype: int, val, out: List[float]) -> None:
    if wtype == 1:
        out.append(struct.unpack("<d", val)[0])
    else:
        out.extend(np.frombuffer(bytes(val), dtype="<f8").tolist())


def _emit_tag(out: bytearray, fnum: int, wtype: int) -> None:
    _write_varint(out, (fnum << 3) | wtype)


def _emit_bytes(out: bytearray, fnum: int, payload: bytes) -> None:
    _emit_tag(out, fnum, 2)
    _write_varint(out, len(payload))
    out.extend(payload)


def _emit_str(out: bytearray, fnum: int, s: str) -> None:
    _emit_bytes(out, fnum, s.encode("utf-8"))


def _emit_varint_field(out: bytearray, fnum: int, value: int) -> None:
    _emit_tag(out, fnum, 0)
    _write_varint(out, value)


# ============================================================================
# message dataclasses (subset of onnx.proto3)
# ============================================================================

@dataclass
class TensorProto:
    name: str = ""
    dims: List[int] = field(default_factory=list)
    data_type: int = FLOAT
    raw_data: bytes = b""
    float_data: List[float] = field(default_factory=list)
    int32_data: List[int] = field(default_factory=list)
    int64_data: List[int] = field(default_factory=list)
    double_data: List[float] = field(default_factory=list)
    uint64_data: List[int] = field(default_factory=list)
    string_data: List[bytes] = field(default_factory=list)

    def to_numpy(self) -> np.ndarray:
        np_dtype = DTYPE_TO_NUMPY.get(self.data_type)
        if self.data_type == BFLOAT16:
            if self.raw_data:
                u16 = np.frombuffer(self.raw_data, dtype="<u2")
                return (u16.astype(np.uint32) << 16).view(np.float32).astype(
                    np.float32).reshape(self.dims)
            u16 = np.asarray(self.int32_data, dtype=np.uint32)
            return (u16 << 16).view(np.float32).reshape(self.dims)
        if np_dtype is None:
            raise TypeError(f"unsupported tensor data_type {self.data_type}")
        if self.raw_data:
            arr = np.frombuffer(self.raw_data, dtype=np.dtype(np_dtype).newbyteorder("<"))
            return arr.astype(np_dtype).reshape(self.dims)
        if self.data_type == FLOAT:
            arr = np.asarray(self.float_data, dtype=np.float32)
        elif self.data_type == DOUBLE:
            arr = np.asarray(self.double_data, dtype=np.float64)
        elif self.data_type == INT64:
            arr = np.asarray(self.int64_data, dtype=np.int64)
        elif self.data_type in (UINT64,):
            arr = np.asarray(self.uint64_data, dtype=np.uint64)
        elif self.data_type in (INT32, INT16, INT8, UINT16, UINT8, BOOL, FLOAT16):
            arr = np.asarray(self.int32_data)
            if self.data_type == FLOAT16:
                arr = arr.astype(np.uint16).view(np.float16)
            else:
                arr = arr.astype(np_dtype)
        else:
            raise TypeError(f"unsupported tensor data_type {self.data_type}")
        return arr.reshape(self.dims)

    @staticmethod
    def from_numpy(arr: np.ndarray, name: str = "") -> "TensorProto":
        arr = np.asarray(arr)  # NOT ascontiguousarray: it promotes 0-d to (1,)
        return TensorProto(name=name, dims=list(arr.shape),
                           data_type=numpy_to_elem_type(arr.dtype),
                           raw_data=arr.astype(
                               arr.dtype.newbyteorder("<")).tobytes())

    @staticmethod
    def parse(data) -> "TensorProto":
        t = TensorProto()
        for fnum, wtype, val in _iter_fields(data):
            if fnum == 1:
                _packed_or_single_i64(wtype, val, t.dims)
            elif fnum == 2:
                t.data_type = val
            elif fnum == 4:
                _packed_or_single_f32(wtype, val, t.float_data)
            elif fnum == 5:
                _packed_or_single_i64(wtype, val, t.int32_data)
            elif fnum == 6:
                t.string_data.append(bytes(val))
            elif fnum == 7:
                _packed_or_single_i64(wtype, val, t.int64_data)
            elif fnum == 8:
                t.name = bytes(val).decode("utf-8")
            elif fnum == 9:
                t.raw_data = bytes(val)
            elif fnum == 10:
                _packed_or_single_f64(wtype, val, t.double_data)
            elif fnum == 11:
                _packed_or_single_i64(wtype, val, t.uint64_data)
            elif fnum == 13:
                raise ValueError("external tensor data is not supported")
        return t

    def serialize(self) -> bytes:
        out = bytearray()
        for d in self.dims:
            _emit_varint_field(out, 1, d)
        _emit_varint_field(out, 2, self.data_type)
        if self.name:
            _emit_str(out, 8, self.name)
        if self.raw_data:
            _emit_bytes(out, 9, self.raw_data)
        return bytes(out)


@dataclass
class AttributeProto:
    name: str = ""
    type: int = 0
    f: float = 0.0
    i: int = 0
    s: bytes = b""
    t: Optional[TensorProto] = None
    g: Optional["GraphProto"] = None
    floats: List[float] = field(default_factory=list)
    ints: List[int] = field(default_factory=list)
    strings: List[bytes] = field(default_factory=list)
    graphs: List["GraphProto"] = field(default_factory=list)

    def value(self) -> Any:
        if self.type == A_FLOAT:
            return self.f
        if self.type == A_INT:
            return self.i
        if self.type == A_STRING:
            return self.s.decode("utf-8")
        if self.type == A_TENSOR:
            return self.t.to_numpy()
        if self.type == A_GRAPH:
            return self.g
        if self.type == A_FLOATS:
            return list(self.floats)
        if self.type == A_INTS:
            return list(self.ints)
        if self.type == A_STRINGS:
            return [s.decode("utf-8") for s in self.strings]
        if self.type == A_GRAPHS:
            return list(self.graphs)
        raise ValueError(f"unsupported attribute type {self.type} for {self.name}")

    @staticmethod
    def parse(data) -> "AttributeProto":
        a = AttributeProto()
        for fnum, wtype, val in _iter_fields(data):
            if fnum == 1:
                a.name = bytes(val).decode("utf-8")
            elif fnum == 2:
                a.f = struct.unpack("<f", val)[0]
            elif fnum == 3:
                a.i = _to_signed64(val)
            elif fnum == 4:
                a.s = bytes(val)
            elif fnum == 5:
                a.t = TensorProto.parse(val)
            elif fnum == 6:
                a.g = GraphProto.parse(val)
            elif fnum == 7:
                _packed_or_single_f32(wtype, val, a.floats)
            elif fnum == 8:
                _packed_or_single_i64(wtype, val, a.ints)
            elif fnum == 9:
                a.strings.append(bytes(val))
            elif fnum == 11:
                a.graphs.append(GraphProto.parse(val))
            elif fnum == 20:
                a.type = val
        return a

    @staticmethod
    def make(name: str, value: Any) -> "AttributeProto":
        a = AttributeProto(name=name)
        if isinstance(value, bool):
            a.type, a.i = A_INT, int(value)
        elif isinstance(value, (int, np.integer)):
            a.type, a.i = A_INT, int(value)
        elif isinstance(value, (float, np.floating)):
            a.type, a.f = A_FLOAT, float(value)
        elif isinstance(value, str):
            a.type, a.s = A_STRING, value.encode("utf-8")
        elif isinstance(value, np.ndarray):
            a.type, a.t = A_TENSOR, TensorProto.from_numpy(value)
        elif isinstance(value, (list, tuple)):
            vals = list(value)
            if all(isinstance(v, (int, np.integer)) for v in vals):
                a.type, a.ints = A_INTS, [int(v) for v in vals]
            elif all(isinstance(v, str) for v in vals):
                a.type, a.strings = A_STRINGS, [v.encode("utf-8") for v in vals]
            else:
                a.type, a.floats = A_FLOATS, [float(v) for v in vals]
        else:
            raise TypeError(f"cannot encode attribute {name}={value!r}")
        return a

    def serialize(self) -> bytes:
        out = bytearray()
        _emit_str(out, 1, self.name)
        if self.type == A_FLOAT:
            _emit_tag(out, 2, 5)
            out.extend(struct.pack("<f", self.f))
        elif self.type == A_INT:
            _emit_varint_field(out, 3, self.i if self.i >= 0 else self.i + (1 << 64))
        elif self.type == A_STRING:
            _emit_bytes(out, 4, self.s)
        elif self.type == A_TENSOR:
            _emit_bytes(out, 5, self.t.serialize())
        elif self.type == A_FLOATS:
            for v in self.floats:
                _emit_tag(out, 7, 5)
                out.extend(struct.pack("<f", v))
        elif self.type == A_INTS:
            for v in self.ints:
                _emit_varint_field(out, 8, v if v >= 0 else v + (1 << 64))
        elif self.type == A_STRINGS:
            for s in self.strings:
                _emit_bytes(out, 9, s)
        else:
            raise TypeError(f"cannot serialize attribute type {self.type}")
        _emit_varint_field(out, 20, self.type)
        return bytes(out)


@dataclass
class NodeProto:
    op_type: str = ""
    name: str = ""
    domain: str = ""
    input: List[str] = field(default_factory=list)
    output: List[str] = field(default_factory=list)
    attribute: List[AttributeProto] = field(default_factory=list)

    def attrs(self) -> Dict[str, Any]:
        return {a.name: a.value() for a in self.attribute}

    @staticmethod
    def parse(data) -> "NodeProto":
        n = NodeProto()
        for fnum, wtype, val in _iter_fields(data):
            if fnum == 1:
                n.input.append(bytes(val).decode("utf-8"))
            elif fnum == 2:
                n.output.append(bytes(val).decode("utf-8"))
            elif fnum == 3:
                n.name = bytes(val).decode("utf-8")
            elif fnum == 4:
                n.op_type = bytes(val).decode("utf-8")
            elif fnum == 5:
                n.attribute.append(AttributeProto.parse(val))
            elif fnum == 7:
                n.domain = bytes(val).decode("utf-8")
        return n

    def serialize(self) -> bytes:
        out = bytearray()
        for s in self.input:
            _emit_str(out, 1, s)
        for s in self.output:
            _emit_str(out, 2, s)
        if self.name:
            _emit_str(out, 3, self.name)
        _emit_str(out, 4, self.op_type)
        for a in self.attribute:
            _emit_bytes(out, 5, a.serialize())
        if self.domain:
            _emit_str(out, 7, self.domain)
        return bytes(out)


@dataclass
class ValueInfoProto:
    name: str = ""
    elem_type: int = FLOAT
    #: ints for static dims, strings for symbolic dims, None when unknown
    shape: Optional[List[Union[int, str, None]]] = None

    @staticmethod
    def parse(data) -> "ValueInfoProto":
        v = ValueInfoProto()
        for fnum, _, val in _iter_fields(data):
            if fnum == 1:
                v.name = bytes(val).decode("utf-8")
            elif fnum == 2:
                v.elem_type, v.shape = _parse_type_proto(val)
        return v

    def serialize(self) -> bytes:
        out = bytearray()
        _emit_str(out, 1, self.name)
        _emit_bytes(out, 2, _serialize_type_proto(self.elem_type, self.shape))
        return bytes(out)


def _parse_type_proto(data) -> Tuple[int, Optional[List]]:
    elem_type, shape = FLOAT, None
    for fnum, _, val in _iter_fields(data):
        if fnum == 1:  # tensor_type
            for f2, _, v2 in _iter_fields(val):
                if f2 == 1:
                    elem_type = v2
                elif f2 == 2:  # TensorShapeProto
                    shape = []
                    for f3, _, v3 in _iter_fields(v2):
                        if f3 == 1:  # Dimension
                            dim: Union[int, str, None] = None
                            for f4, _, v4 in _iter_fields(v3):
                                if f4 == 1:
                                    dim = _to_signed64(v4)
                                elif f4 == 2:
                                    dim = bytes(v4).decode("utf-8")
                            shape.append(dim)
    return elem_type, shape


def _serialize_type_proto(elem_type: int, shape: Optional[List]) -> bytes:
    tt = bytearray()
    _emit_varint_field(tt, 1, elem_type)
    if shape is not None:
        sh = bytearray()
        for dim in shape:
            d = bytearray()
            if isinstance(dim, (int, np.integer)):
                _emit_varint_field(d, 1, int(dim))
            elif isinstance(dim, str):
                _emit_str(d, 2, dim)
            _emit_bytes(sh, 1, bytes(d))
        _emit_bytes(tt, 2, bytes(sh))
    out = bytearray()
    _emit_bytes(out, 1, bytes(tt))
    return bytes(out)


@dataclass
class GraphProto:
    name: str = ""
    node: List[NodeProto] = field(default_factory=list)
    initializer: List[TensorProto] = field(default_factory=list)
    input: List[ValueInfoProto] = field(default_factory=list)
    output: List[ValueInfoProto] = field(default_factory=list)
    value_info: List[ValueInfoProto] = field(default_factory=list)

    @staticmethod
    def parse(data) -> "GraphProto":
        g = GraphProto()
        for fnum, _, val in _iter_fields(data):
            if fnum == 1:
                g.node.append(NodeProto.parse(val))
            elif fnum == 2:
                g.name = bytes(val).decode("utf-8")
            elif fnum == 5:
                g.initializer.append(TensorProto.parse(val))
            elif fnum == 11:
                g.input.append(ValueInfoProto.parse(val))
            elif fnum == 12:
                g.output.append(ValueInfoProto.parse(val))
            elif fnum == 13:
                g.value_info.append(ValueInfoProto.parse(val))
        return g

    def serialize(self) -> bytes:
        out = bytearray()
        for n in self.node:
            _emit_bytes(out, 1, n.serialize())
        if self.name:
            _emit_str(out, 2, self.name)
        for t in self.initializer:
            _emit_bytes(out, 5, t.serialize())
        for v in self.input:
            _emit_bytes(out, 11, v.serialize())
        for v in self.output:
            _emit_bytes(out, 12, v.serialize())
        for v in self.value_info:
            _emit_bytes(out, 13, v.serialize())
        return bytes(out)


@dataclass
class ModelProto:
    ir_version: int = 8
    producer_name: str = "synapseml_tpu"
    producer_version: str = "0.1"
    model_version: int = 0
    opset_version: int = 17
    domain: str = ""
    graph: Optional[GraphProto] = None

    @staticmethod
    def parse(data: bytes) -> "ModelProto":
        m = ModelProto()
        for fnum, _, val in _iter_fields(data):
            if fnum == 1:
                m.ir_version = _to_signed64(val)
            elif fnum == 2:
                m.producer_name = bytes(val).decode("utf-8")
            elif fnum == 3:
                m.producer_version = bytes(val).decode("utf-8")
            elif fnum == 5:
                m.model_version = _to_signed64(val)
            elif fnum == 7:
                m.graph = GraphProto.parse(val)
            elif fnum == 8:  # OperatorSetIdProto
                dom, ver = "", None
                for f2, _, v2 in _iter_fields(val):
                    if f2 == 1:
                        dom = bytes(v2).decode("utf-8")
                    elif f2 == 2:
                        ver = _to_signed64(v2)
                if ver is not None and dom in ("", "ai.onnx"):
                    m.opset_version = ver
        if m.graph is None:
            raise ValueError("ModelProto has no graph")
        return m

    def serialize(self) -> bytes:
        out = bytearray()
        _emit_varint_field(out, 1, self.ir_version)
        _emit_str(out, 2, self.producer_name)
        _emit_str(out, 3, self.producer_version)
        if self.model_version:
            _emit_varint_field(out, 5, self.model_version)
        _emit_bytes(out, 7, self.graph.serialize())
        ops = bytearray()
        _emit_str(ops, 1, self.domain)
        _emit_varint_field(ops, 2, self.opset_version)
        _emit_bytes(out, 8, bytes(ops))
        return bytes(out)


def load_model(source: Union[str, bytes]) -> ModelProto:
    """Parse an ONNX model from a file path or raw bytes."""
    if isinstance(source, str):
        with open(source, "rb") as f:
            source = f.read()
    return ModelProto.parse(source)
