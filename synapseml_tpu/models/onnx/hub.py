"""ONNXHub — model-zoo loader with a local cache.

Re-designs the reference's hub client (reference: deep-learning/.../onnx/
ONNXHub.scala:72-255 — manifest download, SHA-256 verification, cache
directory).  This environment has no egress, so downloads are gated:
models resolve from the cache directory (or an explicit local manifest)
and a clear error names the missing file otherwise.  SHA-256 checks and
the manifest schema match the reference semantics.
"""

from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional

_DEFAULT_CACHE = os.path.join(os.path.expanduser("~"), ".cache",
                              "synapseml_tpu", "onnx_hub")


@dataclass
class ONNXHubModelInfo:
    model: str
    model_path: str
    onnx_sha: Optional[str] = None
    opset: Optional[int] = None
    tags: List[str] = field(default_factory=list)


class ONNXHub:
    """Local-cache ONNX model hub (network access intentionally absent)."""

    def __init__(self, cache_dir: Optional[str] = None):
        self.cache_dir = cache_dir or os.environ.get(
            "SYNAPSEML_TPU_ONNX_HUB", _DEFAULT_CACHE)

    def manifest_path(self) -> str:
        return os.path.join(self.cache_dir, "ONNX_HUB_MANIFEST.json")

    def list_models(self, tags: Optional[List[str]] = None
                    ) -> List[ONNXHubModelInfo]:
        path = self.manifest_path()
        if not os.path.exists(path):
            return []
        with open(path) as f:
            raw = json.load(f)
        infos = [ONNXHubModelInfo(
            model=e.get("model", ""),
            model_path=e.get("model_path", ""),
            onnx_sha=(e.get("metadata", {}) or {}).get("model_sha"),
            opset=e.get("opset_version"),
            tags=(e.get("metadata", {}) or {}).get("tags", []),
        ) for e in raw]
        if tags:
            wanted = {t.lower() for t in tags}
            infos = [i for i in infos
                     if wanted & {t.lower() for t in i.tags}]
        return infos

    def get_model_path(self, name: str) -> str:
        for info in self.list_models():
            if info.model.lower() == name.lower():
                local = os.path.join(self.cache_dir, info.model_path)
                if os.path.exists(local):
                    if info.onnx_sha:
                        self._verify_sha(local, info.onnx_sha)
                    return local
                raise FileNotFoundError(
                    f"model {name!r} is in the manifest but "
                    f"{local} is absent; this build has no network egress — "
                    f"place the file there manually")
        direct = os.path.join(self.cache_dir, name)
        if os.path.exists(direct):
            return direct
        raise FileNotFoundError(
            f"model {name!r} not found under {self.cache_dir}; no network "
            f"egress is available to download it")

    def load_model(self, name: str) -> bytes:
        with open(self.get_model_path(name), "rb") as f:
            return f.read()

    @staticmethod
    def _verify_sha(path: str, expected: str) -> None:
        h = hashlib.sha256()
        with open(path, "rb") as f:
            for chunk in iter(lambda: f.read(1 << 20), b""):
                h.update(chunk)
        if h.hexdigest().lower() != expected.lower():
            raise IOError(f"SHA-256 mismatch for {path}: "
                          f"{h.hexdigest()} != {expected}")
