"""Graph evaluator: ONNX graph → one jittable JAX function.

Where the reference creates an OrtSession per Spark partition and runs it
batch-by-batch over JNI (reference: deep-learning/.../onnx/ONNXRuntime.scala:
25-44 session creation, :58-108 ``applyModel`` hot loop), the TPU build
traces the whole graph once into a single XLA program; `jit` caching keys
on input shapes, so fixed-size minibatches compile exactly once.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np

from .graph import Graph, load_graph
from .ops import OpCall, lower


def evaluate(graph: Graph, inputs: Dict[str, Any],
             outputs: Optional[Sequence[str]] = None,
             dtype: Optional[Any] = None) -> Dict[str, Any]:
    """Evaluate ``graph`` on ``inputs`` (traceable: call under jit).

    ``dtype`` (e.g. ``jnp.bfloat16``): float weights AND float inputs are
    cast to it, so matmuls/convs run the reduced-precision MXU path with
    XLA's f32 accumulation — the role the GPU execution provider's fp16
    mode plays in the reference's ORT stack (ONNXRuntime.scala:46-56).
    Under jit the weight casts constant-fold once into the executable."""
    def _c(v):
        if dtype is not None and np.issubdtype(np.asarray(v).dtype
                                               if not hasattr(v, "dtype")
                                               else v.dtype, np.floating):
            return jnp.asarray(v, dtype)
        return v

    env: Dict[str, Any] = {}
    for k, v in graph.initializers.items():
        env[k] = _c(v)
    for k, v in inputs.items():
        env[k] = _c(v)
    missing = [n for n in graph.input_names if n not in env]
    if missing:
        raise KeyError(f"missing graph inputs: {missing}")

    wanted = list(outputs) if outputs is not None else graph.output_names
    for node in graph.toposort():
        vals = [env[i] if i else None for i in node.inputs]
        call = OpCall(node.op_type, vals, node.attrs, graph.opset,
                      len(node.outputs))
        results = lower(call)
        for name, val in zip(node.outputs, results):
            if name:
                # keep every float tensor at the reduced precision: ops
                # that internally upcast (epsilon math, reductions) would
                # otherwise leak f32 into downstream convs/matmuls
                env[name] = _c(val)
    missing_out = [o for o in wanted if o not in env]
    if missing_out:
        raise KeyError(f"graph values not produced: {missing_out}")
    return {o: env[o] for o in wanted}


class OnnxFunction:
    """A compiled ONNX graph: ``fn(**inputs) -> dict`` with jit caching."""

    def __init__(self, graph: Graph, outputs: Optional[Sequence[str]] = None,
                 dtype: Optional[Any] = None):
        self.graph = graph
        self.input_names = graph.input_names
        self.output_names = list(outputs) if outputs else graph.output_names
        self.dtype = dtype

        def _run(inputs: Dict[str, Any]) -> Dict[str, Any]:
            out = evaluate(self.graph, inputs, self.output_names, dtype=dtype)
            return {k: jnp.asarray(v) for k, v in out.items()}

        self._jitted = jax.jit(_run)

    def __call__(self, **inputs) -> Dict[str, Any]:
        # device arrays pass through untouched — np.asarray on a jax array
        # would DOWNLOAD it and the dispatch would re-upload (a full
        # round trip over the host<->device link per call)
        arrays = {k: v if isinstance(v, jax.Array) else np.asarray(v)
                  for k, v in inputs.items()}
        return dict(self._jitted(arrays))

    def trace(self, inputs: Dict[str, Any]) -> Dict[str, Any]:
        """Traceable call for embedding in larger jitted programs."""
        return evaluate(self.graph, inputs, self.output_names,
                        dtype=self.dtype)


def compile_onnx(source: Union[str, bytes, Graph],
                 outputs: Optional[Sequence[str]] = None,
                 dtype: Optional[Any] = None) -> OnnxFunction:
    graph = source if isinstance(source, Graph) else load_graph(source)
    return OnnxFunction(graph, outputs, dtype=dtype)
