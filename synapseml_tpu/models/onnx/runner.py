"""Graph evaluator: ONNX graph → one jittable JAX function.

Where the reference creates an OrtSession per Spark partition and runs it
batch-by-batch over JNI (reference: deep-learning/.../onnx/ONNXRuntime.scala:
25-44 session creation, :58-108 ``applyModel`` hot loop), the TPU build
traces the whole graph once into a single XLA program; `jit` caching keys
on input shapes, so fixed-size minibatches compile exactly once.
"""

from __future__ import annotations

import hashlib
from typing import Any, Dict, List, Optional, Sequence, Union

import jax
import jax.numpy as jnp
import numpy as np


#: ops that MIX rows when applied over axis 0 (or over all axes, the
#: Reduce* default) — chunking the batch through them would silently
#: change results, so such graphs keep the raise-on-OOM behavior
_ROW_MIXING_OPS = frozenset((
    "ReduceSum", "ReduceMean", "ReduceMax", "ReduceMin", "ReduceProd",
    "ReduceL1", "ReduceL2", "ReduceLogSum", "ReduceLogSumExp",
    "ReduceSumSquare", "Softmax", "LogSoftmax", "Hardmax", "Mean",
    "CumSum", "LpNormalization", "TopK", "ArgMax", "ArgMin",
))


def _mixes_batch_rows(graph) -> bool:
    """True when any node plausibly combines values ACROSS axis 0 —
    chunked execution would compute per-chunk statistics instead of
    whole-batch ones.  Conservative: a hit only disables OOM chunking
    (the call then fails like the unchunked path would)."""
    for n in getattr(graph, "nodes", ()):
        if n.op_type not in _ROW_MIXING_OPS:
            continue
        axis = n.attrs.get("axis")
        axes = n.attrs.get("axes")
        if axis == 0:
            return True
        if axes is not None and 0 in np.atleast_1d(axes):
            return True
        if (axis is None and axes is None
                and n.op_type.startswith("Reduce")):
            return True                  # Reduce* default: ALL axes
    return False


def _graph_oom_key(graph) -> str:
    """Stable structural key for the OOM-safe-batch memory: the same
    model reloaded into a fresh ``OnnxFunction`` keeps its discovered
    safe batch size, and the process-wide memory/gauge stays bounded by
    the number of DISTINCT graphs (``id(self)`` aliased after GC reuse;
    a per-instance sequence forgot the size on every reload)."""
    sig = "|".join((
        getattr(graph, "name", "") or "graph",
        str(len(getattr(graph, "nodes", ()))),
        ",".join(n.op_type for n in getattr(graph, "nodes", ())[:64]),
        ",".join(graph.input_names), ",".join(graph.output_names),
    ))
    return "onnx:" + hashlib.sha1(sig.encode()).hexdigest()[:12]

from .graph import Graph, load_graph
from .ops import OpCall, lower


def evaluate(graph: Graph, inputs: Dict[str, Any],
             outputs: Optional[Sequence[str]] = None,
             dtype: Optional[Any] = None) -> Dict[str, Any]:
    """Evaluate ``graph`` on ``inputs`` (traceable: call under jit).

    ``dtype`` (e.g. ``jnp.bfloat16``): float weights AND float inputs are
    cast to it, so matmuls/convs run the reduced-precision MXU path with
    XLA's f32 accumulation — the role the GPU execution provider's fp16
    mode plays in the reference's ORT stack (ONNXRuntime.scala:46-56).
    Under jit the weight casts constant-fold once into the executable."""
    def _c(v):
        if dtype is not None and np.issubdtype(np.asarray(v).dtype
                                               if not hasattr(v, "dtype")
                                               else v.dtype, np.floating):
            return jnp.asarray(v, dtype)
        return v

    env: Dict[str, Any] = {}
    for k, v in graph.initializers.items():
        env[k] = _c(v)
    for k, v in inputs.items():
        env[k] = _c(v)
    missing = [n for n in graph.input_names if n not in env]
    if missing:
        raise KeyError(f"missing graph inputs: {missing}")

    wanted = list(outputs) if outputs is not None else graph.output_names
    for node in graph.toposort():
        vals = [env[i] if i else None for i in node.inputs]
        call = OpCall(node.op_type, vals, node.attrs, graph.opset,
                      len(node.outputs))
        results = lower(call)
        for name, val in zip(node.outputs, results):
            if name:
                # keep every float tensor at the reduced precision: ops
                # that internally upcast (epsilon math, reductions) would
                # otherwise leak f32 into downstream convs/matmuls
                env[name] = _c(val)
    missing_out = [o for o in wanted if o not in env]
    if missing_out:
        raise KeyError(f"graph values not produced: {missing_out}")
    return {o: env[o] for o in wanted}


class OnnxFunction:
    """A compiled ONNX graph: ``fn(**inputs) -> dict`` with jit caching.

    Calls are OOM-adaptive: when the single-dispatch path dies with XLA
    ``RESOURCE_EXHAUSTED`` and every input shares a leading batch
    dimension, the batch is bisected into chunks that fit (safe size
    remembered per graph in the ``rowguard_safe_batch_size`` gauge)
    and per-output results concatenate along axis 0 — the standard
    batch-major, row-independent inference layout (the same assumption
    ORT-style dynamic batching makes).  Graphs that visibly combine
    values across axis 0 (axis-0 softmax/reductions, all-axes Reduce*)
    are detected and never chunked — their OOM re-raises — and
    non-batch outputs fail loudly on the concatenate rather than
    silently mixing axes."""

    def __init__(self, graph: Graph, outputs: Optional[Sequence[str]] = None,
                 dtype: Optional[Any] = None):
        self.graph = graph
        self.input_names = graph.input_names
        self.output_names = list(outputs) if outputs else graph.output_names
        self.dtype = dtype
        self._oom_key = _graph_oom_key(graph)
        self._chunkable = not _mixes_batch_rows(graph)

        def _run(inputs: Dict[str, Any]) -> Dict[str, Any]:
            out = evaluate(self.graph, inputs, self.output_names, dtype=dtype)
            return {k: jnp.asarray(v) for k, v in out.items()}

        self._jitted = jax.jit(_run)

    def __call__(self, **inputs) -> Dict[str, Any]:
        from ...resilience.rowguard import oom_fault_point, run_adaptive

        # device arrays pass through untouched — np.asarray on a jax array
        # would DOWNLOAD it and the dispatch would re-upload (a full
        # round trip over the host<->device link per call)
        arrays = {k: v if isinstance(v, jax.Array) else np.asarray(v)
                  for k, v in inputs.items()}
        dims = {v.shape[0] for v in arrays.values()
                if getattr(v, "ndim", 0) >= 1}
        if len(dims) != 1 or next(iter(dims)) <= 1 or not self._chunkable:
            # no shared batch axis to bisect (or the graph combines
            # values across rows, so chunking would change results) —
            # dispatch as-is and let an OOM surface
            oom_fault_point(self._oom_key, 1)
            return dict(self._jitted(arrays))
        n = next(iter(dims))

        def run(bs: int) -> Dict[str, Any]:
            if bs >= n:
                oom_fault_point(self._oom_key, n)
                return dict(self._jitted(arrays))
            outs = []
            for s in range(0, n, bs):
                chunk = {k: (v[s:s + bs] if getattr(v, "ndim", 0) >= 1
                             else v) for k, v in arrays.items()}
                oom_fault_point(self._oom_key, min(bs, n - s))
                outs.append(self._jitted(chunk))
            return {k: jnp.concatenate([o[k] for o in outs], axis=0)
                    for k in outs[0]}

        return run_adaptive(self._oom_key, n, run)

    def trace(self, inputs: Dict[str, Any]) -> Dict[str, Any]:
        """Traceable call for embedding in larger jitted programs."""
        return evaluate(self.graph, inputs, self.output_names,
                        dtype=self.dtype)


def compile_onnx(source: Union[str, bytes, Graph],
                 outputs: Optional[Sequence[str]] = None,
                 dtype: Optional[Any] = None) -> OnnxFunction:
    graph = source if isinstance(source, Graph) else load_graph(source)
    return OnnxFunction(graph, outputs, dtype=dtype)
