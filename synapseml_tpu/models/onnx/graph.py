"""ONNX graph IR: topological order, output slicing, and a builder.

``slice_at_outputs`` re-implements the reference's backward-reachability
model-surgery pass (reference: deep-learning/.../onnx/ONNXUtils.scala:259-345
``sliceModelAtOutputs``): keep exactly the nodes an intermediate output
depends on, re-point graph outputs, drop unreferenced initializers.
``GraphBuilder`` constructs valid ONNX protobuf bytes directly — the test
and export path in an environment without the onnx wheel.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Union

import numpy as np

from .protoparse import (FLOAT, AttributeProto, GraphProto, ModelProto,
                         NodeProto, TensorProto, ValueInfoProto,
                         numpy_to_elem_type)


@dataclass
class Node:
    op_type: str
    inputs: List[str]
    outputs: List[str]
    attrs: Dict[str, Any] = field(default_factory=dict)
    name: str = ""
    domain: str = ""


@dataclass
class ValueInfo:
    name: str
    elem_type: int = FLOAT
    shape: Optional[List[Union[int, str, None]]] = None


@dataclass
class Graph:
    name: str
    nodes: List[Node]
    inputs: List[ValueInfo]
    outputs: List[ValueInfo]
    initializers: Dict[str, np.ndarray]
    opset: int = 17

    @property
    def input_names(self) -> List[str]:
        return [v.name for v in self.inputs if v.name not in self.initializers]

    @property
    def output_names(self) -> List[str]:
        return [v.name for v in self.outputs]

    def producers(self) -> Dict[str, Node]:
        out: Dict[str, Node] = {}
        for n in self.nodes:
            for o in n.outputs:
                if o:
                    out[o] = n
        return out

    def toposort(self) -> List[Node]:
        """Topological order of nodes (graph may be stored unordered)."""
        produced = self.producers()
        order: List[Node] = []
        state: Dict[int, int] = {}  # id(node) -> 0 visiting / 1 done

        def visit(n: Node) -> None:
            s = state.get(id(n))
            if s == 1:
                return
            if s == 0:
                raise ValueError(f"cycle through node {n.op_type} {n.name!r}")
            state[id(n)] = 0
            for i in n.inputs:
                if i in produced:
                    visit(produced[i])
            state[id(n)] = 1
            order.append(n)

        import sys
        old = sys.getrecursionlimit()
        sys.setrecursionlimit(max(old, 4 * len(self.nodes) + 100))
        try:
            for n in self.nodes:
                visit(n)
        finally:
            sys.setrecursionlimit(old)
        return order


def from_model(model: ModelProto) -> Graph:
    g = model.graph
    inits = {t.name: t.to_numpy() for t in g.initializer}
    nodes = [Node(n.op_type, list(n.input), list(n.output), n.attrs(),
                  n.name, n.domain) for n in g.node]
    inputs = [ValueInfo(v.name, v.elem_type, v.shape) for v in g.input]
    outputs = [ValueInfo(v.name, v.elem_type, v.shape) for v in g.output]
    return Graph(g.name or "graph", nodes, inputs, outputs, inits,
                 opset=model.opset_version)


def load_graph(source: Union[str, bytes]) -> Graph:
    from .protoparse import load_model
    return from_model(load_model(source))


def slice_at_outputs(graph: Graph, output_names: Sequence[str]) -> Graph:
    """Backward-reachability slice (reference: ONNXUtils.scala:259-345).

    Returns a new graph whose outputs are ``output_names`` and that contains
    only the nodes/initializers those outputs transitively require.
    """
    produced = graph.producers()
    known = (set(produced) | set(graph.initializers)
             | {v.name for v in graph.inputs})
    missing = [o for o in output_names if o not in known]
    if missing:
        raise KeyError(f"outputs not found in graph: {missing}")

    needed_nodes: List[Node] = []
    seen_nodes = set()
    frontier = list(output_names)
    needed_values = set(output_names)
    while frontier:
        name = frontier.pop()
        node = produced.get(name)
        if node is None or id(node) in seen_nodes:
            continue
        seen_nodes.add(id(node))
        needed_nodes.append(node)
        for i in node.inputs:
            if i and i not in needed_values:
                needed_values.add(i)
                frontier.append(i)

    nodes = [n for n in graph.nodes if id(n) in seen_nodes]
    inits = {k: v for k, v in graph.initializers.items() if k in needed_values}
    inputs = [v for v in graph.inputs
              if v.name in needed_values and v.name not in inits]
    outputs = [ValueInfo(o) for o in output_names]
    return Graph(graph.name + "_sliced", nodes, inputs, outputs, inits,
                 opset=graph.opset)


def to_model(graph: Graph) -> ModelProto:
    gp = GraphProto(name=graph.name)
    for n in graph.nodes:
        gp.node.append(NodeProto(
            op_type=n.op_type, name=n.name, domain=n.domain,
            input=list(n.inputs), output=list(n.outputs),
            attribute=[AttributeProto.make(k, v) for k, v in n.attrs.items()]))
    for name, arr in graph.initializers.items():
        gp.initializer.append(TensorProto.from_numpy(np.asarray(arr), name))
    for v in graph.inputs:
        gp.input.append(ValueInfoProto(v.name, v.elem_type, v.shape))
    for v in graph.outputs:
        gp.output.append(ValueInfoProto(v.name, v.elem_type, v.shape))
    return ModelProto(graph=gp, opset_version=graph.opset)


class GraphBuilder:
    """Fluent ONNX graph construction; ``.build()`` → protobuf bytes.

    >>> b = GraphBuilder("mlp")
    >>> x = b.input("x", (None, 4))
    >>> w = b.initializer("w", np.zeros((4, 8), np.float32))
    >>> h = b.node("MatMul", [x, w])
    >>> b.output(b.node("Relu", [h]))
    >>> model_bytes = b.build()
    """

    def __init__(self, name: str = "graph", opset: int = 17):
        self._g = Graph(name, [], [], [], {}, opset=opset)
        self._ctr = 0

    def _fresh(self, base: str) -> str:
        self._ctr += 1
        return f"{base}_{self._ctr}"

    def input(self, name: str, shape: Sequence[Optional[int]],
              dtype=np.float32) -> str:
        self._g.inputs.append(ValueInfo(name, numpy_to_elem_type(dtype),
                                        [d if d else f"d{i}"
                                         for i, d in enumerate(shape)]))
        return name

    def initializer(self, name: str, value: np.ndarray) -> str:
        self._g.initializers[name] = np.asarray(value)
        return name

    def node(self, op_type: str, inputs: Sequence[str],
             outputs: Optional[Sequence[str]] = None,
             n_outputs: int = 1, **attrs) -> Union[str, List[str]]:
        if outputs is None:
            outputs = [self._fresh(op_type.lower()) for _ in range(n_outputs)]
        self._g.nodes.append(Node(op_type, list(inputs), list(outputs),
                                  dict(attrs)))
        return outputs[0] if len(outputs) == 1 else list(outputs)

    def output(self, name: str, dtype=np.float32) -> str:
        self._g.outputs.append(ValueInfo(name, numpy_to_elem_type(dtype)))
        return name

    @property
    def graph(self) -> Graph:
        return self._g

    def build(self) -> bytes:
        return to_model(self._g).serialize()
