"""ONNXModel — batch-inference pipeline Transformer.

Re-designs the reference's ONNX Runtime transformer (reference:
deep-learning/.../onnx/ONNXModel.scala:145-423 — miniBatch → broadcast
model bytes → mapPartitions → OrtSession.run per batch → FlattenBatch →
softmax/argmax UDFs) for XLA: the model protobuf lowers to ONE jitted
program; rows are processed in fixed-size minibatches padded to a static
shape so `jit` compiles exactly once per shape, and the softmax/argmax
post-ops are fused into the same program instead of per-row UDFs.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dataset import Dataset
from ...core.params import (BoolParam, DictParam, IntParam, Param, Params,
                            PyObjectParam, StringParam)
from ...core.pipeline import Model, Transformer
from .graph import Graph, load_graph, slice_at_outputs, to_model
from .runner import evaluate


class ONNXModel(Model):
    """Run an ONNX model over Dataset columns on TPU via XLA.

    Parameters mirror the reference (ONNXModel.scala:60-140):
    ``modelPayload`` (protobuf bytes), ``feedDict`` {onnx input → column},
    ``fetchDict`` {output column → onnx output}, ``miniBatchSize``,
    ``softMaxDict`` / ``argMaxDict`` {input column → output column}.
    """

    modelPayload = PyObjectParam(doc="ONNX model protobuf bytes")
    feedDict = DictParam(doc="map: onnx graph input name -> dataset column")
    fetchDict = DictParam(doc="map: output column -> onnx graph output name")
    miniBatchSize = IntParam(doc="rows per device batch", default=128)
    softMaxDict = DictParam(doc="map: input col -> output col to soft-max")
    argMaxDict = DictParam(doc="map: input col -> output col to arg-max")
    dtype = StringParam(doc="compute dtype for float inputs",
                        default="float32", allowed=("float32", "bfloat16"))

    def __init__(self, model: Union[bytes, str, None] = None, **kw):
        super().__init__(**kw)
        if model is not None:
            self.set_model(model)
        self._fn_cache: Dict[Any, Any] = {}

    def _get_cache(self) -> Dict[Any, Any]:
        # instances deserialized via load_stage skip __init__
        if not hasattr(self, "_fn_cache"):
            self._fn_cache = {}
        return self._fn_cache

    # -- model loading -----------------------------------------------------
    def set_model(self, model: Union[bytes, str]) -> "ONNXModel":
        if isinstance(model, str):
            with open(model, "rb") as f:
                model = f.read()
        self.set("modelPayload", bytes(model))
        self._fn_cache = {}
        self._graph_cache = None
        return self

    def set_feed_dict(self, feed: Dict[str, str]) -> "ONNXModel":
        return self.set("feedDict", feed)

    def set_fetch_dict(self, fetch: Dict[str, str]) -> "ONNXModel":
        return self.set("fetchDict", fetch)

    def set_mini_batch_size(self, n: int) -> "ONNXModel":
        return self.set("miniBatchSize", n)

    def set_softmax_dict(self, d: Dict[str, str]) -> "ONNXModel":
        return self.set("softMaxDict", d)

    def set_argmax_dict(self, d: Dict[str, str]) -> "ONNXModel":
        return self.set("argMaxDict", d)

    def _graph(self) -> Graph:
        payload = self.get_or_default("modelPayload")
        if payload is None:
            raise ValueError("ONNXModel: modelPayload not set")
        # parse once per payload: explainers call transform per-row, and a
        # fresh Graph each call would defeat the jit cache below
        cached = getattr(self, "_graph_cache", None)
        if cached is not None and cached[0] is payload:
            return cached[1]
        graph = load_graph(payload)
        self._graph_cache = (payload, graph)
        return graph

    # -- introspection (reference ONNXModel modelInput/modelOutput) --------
    def model_inputs(self) -> List[str]:
        return self._graph().input_names

    def model_outputs(self) -> List[str]:
        return self._graph().output_names

    def slice_at_output(self, *output_names: str) -> "ONNXModel":
        """Model surgery (reference: ONNXModel.sliceAtOutput,
        ONNXModel.scala:203-209): re-point the graph at intermediate
        outputs, dropping unreachable nodes."""
        sliced = slice_at_outputs(self._graph(), list(output_names))
        clone = self.copy()
        clone.set("modelPayload", to_model(sliced).serialize())
        clone.set("fetchDict", {n: n for n in output_names})
        clone._fn_cache = {}
        return clone

    # -- execution ---------------------------------------------------------
    def _build_fn(self, graph: Graph, fetch_names: List[str],
                  softmax_of: Dict[str, str], argmax_of: Dict[str, str]):
        """One jitted program: graph eval + fused softmax/argmax post-ops.

        dtype="float32" pins matmul/conv to full-precision MXU passes
        (TPU default is bf16 inputs); dtype="bfloat16" keeps the fast path.
        """
        precision = ("float32" if self.get_or_default("dtype") == "float32"
                     else "bfloat16")
        # bfloat16 also casts the WEIGHTS (constant-folded once under jit):
        # without it, f32 initializers keep convs/matmuls on the
        # full-precision path regardless of input dtype
        eval_dtype = (jnp.bfloat16
                      if self.get_or_default("dtype") == "bfloat16" else None)

        def run(inputs: Dict[str, Any]) -> Dict[str, Any]:
            with jax.default_matmul_precision(precision):
                out = evaluate(graph, inputs, fetch_names, dtype=eval_dtype)
            post: Dict[str, Any] = {k: jnp.asarray(v) for k, v in out.items()}
            for src, dst in softmax_of.items():
                post[dst] = jax.nn.softmax(jnp.asarray(out[src]), axis=-1)
            for src, dst in argmax_of.items():
                post[dst] = jnp.argmax(jnp.asarray(out[src]), axis=-1)
            return post

        return jax.jit(run)

    def _transform(self, ds: Dataset) -> Dataset:
        graph = self._graph()
        feed: Dict[str, str] = dict(self.get_or_default("feedDict")
                                    or {n: n for n in graph.input_names})
        fetch: Dict[str, str] = dict(self.get_or_default("fetchDict")
                                     or {n: n for n in graph.output_names})
        batch = int(self.get_or_default("miniBatchSize"))
        dtype = jnp.bfloat16 if self.get_or_default("dtype") == "bfloat16" \
            else jnp.float32

        # fetch cols whose source feeds softmax/argmax post-ops
        softmax_d = dict(self.get_or_default("softMaxDict") or {})
        argmax_d = dict(self.get_or_default("argMaxDict") or {})
        fetch_names = list(dict.fromkeys(fetch.values()))
        out_to_col = {v: k for k, v in fetch.items()}

        # columns referenced by post-op dicts must exist among fetch outputs
        softmax_of = {fetch[src]: dst for src, dst in softmax_d.items()
                      if src in fetch}
        argmax_of = {fetch[src]: dst for src, dst in argmax_d.items()
                     if src in fetch}

        key = (id(graph), tuple(fetch_names), tuple(sorted(softmax_of.items())),
               tuple(sorted(argmax_of.items())),
               self.get_or_default("dtype"))
        cache = self._get_cache()
        if key not in cache:
            cache[key] = self._build_fn(graph, fetch_names,
                                        softmax_of, argmax_of)
        fn = cache[key]

        n = ds.num_rows
        # stack each fed column to (n, ...) once
        feeds_np: Dict[str, np.ndarray] = {}
        for onnx_name, col in feed.items():
            column = ds[col]
            if column.dtype == object:
                arr = np.stack([np.asarray(v) for v in column])
            else:
                arr = np.asarray(column)
            if np.issubdtype(arr.dtype, np.floating):
                arr = arr.astype(np.dtype(dtype))
            feeds_np[onnx_name] = arr

        chunks: Dict[str, List[np.ndarray]] = {}
        for start in range(0, n, batch):
            stop = min(start + batch, n)
            pad = batch - (stop - start)
            ins = {}
            for k, arr in feeds_np.items():
                piece = arr[start:stop]
                if pad:  # pad to static batch so jit compiles once
                    piece = np.concatenate(
                        [piece, np.repeat(piece[-1:], pad, axis=0)], axis=0)
                ins[k] = piece
            outs = fn(ins)
            for name, val in outs.items():
                val = np.asarray(val)[:stop - start]
                chunks.setdefault(name, []).append(val)

        new_cols: Dict[str, Any] = {}
        for name, pieces in chunks.items():
            # fetch outputs map back to their dataset column; post-op dict
            # values are already the destination column names
            col_name = out_to_col.get(name, name)
            stacked = np.concatenate(pieces, axis=0)
            if stacked.ndim == 1:
                new_cols[col_name] = stacked
            else:
                obj = np.empty(len(stacked), dtype=object)
                for i in range(len(stacked)):
                    obj[i] = stacked[i]
                new_cols[col_name] = obj
        return ds.with_columns(new_cols)


class ImageFeaturizer(Transformer):
    """Headless-CNN embeddings (reference: deep-learning/.../onnx/
    ImageFeaturizer.scala:34-270 — ImageTransformer preprocessing feeding a
    sliced ONNXModel).  ``headless=True`` slices the network at
    ``featureTensorName`` so the output column holds flat embeddings; with
    ``headless=False`` the final network outputs (logits) are emitted.
    """

    inputCol = StringParam(doc="image column", default="image")
    outputCol = StringParam(doc="feature column", default="features")
    headless = BoolParam(doc="cut at feature tensor instead of logits",
                         default=True)
    featureTensorName = StringParam(doc="onnx value name of the feature tensor")
    onnxModel = PyObjectParam(doc="the wrapped ONNXModel")
    miniBatchSize = IntParam(doc="rows per device batch", default=128)

    def __init__(self, onnx_model: Optional[ONNXModel] = None, **kw):
        super().__init__(**kw)
        if onnx_model is not None:
            self.set("onnxModel", onnx_model)

    def _transform(self, ds: Dataset) -> Dataset:
        base: ONNXModel = self.get_or_default("onnxModel")
        if base is None:
            raise ValueError("ImageFeaturizer: onnxModel not set")
        graph = base._graph()
        in_name = graph.input_names[0]
        if self.get_or_default("headless"):
            feat = self.get_or_default("featureTensorName")
            if not feat:
                raise ValueError("headless=True requires featureTensorName")
            model = base.slice_at_output(feat)
            out_name = feat
        else:
            model = base.copy()
            out_name = graph.output_names[0]
        model.set("feedDict", {in_name: self.get_or_default("inputCol")})
        model.set("fetchDict", {"_imgfeat": out_name})
        model.set("miniBatchSize", self.get_or_default("miniBatchSize"))
        model._fn_cache = {}
        out = model.transform(ds)
        col = out["_imgfeat"]
        # flatten per-row feature maps to vectors
        if col.dtype == object:
            flat = np.empty(len(col), dtype=object)
            for i, v in enumerate(col):
                flat[i] = np.asarray(v).reshape(-1)
            col = flat
        return ds.with_column(self.get_or_default("outputCol"), col)
