"""ONNX → XLA inference path.

TPU-native replacement for the reference's ONNX Runtime module
(reference: deep-learning/src/main/scala/.../onnx/): a self-contained
protobuf codec, a graph IR with model surgery, op lowerings into JAX, and
the ``ONNXModel`` / ``ImageFeaturizer`` pipeline stages.
"""

from .graph import Graph, GraphBuilder, load_graph, slice_at_outputs, to_model
from .hub import ONNXHub, ONNXHubModelInfo
from .model import ImageFeaturizer, ONNXModel
from .ops import supported_ops
from .protoparse import ModelProto, load_model
from .runner import OnnxFunction, compile_onnx, evaluate

__all__ = [
    "Graph", "GraphBuilder", "load_graph", "slice_at_outputs", "to_model",
    "ONNXHub", "ONNXHubModelInfo", "ImageFeaturizer", "ONNXModel",
    "supported_ops", "ModelProto", "load_model", "OnnxFunction",
    "compile_onnx", "evaluate",
]
