"""ONNX op → JAX/XLA lowerings.

Replaces the reference's ONNX Runtime execution (reference:
deep-learning/.../onnx/ONNXRuntime.scala:24-108 — a CUDA OrtSession per
Spark partition) with tracing each op into ONE XLA program: the whole
graph jit-compiles, XLA fuses elementwise chains into the convolutions /
matmuls, and the MXU sees large batched GEMMs instead of per-op kernel
launches.

Static-vs-traced dispatch: shape-producing subgraphs (``Shape`` →
``Gather`` → ``Concat`` → ``Reshape`` is the classic exporter pattern)
must stay concrete so reshapes get static ints under ``jit``.  Every
value in the evaluator is either a ``np.ndarray`` (static) or a traced
jax array; ops compute with numpy whenever all inputs are static.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

OP_REGISTRY: Dict[str, Callable] = {}


def register(*names: str):
    def deco(fn):
        for n in names:
            OP_REGISTRY[n] = fn
        return fn
    return deco


class OpCall:
    """One node application: resolved inputs + attributes."""

    def __init__(self, op_type: str, inputs: List[Any], attrs: Dict[str, Any],
                 opset: int, n_outputs: int):
        self.op_type = op_type
        self.inputs = inputs          # None for omitted optional inputs
        self.attrs = attrs
        self.opset = opset
        self.n_outputs = n_outputs

    def inp(self, i: int, default=None):
        if i < len(self.inputs) and self.inputs[i] is not None:
            return self.inputs[i]
        return default

    def attr(self, name: str, default=None):
        return self.attrs.get(name, default)

    def static(self, i: int, default=None) -> Optional[np.ndarray]:
        v = self.inp(i)
        if v is None:
            return default
        if not isinstance(v, np.ndarray):
            raise ValueError(
                f"{self.op_type}: input #{i} must be static (shape-like) "
                f"under jit, got traced value")
        return v


def is_static(v) -> bool:
    return isinstance(v, (np.ndarray, np.generic))


def xp(*vals):
    """numpy when every operand is static, jnp otherwise."""
    return np if all(is_static(v) for v in vals if v is not None) else jnp


# ============================================================================
# elementwise / arithmetic
# ============================================================================

def _binop(fn_name):
    def f(call: OpCall):
        a, b = call.inp(0), call.inp(1)
        return [getattr(xp(a, b), fn_name)(a, b)]
    return f


register("Add")(_binop("add"))
register("Sub")(_binop("subtract"))
register("Mul")(_binop("multiply"))
register("Pow")(_binop("power"))
register("Greater")(_binop("greater"))
register("GreaterOrEqual")(_binop("greater_equal"))
register("Less")(_binop("less"))
register("LessOrEqual")(_binop("less_equal"))
register("Equal")(_binop("equal"))
register("And")(_binop("logical_and"))
register("Or")(_binop("logical_or"))
register("Xor")(_binop("logical_xor"))
register("BitwiseAnd")(_binop("bitwise_and"))
register("BitwiseOr")(_binop("bitwise_or"))
register("Mod")(_binop("mod"))


@register("Div")
def _div(c: OpCall):
    a, b = c.inp(0), c.inp(1)
    m = xp(a, b)
    dtype = a.dtype
    if np.issubdtype(dtype, np.integer):
        # ONNX integer Div truncates toward zero; numpy floor-divides.
        return [m.trunc(m.divide(a, b)).astype(dtype)]
    return [m.divide(a, b)]


def _unary(fn_name):
    def f(call: OpCall):
        a = call.inp(0)
        return [getattr(xp(a), fn_name)(a)]
    return f


for onnx_name, np_name in [
        ("Neg", "negative"), ("Abs", "abs"), ("Exp", "exp"), ("Log", "log"),
        ("Sqrt", "sqrt"), ("Floor", "floor"), ("Ceil", "ceil"),
        ("Round", "round"), ("Sin", "sin"), ("Cos", "cos"), ("Tan", "tan"),
        ("Asin", "arcsin"), ("Acos", "arccos"), ("Atan", "arctan"),
        ("Sinh", "sinh"), ("Cosh", "cosh"), ("Tanh", "tanh"),
        ("Sign", "sign"), ("Not", "logical_not"), ("IsNaN", "isnan"),
        ("IsInf", "isinf")]:
    register(onnx_name)(_unary(np_name))


@register("Reciprocal")
def _reciprocal(c: OpCall):
    return [1.0 / c.inp(0)]


@register("Erf")
def _erf(c: OpCall):
    a = c.inp(0)
    if is_static(a):
        return [np.vectorize(math.erf, otypes=[np.asarray(a).dtype])(a)]
    return [jax.scipy.special.erf(a)]


@register("Relu")
def _relu(c: OpCall):
    a = c.inp(0)
    return [xp(a).maximum(a, 0)]


@register("LeakyRelu")
def _leaky_relu(c: OpCall):
    a, alpha = c.inp(0), c.attr("alpha", 0.01)
    return [xp(a).where(a >= 0, a, alpha * a)]


@register("PRelu")
def _prelu(c: OpCall):
    a, slope = c.inp(0), c.inp(1)
    return [xp(a, slope).where(a >= 0, a, slope * a)]


@register("Elu")
def _elu(c: OpCall):
    a, alpha = c.inp(0), c.attr("alpha", 1.0)
    m = xp(a)
    return [m.where(a >= 0, a, alpha * (m.exp(m.minimum(a, 0)) - 1))]


@register("Selu")
def _selu(c: OpCall):
    a = c.inp(0)
    alpha = c.attr("alpha", 1.6732632423543772)
    gamma = c.attr("gamma", 1.0507009873554805)
    m = xp(a)
    return [gamma * m.where(a >= 0, a, alpha * (m.exp(m.minimum(a, 0)) - 1))]


@register("Sigmoid")
def _sigmoid(c: OpCall):
    a = c.inp(0)
    if is_static(a):
        return [1.0 / (1.0 + np.exp(-a))]
    return [jax.nn.sigmoid(a)]


@register("HardSigmoid")
def _hard_sigmoid(c: OpCall):
    a = c.inp(0)
    alpha, beta = c.attr("alpha", 0.2), c.attr("beta", 0.5)
    return [xp(a).clip(alpha * a + beta, 0, 1)]


@register("HardSwish")
def _hard_swish(c: OpCall):
    a = c.inp(0)
    return [a * xp(a).clip(a / 6.0 + 0.5, 0, 1)]


@register("Softplus")
def _softplus(c: OpCall):
    a = c.inp(0)
    if is_static(a):
        return [np.log1p(np.exp(-np.abs(a))) + np.maximum(a, 0)]
    return [jax.nn.softplus(a)]


@register("Softsign")
def _softsign(c: OpCall):
    a = c.inp(0)
    return [a / (1 + xp(a).abs(a))]


@register("Gelu")
def _gelu(c: OpCall):
    a = c.inp(0)
    approx = c.attr("approximate", "none")
    return [jax.nn.gelu(a, approximate=(approx == "tanh"))]


@register("Mish")
def _mish(c: OpCall):
    a = c.inp(0)
    return [a * jnp.tanh(jax.nn.softplus(a))]


@register("Clip")
def _clip(c: OpCall):
    a = c.inp(0)
    if c.opset >= 11:
        lo, hi = c.inp(1), c.inp(2)
    else:
        lo, hi = c.attr("min"), c.attr("max")
    m = xp(a)
    if lo is not None:
        a = m.maximum(a, lo)
    if hi is not None:
        a = m.minimum(a, hi)
    return [a]


@register("Softmax")
def _softmax(c: OpCall):
    a = c.inp(0)
    axis = c.attr("axis", -1 if c.opset >= 13 else 1)
    if c.opset < 13:
        # legacy: flatten to 2D at `axis`, softmax rows, reshape back
        shp = a.shape
        lead = int(np.prod(shp[:axis])) if axis > 0 else 1
        flat = a.reshape(lead, -1)
        out = jax.nn.softmax(jnp.asarray(flat), axis=-1)
        return [out.reshape(shp)]
    return [jax.nn.softmax(jnp.asarray(a), axis=axis)]


@register("LogSoftmax")
def _log_softmax(c: OpCall):
    a = c.inp(0)
    axis = c.attr("axis", -1 if c.opset >= 13 else 1)
    return [jax.nn.log_softmax(jnp.asarray(a), axis=axis)]


@register("Min", "Max", "Sum", "Mean")
def _variadic(c: OpCall):
    vals = [v for v in c.inputs if v is not None]
    m = xp(*vals)
    if c.op_type == "Min":
        out = vals[0]
        for v in vals[1:]:
            out = m.minimum(out, v)
    elif c.op_type == "Max":
        out = vals[0]
        for v in vals[1:]:
            out = m.maximum(out, v)
    else:
        out = vals[0]
        for v in vals[1:]:
            out = m.add(out, v)
        if c.op_type == "Mean":
            out = out / len(vals)
    return [out]


@register("Where")
def _where(c: OpCall):
    cond, a, b = c.inp(0), c.inp(1), c.inp(2)
    return [xp(cond, a, b).where(cond, a, b)]


# ============================================================================
# shape / indexing
# ============================================================================

@register("Shape")
def _shape(c: OpCall):
    a = c.inp(0)
    shp = np.asarray(a.shape if hasattr(a, "shape") else np.shape(a),
                     dtype=np.int64)
    start = c.attr("start", 0)
    end = c.attr("end")
    return [shp[start:end]]


@register("Size")
def _size(c: OpCall):
    a = c.inp(0)
    return [np.asarray(int(np.prod(a.shape)), dtype=np.int64)]


@register("Reshape")
def _reshape(c: OpCall):
    a = c.inp(0)
    if c.opset >= 5:
        shape = c.static(1).astype(np.int64).tolist()
    else:
        shape = list(c.attr("shape"))
    allowzero = c.attr("allowzero", 0)
    out_shape = []
    for i, d in enumerate(shape):
        if d == 0 and not allowzero:
            out_shape.append(a.shape[i])
        else:
            out_shape.append(int(d))
    return [a.reshape(out_shape)]


@register("Flatten")
def _flatten(c: OpCall):
    a = c.inp(0)
    axis = c.attr("axis", 1)
    lead = int(np.prod(a.shape[:axis])) if axis > 0 else 1
    return [a.reshape(lead, -1)]


@register("Transpose")
def _transpose(c: OpCall):
    a = c.inp(0)
    perm = c.attr("perm")
    return [xp(a).transpose(a, perm)]


@register("Squeeze")
def _squeeze(c: OpCall):
    a = c.inp(0)
    if c.opset >= 13:
        axes = c.static(1)
        axes = None if axes is None else tuple(int(x) for x in axes)
    else:
        axes = c.attr("axes")
        axes = None if axes is None else tuple(axes)
    if axes is None:
        axes = tuple(i for i, d in enumerate(a.shape) if d == 1)
    return [xp(a).squeeze(a, axis=axes)]


@register("Unsqueeze")
def _unsqueeze(c: OpCall):
    a = c.inp(0)
    if c.opset >= 13:
        axes = [int(x) for x in c.static(1)]
    else:
        axes = list(c.attr("axes"))
    out_rank = len(a.shape) + len(axes)
    axes = sorted(ax % out_rank for ax in axes)
    m = xp(a)
    for ax in axes:
        a = m.expand_dims(a, ax)
    return [a]


@register("Concat")
def _concat(c: OpCall):
    vals = [v for v in c.inputs if v is not None]
    return [xp(*vals).concatenate(vals, axis=c.attr("axis", 0))]


@register("Split")
def _split(c: OpCall):
    a = c.inp(0)
    axis = c.attr("axis", 0)
    if c.opset >= 13:
        split = c.inp(1)
        split = None if split is None else np.asarray(split).tolist()
    else:
        split = c.attr("split")
    n = c.n_outputs
    if split is None:
        size = a.shape[axis]
        base = -(-size // n)  # ONNX: last chunk may be smaller
        split = [base] * (n - 1) + [size - base * (n - 1)]
    idx = np.cumsum(split)[:-1].tolist()
    m = xp(a)
    return list(m.split(a, idx, axis=axis))


@register("Slice")
def _slice(c: OpCall):
    a = c.inp(0)
    if c.opset >= 10:
        starts = c.static(1).tolist()
        ends = c.static(2).tolist()
        axes = c.static(3)
        steps = c.static(4)
        axes = list(range(len(starts))) if axes is None else axes.tolist()
        steps = [1] * len(starts) if steps is None else steps.tolist()
    else:
        starts = list(c.attr("starts"))
        ends = list(c.attr("ends"))
        axes = list(c.attr("axes", range(len(starts))))
        steps = [1] * len(starts)
    slices = [slice(None)] * len(a.shape)
    for st, en, ax, sp in zip(starts, ends, axes, steps):
        ax = int(ax) % len(a.shape)
        INT_MAX = np.iinfo(np.int64).max
        en = None if en >= INT_MAX else int(en)
        en2 = None if (sp < 0 and en is not None and en < -a.shape[ax]) else en
        slices[ax] = slice(int(st), en2, int(sp))
    return [a[tuple(slices)]]


@register("Gather")
def _gather(c: OpCall):
    a, idx = c.inp(0), c.inp(1)
    axis = c.attr("axis", 0)
    return [xp(a, idx).take(a, idx, axis=axis)]


@register("GatherElements")
def _gather_elements(c: OpCall):
    a, idx = jnp.asarray(c.inp(0)), jnp.asarray(c.inp(1))
    axis = c.attr("axis", 0)
    return [jnp.take_along_axis(a, idx, axis=axis)]


@register("GatherND")
def _gather_nd(c: OpCall):
    data, indices = jnp.asarray(c.inp(0)), np.asarray(c.static(1))
    if c.attr("batch_dims", 0):
        raise NotImplementedError("GatherND batch_dims > 0")
    idx = tuple(indices[..., i] for i in range(indices.shape[-1]))
    return [data[idx]]


@register("ScatterND")
def _scatter_nd(c: OpCall):
    data, indices, updates = (jnp.asarray(c.inp(0)), c.static(1),
                              jnp.asarray(c.inp(2)))
    idx = tuple(indices[..., i] for i in range(indices.shape[-1]))
    return [data.at[idx].set(updates)]


@register("Expand")
def _expand(c: OpCall):
    a = c.inp(0)
    shape = [int(s) for s in c.static(1)]
    # ONNX Expand uses multidirectional broadcasting
    target = np.broadcast_shapes(tuple(a.shape), tuple(shape))
    return [xp(a).broadcast_to(a, target)]


@register("Tile")
def _tile(c: OpCall):
    a = c.inp(0)
    reps = [int(r) for r in c.static(1)]
    return [xp(a).tile(a, reps)]


@register("Pad")
def _pad(c: OpCall):
    a = c.inp(0)
    if c.opset >= 11:
        pads = c.static(1).astype(np.int64)
        cval = c.inp(2)
        cval = 0.0 if cval is None else float(np.asarray(cval))
        axes = c.static(3)
    else:
        pads = np.asarray(c.attr("pads"), dtype=np.int64)
        cval = c.attr("value", 0.0)
        axes = None
    mode = c.attr("mode", "constant")
    rank = len(a.shape)
    pad_width = [(0, 0)] * rank
    if axes is None:
        axes = list(range(rank))
    half = len(pads) // 2
    for j, ax in enumerate(axes):
        pad_width[int(ax) % rank] = (int(pads[j]), int(pads[j + half]))
    m = xp(a)
    if mode == "constant":
        return [m.pad(a, pad_width, mode="constant", constant_values=cval)]
    return [m.pad(a, pad_width, mode={"reflect": "reflect",
                                      "edge": "edge", "wrap": "wrap"}[mode])]


@register("Cast")
def _cast(c: OpCall):
    from .protoparse import DTYPE_TO_NUMPY
    a = c.inp(0)
    to = DTYPE_TO_NUMPY[c.attr("to")]
    return [a.astype(to)]


@register("CastLike")
def _cast_like(c: OpCall):
    a, b = c.inp(0), c.inp(1)
    return [a.astype(b.dtype)]


@register("Identity")
def _identity(c: OpCall):
    return [c.inp(0)]


@register("Dropout")
def _dropout(c: OpCall):
    a = c.inp(0)
    outs = [a]
    if c.n_outputs > 1:
        outs.append(xp(a).ones(a.shape, dtype=bool))
    return outs


@register("Constant")
def _constant(c: OpCall):
    for key in ("value", "value_float", "value_int", "value_floats",
                "value_ints", "value_string"):
        v = c.attr(key)
        if v is not None:
            if key == "value_int":
                return [np.asarray(v, dtype=np.int64)]
            if key == "value_ints":
                return [np.asarray(v, dtype=np.int64)]
            if key == "value_float":
                return [np.asarray(v, dtype=np.float32)]
            if key == "value_floats":
                return [np.asarray(v, dtype=np.float32)]
            return [np.asarray(v)]
    raise ValueError("Constant node with no value attribute")


@register("ConstantOfShape")
def _constant_of_shape(c: OpCall):
    shape = [int(s) for s in c.static(0)]
    value = c.attr("value")
    if value is None:
        value = np.zeros(1, dtype=np.float32)
    value = np.asarray(value)
    return [np.full(shape, value.reshape(-1)[0], dtype=value.dtype)]


@register("Range")
def _range(c: OpCall):
    start, limit, delta = (np.asarray(c.static(0)), np.asarray(c.static(1)),
                           np.asarray(c.static(2)))
    return [np.arange(start.item(), limit.item(), delta.item(),
                      dtype=start.dtype)]


@register("OneHot")
def _onehot(c: OpCall):
    indices, depth, values = c.inp(0), int(np.asarray(c.static(1)).item()), c.inp(2)
    axis = c.attr("axis", -1)
    off, on = values[0], values[1]
    oh = jax.nn.one_hot(jnp.asarray(indices) % depth, depth, axis=axis)
    return [oh * (on - off) + off]


@register("TopK")
def _topk(c: OpCall):
    a = c.inp(0)
    k = int(np.asarray(c.static(1)).item())
    axis = c.attr("axis", -1)
    largest = c.attr("largest", 1)
    a = jnp.asarray(a)
    a_m = jnp.moveaxis(a, axis, -1)
    vals, idx = lax.top_k(a_m if largest else -a_m, k)
    if not largest:
        vals = -vals
    return [jnp.moveaxis(vals, -1, axis),
            jnp.moveaxis(idx.astype(jnp.int64), -1, axis)]


@register("ArgMax", "ArgMin")
def _argmax(c: OpCall):
    a = c.inp(0)
    axis = c.attr("axis", 0)
    keepdims = c.attr("keepdims", 1)
    fn = "argmax" if c.op_type == "ArgMax" else "argmin"
    out = getattr(xp(a), fn)(a, axis=axis)
    out = out.astype(np.int64)
    if keepdims:
        out = xp(a).expand_dims(out, axis)
    return [out]


@register("CumSum")
def _cumsum(c: OpCall):
    a = c.inp(0)
    axis = int(np.asarray(c.static(1)).item())
    if c.attr("exclusive", 0) or c.attr("reverse", 0):
        raise NotImplementedError("CumSum exclusive/reverse")
    return [xp(a).cumsum(a, axis=axis)]


@register("Trilu")
def _trilu(c: OpCall):
    a = c.inp(0)
    k = c.inp(1)
    k = 0 if k is None else int(np.asarray(k).item())
    upper = c.attr("upper", 1)
    m = xp(a)
    return [m.triu(a, k) if upper else m.tril(a, k)]


@register("NonZero")
def _nonzero(c: OpCall):
    a = c.static(0)  # data-dependent shape: only legal on static values
    return [np.stack(np.nonzero(a)).astype(np.int64)]


@register("Einsum")
def _einsum(c: OpCall):
    eq = c.attr("equation")
    vals = [jnp.asarray(v) for v in c.inputs if v is not None]
    return [jnp.einsum(eq, *vals)]


# ============================================================================
# reductions
# ============================================================================

def _reduce(np_name):
    def f(c: OpCall):
        a = c.inp(0)
        if c.opset >= 18 or (c.op_type == "ReduceSum" and c.opset >= 13):
            axes = c.inp(1)
            axes = None if axes is None else tuple(int(x) for x in np.asarray(axes))
        else:
            axes = c.attr("axes")
            axes = None if axes is None else tuple(axes)
        keepdims = bool(c.attr("keepdims", 1))
        if axes is None and c.attr("noop_with_empty_axes", 0):
            return [a]
        m = xp(a)
        return [getattr(m, np_name)(a, axis=axes, keepdims=keepdims)]
    return f


register("ReduceSum")(_reduce("sum"))
register("ReduceMean")(_reduce("mean"))
register("ReduceMax")(_reduce("max"))
register("ReduceMin")(_reduce("min"))
register("ReduceProd")(_reduce("prod"))


@register("ReduceL2")
def _reduce_l2(c: OpCall):
    a = c.inp(0)
    if c.opset >= 18:
        axes = c.inp(1)
        axes = None if axes is None else tuple(int(x) for x in np.asarray(axes))
    else:
        axes = c.attr("axes")
        axes = None if axes is None else tuple(axes)
    keepdims = bool(c.attr("keepdims", 1))
    m = xp(a)
    return [m.sqrt(m.sum(m.square(a), axis=axes, keepdims=keepdims))]


@register("ReduceLogSumExp")
def _reduce_lse(c: OpCall):
    a = jnp.asarray(c.inp(0))
    axes = c.attr("axes")
    axes = None if axes is None else tuple(axes)
    keepdims = bool(c.attr("keepdims", 1))
    return [jax.scipy.special.logsumexp(a, axis=axes, keepdims=keepdims)]


# ============================================================================
# linear algebra
# ============================================================================

@register("MatMul")
def _matmul(c: OpCall):
    a, b = c.inp(0), c.inp(1)
    return [jnp.matmul(jnp.asarray(a), jnp.asarray(b),
                       preferred_element_type=jnp.float32)
            if not (is_static(a) and is_static(b)) else np.matmul(a, b)]


@register("Gemm")
def _gemm(c: OpCall):
    a, b, bias = c.inp(0), c.inp(1), c.inp(2)
    alpha, beta = c.attr("alpha", 1.0), c.attr("beta", 1.0)
    if c.attr("transA", 0):
        a = a.T
    if c.attr("transB", 0):
        b = b.T
    out = alpha * jnp.matmul(jnp.asarray(a), jnp.asarray(b),
                             preferred_element_type=jnp.float32)
    if bias is not None:
        out = out + beta * bias
    return [out]


# ============================================================================
# convolutions / pooling / normalization
# ============================================================================

def _conv_pads(call: OpCall, a_shape, k_shape, strides, dilations):
    """Resolve ONNX pads/auto_pad to lax padding list [(lo,hi), ...]."""
    spatial = len(k_shape)
    auto = call.attr("auto_pad", "NOTSET")
    if auto in ("NOTSET", ""):
        pads = call.attr("pads", [0] * 2 * spatial)
        return [(int(pads[i]), int(pads[i + spatial])) for i in range(spatial)]
    if auto == "VALID":
        return [(0, 0)] * spatial
    out = []
    for i in range(spatial):
        eff_k = (k_shape[i] - 1) * dilations[i] + 1
        out_dim = -(-a_shape[i] // strides[i])
        total = max(0, (out_dim - 1) * strides[i] + eff_k - a_shape[i])
        lo = total // 2 if auto == "SAME_UPPER" else total - total // 2
        out.append((lo, total - lo))
    return out


@register("Conv")
def _conv(c: OpCall):
    x, w, b = jnp.asarray(c.inp(0)), jnp.asarray(c.inp(1)), c.inp(2)
    spatial = x.ndim - 2
    strides = list(c.attr("strides", [1] * spatial))
    dilations = list(c.attr("dilations", [1] * spatial))
    group = c.attr("group", 1)
    pads = _conv_pads(c, x.shape[2:], w.shape[2:], strides, dilations)
    spec = "NCHW"[:x.ndim] if spatial == 2 else None
    if spatial == 1:
        dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NCH", "OIH", "NCH"))
    elif spatial == 2:
        dn = lax.conv_dimension_numbers(x.shape, w.shape, ("NCHW", "OIHW", "NCHW"))
    elif spatial == 3:
        dn = lax.conv_dimension_numbers(x.shape, w.shape,
                                        ("NCDHW", "OIDHW", "NCDHW"))
    else:
        raise NotImplementedError(f"Conv with {spatial} spatial dims")
    out = lax.conv_general_dilated(
        x, w, window_strides=strides, padding=pads,
        rhs_dilation=dilations, dimension_numbers=dn,
        feature_group_count=group,
        preferred_element_type=jnp.float32)
    if b is not None:
        out = out + jnp.asarray(b).reshape((1, -1) + (1,) * spatial)
    return [out]


@register("ConvTranspose")
def _conv_transpose(c: OpCall):
    x, w, b = jnp.asarray(c.inp(0)), jnp.asarray(c.inp(1)), c.inp(2)
    spatial = x.ndim - 2
    strides = list(c.attr("strides", [1] * spatial))
    dilations = list(c.attr("dilations", [1] * spatial))
    group = c.attr("group", 1)
    if group != 1:
        raise NotImplementedError("ConvTranspose group > 1")
    pads = c.attr("pads", [0] * 2 * spatial)
    out_pads = c.attr("output_padding", [0] * spatial)
    # ONNX kernel layout is (C_in, C_out/group, *k); lax wants IOHW via dims
    lax_pads = []
    for i in range(spatial):
        eff_k = (w.shape[2 + i] - 1) * dilations[i] + 1
        lo = eff_k - 1 - int(pads[i])
        hi = eff_k - 1 - int(pads[i + spatial]) + int(out_pads[i])
        lax_pads.append((lo, hi))
    x_dil = lax.conv_general_dilated(
        x, jnp.flip(w, axis=tuple(range(2, 2 + spatial))).swapaxes(0, 1),
        window_strides=[1] * spatial, padding=lax_pads,
        lhs_dilation=strides, rhs_dilation=dilations,
        dimension_numbers=lax.conv_dimension_numbers(
            x.shape, w.shape[:2][::-1] + w.shape[2:],
            ("NCHW"[:x.ndim], "OIHW"[:x.ndim], "NCHW"[:x.ndim])
            if spatial == 2 else
            (("NCH", "OIH", "NCH") if spatial == 1 else
             ("NCDHW", "OIDHW", "NCDHW"))),
        preferred_element_type=jnp.float32)
    if b is not None:
        x_dil = x_dil + jnp.asarray(b).reshape((1, -1) + (1,) * spatial)
    return [x_dil]


def _pool(c: OpCall, reducer, init, is_avg=False):
    x = jnp.asarray(c.inp(0))
    spatial = x.ndim - 2
    kernel = list(c.attr("kernel_shape"))
    strides = list(c.attr("strides", [1] * spatial))
    dilations = list(c.attr("dilations", [1] * spatial))
    pads = _conv_pads(c, x.shape[2:], kernel, strides, dilations)
    window = (1, 1) + tuple(kernel)
    strd = (1, 1) + tuple(strides)
    dil = (1, 1) + tuple(dilations)
    padding = ((0, 0), (0, 0)) + tuple(pads)
    out = lax.reduce_window(x, init, reducer, window, strd, padding,
                            window_dilation=dil)
    if is_avg:
        if c.attr("count_include_pad", 0):
            denom = float(np.prod(kernel))
            out = out / denom
        else:
            ones = jnp.ones_like(x)
            counts = lax.reduce_window(ones, 0.0, lax.add, window, strd,
                                       padding, window_dilation=dil)
            out = out / counts
    return [out]


@register("MaxPool")
def _maxpool(c: OpCall):
    return _pool(c, lax.max, -jnp.inf)


@register("AveragePool")
def _avgpool(c: OpCall):
    return _pool(c, lax.add, 0.0, is_avg=True)


@register("GlobalAveragePool")
def _global_avgpool(c: OpCall):
    x = c.inp(0)
    axes = tuple(range(2, len(x.shape)))
    return [xp(x).mean(x, axis=axes, keepdims=True)]


@register("GlobalMaxPool")
def _global_maxpool(c: OpCall):
    x = c.inp(0)
    axes = tuple(range(2, len(x.shape)))
    return [xp(x).max(x, axis=axes, keepdims=True)]


@register("BatchNormalization")
def _batchnorm(c: OpCall):
    x, scale, bias, mean, var = (c.inp(0), c.inp(1), c.inp(2), c.inp(3),
                                 c.inp(4))
    eps = c.attr("epsilon", 1e-5)
    shape = (1, -1) + (1,) * (len(x.shape) - 2)
    m = xp(x, scale, bias, mean, var)
    inv = scale / m.sqrt(var + eps)
    return [x * inv.reshape(shape) + (bias - mean * inv).reshape(shape)]


@register("InstanceNormalization")
def _instancenorm(c: OpCall):
    x, scale, bias = jnp.asarray(c.inp(0)), c.inp(1), c.inp(2)
    eps = c.attr("epsilon", 1e-5)
    axes = tuple(range(2, x.ndim))
    mean = x.mean(axis=axes, keepdims=True)
    var = x.var(axis=axes, keepdims=True)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return [(x - mean) / jnp.sqrt(var + eps) * scale.reshape(shape)
            + bias.reshape(shape)]


@register("LayerNormalization")
def _layernorm(c: OpCall):
    x, scale, bias = jnp.asarray(c.inp(0)), c.inp(1), c.inp(2)
    axis = c.attr("axis", -1)
    eps = c.attr("epsilon", 1e-5)
    axes = tuple(range(axis % x.ndim, x.ndim))
    mean = x.mean(axis=axes, keepdims=True)
    var = x.var(axis=axes, keepdims=True)
    inv = 1.0 / jnp.sqrt(var + eps)
    out = (x - mean) * inv * scale
    if bias is not None:
        out = out + bias
    outs = [out]
    if c.n_outputs > 1:
        outs.append(mean)
    if c.n_outputs > 2:
        outs.append(inv)
    return outs


@register("GroupNormalization")
def _groupnorm(c: OpCall):
    x, scale, bias = jnp.asarray(c.inp(0)), c.inp(1), c.inp(2)
    ngroups = c.attr("num_groups")
    eps = c.attr("epsilon", 1e-5)
    n, ch = x.shape[0], x.shape[1]
    grouped = x.reshape((n, ngroups, ch // ngroups) + x.shape[2:])
    axes = tuple(range(2, grouped.ndim))
    mean = grouped.mean(axis=axes, keepdims=True)
    var = grouped.var(axis=axes, keepdims=True)
    normed = ((grouped - mean) / jnp.sqrt(var + eps)).reshape(x.shape)
    shape = (1, -1) + (1,) * (x.ndim - 2)
    return [normed * scale.reshape(shape) + bias.reshape(shape)]


@register("LRN")
def _lrn(c: OpCall):
    x = jnp.asarray(c.inp(0))
    size = c.attr("size")
    alpha, beta, bias = (c.attr("alpha", 1e-4), c.attr("beta", 0.75),
                         c.attr("bias", 1.0))
    sq = jnp.square(x)
    half_lo = (size - 1) // 2
    half_hi = size - 1 - half_lo
    window = (1, size) + (1,) * (x.ndim - 2)
    padding = ((0, 0), (half_lo, half_hi)) + ((0, 0),) * (x.ndim - 2)
    sums = lax.reduce_window(sq, 0.0, lax.add, window, (1,) * x.ndim, padding)
    return [x / jnp.power(bias + alpha / size * sums, beta)]


@register("Resize")
def _resize(c: OpCall):
    x = jnp.asarray(c.inp(0))
    scales = c.inp(2)
    sizes = c.inp(3)
    mode = c.attr("mode", "nearest")
    if sizes is not None:
        out_shape = [int(s) for s in np.asarray(sizes)]
    elif scales is not None and len(np.asarray(scales)):
        sc = np.asarray(scales, dtype=np.float64)
        out_shape = [int(math.floor(d * s)) for d, s in zip(x.shape, sc)]
    else:
        raise ValueError("Resize needs scales or sizes")
    method = {"nearest": "nearest", "linear": "linear",
              "cubic": "cubic"}[mode]
    return [jax.image.resize(x, out_shape, method=method)]


@register("Upsample")
def _upsample(c: OpCall):
    x = jnp.asarray(c.inp(0))
    scales = c.inp(1)
    sc = np.asarray(scales if scales is not None else c.attr("scales"),
                    dtype=np.float64)
    out_shape = [int(math.floor(d * s)) for d, s in zip(x.shape, sc)]
    mode = c.attr("mode", "nearest")
    return [jax.image.resize(x, out_shape,
                             method="nearest" if mode == "nearest" else "linear")]


@register("DepthToSpace")
def _depth_to_space(c: OpCall):
    x = jnp.asarray(c.inp(0))
    bs = c.attr("blocksize")
    n, ch, h, w = x.shape
    if c.attr("mode", "DCR") == "DCR":
        t = x.reshape(n, bs, bs, ch // (bs * bs), h, w)
        t = t.transpose(0, 3, 4, 1, 5, 2)
    else:
        t = x.reshape(n, ch // (bs * bs), bs, bs, h, w)
        t = t.transpose(0, 1, 4, 2, 5, 3)
    return [t.reshape(n, ch // (bs * bs), h * bs, w * bs)]


@register("SpaceToDepth")
def _space_to_depth(c: OpCall):
    x = jnp.asarray(c.inp(0))
    bs = c.attr("blocksize")
    n, ch, h, w = x.shape
    t = x.reshape(n, ch, h // bs, bs, w // bs, bs)
    t = t.transpose(0, 3, 5, 1, 2, 4)
    return [t.reshape(n, ch * bs * bs, h // bs, w // bs)]


def lower(call: OpCall) -> List[Any]:
    fn = OP_REGISTRY.get(call.op_type)
    if fn is None:
        raise NotImplementedError(
            f"ONNX op {call.op_type!r} has no XLA lowering "
            f"({len(OP_REGISTRY)} ops supported)")
    return fn(call)


def supported_ops() -> List[str]:
    return sorted(OP_REGISTRY)
