"""Local ONNX model zoo: construct standard architectures as ONNX graphs.

The reference downloads zoo models through ONNXHub (reference:
deep-learning/.../onnx/ONNXHub.scala:181-255 — manifest, SHA check, cached
bytes) and benchmarks ResNet-50 batch inference through ONNXModel
(ONNXModel.scala:242-251, ImageFeaturizer.scala:34-270).  In a zero-egress
environment the zoo is CONSTRUCTED instead of fetched: this module emits
real, full-size ONNX graphs for well-known architectures via
:class:`~synapseml_tpu.models.onnx.graph.GraphBuilder`, with weights
supplied or randomly initialized.  Weight names follow torchvision's
state-dict convention, so the same dict can drive a torch reference
implementation (how the tests verify numerical correctness) or be filled
from a real torchvision checkpoint via
``models.dl.checkpoints.read_checkpoint``.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from .graph import GraphBuilder

#: bottleneck block counts per stage
RESNET50_STAGES = (3, 4, 6, 3)


def build_bert_classifier(state_dict: Dict[str, np.ndarray],
                          num_layers: int, num_heads: int,
                          seq_len: int = 16,
                          input_ids_name: str = "input_ids",
                          mask_name: str = "attention_mask",
                          output_name: str = "logits") -> bytes:
    """A BertForSequenceClassification forward pass as an ONNX graph, built
    from an HF-format state dict (the same tensor names
    ``models.dl.checkpoints.import_bert`` consumes) — the transformer
    counterpart of :func:`build_resnet50` for proving the ONNX→XLA path on
    attention/LayerNorm/Gelu graphs.  Fixed ``seq_len``; single-segment
    inputs (token-type row 0 folds into the additive embedding)."""
    def g(key):
        for prefix in ("bert.", ""):
            if prefix + key in state_dict:
                return np.asarray(state_dict[prefix + key], np.float32)
        raise KeyError(key)

    d_model = g("embeddings.word_embeddings.weight").shape[1]
    d_head = d_model // num_heads
    b = GraphBuilder("bert_classifier", opset=17)
    ids = b.input(input_ids_name, (None, seq_len), dtype=np.int64)
    mask = b.input(mask_name, (None, seq_len), dtype=np.float32)

    def init(name, value):
        return b.initializer(name.replace(".", "_"), value)

    def linear(x, key, out_name_hint):
        w = init(key + ".w", g(key + ".weight").T)
        bias = init(key + ".b", g(key + ".bias"))
        return b.node("Add", [b.node("MatMul", [x, w]), bias])

    def layer_norm(x, key):
        return b.node("LayerNormalization",
                      [x, init(key + ".g", g(key + ".weight")),
                       init(key + ".beta", g(key + ".bias"))],
                      axis=-1, epsilon=1e-12)

    # embeddings: gather words; positions + segment-0 are additive constants
    tok = b.node("Gather", [init("tok", g("embeddings.word_embeddings.weight")),
                            ids], axis=0)
    pos_const = (g("embeddings.position_embeddings.weight")[:seq_len]
                 + g("embeddings.token_type_embeddings.weight")[0:1])
    x = b.node("Add", [tok, init("pos", pos_const[None, :, :])])
    x = layer_norm(x, "embeddings.LayerNorm")

    # additive attention mask (B, 1, 1, S): (1 - mask) * -1e9
    one = init("one", np.float32(1.0))
    m4 = b.node("Unsqueeze", [mask, init("axes11", np.array([1, 2], np.int64))])
    neg = b.node("Mul", [b.node("Sub", [one, m4]),
                         init("negbig", np.float32(-1e9))])

    perm_heads = [0, 2, 1, 3]
    shape_split = init("shape_split",
                       np.array([0, seq_len, num_heads, d_head], np.int64))
    shape_merge = init("shape_merge", np.array([0, seq_len, d_model], np.int64))
    # erf-expanded gelu constants: standard ONNX only defines the Gelu op
    # from opset 20, so this opset-17 graph spells 0.5*x*(1+erf(x/sqrt(2)))
    # in primitives and stays valid for external runtimes
    half = init("gelu_half", np.float32(0.5))
    sqrt2 = init("gelu_sqrt2", np.float32(np.sqrt(2.0)))
    for i in range(num_layers):
        p = f"encoder.layer.{i}."

        def heads(name):
            h = linear(x, p + "attention.self." + name, name)
            h = b.node("Reshape", [h, shape_split])
            return b.node("Transpose", [h], perm=perm_heads)  # (B,H,S,dh)

        q, k, v = heads("query"), heads("key"), heads("value")
        kt = b.node("Transpose", [k], perm=[0, 1, 3, 2])
        scores = b.node("Div", [b.node("MatMul", [q, kt]),
                                init(f"scale{i}", np.float32(np.sqrt(d_head)))])
        scores = b.node("Add", [scores, neg])
        probs = b.node("Softmax", [scores], axis=-1)
        ctx = b.node("MatMul", [probs, v])
        ctx = b.node("Transpose", [ctx], perm=perm_heads)
        ctx = b.node("Reshape", [ctx, shape_merge])
        att = linear(ctx, p + "attention.output.dense", "attout")
        x = layer_norm(b.node("Add", [att, x]),
                       p + "attention.output.LayerNorm")
        ff = linear(x, p + "intermediate.dense", "ffup")
        h = b.node("Mul", [
            b.node("Mul", [ff, half]),
            b.node("Add", [one,
                           b.node("Erf", [b.node("Div", [ff, sqrt2])])])])
        h = linear(h, p + "output.dense", "ffdown")
        x = layer_norm(b.node("Add", [h, x]), p + "output.LayerNorm")

    cls = b.node("Gather", [x, init("zero", np.array(0, np.int64))], axis=1)
    pooled = b.node("Tanh", [linear(cls, "pooler.dense", "pool")])
    wcls = init("cls.w", np.asarray(state_dict["classifier.weight"],
                                    np.float32).T)
    bcls = init("cls.b", np.asarray(state_dict["classifier.bias"], np.float32))
    b.node("Add", [b.node("MatMul", [pooled, wcls]), bcls],
           outputs=[output_name])
    b.output(output_name)
    return b.build()


def _rand_weights_resnet50(num_classes: int, seed: int) -> Dict[str, np.ndarray]:
    rng = np.random.default_rng(seed)
    w: Dict[str, np.ndarray] = {}

    def conv(name, cout, cin, k):
        fan_in = cin * k * k
        w[name + ".weight"] = (rng.normal(size=(cout, cin, k, k))
                               * np.sqrt(2.0 / fan_in)).astype(np.float32)

    def bn(name, c):
        w[name + ".weight"] = np.ones(c, np.float32)
        w[name + ".bias"] = np.zeros(c, np.float32)
        w[name + ".running_mean"] = (rng.normal(size=c) * 0.01).astype(np.float32)
        w[name + ".running_var"] = np.ones(c, np.float32)

    conv("conv1", 64, 3, 7)
    bn("bn1", 64)
    cin = 64
    for s, blocks in enumerate(RESNET50_STAGES):
        width = 64 * 2 ** s
        for j in range(blocks):
            p = f"layer{s + 1}.{j}"
            conv(f"{p}.conv1", width, cin, 1)
            bn(f"{p}.bn1", width)
            conv(f"{p}.conv2", width, width, 3)
            bn(f"{p}.bn2", width)
            conv(f"{p}.conv3", width * 4, width, 1)
            bn(f"{p}.bn3", width * 4)
            if j == 0:
                conv(f"{p}.downsample.0", width * 4, cin, 1)
                bn(f"{p}.downsample.1", width * 4)
            cin = width * 4
    w["fc.weight"] = (rng.normal(size=(num_classes, cin)) * 0.01).astype(np.float32)
    w["fc.bias"] = np.zeros(num_classes, np.float32)
    return w


def build_resnet50(num_classes: int = 1000, seed: int = 0,
                   weights: Optional[Dict[str, np.ndarray]] = None,
                   input_name: str = "data", output_name: str = "logits",
                   ) -> Tuple[bytes, Dict[str, np.ndarray]]:
    """ResNet-50 v1 (bottleneck [3,4,6,3]) as ONNX model bytes.

    Input ``data``: (N, 3, H, W) float32 NCHW; output ``logits``:
    (N, num_classes).  Returns ``(model_bytes, weights)`` — feed the weights
    to a torch reference with ``load_state_dict`` for parity checks.
    """
    w = weights if weights is not None else _rand_weights_resnet50(num_classes, seed)
    b = GraphBuilder("resnet50", opset=17)
    x = b.input(input_name, (None, 3, None, None))

    def init(name):
        return b.initializer(name.replace(".", "_"), w[name])

    def conv(x, name, k, stride=1):
        pad = (k - 1) // 2
        return b.node("Conv", [x, init(name + ".weight")],
                      kernel_shape=[k, k], strides=[stride, stride],
                      pads=[pad, pad, pad, pad])

    def bn(x, name):
        return b.node("BatchNormalization",
                      [x, init(name + ".weight"), init(name + ".bias"),
                       init(name + ".running_mean"),
                       init(name + ".running_var")], epsilon=1e-5)

    y = conv(x, "conv1", 7, 2)
    y = bn(y, "bn1")
    y = b.node("Relu", [y])
    y = b.node("MaxPool", [y], kernel_shape=[3, 3], strides=[2, 2],
               pads=[1, 1, 1, 1])

    for s, blocks in enumerate(RESNET50_STAGES):
        for j in range(blocks):
            p = f"layer{s + 1}.{j}"
            stride = 2 if (s > 0 and j == 0) else 1
            h = conv(y, f"{p}.conv1", 1)
            h = b.node("Relu", [bn(h, f"{p}.bn1")])
            h = conv(h, f"{p}.conv2", 3, stride)
            h = b.node("Relu", [bn(h, f"{p}.bn2")])
            h = bn(conv(h, f"{p}.conv3", 1), f"{p}.bn3")
            if j == 0:
                shortcut = bn(conv(y, f"{p}.downsample.0", 1, stride),
                              f"{p}.downsample.1")
            else:
                shortcut = y
            y = b.node("Relu", [b.node("Add", [h, shortcut])])

    y = b.node("GlobalAveragePool", [y])
    y = b.node("Flatten", [y], axis=1)
    y = b.node("Gemm", [y, init("fc.weight"), init("fc.bias")],
               transB=1, outputs=[output_name])
    b.output(output_name)
    return b.build(), w
