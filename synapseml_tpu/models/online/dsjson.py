"""Decision-Service JSON ingestion for bandit logs.

Counterpart of the reference's VowpalWabbitDSJsonTransformer
(reference: vw/.../VowpalWabbitDSJsonTransformer.scala:20-108): each row of
``dsJsonColumn`` holds one ds-json event; the transform extracts the
header fields into columns named exactly as the reference does —
``EventId``, ``rewards`` (a dict keyed by the ``rewards`` param aliases),
``probLog`` (``_label_probability``) and ``chosenActionIndex``
(``_labelIndex``) — ready for the policy-evaluation stages.
"""

from __future__ import annotations

import json
from typing import Dict, Optional

import numpy as np

from ...core.dataset import Dataset
from ...core.params import DictParam, StringParam
from ...core.pipeline import Transformer

EVENT_ID_COL = "EventId"
REWARDS_COL = "rewards"
PROB_LOGGED_COL = "probLog"
CHOSEN_ACTION_INDEX_COL = "chosenActionIndex"


def _reward_value(raw) -> float:
    """Missing or malformed reward fields become NaN (the reference emits
    Spark nulls); one corrupt event must not abort the whole batch."""
    if raw is None:
        return float("nan")
    try:
        return float(raw)
    except (TypeError, ValueError):
        return float("nan")


class DSJsonTransformer(Transformer):
    """Parse ds-json bandit events into typed columns."""

    dsJsonColumn = StringParam(doc="column containing ds-json",
                               default="value")
    rewards = DictParam(doc="output alias → ds-json field to extract as a "
                            "reward", default={"reward": "_label_cost"})

    def _transform(self, ds: Dataset) -> Dataset:
        rewards: Dict[str, str] = dict(self.rewards)
        n = ds.num_rows
        event_ids = np.empty(n, object)
        reward_rows = np.empty(n, object)
        prob = np.full(n, np.nan, np.float32)
        # -1 = missing (the reference emits Spark nulls for absent fields;
        # 0 is a valid action index so it cannot double as the sentinel)
        chosen = np.full(n, -1, np.int32)
        for i, raw in enumerate(ds[self.dsJsonColumn]):
            obj = json.loads(str(raw))
            event_ids[i] = obj.get(EVENT_ID_COL)
            reward_rows[i] = {alias: _reward_value(obj.get(field))
                              for alias, field in rewards.items()}
            p = obj.get("_label_probability")
            if p is not None:
                prob[i] = float(p)
            idx = obj.get("_labelIndex")
            if idx is not None:
                chosen[i] = int(idx)
        return ds.with_columns({
            EVENT_ID_COL: event_ids,
            REWARDS_COL: reward_rows,
            PROB_LOGGED_COL: prob,
            CHOSEN_ACTION_INDEX_COL: chosen,
        })
