"""Online SGD estimator/model pipeline stages.

Re-designs the reference's VW Spark estimators (reference:
vw/.../VowpalWabbitClassifier.scala:1-173, VowpalWabbitRegressor.scala,
VowpalWabbitBase.scala:45 passThroughArgs, VowpalWabbitBaseLearner.scala:
135-211 trainInternal/trainInternalDistributed): same param surface
(learningRate/powerT/l1/l2/numPasses/hashSeed), training backed by the
jitted scan in :mod:`.sgd`, distribution by parameter averaging over the
device mesh instead of spanning-tree allreduce.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from ...core.dataset import Dataset
from ...core.params import (BoolParam, DictParam, FloatParam, IntParam,
                            PyObjectParam, StringParam)
from ...core.pipeline import Estimator, Model
from .sgd import SGDConfig, SGDState, init_state, predict_margin, train_sgd


class _OnlineSGDParams:
    featuresCol = StringParam(doc="dense vector column", default="features")
    labelCol = StringParam(doc="label column", default="label")
    weightCol = StringParam(doc="importance weight column")
    predictionCol = StringParam(doc="prediction output", default="prediction")
    learningRate = FloatParam(doc="base learning rate (VW -l)", default=0.5)
    powerT = FloatParam(doc="t-decay exponent (VW --power_t)", default=0.5)
    initialT = FloatParam(doc="schedule offset (VW --initial_t)", default=1.0)
    l1 = FloatParam(doc="L1 regularization (VW --l1)", default=0.0)
    l2 = FloatParam(doc="L2 regularization (VW --l2)", default=0.0)
    numPasses = IntParam(doc="passes over the data (VW --passes)", default=1)
    batchSize = IntParam(doc="rows per jitted update step", default=32)
    adaptive = BoolParam(doc="AdaGrad per-coordinate rates", default=True)
    normalized = BoolParam(doc="scale-invariant updates", default=True)
    useBarrierExecutionMode = BoolParam(doc="parity: gang-schedule tasks",
                                        default=False)
    numSyncsPerPass = IntParam(doc="extra mid-pass weight averages "
                               "(VowpalWabbitSyncSchedule.scala)", default=0)
    hashSeed = IntParam(doc="featurizer hash seed", default=0)
    passThroughArgs = DictParam(doc="extra engine args (ParamsStringBuilder "
                                "pass-through analogue)")
    initialModel = PyObjectParam(doc="warm-start SGDState")

    def _config(self, loss: str, **over) -> SGDConfig:
        extra = dict(self.get_or_default("passThroughArgs") or {})
        extra.update(over)
        # mid-pass syncs (VowpalWabbitSyncSchedule analogue) become
        # fully-synchronous per-batch gradient pmean on the mesh
        sync = 1 if self.numSyncsPerPass > 0 else 0
        return SGDConfig(
            loss=extra.pop("loss", loss),
            learning_rate=self.learningRate, power_t=self.powerT,
            initial_t=self.initialT, l1=self.l1, l2=self.l2,
            num_passes=self.numPasses, batch_size=self.batchSize,
            adaptive=self.adaptive, normalized=self.normalized,
            sync_every_batches=extra.pop("sync_every_batches", sync),
            **extra)

    def _xyw(self, ds: Dataset):
        x = ds.to_numpy([self.featuresCol], np.float32)
        y = ds[self.labelCol].astype(np.float32)
        w = (ds[self.weightCol].astype(np.float32)
             if self.is_set("weightCol") and self.weightCol in ds else None)
        return x, y, w


class OnlineSGDClassifier(_OnlineSGDParams, Estimator):
    """Binary linear classifier with logistic/hinge loss
    (VowpalWabbitClassifier analogue)."""

    lossFunction = StringParam(doc="logistic|hinge", default="logistic",
                               allowed=("logistic", "hinge"))
    probabilityCol = StringParam(doc="probability output", default="probability")
    rawPredictionCol = StringParam(doc="margin output", default="rawPrediction")
    mesh = PyObjectParam(doc="device mesh for data-parallel training")

    def __init__(self, featuresCol: Optional[str] = None,
                 labelCol: Optional[str] = None, **kw):
        super().__init__(**kw)
        if featuresCol is not None:
            self.set("featuresCol", featuresCol)
        if labelCol is not None:
            self.set("labelCol", labelCol)

    def _fit(self, ds: Dataset) -> "OnlineSGDClassificationModel":
        x, y, w = self._xyw(ds)
        y_pm = np.where(y > 0, 1.0, -1.0).astype(np.float32)
        cfg = self._config(self.lossFunction)
        state, stats = train_sgd(x, y_pm, cfg, sample_weight=w,
                                 mesh=self.get("mesh"),
                                 init=self.get("initialModel"))
        model = OnlineSGDClassificationModel()
        model._copy_values_from(self)
        model.clear("mesh")  # meshes are runtime handles, not model state
        model.state = state
        model.training_stats = stats
        return model


class OnlineSGDClassificationModel(_OnlineSGDParams, Model):
    lossFunction = StringParam(doc="logistic|hinge", default="logistic")
    probabilityCol = StringParam(doc="probability output", default="probability")
    rawPredictionCol = StringParam(doc="margin output", default="rawPrediction")
    mesh = PyObjectParam(doc="unused at predict")

    state: Optional[SGDState] = None
    training_stats: Optional[dict] = None

    def _transform(self, ds: Dataset) -> Dataset:
        x = ds.to_numpy([self.featuresCol], np.float32)
        margin = predict_margin(self.state, x)
        proba = 1.0 / (1.0 + np.exp(-margin))
        return ds.with_columns({
            self.rawPredictionCol: margin,
            self.probabilityCol: [np.array([1 - p, p]) for p in proba],
            self.predictionCol: (margin > 0).astype(np.float64),
        })

    def _save_extra(self, path: str) -> None:
        import os
        np.savez(os.path.join(path, "state.npz"),
                 **{f: np.asarray(getattr(self.state, f))
                    for f in SGDState._fields})

    def _load_extra(self, path: str) -> None:
        import os
        import jax.numpy as jnp
        with np.load(os.path.join(path, "state.npz")) as z:
            self.state = SGDState(**{f: jnp.asarray(z[f])
                                     for f in SGDState._fields})


class OnlineSGDRegressor(_OnlineSGDParams, Estimator):
    """Linear regressor with squared/quantile/poisson loss
    (VowpalWabbitRegressor analogue)."""

    lossFunction = StringParam(doc="squared|quantile|poisson",
                               default="squared",
                               allowed=("squared", "quantile", "poisson"))
    quantileTau = FloatParam(doc="quantile loss tau", default=0.5)
    mesh = PyObjectParam(doc="device mesh for data-parallel training")

    def __init__(self, featuresCol: Optional[str] = None,
                 labelCol: Optional[str] = None, **kw):
        super().__init__(**kw)
        if featuresCol is not None:
            self.set("featuresCol", featuresCol)
        if labelCol is not None:
            self.set("labelCol", labelCol)

    def _fit(self, ds: Dataset) -> "OnlineSGDRegressionModel":
        x, y, w = self._xyw(ds)
        cfg = self._config(self.lossFunction, quantile_tau=self.quantileTau)
        state, stats = train_sgd(x, y, cfg, sample_weight=w,
                                 mesh=self.get("mesh"),
                                 init=self.get("initialModel"))
        model = OnlineSGDRegressionModel()
        model._copy_values_from(self)
        model.clear("mesh")  # meshes are runtime handles, not model state
        model.state = state
        model.training_stats = stats
        return model


class OnlineSGDRegressionModel(_OnlineSGDParams, Model):
    lossFunction = StringParam(doc="squared|quantile|poisson",
                               default="squared")
    quantileTau = FloatParam(doc="quantile loss tau", default=0.5)
    mesh = PyObjectParam(doc="unused at predict")

    state: Optional[SGDState] = None
    training_stats: Optional[dict] = None

    def _transform(self, ds: Dataset) -> Dataset:
        x = ds.to_numpy([self.featuresCol], np.float32)
        margin = predict_margin(self.state, x)
        if self.lossFunction == "poisson":
            margin = np.exp(margin)
        return ds.with_column(self.predictionCol, margin.astype(np.float64))

    _save_extra = OnlineSGDClassificationModel._save_extra
    _load_extra = OnlineSGDClassificationModel._load_extra
