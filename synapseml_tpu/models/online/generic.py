"""Raw VW-format example learners (reference:
vw/.../VowpalWabbitGeneric.scala:1-131 — an Estimator driven by VW text
examples like ``0 |a b c``, learning via ``vw.learnFromString`` per row —
and VowpalWabbitGenericProgressive, which emits the 1-step-ahead
prediction for every row while learning).

TPU re-design: the text lines are parsed host-side into hashed dense
vectors (murmur with namespace prefix, matching our HashingFeaturizer's
convention), then the learn loop is the same jitted ``lax.scan`` SGD the
other online learners use — per-row JNI string calls become batched
on-device updates.  Progressive validation falls out of the scan: the
margin is computed against the pre-update weights of each row's batch.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import numpy as np

from ...core.dataset import Dataset
from ...core.hashing import murmurhash3_32
from ...core.params import IntParam, PyObjectParam, StringParam
from ...core.pipeline import Estimator, Model, Transformer
from .estimators import _OnlineSGDParams
from .sgd import SGDState, predict_margin, train_sgd


def parse_vw_line(line: str) -> Tuple[Optional[float], float,
                                      List[Tuple[str, str, float]]]:
    """Parse one VW-format example into (label, importance, features).

    Features are (namespace, feature_name, value) triples.  Supported
    grammar (the subset the reference's test corpus uses):
    ``[label [importance]] |ns[:w] f[:v] ... |ns2 ...``.
    """
    head, _, rest = line.partition("|")
    label: Optional[float] = None
    importance = 1.0
    head_toks = head.split()
    if head_toks:
        try:
            label = float(head_toks[0])
        except ValueError:
            label = None  # tag-only head (e.g. "'row1 |f x") — unlabeled
        if label is not None and len(head_toks) > 1:
            try:
                importance = float(head_toks[1])
            except ValueError:
                pass  # a tag, not an importance weight
    feats: List[Tuple[str, str, float]] = []
    for seg in rest.split("|") if rest else []:
        toks = seg.split()
        if not toks:
            continue
        ns_weight = 1.0
        # a namespace token is attached to the '|' (no leading space)
        if seg[:1] not in (" ", "\t"):
            ns_tok = toks[0]
            toks = toks[1:]
            ns, _, w = ns_tok.partition(":")
            if w:
                try:
                    ns_weight = float(w)
                except ValueError:
                    pass
        else:
            ns = ""
        for tok in toks:
            name, _, val = tok.partition(":")
            try:
                value = float(val) if val else 1.0
            except ValueError:
                value = 1.0
            feats.append((ns, name, value * ns_weight))
    return label, importance, feats


def vectorize_vw_lines(lines, num_bits: int, seed: int
                       ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Hash parsed VW lines into a dense (n, 2^bits) matrix + labels +
    importance weights (hashing matches VowpalWabbitMurmurWithPrefix
    semantics: feature index = murmur(ns + name))."""
    dim = 1 << num_bits
    n = len(lines)
    # native C++ parser+hasher when the toolchain is up (the reference's
    # VW parse path is native C++ behind JNI; ours is ctypes)
    from ...native import coo_densify, vw_parse_batch
    parsed = vw_parse_batch(lines, num_bits, seed)
    if parsed is not None:
        rows, idxs, vals, y, w, _has = parsed
        x = np.zeros((n, dim), np.float32)
        if not coo_densify(rows, idxs, vals, x):
            np.add.at(x, (rows, idxs), vals)
        return x, y, w
    x = np.zeros((n, dim), np.float32)
    y = np.zeros(n, np.float32)
    w = np.ones(n, np.float32)
    for i, line in enumerate(lines):
        label, imp, feats = parse_vw_line(str(line))
        if label is not None:
            y[i] = label
            w[i] = imp
        else:
            # VW treats label-less lines as predict-only examples; zero
            # importance keeps them out of the loss without reindexing
            w[i] = 0.0
        for ns, name, value in feats:
            idx = murmurhash3_32(ns + name, seed) % dim
            x[i, idx] += value
    return x, y, w


class _GenericParams(_OnlineSGDParams):
    inputCol = StringParam(doc="VW-format example column", default="value")
    numBits = IntParam(doc="log2 of hash dimension (VW -b)", default=12)
    lossFunction = StringParam(doc="squared|logistic|hinge|quantile",
                               default="squared",
                               allowed=("squared", "logistic", "hinge",
                                        "quantile"))


class OnlineGeneric(_GenericParams, Estimator):
    """VowpalWabbitGeneric analogue: fit from raw VW text examples."""

    mesh = PyObjectParam(doc="device mesh for data-parallel training")

    def _fit(self, ds: Dataset) -> "OnlineGenericModel":
        x, y, w = vectorize_vw_lines(ds[self.inputCol], int(self.numBits),
                                     int(self.hashSeed))
        loss = str(self.lossFunction)
        if loss in ("logistic", "hinge"):
            y = np.where(y > 0, 1.0, -1.0).astype(np.float32)
        cfg = self._config(loss)
        state, stats = train_sgd(x, y, cfg, sample_weight=w,
                                 init=self.get("initialModel"),
                                 mesh=self.get("mesh"))
        model = OnlineGenericModel(
            inputCol=self.inputCol, numBits=self.numBits,
            hashSeed=self.hashSeed, lossFunction=loss,
            predictionCol=self.predictionCol, state=state)
        model.training_stats = stats
        return model


class OnlineGenericModel(_GenericParams, Model):
    """Scores raw VW text examples (reference:
    VowpalWabbitGenericModel.transform, VowpalWabbitGeneric.scala:87)."""

    state = PyObjectParam(doc="fitted SGDState")

    def _transform(self, ds: Dataset) -> Dataset:
        x, _, _ = vectorize_vw_lines(ds[self.inputCol], int(self.numBits),
                                     int(self.hashSeed))
        state: SGDState = self.get("state")
        margin = np.asarray(predict_margin(state, x))
        if str(self.lossFunction) == "logistic":
            out = 1.0 / (1.0 + np.exp(-margin))
        else:
            out = margin
        return ds.with_column(self.predictionCol, out)


class OnlineGenericProgressive(_GenericParams, Transformer):
    """VowpalWabbitGenericProgressive analogue: one-pass learn that emits
    each row's pre-update (progressive validation) prediction."""

    def _transform(self, ds: Dataset) -> Dataset:
        x, y, w = vectorize_vw_lines(ds[self.inputCol], int(self.numBits),
                                     int(self.hashSeed))
        loss = str(self.lossFunction)
        yt = (np.where(y > 0, 1.0, -1.0).astype(np.float32)
              if loss in ("logistic", "hinge") else y)
        import dataclasses
        cfg = self._config(loss)
        one_pass = dataclasses.replace(cfg, num_passes=1)
        bs = max(1, int(self.batchSize))
        preds = np.zeros(len(x), np.float32)
        state: Optional[SGDState] = self.get("initialModel")
        for start in range(0, len(x), bs):
            sl = slice(start, start + bs)
            if state is not None:
                preds[sl] = np.asarray(predict_margin(state, x[sl]))
            state, _ = train_sgd(x[sl], yt[sl], one_pass,
                                 sample_weight=w[sl], init=state)
        if loss == "logistic":
            preds = 1.0 / (1.0 + np.exp(-preds))
        return ds.with_column(self.predictionCol, preds)
