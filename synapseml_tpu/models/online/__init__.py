"""Online linear learners — the Vowpal-Wabbit-equivalent engine.

The reference wraps the VW C++ core over JNI (reference:
vw/src/main/scala/.../VowpalWabbitBaseLearner.scala:123-260,
build.sbt:436 vw-jni 9.3.0).  Here the learn loop is a jit-compiled
``lax.scan`` over minibatches with AdaGrad-normalized updates — per-row
JNI calls become on-device vectorized steps — and VW's spanning-tree
AllReduce (VowpalWabbitClusterUtil.scala:16-40) becomes parameter
averaging with ``psum`` over the device mesh.
"""

from .sgd import SGDConfig, SGDState, train_sgd, predict_margin
from .estimators import (OnlineSGDClassifier, OnlineSGDClassificationModel,
                         OnlineSGDRegressor, OnlineSGDRegressionModel)
from .dsjson import DSJsonTransformer
from .featurizer import (FeatureInteractions, HashingFeaturizer,
                         VectorZipper)
from .bandit import (ContextualBandit, ContextualBanditModel)
from .generic import (OnlineGeneric, OnlineGenericModel,
                      OnlineGenericProgressive, parse_vw_line,
                      vectorize_vw_lines)
from .policyeval import (CressieReadInterval, PolicyEvalTransformer,
                         bernstein_bound, cressie_read, ips, snips)

__all__ = [
    "SGDConfig", "SGDState", "train_sgd", "predict_margin",
    "OnlineSGDClassifier", "OnlineSGDClassificationModel",
    "OnlineSGDRegressor", "OnlineSGDRegressionModel",
    "DSJsonTransformer", "HashingFeaturizer", "FeatureInteractions",
    "VectorZipper",
    "ContextualBandit", "ContextualBanditModel",
    "OnlineGeneric", "OnlineGenericModel", "OnlineGenericProgressive",
    "parse_vw_line", "vectorize_vw_lines",
    "PolicyEvalTransformer", "CressieReadInterval",
    "ips", "snips", "cressie_read", "bernstein_bound",
]
