"""Counterfactual (off-policy) evaluation.

Re-designs the reference's CSE transformer + policy-eval helpers
(reference: vw/.../VowpalWabbitCSETransformer.scala:222,
vw/.../policyeval/CressieRead.scala:112, CressieReadInterval.scala:216):
IPS and SNIPS value estimators plus Cressie-Read empirical-likelihood
confidence intervals for importance-weighted means, computed with stable
streaming sums (KahanSum, vw/KahanSum.scala:68 — here numpy pairwise
summation provides the same stability).
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import numpy as np

from ...core.dataset import Dataset
from ...core.params import FloatParam, IntParam, StringParam
from ...core.pipeline import Transformer


def ips(rewards: np.ndarray, logged_probs: np.ndarray,
        target_probs: np.ndarray, wmax: float = 0.0) -> float:
    """Inverse-propensity-score value of the target policy."""
    w = np.asarray(target_probs, np.float64) / np.maximum(logged_probs, 1e-12)
    if wmax > 0:
        w = np.minimum(w, wmax)
    return float(np.mean(w * rewards))


def snips(rewards: np.ndarray, logged_probs: np.ndarray,
          target_probs: np.ndarray) -> float:
    """Self-normalized IPS (ratio estimator)."""
    w = np.asarray(target_probs, np.float64) / np.maximum(logged_probs, 1e-12)
    denom = w.sum()
    return float((w * rewards).sum() / max(denom, 1e-12))


def cressie_read(rewards: np.ndarray, logged_probs: np.ndarray,
                 target_probs: np.ndarray) -> float:
    """Cressie-Read power-divergence point estimate of policy value
    (reference: policyeval/CressieRead.scala:112).

    Empirical-likelihood reweighting: find the maximum-likelihood
    importance-weight normalization q_i ∝ 1/(1 + beta * (w_i - 1)) with
    E_q[w] = 1, then report E_q[w r].  beta is solved by bisection on the
    monotone constraint function.
    """
    w = np.asarray(target_probs, np.float64) / np.maximum(logged_probs, 1e-12)
    r = np.asarray(rewards, np.float64)
    n = len(w)
    if n == 0:
        return float("nan")

    def constraint(beta: float) -> float:
        q = 1.0 / np.maximum(1.0 + beta * (w - 1.0), 1e-12)
        q = q / q.sum()
        return float((q * w).sum() - 1.0)

    # beta range keeping 1 + beta*(w-1) > 0 for all observed w
    w_min, w_max = float(w.min()), float(w.max())
    lo = -1.0 / max(w_max - 1.0, 1e-12) + 1e-9 if w_max > 1 else -1e6
    hi = 1.0 / max(1.0 - w_min, 1e-12) - 1e-9 if w_min < 1 else 1e6
    c_lo, c_hi = constraint(lo), constraint(hi)
    if c_lo * c_hi > 0:  # no interior root: fall back to SNIPS weighting
        q = w / w.sum()
        return float((q * r).sum())
    for _ in range(80):
        mid = 0.5 * (lo + hi)
        c = constraint(mid)
        if c_lo * c <= 0:
            hi, c_hi = mid, c
        else:
            lo, c_lo = mid, c
    beta = 0.5 * (lo + hi)
    q = 1.0 / np.maximum(1.0 + beta * (w - 1.0), 1e-12)
    q = q / q.sum()
    return float((q * w * r).sum())


def bernstein_bound(rewards: np.ndarray, logged_probs: np.ndarray,
                    target_probs: np.ndarray, delta: float = 0.05,
                    wmax: Optional[float] = None):
    """Empirical-Bernstein lower/upper bound on the IPS value."""
    w = np.asarray(target_probs, np.float64) / np.maximum(logged_probs, 1e-12)
    z = w * np.asarray(rewards, np.float64)
    if wmax:
        z = np.minimum(z, wmax)
    n = len(z)
    if n < 2:
        return float("nan"), float("nan")
    mean = z.mean()
    var = z.var(ddof=1)
    log_term = np.log(3.0 / delta)
    rng = z.max() - z.min() if n else 1.0
    slack = np.sqrt(2 * var * log_term / n) + 3 * rng * log_term / n
    return float(mean - slack), float(mean + slack)


@dataclasses.dataclass
class CressieReadInterval:
    """Empirical-likelihood CI for an importance-weighted mean
    (reference: policyeval/CressieReadInterval.scala:216).  The interval is
    the set of values v for which the EL ratio test does not reject; we
    scan the dual with the chi-square(1) calibration."""

    delta: float = 0.05
    wmax: float = 100.0

    def interval(self, rewards, logged_probs, target_probs):
        from scipy.stats import chi2  # scipy ships with the image's numpy stack
        w = np.asarray(target_probs, np.float64) / np.maximum(logged_probs, 1e-12)
        w = np.minimum(w, self.wmax)
        z = w * np.asarray(rewards, np.float64)
        n = len(z)
        if n == 0:
            return float("nan"), float("nan")
        crit = chi2.ppf(1 - self.delta, df=1)

        def el_stat(v: float) -> float:
            # EL ratio for H0: E[z] = v, via the standard dual
            d = z - v
            lo_l, hi_l = -1.0 / max(d.max(), 1e-12), -1.0 / min(d.min(), -1e-12)
            if d.max() <= 0 or d.min() >= 0:
                return np.inf  # v outside the convex hull: reject
            lam_lo, lam_hi = lo_l + 1e-10, hi_l - 1e-10

            def dldl(lam):
                return float(np.sum(d / (1.0 + lam * d)))

            a, b = lam_lo, lam_hi
            for _ in range(60):
                m = 0.5 * (a + b)
                if dldl(a) * dldl(m) <= 0:
                    b = m
                else:
                    a = m
            lam = 0.5 * (a + b)
            return float(2.0 * np.sum(np.log1p(lam * d)))

        est = z.mean()
        span = max(z.max() - z.min(), 1e-9)
        lo_v, hi_v = est, est
        stepn = 200
        for k in range(1, stepn + 1):
            v = est - span * k / stepn
            if v < z.min() or el_stat(v) > crit:
                break
            lo_v = v
        for k in range(1, stepn + 1):
            v = est + span * k / stepn
            if v > z.max() or el_stat(v) > crit:
                break
            hi_v = v
        return float(lo_v), float(hi_v)


class PolicyEvalTransformer(Transformer):
    """Aggregate logged bandit rows into off-policy value estimates —
    the CSE (counterfactual slate/statistics estimation) transformer
    analogue (VowpalWabbitCSETransformer.scala: per-slot IPS/SNIPS +
    CressieRead interval output schema)."""

    rewardCol = StringParam(doc="observed reward column", default="reward")
    loggedProbCol = StringParam(doc="logging policy P(a) column",
                                default="probLog")
    targetProbCol = StringParam(doc="target policy P(a) column",
                                default="probPred")
    countCol = StringParam(doc="example count column (weights)", default="count")
    minImportanceWeight = FloatParam(doc="clip floor for 1/p", default=0.0)
    maxImportanceWeight = FloatParam(doc="clip cap for 1/p", default=100.0)
    delta = FloatParam(doc="CI significance", default=0.05)

    def _transform(self, ds: Dataset) -> Dataset:
        r = ds[self.rewardCol].astype(np.float64)
        pl = ds[self.loggedProbCol].astype(np.float64)
        pt = ds[self.targetProbCol].astype(np.float64)
        if self.countCol in ds:
            counts = ds[self.countCol].astype(np.int64)
            r = np.repeat(r, counts)
            pl = np.repeat(pl, counts)
            pt = np.repeat(pt, counts)
        lo, hi = CressieReadInterval(
            delta=self.delta, wmax=self.maxImportanceWeight
        ).interval(r, pl, pt)
        blo, bhi = bernstein_bound(r, pl, pt, delta=self.delta,
                                   wmax=self.maxImportanceWeight)
        return Dataset({
            "ips": np.asarray([ips(r, pl, pt, self.maxImportanceWeight)]),
            "snips": np.asarray([snips(r, pl, pt)]),
            "cressieRead": np.asarray([cressie_read(r, pl, pt)]),
            "cressieReadLower": np.asarray([lo]),
            "cressieReadUpper": np.asarray([hi]),
            "bernsteinLower": np.asarray([blo]),
            "bernsteinUpper": np.asarray([bhi]),
            "exampleCount": np.asarray([float(len(r))]),
        }, num_partitions=1)
