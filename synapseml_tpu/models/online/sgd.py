"""Jitted online-SGD core.

Re-designs VW's learn loop (reference: vw JNI ``VowpalWabbitNative.learn``
called per row, VowpalWabbitBaseLearner.scala:123-160) as a ``lax.scan``
over minibatches: each step consumes a (B, D) dense block, computes
margins on the MXU, and applies an AdaGrad-normalized update — VW's
``--adaptive --normalized --invariant`` default triple, restated for
batched hardware:

- *adaptive*: per-coordinate learning rate eta / sqrt(sum g^2)
- *normalized*: gradients scaled by the running max |x_d| so feature
  scales don't skew the step size
- the per-example t-schedule ``eta * (t0 / (t0 + t))^power_t``

Multipass + distributed: each shard scans its rows locally; at pass end
weights are parameter-averaged over the mesh (`pmean`), the TPU analogue
of VW's spanning-tree AllReduce at pass boundaries
(VowpalWabbitSyncSchedule.scala:16-72, VowpalWabbitClusterUtil.scala:35-40).
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ...parallel.mesh import DATA_AXIS


@dataclasses.dataclass(frozen=True)
class SGDConfig:
    """VW arg-surface analogue (reference: VowpalWabbitBase.scala params
    learningRate/powerT/l1/l2/numPasses + passThroughArgs)."""
    loss: str = "squared"          # squared | logistic | hinge | quantile | poisson
    learning_rate: float = 0.5
    power_t: float = 0.5
    initial_t: float = 1.0
    l1: float = 0.0
    l2: float = 0.0
    num_passes: int = 1
    batch_size: int = 32
    adaptive: bool = True
    normalized: bool = True
    quantile_tau: float = 0.5
    link: str = "identity"         # identity | logistic
    #: average weights across shards every k batches (0 = only at pass end)
    sync_every_batches: int = 0


class SGDState(NamedTuple):
    w: jnp.ndarray          # (D,) weights
    bias: jnp.ndarray       # () bias
    g2: jnp.ndarray         # (D,) adagrad accumulator
    g2_bias: jnp.ndarray    # ()
    x_max: jnp.ndarray      # (D,) running max |x| for normalization
    t: jnp.ndarray          # () example counter


def init_state(dim: int) -> SGDState:
    return SGDState(
        w=jnp.zeros(dim, jnp.float32), bias=jnp.zeros((), jnp.float32),
        g2=jnp.full(dim, 1e-6, jnp.float32), g2_bias=jnp.asarray(1e-6, jnp.float32),
        x_max=jnp.full(dim, 1e-6, jnp.float32), t=jnp.zeros((), jnp.float32))


def _loss_grad(loss: str, margin, y, tau: float):
    """d loss / d margin, elementwise.  Labels: logistic/hinge use ±1."""
    if loss == "squared":
        return margin - y
    if loss == "logistic":
        return -y / (1.0 + jnp.exp(y * margin))
    if loss == "hinge":
        return jnp.where(y * margin < 1.0, -y, 0.0)
    if loss == "quantile":
        return jnp.where(margin > y, 1.0 - tau, -tau)
    if loss == "poisson":
        return jnp.exp(margin) - y
    raise ValueError(f"unknown loss {loss!r}")


def _loss_value(loss: str, margin, y, tau: float):
    if loss == "squared":
        return 0.5 * (margin - y) ** 2
    if loss == "logistic":
        return jnp.log1p(jnp.exp(-y * margin))
    if loss == "hinge":
        return jnp.maximum(0.0, 1.0 - y * margin)
    if loss == "quantile":
        e = y - margin
        return jnp.where(e >= 0, tau * e, (tau - 1.0) * e)
    if loss == "poisson":
        return jnp.exp(margin) - y * margin
    raise ValueError(f"unknown loss {loss!r}")


def make_scan_step(cfg: SGDConfig, axis: Optional[str] = None):
    """One minibatch update, suitable for lax.scan.

    carry = (state, loss_sum, weight_sum); block = (x (B,D), y (B,),
    sample_weight (B,), valid-mask (B,)).
    """

    def step(carry, block):
        state, loss_sum, weight_sum = carry
        x, y, sw, mask = block
        eff_w = sw * mask
        margin = x @ state.w + state.bias                       # MXU
        g_m = _loss_grad(cfg.loss, margin, y, cfg.quantile_tau) * eff_w
        B = x.shape[0]
        denom = jnp.maximum(eff_w.sum(), 1.0)
        grad_w = (x * g_m[:, None]).sum(0) / denom + cfg.l2 * state.w
        grad_b = g_m.sum() / denom
        if axis is not None and cfg.sync_every_batches == 1:
            grad_w = lax.pmean(grad_w, axis)
            grad_b = lax.pmean(grad_b, axis)
        x_max = jnp.maximum(state.x_max, jnp.abs(x).max(0))
        if cfg.normalized:
            grad_w = grad_w / x_max
        g2 = state.g2 + grad_w ** 2
        g2_b = state.g2_bias + grad_b ** 2
        t = state.t + eff_w.sum()
        if cfg.adaptive:
            # VW --adaptive: the accumulator IS the schedule — per-coordinate
            # rate lr / (sum g^2)^power_t, no extra t-decay on top
            denom_w = g2 ** cfg.power_t
            denom_b = g2_b ** cfg.power_t
            step_w = cfg.learning_rate * grad_w / denom_w
            step_b = cfg.learning_rate * grad_b / denom_b
            shrink = cfg.learning_rate * cfg.l1 / jnp.maximum(denom_w, 1e-12)
        else:
            eta = cfg.learning_rate * (cfg.initial_t /
                                       (cfg.initial_t + t)) ** cfg.power_t
            step_w = eta * grad_w
            step_b = eta * grad_b
            shrink = eta * cfg.l1
        w = state.w - step_w
        if cfg.l1 > 0:
            # truncated-gradient L1 (VW --l1): shrink toward zero
            w = jnp.sign(w) * jnp.maximum(jnp.abs(w) - shrink, 0.0)
        new_state = SGDState(w=w, bias=state.bias - step_b, g2=g2,
                             g2_bias=g2_b, x_max=x_max, t=t)
        loss_sum = loss_sum + (_loss_value(cfg.loss, margin, y,
                                           cfg.quantile_tau) * eff_w).sum()
        weight_sum = weight_sum + eff_w.sum()
        return (new_state, loss_sum, weight_sum), None

    return step


def _pad_blocks(x: np.ndarray, y: np.ndarray, sw: np.ndarray,
                batch: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    n, d = x.shape
    n_blocks = max(1, -(-n // batch))
    pad = n_blocks * batch - n
    if pad:
        x = np.concatenate([x, np.zeros((pad, d), x.dtype)])
        y = np.concatenate([y, np.zeros(pad, y.dtype)])
        sw = np.concatenate([sw, np.zeros(pad, sw.dtype)])
    mask = np.ones(n_blocks * batch, np.float32)
    if pad:
        mask[-pad:] = 0.0
    return (x.reshape(n_blocks, batch, d), y.reshape(n_blocks, batch),
            sw.reshape(n_blocks, batch), mask.reshape(n_blocks, batch))


@partial(jax.jit, static_argnames=("cfg",))
def _run_pass(cfg: SGDConfig, state: SGDState, xb, yb, swb, maskb):
    step = make_scan_step(cfg)
    (state, loss_sum, w_sum), _ = lax.scan(
        step, (state, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        (xb, yb, swb, maskb))
    return state, loss_sum, w_sum


def _sync_state(state: SGDState) -> SGDState:
    """Cross-shard parameter averaging weighted by examples seen
    (mergeModels analogue, VowpalWabbitBaseLearner.scala:228-260)."""
    seen = jnp.maximum(state.t, 1e-6)
    total = lax.psum(seen, DATA_AXIS)
    return state._replace(
        w=lax.psum(state.w * seen, DATA_AXIS) / total,
        bias=lax.psum(state.bias * seen, DATA_AXIS) / total,
        g2=lax.psum(state.g2 * seen, DATA_AXIS) / total,
        g2_bias=lax.psum(state.g2_bias * seen, DATA_AXIS) / total,
        x_max=lax.pmax(state.x_max, DATA_AXIS),
        t=total)


def _make_sharded_pass(cfg: SGDConfig, mesh: Mesh):
    k = cfg.sync_every_batches

    def local_pass(state, xb, yb, swb, maskb):
        step = make_scan_step(cfg, axis=DATA_AXIS)
        init = (state, jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32))
        if k > 1:
            # mid-pass sync schedule: average weights after every chunk of
            # k batches (caller pads the block count to a multiple of k)
            nb = xb.shape[0]

            def chunk(carry, blocks):
                carry, _ = lax.scan(step, carry, blocks)
                st, ls, ws = carry
                return (_sync_state(st), ls, ws), None

            reshape = lambda a: a.reshape(nb // k, k, *a.shape[1:])  # noqa: E731
            (state, loss_sum, w_sum), _ = lax.scan(
                chunk, init, (reshape(xb), reshape(yb),
                              reshape(swb), reshape(maskb)))
        else:
            (state, loss_sum, w_sum), _ = lax.scan(
                step, init, (xb, yb, swb, maskb))
            state = _sync_state(state)  # pass-end allreduce
        return state, lax.psum(loss_sum, DATA_AXIS), lax.psum(w_sum, DATA_AXIS)

    shards = mesh.devices.size
    return jax.jit(jax.shard_map(
        local_pass, mesh=mesh,
        in_specs=(P(), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS), P(DATA_AXIS)),
        out_specs=(P(), P(), P()), check_vma=False)), shards


def train_sgd(x: np.ndarray, y: np.ndarray, cfg: SGDConfig,
              sample_weight: Optional[np.ndarray] = None,
              mesh: Optional[Mesh] = None,
              init: Optional[SGDState] = None):
    """Run ``cfg.num_passes`` passes; returns (state, stats dict).

    With a mesh, rows are sharded over ``DATA_AXIS`` and weights are
    parameter-averaged at every pass end (``trainInternalDistributed``
    analogue, VowpalWabbitBaseLearner.scala:197-211).
    """
    x = np.ascontiguousarray(x, np.float32)
    y = np.asarray(y, np.float32)
    sw = (np.asarray(sample_weight, np.float32) if sample_weight is not None
          else np.ones(len(y), np.float32))
    n, d = x.shape
    state = init if init is not None else init_state(d)

    if mesh is not None:
        run, shards = _make_sharded_pass(cfg, mesh)
        # pad rows so each shard gets whole blocks of cfg.batch_size — and,
        # with a mid-pass sync schedule, whole chunks of k blocks
        unit = cfg.batch_size * max(1, cfg.sync_every_batches)
        per = -(-n // shards)
        per = -(-per // unit) * unit
        tot = per * shards
        pad = tot - n
        if pad:
            x = np.concatenate([x, np.zeros((pad, d), np.float32)])
            y = np.concatenate([y, np.zeros(pad, np.float32)])
            sw = np.concatenate([sw, np.zeros(pad, np.float32)])
        mask = np.ones(tot, np.float32)
        if pad:
            mask[-pad:] = 0.0
        blocks = tot // cfg.batch_size
        xb = x.reshape(blocks, cfg.batch_size, d)
        yb = y.reshape(blocks, cfg.batch_size)
        swb = sw.reshape(blocks, cfg.batch_size)
        maskb = mask.reshape(blocks, cfg.batch_size)
    else:
        xb, yb, swb, maskb = _pad_blocks(x, y, sw, cfg.batch_size)

    loss_sum = w_sum = 0.0
    for _ in range(cfg.num_passes):
        if mesh is not None:
            state, ls, ws = run(state, xb, yb, swb, maskb)
        else:
            state, ls, ws = _run_pass(cfg, state, xb, yb, swb, maskb)
        loss_sum += float(ls)
        w_sum += float(ws)
    stats = {"average_loss": loss_sum / max(w_sum, 1e-12),
             "examples": float(state.t)}
    return state, stats


def predict_margin(state: SGDState, x: np.ndarray) -> np.ndarray:
    x = np.ascontiguousarray(x, np.float32)
    return np.asarray(jnp.asarray(x) @ state.w + state.bias)


def merge_states(states, weights=None) -> SGDState:
    """Parameter-average independently trained states
    (VowpalWabbitNative.mergeModels analogue)."""
    ws = np.asarray(weights if weights is not None
                    else [float(s.t) for s in states], np.float64)
    ws = ws / max(ws.sum(), 1e-12)
    def avg(field):
        return jnp.asarray(sum(np.asarray(getattr(s, field)) * wi
                               for s, wi in zip(states, ws)), jnp.float32)
    return SGDState(w=avg("w"), bias=avg("bias"), g2=avg("g2"),
                    g2_bias=avg("g2_bias"),
                    x_max=jnp.asarray(np.max([np.asarray(s.x_max) for s in states], 0)),
                    t=jnp.asarray(sum(float(s.t) for s in states), jnp.float32))
