"""Contextual bandit learner.

Re-designs the reference's VW contextual-bandit estimator (reference:
vw/.../VowpalWabbitContextualBandit.scala:1-376: schema = shared context
features + per-action features + chosen action/cost/probability columns).
Learning is IPS-weighted cost regression on the chosen action's feature
vector (VW's ``cb_type ips`` reduction to regression): each logged row
contributes an importance weight 1/p(action), and the policy scores every
action in one batched matmul at decision time.
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

from ...core.dataset import Dataset
from ...core.params import (BoolParam, FloatParam, IntParam, PyObjectParam,
                            StringParam)
from ...core.pipeline import Estimator, Model
from .sgd import SGDConfig, SGDState, predict_margin, train_sgd


class _BanditParams:
    sharedCol = StringParam(doc="shared context feature-vector column",
                            default="shared")
    featuresCol = StringParam(doc="list-of-action feature-vector column",
                              default="features")
    chosenActionCol = StringParam(doc="1-based chosen action index column",
                                  default="chosenAction")
    labelCol = StringParam(doc="observed cost column", default="label")
    probabilityCol = StringParam(doc="logged P(chosen action) column",
                                 default="probability")
    predictionCol = StringParam(doc="per-action score output",
                                default="prediction")
    learningRate = FloatParam(doc="base learning rate", default=0.5)
    powerT = FloatParam(doc="t-decay exponent", default=0.5)
    l1 = FloatParam(doc="L1 regularization", default=0.0)
    l2 = FloatParam(doc="L2 regularization", default=0.0)
    numPasses = IntParam(doc="passes over the data", default=1)
    batchSize = IntParam(doc="rows per update step", default=32)
    epsilon = FloatParam(doc="exploration rate for the served policy",
                         default=0.05)
    ipsClip = FloatParam(doc="importance weight cap (0 = uncapped)",
                         default=0.0)
    useInteractions = BoolParam(doc="include shared x action quadratic "
                                "features (VW -q sa)", default=True)
    useBarrierExecutionMode = BoolParam(doc="parity", default=False)
    mesh = PyObjectParam(doc="device mesh for data-parallel training")


def _row_features(shared: Optional[np.ndarray], action: np.ndarray,
                  interactions: bool) -> np.ndarray:
    """Chosen-action example = [action ++ shared ++ vec(shared ⊗ action)].
    The quadratic block is VW's ``-q sa`` namespace interaction — without
    it a linear scorer cannot express action-dependent context effects."""
    if shared is None:
        return action
    parts = [action, shared]
    if interactions:
        parts.append(np.outer(shared, action).ravel())
    return np.concatenate(parts)


class ContextualBandit(_BanditParams, Estimator):
    def __init__(self, **kw):
        super().__init__(**kw)

    def _fit(self, ds: Dataset) -> "ContextualBanditModel":
        n = ds.num_rows
        actions_col = ds[self.featuresCol]
        shared_col = ds[self.sharedCol] if self.sharedCol in ds else None
        chosen = ds[self.chosenActionCol].astype(np.int64) - 1  # 1-based
        cost = ds[self.labelCol].astype(np.float32)
        prob = ds[self.probabilityCol].astype(np.float32)
        xs: List[np.ndarray] = []
        for i in range(n):
            acts = [np.asarray(a, np.float32).ravel() for a in actions_col[i]]
            sh = (np.asarray(shared_col[i], np.float32).ravel()
                  if shared_col is not None else None)
            xs.append(_row_features(sh, acts[chosen[i]], self.useInteractions))
        x = np.stack(xs)
        iw = 1.0 / np.maximum(prob, 1e-6)
        if self.ipsClip > 0:
            iw = np.minimum(iw, self.ipsClip)
        cfg = SGDConfig(loss="squared", learning_rate=self.learningRate,
                        power_t=self.powerT, l1=self.l1, l2=self.l2,
                        num_passes=self.numPasses, batch_size=self.batchSize)
        state, stats = train_sgd(x, cost, cfg, sample_weight=iw,
                                 mesh=self.get("mesh"))
        model = ContextualBanditModel()
        model._copy_values_from(self)
        model.clear("mesh")
        model.state = state
        model.training_stats = stats
        return model


class ContextualBanditModel(_BanditParams, Model):
    state: Optional[SGDState] = None
    training_stats: Optional[dict] = None

    def _transform(self, ds: Dataset) -> Dataset:
        """Score every action; output predicted cost per action plus the
        greedy (cost-minimizing) action and its epsilon-greedy probability
        vector."""
        actions_col = ds[self.featuresCol]
        shared_col = ds[self.sharedCol] if self.sharedCol in ds else None
        scores_out, best_out, pmf_out = [], [], []
        eps = self.epsilon
        for i in range(ds.num_rows):
            acts = [np.asarray(a, np.float32).ravel() for a in actions_col[i]]
            sh = (np.asarray(shared_col[i], np.float32).ravel()
                  if shared_col is not None else None)
            x = np.stack([_row_features(sh, a, self.useInteractions) for a in acts])
            scores = predict_margin(self.state, x)
            k = len(acts)
            best = int(np.argmin(scores))
            pmf = np.full(k, eps / k)
            pmf[best] += 1.0 - eps
            scores_out.append(scores.astype(np.float64))
            best_out.append(best + 1)  # 1-based like the input schema
            pmf_out.append(pmf)
        return ds.with_columns({
            self.predictionCol: scores_out,
            "chosenActionOut": np.asarray(best_out, np.int64),
            "probabilities": pmf_out,
        })

    def _save_extra(self, path: str) -> None:
        import os
        np.savez(os.path.join(path, "state.npz"),
                 **{f: np.asarray(getattr(self.state, f))
                    for f in SGDState._fields})

    def _load_extra(self, path: str) -> None:
        import os
        import jax.numpy as jnp
        with np.load(os.path.join(path, "state.npz")) as z:
            self.state = SGDState(**{f: jnp.asarray(z[f])
                                     for f in SGDState._fields})
