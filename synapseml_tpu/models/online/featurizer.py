"""Hashing-trick featurizer + namespace interactions.

Re-designs the reference's VW feature engineering (reference:
vw/.../VowpalWabbitFeaturizer.scala:25,150-165 — murmur hash with
column-name prefix into a SparseVector — and
VowpalWabbitInteractions.scala:96 — namespace crossing).  TPU difference:
output is a *dense* vector column sized for the MXU; hash dimension
defaults accordingly (VW defaults to 2^18 sparse bits).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ...core.dataset import Dataset
from ...core.hashing import murmurhash3_32, murmurhash3_column
from ...core.params import (BoolParam, IntParam, ListParam, StringParam)
from ...core.pipeline import Transformer


class HashingFeaturizer(Transformer):
    """Hash input columns into one dense vector column.

    - numeric columns contribute value at index hash(colName)
    - string columns contribute 1.0 at index hash(colName + value)
    - list-of-string columns contribute counts per token
    (reference: VowpalWabbitFeaturizer.scala featurizer dispatch by dtype)
    """

    inputCols = ListParam(doc="columns to hash")
    outputCol = StringParam(doc="dense vector output", default="features")
    numBits = IntParam(doc="log2 of hash dimension", default=12)
    seed = IntParam(doc="murmur seed (hashSeed param)", default=0)
    sumCollisions = BoolParam(doc="sum colliding values (vs overwrite)",
                              default=True)
    preserveOrderNumBits = IntParam(doc="parity: VW order-preserving bits",
                                    default=0)
    signedMode = BoolParam(doc="use a hash bit as value sign", default=False)

    def __init__(self, inputCols: Optional[Sequence[str]] = None,
                 outputCol: Optional[str] = None, **kw):
        super().__init__(**kw)
        if inputCols is not None:
            self.set("inputCols", list(inputCols))
        if outputCol is not None:
            self.set("outputCol", outputCol)

    def _transform(self, ds: Dataset) -> Dataset:
        dim = 1 << self.numBits
        seed = self.seed
        n = ds.num_rows
        out = np.zeros((n, dim), np.float32)
        for c in self.inputCols:
            v = ds[c]
            if v.dtype != object:  # numeric: fixed index per column
                idx = murmurhash3_32(c, seed) % dim
                vals = v.astype(np.float32)
                if self.sumCollisions:
                    out[:, idx] += vals
                else:
                    out[:, idx] = vals
            else:
                # flatten (row, token) pairs and hash the whole column in
                # one native batch call (textproc.cpp), then scatter
                rows: List[int] = []
                flat: List[str] = []
                for i, x in enumerate(v):
                    tokens = x if isinstance(x, (list, tuple, np.ndarray)) else [x]
                    for t in tokens:
                        rows.append(i)
                        flat.append(c + str(t))
                if not flat:
                    continue
                hashes = murmurhash3_column(flat, seed).astype(np.int64)
                ridx = np.asarray(rows, np.int64)
                vals = np.ones(len(flat), np.float32)
                if self.signedMode:
                    vals = np.where((hashes >> 31) & 1, -1.0, 1.0).astype(np.float32)
                if self.sumCollisions:
                    np.add.at(out, (ridx, hashes % dim), vals)
                else:
                    out[ridx, hashes % dim] = vals
        return ds.with_column(self.outputCol, [row for row in out])


class FeatureInteractions(Transformer):
    """Quadratic/cubic crossing of hashed vector columns — VW's ``-q``/
    namespace interactions (reference: VowpalWabbitInteractions.scala:96).
    The cross of vectors a, b is the outer product flattened and re-hashed
    into ``numBits`` dims; on TPU the outer product is one einsum."""

    inputCols = ListParam(doc="vector columns to cross")
    outputCol = StringParam(doc="crossed vector output", default="interactions")
    numBits = IntParam(doc="log2 of output dimension", default=12)
    sumCollisions = BoolParam(doc="sum colliding values", default=True)

    def __init__(self, inputCols: Optional[Sequence[str]] = None,
                 outputCol: Optional[str] = None, **kw):
        super().__init__(**kw)
        if inputCols is not None:
            self.set("inputCols", list(inputCols))
        if outputCol is not None:
            self.set("outputCol", outputCol)

    def _transform(self, ds: Dataset) -> Dataset:
        cols = [np.stack([np.asarray(v, np.float32).ravel() for v in ds[c]])
                for c in self.inputCols]
        cross = cols[0]
        for other in cols[1:]:
            n = cross.shape[0]
            cross = np.einsum("ni,nj->nij", cross, other).reshape(n, -1)
        dim = 1 << self.numBits
        d_in = cross.shape[1]
        # deterministic index re-hash: position p -> murmur(p) % dim
        idx = np.array([murmurhash3_32(p.to_bytes(4, "little")) % dim
                        for p in range(d_in)], np.int64)
        out = np.zeros((cross.shape[0], dim), np.float32)
        if self.sumCollisions:
            np.add.at(out, (slice(None), idx), cross)
        else:
            # overwrite-on-collision: last position hashing to a slot wins
            out[:, idx] = cross
        return ds.with_column(self.outputCol, [row for row in out])


class VectorZipper(Transformer):
    """Combine one or more input columns into a sequence column
    (reference: vw/VectorZipper.scala:15-45 — used to assemble per-action
    columns into the action-features list for contextual bandits)."""

    inputCols = ListParam(doc="columns to zip")
    outputCol = StringParam(doc="sequence output column", default="zipped")

    def _transform(self, ds: Dataset) -> Dataset:
        cols = [ds[c] for c in self.inputCols]
        out = np.empty(ds.num_rows, object)
        for i in range(ds.num_rows):
            out[i] = [c[i] for c in cols]
        return ds.with_column(self.outputCol, out)
