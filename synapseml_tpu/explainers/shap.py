"""Kernel SHAP family.

Re-designs the reference's Kernel SHAP (reference:
explainers/KernelSHAPBase.scala:37 + KernelSHAPSampler coalition sampling,
TabularSHAP.scala, VectorSHAP.scala, TextSHAP.scala, ImageSHAP.scala):
sample feature coalitions weighted by the Shapley kernel, score
background-blended inputs, and solve a constrained weighted least squares
whose solution is the Shapley value vector.  The empty/full coalitions are
pinned with large weights so phi_0 = E[f(background)] and
sum(phi) = f(x) - phi_0 hold (the reference imposes the same constraints
analytically)."""

from __future__ import annotations

from math import comb
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.dataset import Dataset
from ..core.params import (FloatParam, IntParam, ListParam, PyObjectParam,
                           StringParam)
from ..core.pipeline import Transformer
from .common import LocalExplainerParams, extract_targets, replicate_row
from .lime import _concat_cols, _solve_rows
from .solvers import least_squares_regression


def shapley_kernel_weight(d: int, s: int) -> float:
    """pi(s) = (d-1) / (C(d,s) * s * (d-s)); infinite at s in {0, d}."""
    if s <= 0 or s >= d:
        return 1e6  # constraint rows
    return (d - 1) / (comb(d, s) * s * (d - s))


def sample_coalitions(d: int, n_samples: int, rng) -> np.ndarray:
    """(S, d) binary coalition matrix; first two rows are empty/full.
    Coalition sizes are drawn with probability proportional to the Shapley
    kernel mass at each size (KernelSHAPSampler analogue)."""
    sizes = np.arange(1, d)
    if len(sizes) == 0:
        probs = None
    else:
        mass = np.array([(d - 1) / (s * (d - s)) for s in sizes], np.float64)
        probs = mass / mass.sum()
    out = np.zeros((n_samples, d), bool)
    out[1, :] = True  # row 0 empty, row 1 full
    for i in range(2, n_samples):
        if probs is None:
            out[i] = rng.random(d) < 0.5
            continue
        s = rng.choice(sizes, p=probs)
        idx = rng.choice(d, size=s, replace=False)
        out[i, idx] = True
    return out


class _SHAPParams(LocalExplainerParams):
    infWeight = FloatParam(doc="weight pinning the empty/full coalitions",
                           default=1e6)


class _SHAPBase(_SHAPParams, Transformer):
    """Shared solve: subclasses build coalitions + perturbed inputs."""

    def _weights(self, coalitions: np.ndarray) -> np.ndarray:
        """Regression weights per sampled coalition.

        ``sample_coalitions`` already draws each coalition with probability
        proportional to its Shapley kernel weight (size ∝ kernel mass, then
        a uniform subset of that size), so the importance-sampled least
        squares must weight interior samples UNIFORMLY — re-applying the
        kernel here would square the size weighting.  Only the pinned
        empty/full constraint rows carry ``infWeight``."""
        d = coalitions.shape[1]
        sizes = coalitions.sum(1).astype(int)
        return np.where((sizes == 0) | (sizes == d),
                        float(self.infWeight), 1.0).astype(np.float64)


class TabularSHAP(_SHAPBase):
    """Kernel SHAP over numeric/categorical columns
    (TabularSHAP.scala analogue)."""

    inputCols = ListParam(doc="feature columns to explain")
    backgroundData = PyObjectParam(doc="Dataset of background rows")

    def __init__(self, model=None, inputCols: Optional[Sequence[str]] = None,
                 **kw):
        super().__init__(**kw)
        if model is not None:
            self.set("model", model)
        if inputCols is not None:
            self.set("inputCols", list(inputCols))

    def _transform(self, ds: Dataset) -> Dataset:
        bg = self.get("backgroundData")
        if bg is None:
            raise ValueError("TabularSHAP requires backgroundData")
        cols = self.inputCols
        d = len(cols)
        S = self.numSamples
        rng = np.random.default_rng(self.seed)
        n = ds.num_rows
        blocks, coalition_list = [], []
        for i in range(n):
            coalitions = sample_coalitions(d, S, rng)
            bg_idx = rng.integers(0, bg.num_rows, S)
            perturbed = replicate_row(ds, i, S)
            for j, c in enumerate(cols):
                inst_val = ds[c][i]
                bg_vals = bg[c][bg_idx]
                on = coalitions[:, j]
                if ds[c].dtype == object:
                    col = np.empty(S, dtype=object)
                    for s in range(S):
                        col[s] = inst_val if on[s] else bg_vals[s]
                    perturbed[c] = col
                else:
                    perturbed[c] = np.where(on, inst_val, bg_vals).astype(ds[c].dtype)
            blocks.append(perturbed)
            coalition_list.append(coalitions)
        merged = {c: _concat_cols([b[c] for b in blocks]) for c in blocks[0]}
        scored = self.model.transform(Dataset(merged, ds.num_partitions))
        targets = extract_targets(scored, self.targetCol,
                                  self.get("targetClasses"))
        T = targets.shape[1]
        tg = targets.reshape(n, S, T)
        st = np.stack(coalition_list).astype(np.float32)
        w = np.stack([self._weights(c) for c in coalition_list])
        coefs, r2 = _solve_rows(st, tg, w, 0.0)
        # phi_0 (intercept) = value at empty coalition; append it like the
        # reference (explanation vector length d+1, base value first)
        out, r2s = [], []
        for i in range(n):
            base = tg[i, 0]                      # empty coalition output
            phis = coefs[i]                      # (T, d)
            out.append(np.concatenate([base[:, None], phis], 1).astype(np.float64))
            r2s.append(r2[i].astype(np.float64))
        return ds.with_columns({self.outputCol: out, self.metricsCol: r2s})


class VectorSHAP(_SHAPBase):
    """Kernel SHAP over a dense vector column (VectorSHAP.scala analogue)."""

    inputCol = StringParam(doc="vector column", default="features")
    backgroundData = PyObjectParam(doc="Dataset of background rows")

    def __init__(self, model=None, inputCol: Optional[str] = None, **kw):
        super().__init__(**kw)
        if model is not None:
            self.set("model", model)
        if inputCol is not None:
            self.set("inputCol", inputCol)

    def _transform(self, ds: Dataset) -> Dataset:
        bg = self.get("backgroundData")
        if bg is None:
            raise ValueError("VectorSHAP requires backgroundData")
        bg_mat = np.stack([np.asarray(v, np.float64)
                           for v in bg[self.inputCol]])
        rng = np.random.default_rng(self.seed)
        n = ds.num_rows
        S = self.numSamples
        d = bg_mat.shape[1]
        blocks, coalition_list = [], []
        for i in range(n):
            inst = np.asarray(ds[self.inputCol][i], np.float64)
            coalitions = sample_coalitions(d, S, rng)
            bg_rows = bg_mat[rng.integers(0, len(bg_mat), S)]
            z = np.where(coalitions, inst, bg_rows)
            perturbed = replicate_row(ds, i, S)
            col = np.empty(S, dtype=object)
            for s in range(S):
                col[s] = z[s]
            perturbed[self.inputCol] = col
            blocks.append(perturbed)
            coalition_list.append(coalitions)
        merged = {c: _concat_cols([b[c] for b in blocks]) for c in blocks[0]}
        scored = self.model.transform(Dataset(merged, ds.num_partitions))
        targets = extract_targets(scored, self.targetCol,
                                  self.get("targetClasses"))
        T = targets.shape[1]
        tg = targets.reshape(n, S, T)
        st = np.stack(coalition_list).astype(np.float32)
        w = np.stack([self._weights(c) for c in coalition_list])
        coefs, r2 = _solve_rows(st, tg, w, 0.0)
        out, r2s = [], []
        for i in range(n):
            base = tg[i, 0]
            out.append(np.concatenate([base[:, None], coefs[i]], 1).astype(np.float64))
            r2s.append(r2[i].astype(np.float64))
        return ds.with_columns({self.outputCol: out, self.metricsCol: r2s})


class TextSHAP(_SHAPBase):
    """Kernel SHAP over text tokens (TextSHAP.scala analogue): coalition =
    subset of token positions kept; removed tokens are deleted."""

    inputCol = StringParam(doc="text column", default="text")
    tokensCol = StringParam(doc="tokenization output", default="tokens")

    def __init__(self, model=None, inputCol: Optional[str] = None, **kw):
        super().__init__(**kw)
        if model is not None:
            self.set("model", model)
        if inputCol is not None:
            self.set("inputCol", inputCol)

    def _transform(self, ds: Dataset) -> Dataset:
        rng = np.random.default_rng(self.seed)
        exp_col, r2_col, tok_col = [], [], []
        for i in range(ds.num_rows):
            tokens = str(ds[self.inputCol][i]).split()
            d = max(len(tokens), 1)
            S = self.numSamples
            coalitions = sample_coalitions(d, S, rng)
            texts = [" ".join(t for t, m in zip(tokens, row) if m)
                     for row in coalitions]
            perturbed = replicate_row(ds, i, S)
            col = np.empty(S, dtype=object)
            col[:] = texts
            perturbed[self.inputCol] = col
            scored = self.model.transform(Dataset(perturbed, 1))
            targets = extract_targets(scored, self.targetCol,
                                      self.get("targetClasses"))
            st = coalitions.astype(np.float32)
            w = self._weights(coalitions)
            coefs, r2 = _solve_rows(st[None], targets[None], w[None], 0.0)
            base = targets[0]
            exp_col.append(np.concatenate([base[:, None], coefs[0]], 1)
                           .astype(np.float64))
            r2_col.append(r2[0].astype(np.float64))
            tok_col.append(tokens)
        return ds.with_columns({self.outputCol: exp_col,
                                self.metricsCol: r2_col,
                                self.tokensCol: tok_col})


class ImageSHAP(_SHAPBase):
    """Kernel SHAP over superpixels (ImageSHAP.scala analogue)."""

    inputCol = StringParam(doc="image column (H,W,C arrays)", default="image")
    cellSize = FloatParam(doc="superpixel cell size", default=16.0)
    modifier = FloatParam(doc="superpixel compactness", default=130.0)
    superpixelCol = StringParam(doc="superpixel assignment output",
                                default="superpixels")

    def __init__(self, model=None, inputCol: Optional[str] = None, **kw):
        super().__init__(**kw)
        if model is not None:
            self.set("model", model)
        if inputCol is not None:
            self.set("inputCol", inputCol)

    def _transform(self, ds: Dataset) -> Dataset:
        from ..image.superpixel import slic_segments
        rng = np.random.default_rng(self.seed)
        exp_col, r2_col, sp_col = [], [], []
        for i in range(ds.num_rows):
            img = np.asarray(ds[self.inputCol][i], np.float32)
            seg = slic_segments(img, cell_size=self.cellSize,
                                modifier=self.modifier)
            d = int(seg.max()) + 1
            S = self.numSamples
            coalitions = sample_coalitions(d, S, rng)
            mean_color = img.reshape(-1, img.shape[-1]).mean(0)
            imgs = np.empty(S, dtype=object)
            for s in range(S):
                keep = coalitions[s][seg]
                imgs[s] = np.where(keep[..., None], img, mean_color).astype(img.dtype)
            perturbed = replicate_row(ds, i, S)
            perturbed[self.inputCol] = imgs
            scored = self.model.transform(Dataset(perturbed, 1))
            targets = extract_targets(scored, self.targetCol,
                                      self.get("targetClasses"))
            st = coalitions.astype(np.float32)
            w = self._weights(coalitions)
            coefs, r2 = _solve_rows(st[None], targets[None], w[None], 0.0)
            base = targets[0]
            exp_col.append(np.concatenate([base[:, None], coefs[0]], 1)
                           .astype(np.float64))
            r2_col.append(r2[0].astype(np.float64))
            sp_col.append(seg)
        return ds.with_columns({self.outputCol: exp_col,
                                self.metricsCol: r2_col,
                                self.superpixelCol: sp_col})
