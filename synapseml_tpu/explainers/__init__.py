"""Model interpretability — LIME, Kernel SHAP, ICE.

Re-designs the reference's ``explainers`` package (reference:
core/src/main/scala/com/microsoft/azure/synapse/ml/explainers/
LocalExplainer.scala:13, LIMEBase.scala:137, KernelSHAPBase.scala:37,
ICEExplainer.scala:130).  All explainers only need ``model.transform``
over perturbed copies of a row — perturbation batches are built host-side
and scored in a few large batched calls so the model's jitted path sees
MXU-sized blocks, then per-row weighted regressions are solved with one
vmapped jnp solve.
"""

from .solvers import lasso_regression, least_squares_regression
from .lime import TabularLIME, TextLIME, VectorLIME, ImageLIME
from .shap import TabularSHAP, TextSHAP, VectorSHAP, ImageSHAP
from .ice import ICETransformer

__all__ = [
    "lasso_regression", "least_squares_regression",
    "TabularLIME", "VectorLIME", "TextLIME", "ImageLIME",
    "TabularSHAP", "VectorSHAP", "TextSHAP", "ImageSHAP",
    "ICETransformer",
]
