"""Weighted linear solvers used by LIME / Kernel SHAP.

Re-designs the reference's internal regression solvers (reference:
explainers/LassoRegression.scala, explainers/LeastSquaresRegression.scala —
private breeze-based solvers used by LIMEBase.scala:137 and
KernelSHAPBase.scala).  Here: closed-form weighted least squares and
ISTA-style coordinate descent for lasso, both jit-compiled; the SHAP/LIME
per-row solves are tiny, so everything stays in float64-free float32 on
device.
"""

from __future__ import annotations

from functools import partial
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


class RegressionResult(NamedTuple):
    coefficients: jnp.ndarray   # (D,)
    intercept: jnp.ndarray      # ()
    r_squared: jnp.ndarray      # ()
    loss: jnp.ndarray           # ()


@jax.jit
def least_squares_regression(x, y, sample_weight=None,
                             l2: float = 1e-6) -> RegressionResult:
    """Weighted ridge-stabilized least squares with intercept."""
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    w = (jnp.asarray(sample_weight, jnp.float32) if sample_weight is not None
         else jnp.ones_like(y))
    w = w / jnp.maximum(w.sum(), 1e-12)
    xm = (w[:, None] * x).sum(0)
    ym = (w * y).sum()
    xc = x - xm
    yc = y - ym
    g = (xc * w[:, None]).T @ xc + l2 * jnp.eye(x.shape[1], dtype=jnp.float32)
    b = (xc * w[:, None]).T @ yc
    coef = jnp.linalg.solve(g, b)
    intercept = ym - xm @ coef
    pred = x @ coef + intercept
    ss_res = (w * (y - pred) ** 2).sum()
    ss_tot = (w * yc ** 2).sum()
    r2 = 1.0 - ss_res / jnp.maximum(ss_tot, 1e-12)
    return RegressionResult(coef, intercept, r2, ss_res)


@partial(jax.jit, static_argnames=("max_iter",))
def lasso_regression(x, y, alpha: float, sample_weight=None,
                     max_iter: int = 200) -> RegressionResult:
    """Weighted lasso via proximal gradient (ISTA) with fixed step 1/L."""
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    n, d = x.shape
    w = (jnp.asarray(sample_weight, jnp.float32) if sample_weight is not None
         else jnp.ones_like(y))
    w = w / jnp.maximum(w.sum(), 1e-12)
    xm = (w[:, None] * x).sum(0)
    ym = (w * y).sum()
    xc = x - xm
    yc = y - ym
    g = (xc * w[:, None]).T @ xc
    b = (xc * w[:, None]).T @ yc
    lipschitz = jnp.maximum(jnp.trace(g), 1e-8)  # cheap upper bound on λmax
    step = 1.0 / lipschitz

    def body(_, coef):
        grad = g @ coef - b
        z = coef - step * grad
        return jnp.sign(z) * jnp.maximum(jnp.abs(z) - step * alpha, 0.0)

    coef = lax.fori_loop(0, max_iter, body, jnp.zeros(d, jnp.float32))
    intercept = ym - xm @ coef
    pred = x @ coef + intercept
    ss_res = (w * (y - pred) ** 2).sum()
    ss_tot = (w * yc ** 2).sum()
    r2 = 1.0 - ss_res / jnp.maximum(ss_tot, 1e-12)
    return RegressionResult(coef, intercept, r2,
                            ss_res + alpha * jnp.abs(coef).sum())
