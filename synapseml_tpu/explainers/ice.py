"""Individual Conditional Expectation / Partial Dependence.

Re-designs the reference's ICE transformer (reference:
explainers/ICEExplainer.scala:130 — ICETransformer with kind
"individual"|"average"|"feature", numeric ranges and categorical top-K).
All grid×row evaluations are flattened into one ``model.transform`` call.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.dataset import Dataset
from ..core.params import (IntParam, ListParam, PyObjectParam, StringParam)
from ..core.pipeline import Transformer
from .common import extract_targets
from .lime import _concat_cols


class ICETransformer(Transformer):
    model = PyObjectParam(doc="fitted model to probe")
    targetCol = StringParam(doc="model output column", default="probability")
    targetClasses = ListParam(doc="class indices for vector outputs",
                              default=None)
    kind = StringParam(doc="individual|average", default="individual",
                       allowed=("individual", "average"))
    categoricalFeatures = ListParam(doc="categorical feature columns",
                                    default=None)
    numericFeatures = ListParam(doc="numeric feature columns", default=None)
    numSplits = IntParam(doc="grid points for numeric features", default=10)
    topNValues = IntParam(doc="top-K values for categorical features",
                          default=10)
    outputColSuffix = StringParam(doc="suffix for per-feature output columns",
                                  default="_dependence")

    def __init__(self, model=None, **kw):
        super().__init__(**kw)
        if model is not None:
            self.set("model", model)

    def _grid(self, ds: Dataset, col: str, categorical: bool) -> np.ndarray:
        v = ds[col]
        if categorical:
            vals, counts = np.unique(
                v.astype(str) if v.dtype == object else v, return_counts=True)
            top = vals[np.argsort(-counts)][:self.topNValues]
            if v.dtype == object:
                out = np.empty(len(top), dtype=object)
                out[:] = top
                return out
            return top.astype(v.dtype)
        x = v.astype(np.float64)
        lo, hi = np.nanmin(x), np.nanmax(x)
        return np.linspace(lo, hi, self.numSplits).astype(v.dtype)

    def _transform(self, ds: Dataset) -> Dataset:
        n = ds.num_rows
        out_cols: Dict[str, List] = {}
        feats = ([(c, False) for c in (self.get_or_default("numericFeatures") or [])]
                 + [(c, True) for c in (self.get_or_default("categoricalFeatures") or [])])
        if not feats:
            raise ValueError("ICETransformer needs numericFeatures and/or "
                             "categoricalFeatures")
        result_ds = ds
        pdp_cols: Dict[str, List] = {}
        for col, categorical in feats:
            grid = self._grid(ds, col, categorical)
            G = len(grid)
            # build n*G rows: row i repeated with col set to each grid value
            rep: Dict[str, np.ndarray] = {}
            for c in ds.columns:
                v = ds[c]
                if v.dtype == object:
                    big = np.empty(n * G, dtype=object)
                    for i in range(n):
                        for g in range(G):
                            big[i * G + g] = v[i]
                    rep[c] = big
                else:
                    rep[c] = np.repeat(v, G)
            if grid.dtype == object:
                gcol = np.empty(n * G, dtype=object)
                for i in range(n):
                    gcol[i * G:(i + 1) * G] = grid
                rep[col] = gcol
            else:
                rep[col] = np.tile(grid, n)
            scored = self.model.transform(Dataset(rep, ds.num_partitions))
            targets = extract_targets(scored, self.targetCol,
                                      self.get("targetClasses"))
            curves = targets.reshape(n, G, -1)
            name = f"{col}{self.outputColSuffix}"
            if self.kind == "average":
                # one output row per feature: grid values + (G, T) PDP matrix
                pdp_cols.setdefault("feature", []).append(col)
                pdp_cols.setdefault("values", []).append(
                    list(grid) if grid.dtype == object
                    else grid.astype(np.float64))
                pdp_cols.setdefault("dependence", []).append(
                    curves.mean(0).astype(np.float64))
            else:
                result_ds = result_ds.with_column(
                    name, [curves[i].astype(np.float64) for i in range(n)])
        if self.kind == "average":
            return Dataset(pdp_cols, num_partitions=1)
        return result_ds
