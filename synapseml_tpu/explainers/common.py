"""Shared plumbing for local explainers (LocalExplainer.scala:13 analogue)."""

from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..core.dataset import Dataset
from ..core.params import (FloatParam, IntParam, ListParam, PyObjectParam,
                           StringParam)
from ..core.pipeline import Transformer


class LocalExplainerParams:
    model = PyObjectParam(doc="fitted model whose output is explained")
    targetCol = StringParam(doc="model output column to explain",
                            default="probability")
    targetClasses = ListParam(doc="class indices to explain (vector outputs)",
                              default=None)
    outputCol = StringParam(doc="explanation output column", default="explanation")
    metricsCol = StringParam(doc="fit-quality output column (r2)", default="r2")
    numSamples = IntParam(doc="perturbations per row", default=1000)
    seed = IntParam(doc="sampling seed", default=0)


def extract_targets(scored: Dataset, target_col: str,
                    target_classes: Optional[Sequence[int]]) -> np.ndarray:
    """(n, T) matrix of model outputs: scalar column -> T=1; vector column ->
    selected class indices (default: class 1 if binary-like else all)."""
    col = scored[target_col]
    if col.dtype != object:
        return col.astype(np.float64)[:, None]
    mat = np.stack([np.asarray(v, np.float64).ravel() for v in col])
    if target_classes:
        return mat[:, list(target_classes)]
    if mat.shape[1] == 2:
        return mat[:, 1:2]
    return mat


def replicate_row(ds: Dataset, row_idx: int, n: int) -> dict:
    """n copies of one row as a column dict."""
    out = {}
    for c in ds.columns:
        v = ds[c]
        if v.dtype == object:
            col = np.empty(n, dtype=object)
            for i in range(n):
                col[i] = v[row_idx]
            out[c] = col
        else:
            out[c] = np.repeat(v[row_idx:row_idx + 1], n)
    return out
