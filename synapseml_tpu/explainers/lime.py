"""LIME family — local linear surrogate explanations.

Re-designs the reference's LIME implementations (reference:
explainers/LIMEBase.scala:137 + TabularLIME.scala, VectorLIME.scala,
TextLIME.scala, ImageLIME.scala): for each row, sample perturbed copies,
score them with the wrapped model, and fit a kernel-weighted lasso whose
coefficients are the explanation.  TPU shape: all rows' perturbations are
scored in ONE ``model.transform`` call (the reference scores per-row
sample DataFrames), and the per-row weighted solves are a single vmapped
jnp program.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dataset import Dataset
from ..core.params import (BoolParam, FloatParam, IntParam, ListParam,
                           PyObjectParam, StringParam)
from ..core.pipeline import Transformer
from .common import LocalExplainerParams, extract_targets, replicate_row
from .solvers import lasso_regression, least_squares_regression


class _LIMEParams(LocalExplainerParams):
    kernelWidth = FloatParam(doc="similarity kernel width (default "
                             "sqrt(d)*0.75 at fit time)", default=0.0)
    regularization = FloatParam(doc="lasso alpha (0 = least squares)",
                                default=0.0)


def _solve_rows(states: np.ndarray, targets: np.ndarray, weights: np.ndarray,
                alpha: float):
    """states (R, S, D), targets (R, S, T), weights (R, S) ->
    coefs (R, T, D), r2 (R, T)."""
    R, S, D = states.shape
    T = targets.shape[2]

    def one(xs, ys, ws):
        if alpha > 0:
            res = jax.vmap(lambda y: lasso_regression(xs, y, alpha, ws),
                           in_axes=1)(ys)
        else:
            res = jax.vmap(lambda y: least_squares_regression(xs, y, ws),
                           in_axes=1)(ys)
        return res.coefficients, res.r_squared

    coefs, r2 = jax.jit(jax.vmap(one))(
        jnp.asarray(states, jnp.float32), jnp.asarray(targets, jnp.float32),
        jnp.asarray(weights, jnp.float32))
    return np.asarray(coefs), np.asarray(r2)


def _kernel_weights(states01: np.ndarray, width: float) -> np.ndarray:
    """exp(-d^2 / width^2) with d = distance from the all-ones (original)
    state (LIMEBase.getSampleWeightUdf analogue)."""
    d2 = ((1.0 - states01) ** 2).sum(-1)
    return np.exp(-d2 / max(width, 1e-9) ** 2)


class _LIMEBase(_LIMEParams, Transformer):
    """Shared transform loop: subclasses implement ``_perturb_row``."""

    def _prepare(self, ds: Dataset) -> Dict:
        """Row-independent context (background stats etc.), computed ONCE
        per transform instead of per explained row."""
        return {}

    def _perturb_row(self, ds: Dataset, i: int, rng, ctx: Dict) -> Dict:
        """Returns dict(perturbed=column dict, states=(S, D) regression
        features, states01=(S, D) similarity space in [0,1])."""
        raise NotImplementedError

    def _transform(self, ds: Dataset) -> Dataset:
        rng = np.random.default_rng(self.seed)
        n = ds.num_rows
        ctx = self._prepare(ds)
        blocks, states, states01 = [], [], []
        for i in range(n):
            p = self._perturb_row(ds, i, rng, ctx)
            blocks.append(p["perturbed"])
            states.append(p["states"])
            states01.append(p["states01"])
        merged = {c: _concat_cols([b[c] for b in blocks])
                  for c in blocks[0]}
        big = Dataset(merged, ds.num_partitions)
        scored = self.model.transform(big)
        targets = extract_targets(scored, self.targetCol,
                                  self.get("targetClasses"))
        S = states[0].shape[0]
        D = states[0].shape[1]
        T = targets.shape[1]
        st = np.stack(states)                    # (R, S, D)
        st01 = np.stack(states01)
        tg = targets.reshape(n, S, T)
        width = self.kernelWidth or (np.sqrt(D) * 0.75)
        w = _kernel_weights(st01, width)
        coefs, r2 = _solve_rows(st, tg, w, self.regularization)
        exp_col = [coefs[i].astype(np.float64) for i in range(n)]  # (T, D)
        r2_col = [r2[i].astype(np.float64) for i in range(n)]
        return ds.with_columns({self.outputCol: exp_col,
                                self.metricsCol: r2_col})


def _concat_cols(cols: List[np.ndarray]) -> np.ndarray:
    if cols[0].dtype == object:
        out = np.empty(sum(len(c) for c in cols), dtype=object)
        k = 0
        for c in cols:
            out[k:k + len(c)] = c
            k += len(c)
        return out
    return np.concatenate(cols)


class TabularLIME(_LIMEBase):
    """LIME over numeric/categorical columns (TabularLIME.scala analogue).
    Numeric features are perturbed with background-std gaussian noise;
    categorical features are resampled from the background distribution."""

    inputCols = ListParam(doc="feature columns to explain")
    backgroundData = PyObjectParam(doc="Dataset for sampling statistics")
    categoricalFeatures = ListParam(doc="subset of inputCols treated as "
                                    "categorical", default=None)

    def __init__(self, model=None, inputCols: Optional[Sequence[str]] = None,
                 **kw):
        super().__init__(**kw)
        if model is not None:
            self.set("model", model)
        if inputCols is not None:
            self.set("inputCols", list(inputCols))

    def _background(self) -> Dataset:
        bg = self.get("backgroundData")
        if bg is None:
            raise ValueError("TabularLIME requires backgroundData")
        return bg

    def _prepare(self, ds: Dataset) -> Dict:
        bg = self._background()
        cats = set(self.get_or_default("categoricalFeatures") or [])
        stats = {}
        for c in self.inputCols:
            if c not in cats:
                vals = bg[c].astype(np.float64)
                stats[c] = (float(np.nanmean(vals)),
                            float(np.nanstd(vals)) or 1.0)
        return {"bg": bg, "cats": cats, "stats": stats}

    def _perturb_row(self, ds: Dataset, i: int, rng, ctx: Dict) -> Dict:
        bg, cats, stats = ctx["bg"], ctx["cats"], ctx["stats"]
        cols = self.inputCols
        S = self.numSamples
        perturbed = replicate_row(ds, i, S)
        states = np.zeros((S, len(cols)), np.float32)
        states01 = np.zeros((S, len(cols)), np.float32)
        for j, c in enumerate(cols):
            if c in cats:
                bg_col = bg[c]
                samples = bg_col[rng.integers(0, len(bg_col), S)]
                orig = ds[c][i]
                same = np.array([s == orig for s in samples])
                # keep original value on ~half so locality is represented
                keep = rng.random(S) < 0.5
                final = np.where(keep, orig, samples)
                if ds[c].dtype == object:
                    col = np.empty(S, dtype=object)
                    col[:] = final
                    perturbed[c] = col
                else:
                    perturbed[c] = final.astype(ds[c].dtype)
                ind = np.where(keep, 1.0, same.astype(np.float64))
                states[:, j] = ind
                states01[:, j] = ind
            else:
                mu, sd = stats[c]
                orig = float(ds[c][i])
                z = orig + rng.normal(0.0, sd, S)
                if np.issubdtype(ds[c].dtype, np.integer):
                    z = np.round(z)
                # regress on the values the model actually sees
                fed = z.astype(ds[c].dtype)
                perturbed[c] = fed
                z = fed.astype(np.float64)
                states[:, j] = (z - mu) / sd
                # similarity in [0,1]: 1 at the original value
                states01[:, j] = np.exp(-0.5 * ((z - orig) / sd) ** 2)
        return {"perturbed": perturbed, "states": states,
                "states01": states01}


class VectorLIME(_LIMEBase):
    """LIME over a dense vector column (VectorLIME.scala analogue)."""

    inputCol = StringParam(doc="vector column to explain", default="features")
    backgroundData = PyObjectParam(doc="Dataset for sampling statistics")

    def __init__(self, model=None, inputCol: Optional[str] = None, **kw):
        super().__init__(**kw)
        if model is not None:
            self.set("model", model)
        if inputCol is not None:
            self.set("inputCol", inputCol)

    def _prepare(self, ds: Dataset) -> Dict:
        bg = self.get("backgroundData")
        mat = (np.stack([np.asarray(v, np.float64) for v in bg[self.inputCol]])
               if bg is not None else
               np.stack([np.asarray(v, np.float64) for v in ds[self.inputCol]]))
        return {"mu": mat.mean(0),
                "sd": np.where(mat.std(0) > 0, mat.std(0), 1.0)}

    def _perturb_row(self, ds: Dataset, i: int, rng, ctx: Dict) -> Dict:
        mu, sd = ctx["mu"], ctx["sd"]
        orig = np.asarray(ds[self.inputCol][i], np.float64)
        S = self.numSamples
        z = orig + rng.normal(0.0, 1.0, (S, len(orig))) * sd
        perturbed = replicate_row(ds, i, S)
        col = np.empty(S, dtype=object)
        for s in range(S):
            col[s] = z[s]
        perturbed[self.inputCol] = col
        states = ((z - mu) / sd).astype(np.float32)
        states01 = np.exp(-0.5 * ((z - orig) / sd) ** 2).astype(np.float32)
        return {"perturbed": perturbed, "states": states,
                "states01": states01}


class TextLIME(_LIMEBase):
    """LIME over text: binary token masking (TextLIME.scala analogue).
    Explanation has one coefficient per token position."""

    inputCol = StringParam(doc="text column", default="text")
    tokensCol = StringParam(doc="output column with the tokenization",
                            default="tokens")
    samplingFraction = FloatParam(doc="P(token kept) per sample", default=0.7)

    def __init__(self, model=None, inputCol: Optional[str] = None, **kw):
        super().__init__(**kw)
        if model is not None:
            self.set("model", model)
        if inputCol is not None:
            self.set("inputCol", inputCol)

    def _transform(self, ds: Dataset) -> Dataset:
        # token counts differ per row -> solve per row (vmap not rectangular)
        rng = np.random.default_rng(self.seed)
        exp_col, r2_col, tok_col = [], [], []
        for i in range(ds.num_rows):
            tokens = str(ds[self.inputCol][i]).split()
            d = max(len(tokens), 1)
            S = self.numSamples
            mask = rng.random((S, d)) < self.samplingFraction
            mask[0, :] = True  # include the unperturbed text
            texts = [" ".join(t for t, m in zip(tokens, row) if m)
                     for row in mask]
            perturbed = replicate_row(ds, i, S)
            col = np.empty(S, dtype=object)
            col[:] = texts
            perturbed[self.inputCol] = col
            scored = self.model.transform(Dataset(perturbed, 1))
            targets = extract_targets(scored, self.targetCol,
                                      self.get("targetClasses"))
            states = mask.astype(np.float32)
            width = self.kernelWidth or (np.sqrt(d) * 0.75)
            w = _kernel_weights(states, width)
            coefs, r2 = _solve_rows(states[None], targets[None], w[None],
                                    self.regularization)
            exp_col.append(coefs[0].astype(np.float64))
            r2_col.append(r2[0].astype(np.float64))
            tok_col.append(tokens)
        return ds.with_columns({self.outputCol: exp_col,
                                self.metricsCol: r2_col,
                                self.tokensCol: tok_col})


class ImageLIME(_LIMEBase):
    """LIME over images via superpixel masking (ImageLIME.scala analogue:
    cellSize/modifier SLIC params, samplingFraction superpixel keep rate)."""

    inputCol = StringParam(doc="image column (H,W,C arrays)", default="image")
    cellSize = FloatParam(doc="superpixel cell size", default=16.0)
    modifier = FloatParam(doc="superpixel compactness", default=130.0)
    samplingFraction = FloatParam(doc="P(superpixel kept)", default=0.7)
    superpixelCol = StringParam(doc="output: superpixel assignment",
                                default="superpixels")

    def __init__(self, model=None, inputCol: Optional[str] = None, **kw):
        super().__init__(**kw)
        if model is not None:
            self.set("model", model)
        if inputCol is not None:
            self.set("inputCol", inputCol)

    def _transform(self, ds: Dataset) -> Dataset:
        from ..image.superpixel import slic_segments
        rng = np.random.default_rng(self.seed)
        exp_col, r2_col, sp_col = [], [], []
        for i in range(ds.num_rows):
            img = np.asarray(ds[self.inputCol][i], np.float32)
            seg = slic_segments(img, cell_size=self.cellSize,
                                modifier=self.modifier)
            d = int(seg.max()) + 1
            S = self.numSamples
            mask = rng.random((S, d)) < self.samplingFraction
            mask[0, :] = True
            imgs = np.empty(S, dtype=object)
            mean_color = img.reshape(-1, img.shape[-1]).mean(0)
            for s in range(S):
                keep = mask[s][seg]           # (H, W) bool
                out = np.where(keep[..., None], img, mean_color)
                imgs[s] = out.astype(img.dtype)
            perturbed = replicate_row(ds, i, S)
            perturbed[self.inputCol] = imgs
            scored = self.model.transform(Dataset(perturbed, 1))
            targets = extract_targets(scored, self.targetCol,
                                      self.get("targetClasses"))
            states = mask.astype(np.float32)
            width = self.kernelWidth or (np.sqrt(d) * 0.75)
            w = _kernel_weights(states, width)
            coefs, r2 = _solve_rows(states[None], targets[None], w[None],
                                    self.regularization)
            exp_col.append(coefs[0].astype(np.float64))
            r2_col.append(r2[0].astype(np.float64))
            sp_col.append(seg)
        return ds.with_columns({self.outputCol: exp_col,
                                self.metricsCol: r2_col,
                                self.superpixelCol: sp_col})
