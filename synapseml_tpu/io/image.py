"""Image file ingestion (reference: core/.../io/image/ImageUtils +
org/apache/spark/ml/source/image/PatchedImageFileFormat.scala — reads a
directory of images into the image schema {path, height, width,
nChannels, mode, data}; ``dropImageFailures`` filters undecodable
files)."""

from __future__ import annotations

import io
import os
from typing import List, Optional

import numpy as np

from ..core.dataset import Dataset
from .binary import BinaryFileReader

#: reference ImageSchema modes (OpenCV type codes): CV_8UC1/CV_8UC3/CV_8UC4
MODE_GRAY = 0
MODE_BGR = 16
MODE_BGRA = 24

_IMAGE_EXT = (".jpg", ".jpeg", ".png", ".bmp", ".gif", ".tif", ".tiff",
              ".webp")


def decode_image(data: bytes):
    """bytes → (H, W, C) uint8 array in BGR order, or None if
    undecodable (reference: ImageUtils.safeRead — OpenCV decodes BGR,
    so the TPU build keeps the same channel order for parity)."""
    try:
        from PIL import Image
    except ImportError:  # pragma: no cover - PIL is in the image
        return None
    try:
        img = Image.open(io.BytesIO(data))
        img.load()
    except Exception:
        return None
    if img.mode not in ("L", "RGB", "RGBA"):
        img = img.convert("RGB")
    arr = np.asarray(img, dtype=np.uint8)
    if arr.ndim == 2:
        return arr[:, :, None]
    return arr[:, :, ::-1] if arr.shape[2] in (3, 4) else arr


def read_images(path: str, recursive: bool = False,
                drop_image_failures: bool = True,
                sample_ratio: float = 1.0, seed: int = 0) -> Dataset:
    """Directory → image-schema Dataset (reference:
    PatchedImageFileFormat.scala + ImageSchemaUtils)."""
    raw = BinaryFileReader.read(path, recursive=recursive,
                                sample_ratio=sample_ratio,
                                inspect_zip=False, seed=seed)
    rows = []
    for p, b in zip(raw["path"], raw["bytes"]):
        if not str(p).lower().endswith(_IMAGE_EXT):
            continue
        arr = decode_image(b)
        if arr is None:
            if drop_image_failures:
                continue
            rows.append((p, 0, 0, 0, -1, None))
        else:
            h, w, c = arr.shape
            mode = {1: MODE_GRAY, 3: MODE_BGR, 4: MODE_BGRA}.get(c, -1)
            rows.append((p, h, w, c, mode, arr))
    n = len(rows)
    data_col = np.empty(n, dtype=object)
    for i, r in enumerate(rows):
        data_col[i] = r[5]
    return Dataset({
        "path": np.asarray([r[0] for r in rows], dtype=object),
        "height": np.asarray([r[1] for r in rows], dtype=np.int64),
        "width": np.asarray([r[2] for r in rows], dtype=np.int64),
        "nChannels": np.asarray([r[3] for r in rows], dtype=np.int64),
        "mode": np.asarray([r[4] for r in rows], dtype=np.int64),
        "data": data_col,
    })
