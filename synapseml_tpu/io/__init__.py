"""IO: HTTP client stages + serving (reference: core/.../io/)."""

from .http import (HTTPClient, HTTPRequestData, HTTPResponseData,
                   HTTPTransformer, JSONInputParser, JSONOutputParser,
                   SimpleHTTPTransformer)

__all__ = [
    "HTTPClient", "HTTPRequestData", "HTTPResponseData", "HTTPTransformer",
    "JSONInputParser", "JSONOutputParser", "SimpleHTTPTransformer",
]
