"""IO: HTTP client stages, binary/image file formats, PowerBI sink
(reference: core/.../io/)."""

from .http import (HTTPClient, HTTPRequestData, HTTPResponseData,
                   CustomInputParser, CustomOutputParser,
                   HTTPTransformer, JSONInputParser, JSONOutputParser,
                   StringOutputParser,
                   SimpleHTTPTransformer)
from .binary import BinaryFileReader, read_binary_files
from .colstore import (ChunkedColumnSource, SparseChunkedSource,
                       csv_to_colstore, dense_to_csr, write_csr,
                       write_matrix)
from .image import decode_image, read_images
from .port_forward import (ForwardSession, TcpRelay,
                           forward_port_to_remote)
from .powerbi import PowerBIResponseError, PowerBIWriter

__all__ = [
    "HTTPClient", "HTTPRequestData", "HTTPResponseData", "HTTPTransformer",
    "CustomInputParser", "CustomOutputParser", "JSONInputParser",
    "JSONOutputParser", "StringOutputParser", "SimpleHTTPTransformer",
    "BinaryFileReader", "read_binary_files", "decode_image", "read_images",
    "ChunkedColumnSource", "SparseChunkedSource", "csv_to_colstore",
    "dense_to_csr", "write_csr", "write_matrix",
    "PowerBIWriter", "PowerBIResponseError",
    "ForwardSession", "TcpRelay", "forward_port_to_remote",
]
