"""Binary file ingestion (reference: core/.../io/binary/
BinaryFileFormat.scala:250, BinaryFileReader.scala:105 — recursive
directory walk, optional zip inspection, seeded subsampling; schema
{path, bytes} per BinaryFileSchema)."""

from __future__ import annotations

import io
import os
import zipfile
from typing import Iterator, List, Optional, Tuple

import numpy as np

from ..core.dataset import Dataset


def _walk(path: str, recursive: bool) -> List[str]:
    """Files under ``path`` (reference: BinaryFileReader.recursePath —
    symlink-cycle-safe recursion)."""
    if os.path.isfile(path):
        return [path]
    out: List[str] = []
    seen = set()
    for root, dirs, files in os.walk(path, followlinks=True):
        real = os.path.realpath(root)
        if real in seen:
            dirs[:] = []
            continue
        seen.add(real)
        for f in sorted(files):
            out.append(os.path.join(root, f))
        if not recursive:
            break
    return out


def _iter_entries(fp: str, inspect_zip: bool
                  ) -> Iterator[Tuple[str, bytes]]:
    """(path, bytes) rows; zip members get ``archive.zip/member`` paths
    (reference: BinaryFileFormat.scala zip handling +
    KeyValueReaderIterator.scala)."""
    if inspect_zip and fp.endswith(".zip") and zipfile.is_zipfile(fp):
        with zipfile.ZipFile(fp) as zf:
            for name in zf.namelist():
                if name.endswith("/"):
                    continue
                yield f"{fp}/{name}", zf.read(name)
    else:
        with open(fp, "rb") as f:
            yield fp, f.read()


class BinaryFileReader:
    """Directory of binary files → Dataset (reference:
    BinaryFileReader.read — sampleRatio/inspectZip/seed options)."""

    @staticmethod
    def read(path: str, recursive: bool = False, sample_ratio: float = 1.0,
             inspect_zip: bool = True, seed: int = 0) -> Dataset:
        rng = np.random.default_rng(seed)
        paths: List[str] = []
        blobs: List[bytes] = []
        for fp in _walk(path, recursive):
            for name, data in _iter_entries(fp, inspect_zip):
                if sample_ratio < 1.0 and rng.random() >= sample_ratio:
                    continue
                paths.append(name)
                blobs.append(data)
        path_col = np.asarray(paths, dtype=object)
        byte_col = np.empty(len(blobs), dtype=object)
        for i, b in enumerate(blobs):
            byte_col[i] = b
        return Dataset({"path": path_col, "bytes": byte_col})


def read_binary_files(path: str, **kw) -> Dataset:
    """Module-level convenience (reference: IOImplicits' reader syntax)."""
    return BinaryFileReader.read(path, **kw)
