"""PowerBI streaming-dataset sink (reference: core/.../io/powerbi/
PowerBIWriter.scala:27-116 — rows are minibatched (fixed/dynamic/timed),
optionally funneled through PartitionConsolidator, and POSTed as JSON
arrays; non-200 responses raise)."""

from __future__ import annotations

import json
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import numpy as np

from ..core.dataset import Dataset
from .http import HTTPClient, HTTPRequestData

_APPLICABLE_OPTIONS = {
    "consolidate", "concurrency", "concurrentTimeout", "minibatcher",
    "maxBatchSize", "batchSize", "buffered", "maxBufferSize",
    "millisToWait",
}


class PowerBIResponseError(RuntimeError):
    """Non-200 from the PowerBI endpoint (reference: PowerBIWriter's
    CustomOutputParser throws HttpResponseException)."""

    def __init__(self, status_code: int, reason: str, content: str):
        super().__init__(
            f"Request failed with\n code: {status_code},\n "
            f"reason: {reason},\n content: {content}")
        self.status_code = status_code


def _batch_sizes(ds: Dataset, options: Dict[str, str]) -> List[int]:
    """Row counts per POST, honoring the reference's minibatcher modes
    (PowerBIWriter.scala:55-68)."""
    kind = options.get("minibatcher", "fixed")
    n = ds.num_rows
    if kind == "fixed":
        b = int(options.get("batchSize", 10))
        return [min(b, n - s) for s in range(0, n, b)]
    if kind in ("dynamic", "timed"):
        cap = int(options.get("maxBatchSize", 2 ** 31 - 1))
        sizes = []
        for a, b in ds.partition_bounds():
            size = b - a
            while size > 0:
                sizes.append(min(size, cap))
                size -= cap
        return sizes
    raise ValueError(f"unknown minibatcher {kind!r}")


class PowerBIWriter:
    """Dataset → PowerBI push-dataset REST endpoint."""

    @staticmethod
    def write(ds: Dataset, url: str,
              options: Optional[Dict[str, str]] = None) -> None:
        options = dict(options or {})
        unknown = set(options) - _APPLICABLE_OPTIONS
        if unknown:
            raise ValueError(
                f"{sorted(unknown)} not applicable options "
                f"{sorted(_APPLICABLE_OPTIONS)}")

        if options.get("consolidate", "false").lower() == "true":
            from ..ops.stages import PartitionConsolidator
            ds = PartitionConsolidator().transform(ds)

        concurrency = int(options.get("concurrency", 1))
        cols = list(ds.columns)
        sizes = _batch_sizes(ds, options)
        http = HTTPClient(timeout_s=float(
            options.get("concurrentTimeout", 30.0)))

        def post(bounds):
            start, stop = bounds
            rows = []
            for i in range(start, stop):
                row = {}
                for c in cols:
                    v = ds[c][i]
                    row[c] = v.item() if isinstance(v, np.generic) else v
                rows.append(row)
            resp = http.send(HTTPRequestData(
                url=url, method="POST",
                headers={"Content-Type": "application/json"},
                entity=json.dumps(rows).encode()))
            if resp.status_code != 200:
                raise PowerBIResponseError(
                    resp.status_code, resp.reason,
                    (resp.entity or b"").decode("utf-8", "replace"))

        bounds = []
        start = 0
        for s in sizes:
            bounds.append((start, start + s))
            start += s
        with ThreadPoolExecutor(max_workers=max(1, concurrency)) as pool:
            # list() propagates the first PowerBIResponseError
            list(pool.map(post, bounds))

    #: reference exposes stream() as well; the TPU build's streaming
    #: entry point is the serving layer, so write() is the parity point
    stream = write
