"""HTTP client pipeline stages.

Re-designs the reference's HTTP stack (reference: core/.../io/http/
HTTPTransformer.scala:44-95 — ``concurrency``/``concurrentTimeout``
params over an async Apache HttpClient; HTTPClients.scala:65-189 —
``AdvancedHTTPHandling`` retry/backoff on 429/5xx; HTTPSchema.scala —
request/response row codecs; SimpleHTTPTransformer.scala:65 — JSON
in/out convenience).  Python shape: dataclass request/response rows, a
stdlib-``urllib`` client with the same backoff policy, and a thread pool
for concurrency (requests are IO-bound; the GIL is released in socket
waits, matching the reference's async client semantics).
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from ..core.dataset import Dataset
from ..core.params import (DictParam, FloatParam, IntParam, ListParam,
                           Param, PyObjectParam, StringParam, UDFParam)
from ..core.pipeline import Transformer


@dataclass
class HTTPRequestData:
    """Request row (reference: HTTPSchema request codec)."""
    url: str
    method: str = "GET"
    headers: Dict[str, str] = field(default_factory=dict)
    entity: Optional[bytes] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "HTTPRequestData":
        entity = d.get("entity")
        if isinstance(entity, str):
            entity = entity.encode("utf-8")
        return HTTPRequestData(url=d["url"], method=d.get("method", "GET"),
                               headers=dict(d.get("headers", {})),
                               entity=entity)


@dataclass
class HTTPResponseData:
    """Response row (reference: HTTPSchema response codec)."""
    status_code: int
    reason: str = ""
    headers: Dict[str, str] = field(default_factory=dict)
    entity: bytes = b""

    def json(self) -> Any:
        return json.loads(self.entity.decode("utf-8"))

    def text(self) -> str:
        return self.entity.decode("utf-8", errors="replace")


#: statuses the advanced handler retries (reference: HTTPClients.scala:65)
RETRY_STATUSES = (429, 500, 502, 503, 504)


class HTTPClient:
    """Blocking client with exponential backoff on 429/5xx
    (reference: AdvancedHTTPHandling, HTTPClients.scala:65-175)."""

    def __init__(self, retries: int = 3, backoffs_ms: Sequence[int] = (100, 500, 1000),
                 timeout_s: float = 60.0):
        self.retries = retries
        self.backoffs_ms = list(backoffs_ms)
        self.timeout_s = timeout_s

    def send(self, req: HTTPRequestData) -> HTTPResponseData:
        last: Optional[HTTPResponseData] = None
        for attempt in range(self.retries + 1):
            try:
                r = urllib.request.Request(
                    req.url, data=req.entity, method=req.method,
                    headers=dict(req.headers))
                with urllib.request.urlopen(r, timeout=self.timeout_s) as resp:
                    return HTTPResponseData(
                        status_code=resp.status,
                        reason=getattr(resp, "reason", "") or "",
                        headers=dict(resp.headers),
                        entity=resp.read())
            except urllib.error.HTTPError as e:
                last = HTTPResponseData(status_code=e.code,
                                        reason=str(e.reason),
                                        headers=dict(e.headers or {}),
                                        entity=e.read() or b"")
                if e.code not in RETRY_STATUSES:
                    return last
            except (urllib.error.URLError, OSError) as e:
                last = HTTPResponseData(status_code=0, reason=str(e))
            if attempt < self.retries:
                idx = min(attempt, len(self.backoffs_ms) - 1)
                time.sleep(self.backoffs_ms[idx] / 1000.0)
        return last if last is not None else HTTPResponseData(
            status_code=0, reason="no attempt made")


class HTTPTransformer(Transformer):
    """Send one HTTP request per row, concurrently
    (reference: HTTPTransformer.scala:95; params ``concurrency`` and
    ``concurrentTimeout`` match :44-60)."""

    inputCol = StringParam(doc="column of request dicts/HTTPRequestData",
                           default="request")
    outputCol = StringParam(doc="column of HTTPResponseData", default="response")
    concurrency = IntParam(doc="concurrent requests per host", default=1)
    concurrentTimeout = FloatParam(doc="seconds to wait for the batch "
                                   "(None = forever)")
    handler = UDFParam(doc="custom (client, request) -> response handler")
    retries = IntParam(doc="retry count for 429/5xx", default=3)

    def _transform(self, ds: Dataset) -> Dataset:
        client = HTTPClient(retries=int(self.retries))
        handler: Optional[Callable] = self.get("handler")

        def send_one(raw) -> HTTPResponseData:
            req = raw if isinstance(raw, HTTPRequestData) \
                else HTTPRequestData.from_dict(raw)
            if handler is not None:
                return handler(client, req)
            return client.send(req)

        reqs = list(ds[self.inputCol])
        workers = max(1, int(self.concurrency))
        timeout = self.get("concurrentTimeout")
        if workers == 1:
            responses = [send_one(r) for r in reqs]
        else:
            with ThreadPoolExecutor(max_workers=workers) as pool:
                futs = [pool.submit(send_one, r) for r in reqs]
                deadline = (time.monotonic() + float(timeout)
                            if timeout else None)
                responses = []
                for f in futs:
                    left = (deadline - time.monotonic()) if deadline else None
                    responses.append(f.result(timeout=left))
        col = np.empty(len(responses), dtype=object)
        col[:] = responses
        return ds.with_column(self.outputCol, col)


class JSONInputParser:
    """Row dict -> HTTPRequestData with a JSON body
    (reference: SimpleHTTPTransformer JSONInputParser)."""

    def __init__(self, url: str, method: str = "POST",
                 headers: Optional[Dict[str, str]] = None):
        self.url = url
        self.method = method
        self.headers = dict(headers or {})
        self.headers.setdefault("Content-Type", "application/json")

    @staticmethod
    def _json_default(o):
        if isinstance(o, np.ndarray):
            return o.tolist()
        if isinstance(o, np.generic):
            return o.item()
        raise TypeError(f"not JSON serializable: {type(o)}")

    def __call__(self, row: Dict[str, Any]) -> HTTPRequestData:
        body = json.dumps(row, default=self._json_default).encode()
        return HTTPRequestData(url=self.url, method=self.method,
                               headers=self.headers, entity=body)


class JSONOutputParser:
    """HTTPResponseData -> parsed JSON (reference: JSONOutputParser)."""

    def __call__(self, resp: HTTPResponseData) -> Any:
        if resp.status_code == 0 or not resp.entity:
            return None
        try:
            return resp.json()
        except (ValueError, UnicodeDecodeError):
            return None

class CustomInputParser:
    """Row dict -> HTTPRequestData via a user function
    (reference: parsers CustomInputParser — udf-driven request building)."""

    def __init__(self, udf):
        self.udf = udf

    def __call__(self, row):
        out = self.udf(row)
        if isinstance(out, HTTPRequestData):
            return out
        raise TypeError("CustomInputParser udf must return HTTPRequestData")


class StringOutputParser:
    """HTTPResponseData -> decoded body string
    (reference: parsers StringOutputParser)."""

    def __call__(self, resp: HTTPResponseData) -> Optional[str]:
        if resp.status_code == 0 or resp.entity is None:
            return None
        return resp.entity.decode("utf-8", errors="replace")


class CustomOutputParser:
    """HTTPResponseData -> anything via a user function
    (reference: parsers CustomOutputParser)."""

    def __init__(self, udf):
        self.udf = udf

    def __call__(self, resp: HTTPResponseData):
        return self.udf(resp)



class SimpleHTTPTransformer(Transformer):
    """JSON-in / JSON-out service call per row
    (reference: SimpleHTTPTransformer.scala:65): selected input columns
    become the JSON body; the JSON response lands in ``outputCol``.
    ``errorCol`` collects status line for failed rows (reference
    ``HasErrorCol`` pattern)."""

    inputCols = ListParam(doc="columns forming the JSON request body")
    outputCol = StringParam(doc="parsed JSON output column", default="output")
    errorCol = StringParam(doc="error column", default="errors")
    url = StringParam(doc="service endpoint")
    method = StringParam(doc="HTTP method", default="POST")
    headers = DictParam(doc="extra headers", default=None)
    concurrency = IntParam(doc="concurrent requests", default=1)
    retries = IntParam(doc="retry count", default=3)
    inputParser = UDFParam(doc="custom row -> HTTPRequestData")
    outputParser = UDFParam(doc="custom HTTPResponseData -> value")

    def _transform(self, ds: Dataset) -> Dataset:
        in_cols = self.inputCols or [c for c in ds.columns]
        parser = self.get("inputParser") or JSONInputParser(
            self.url, self.method, self.get("headers"))
        out_parser = self.get("outputParser") or JSONOutputParser()

        reqs = np.empty(ds.num_rows, dtype=object)
        for i in range(ds.num_rows):
            reqs[i] = parser({c: ds[c][i] for c in in_cols})
        http = HTTPTransformer(
            inputCol="_req", outputCol="_resp",
            concurrency=int(self.concurrency), retries=int(self.retries))
        scored = http.transform(ds.with_column("_req", reqs))
        out = np.empty(ds.num_rows, dtype=object)
        errors = np.empty(ds.num_rows, dtype=object)
        for i, resp in enumerate(scored["_resp"]):
            out[i] = out_parser(resp)
            errors[i] = (None if 200 <= resp.status_code < 300
                         else f"{resp.status_code} {resp.reason}")
        return ds.with_columns({self.outputCol: out, self.errorCol: errors})
