"""HTTP client pipeline stages.

Re-designs the reference's HTTP stack (reference: core/.../io/http/
HTTPTransformer.scala:44-95 — ``concurrency``/``concurrentTimeout``
params over an async Apache HttpClient; HTTPClients.scala:65-189 —
``AdvancedHTTPHandling`` retry/backoff on 429/5xx; HTTPSchema.scala —
request/response row codecs; SimpleHTTPTransformer.scala:65 — JSON
in/out convenience).  Python shape: dataclass request/response rows, a
stdlib-``urllib`` client with a composable retry policy, and a thread
pool for concurrency (requests are IO-bound; the GIL is released in
socket waits, matching the reference's async client semantics).

Failure handling routes through :mod:`synapseml_tpu.resilience`: the
client takes a :class:`~synapseml_tpu.resilience.RetryPolicy`
(exponential backoff + full jitter, ``Retry-After`` honoring, shared
retry budgets) and an optional per-endpoint
:class:`~synapseml_tpu.resilience.CircuitBreaker`; a
:class:`~synapseml_tpu.resilience.Deadline` propagates the caller's
remaining patience through every retry, and the ``http.send`` fault
site lets tests inject 429/503s, resets and slow responses
deterministically.
"""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from concurrent.futures import TimeoutError as FutureTimeoutError
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Union

import numpy as np

from ..core.dataset import Dataset
from ..core.params import (DictParam, FloatParam, IntParam, ListParam,
                           Param, PyObjectParam, StringParam, UDFParam)
from ..core.pipeline import Transformer
from ..resilience import (Deadline, RetryPolicy, get_faults,
                          parse_retry_after)
from ..resilience.rowguard import HasErrorCol
from ..telemetry import get_registry


@dataclass
class HTTPRequestData:
    """Request row (reference: HTTPSchema request codec)."""
    url: str
    method: str = "GET"
    headers: Dict[str, str] = field(default_factory=dict)
    entity: Optional[bytes] = None

    @staticmethod
    def from_dict(d: Dict[str, Any]) -> "HTTPRequestData":
        entity = d.get("entity")
        if isinstance(entity, str):
            entity = entity.encode("utf-8")
        return HTTPRequestData(url=d["url"], method=d.get("method", "GET"),
                               headers=dict(d.get("headers", {})),
                               entity=entity)


@dataclass
class HTTPResponseData:
    """Response row (reference: HTTPSchema response codec)."""
    status_code: int
    reason: str = ""
    headers: Dict[str, str] = field(default_factory=dict)
    entity: bytes = b""

    def json(self) -> Any:
        return json.loads(self.entity.decode("utf-8"))

    def text(self) -> str:
        return self.entity.decode("utf-8", errors="replace")


#: statuses the advanced handler retries (reference: HTTPClients.scala:65)
RETRY_STATUSES = (429, 500, 502, 503, 504)


class HTTPClient:
    """Blocking client with policy-driven retries on 429/5xx
    (reference: AdvancedHTTPHandling, HTTPClients.scala:65-175).

    ``policy`` owns the retry shape (exponential backoff + full jitter by
    default, ``Retry-After`` honored as a floor); ``breaker`` — when the
    circuit is open the client fabricates a 503 carrying the remaining
    cooldown as ``Retry-After`` without touching the network.

    Compatibility: an EXPLICIT ``backoffs_ms`` builds a fixed-ladder
    policy with the identical unjittered timing.  Call sites passing only
    ``retries`` (or nothing) now get the jittered exponential default
    instead of the old hard-coded 100/500/1000 ms ladder — deliberate:
    full jitter decorrelates retry storms and ``Retry-After`` (which the
    old ladder ignored) lets throttling servers set the real pace.
    """

    def __init__(self, retries: int = 3,
                 backoffs_ms: Optional[Sequence[int]] = None,
                 timeout_s: float = 60.0,
                 policy: Optional[RetryPolicy] = None,
                 breaker=None):
        if policy is None:
            policy = (RetryPolicy.from_ladder(backoffs_ms, retries)
                      if backoffs_ms is not None
                      else RetryPolicy(max_retries=retries))
        self.policy = policy
        self.breaker = breaker
        self.timeout_s = timeout_s
        self._m_retries = get_registry().counter(
            "resilience_retries_total", "retries slept through a policy",
            ("site",))

    #: legacy surface (old call sites introspected these)
    @property
    def retries(self) -> int:
        return self.policy.max_retries

    def _attempt(self, req: HTTPRequestData,
                 timeout_s: float) -> HTTPResponseData:
        """One network attempt → response row (status 0 = transport
        error).  The ``http.send`` fault site can fabricate 429/503s,
        raise resets, or delay here — upstream of the real socket."""
        fault = get_faults().http_fault("http.send", url=req.url)
        if fault is not None:
            status, headers = fault
            return HTTPResponseData(status_code=status,
                                    reason="injected fault",
                                    headers=headers)
        r = urllib.request.Request(
            req.url, data=req.entity, method=req.method,
            headers=dict(req.headers))
        with urllib.request.urlopen(r, timeout=timeout_s) as resp:
            return HTTPResponseData(
                status_code=resp.status,
                reason=getattr(resp, "reason", "") or "",
                headers=dict(resp.headers),
                entity=resp.read())

    def send(self, req: HTTPRequestData,
             deadline: Optional[Deadline] = None) -> HTTPResponseData:
        policy = self.policy
        last: Optional[HTTPResponseData] = None
        for attempt in range(policy.max_retries + 1):
            if deadline is not None and deadline.expired:
                return last if last is not None else HTTPResponseData(
                    status_code=504, reason="deadline expired before attempt")
            if self.breaker is not None and not self.breaker.allow():
                ra = self.breaker.retry_after_s()
                return HTTPResponseData(
                    status_code=503, reason="circuit breaker open",
                    headers={"Retry-After": f"{ra:.3f}"})
            timeout = (deadline.limit(self.timeout_s) if deadline is not None
                       else self.timeout_s)
            try:
                last = self._attempt(req, max(timeout, 1e-3))
            except urllib.error.HTTPError as e:
                last = HTTPResponseData(status_code=e.code,
                                        reason=str(e.reason),
                                        headers=dict(e.headers or {}),
                                        entity=e.read() or b"")
            except (urllib.error.URLError, OSError) as e:
                last = HTTPResponseData(status_code=0, reason=str(e))
            if not policy.retryable(last.status_code):
                # success and non-retryable client errors both close the
                # failure streak — the breaker counts outages, not 404s
                if self.breaker is not None:
                    self.breaker.record_success()
                return last
            if self.breaker is not None:
                self.breaker.record_failure()
            if attempt >= policy.max_retries or not policy.acquire_retry():
                return last
            ra = parse_retry_after(last.headers.get("Retry-After")) \
                if policy.honor_retry_after else None
            delay = policy.backoff_s(attempt, ra)
            if deadline is not None:
                remaining = deadline.remaining()
                if remaining <= 0:
                    return last
                delay = min(delay, remaining)
            self._m_retries.inc(1, site="http")
            policy.sleep(delay, site="http.backoff")
        return last if last is not None else HTTPResponseData(
            status_code=0, reason="no attempt made")


class HTTPTransformer(Transformer):
    """Send one HTTP request per row, concurrently
    (reference: HTTPTransformer.scala:95; params ``concurrency`` and
    ``concurrentTimeout`` match :44-60)."""

    inputCol = StringParam(doc="column of request dicts/HTTPRequestData",
                           default="request")
    outputCol = StringParam(doc="column of HTTPResponseData", default="response")
    concurrency = IntParam(doc="concurrent requests per host", default=1)
    concurrentTimeout = FloatParam(doc="seconds to wait for the batch "
                                   "(None = forever)")
    handler = UDFParam(doc="custom (client, request) -> response handler")
    retries = IntParam(doc="retry count for 429/5xx", default=3)
    retryPolicy = PyObjectParam(doc="RetryPolicy overriding `retries` "
                                    "(exp backoff + jitter + Retry-After)")
    breaker = PyObjectParam(doc="CircuitBreaker shared across this stage's "
                                "requests (fail fast while open)")

    def _transform(self, ds: Dataset) -> Dataset:
        client = HTTPClient(retries=int(self.retries),
                            policy=self.get("retryPolicy"),
                            breaker=self.get("breaker"))
        handler: Optional[Callable] = self.get("handler")
        timeout = self.get("concurrentTimeout")
        # ONE deadline bounds the whole batch and propagates into every
        # send: once it expires, in-flight sends stop retrying instead of
        # running out their full backoff schedule on leaked pool threads
        # (custom handlers keep their (client, request) signature and are
        # bounded only by the collection loop below)
        deadline = Deadline(float(timeout)) if timeout else None

        def send_one(raw) -> HTTPResponseData:
            req = raw if isinstance(raw, HTTPRequestData) \
                else HTTPRequestData.from_dict(raw)
            if handler is not None:
                return handler(client, req)
            return client.send(req, deadline=deadline)

        reqs = list(ds[self.inputCol])
        workers = max(1, int(self.concurrency))
        if workers == 1:
            responses = [send_one(r) for r in reqs]
        else:
            # remaining() is clamped at 0, so rows past the budget collect
            # synthetic 504 rows (the old arithmetic handed f.result a
            # NEGATIVE timeout, which raises ValueError and aborted the
            # whole transform)
            pool = ThreadPoolExecutor(max_workers=workers)
            futs = [pool.submit(send_one, r) for r in reqs]
            responses = []
            try:
                for f in futs:
                    left = deadline.remaining() if deadline else None
                    try:
                        responses.append(f.result(timeout=left))
                    except FutureTimeoutError:
                        f.cancel()
                        responses.append(HTTPResponseData(
                            status_code=504,
                            reason="concurrentTimeout exceeded"))
            finally:
                # never-started rows are cancelled; already-running sends
                # finish on their worker threads without blocking the
                # caller (shutdown does not wait)
                for f in futs:
                    f.cancel()
                pool.shutdown(wait=False)
        col = np.empty(len(responses), dtype=object)
        col[:] = responses
        return ds.with_column(self.outputCol, col)


class JSONInputParser:
    """Row dict -> HTTPRequestData with a JSON body
    (reference: SimpleHTTPTransformer JSONInputParser)."""

    def __init__(self, url: str, method: str = "POST",
                 headers: Optional[Dict[str, str]] = None):
        self.url = url
        self.method = method
        self.headers = dict(headers or {})
        self.headers.setdefault("Content-Type", "application/json")

    @staticmethod
    def _json_default(o):
        if isinstance(o, np.ndarray):
            return o.tolist()
        if isinstance(o, np.generic):
            return o.item()
        raise TypeError(f"not JSON serializable: {type(o)}")

    def __call__(self, row: Dict[str, Any]) -> HTTPRequestData:
        body = json.dumps(row, default=self._json_default).encode()
        return HTTPRequestData(url=self.url, method=self.method,
                               headers=self.headers, entity=body)


class JSONOutputParser:
    """HTTPResponseData -> parsed JSON (reference: JSONOutputParser)."""

    def __call__(self, resp: HTTPResponseData) -> Any:
        if resp.status_code == 0 or not resp.entity:
            return None
        try:
            return resp.json()
        except (ValueError, UnicodeDecodeError):
            return None

class CustomInputParser:
    """Row dict -> HTTPRequestData via a user function
    (reference: parsers CustomInputParser — udf-driven request building)."""

    def __init__(self, udf):
        self.udf = udf

    def __call__(self, row):
        out = self.udf(row)
        if isinstance(out, HTTPRequestData):
            return out
        raise TypeError("CustomInputParser udf must return HTTPRequestData")


class StringOutputParser:
    """HTTPResponseData -> decoded body string
    (reference: parsers StringOutputParser)."""

    def __call__(self, resp: HTTPResponseData) -> Optional[str]:
        if resp.status_code == 0 or resp.entity is None:
            return None
        return resp.entity.decode("utf-8", errors="replace")


class CustomOutputParser:
    """HTTPResponseData -> anything via a user function
    (reference: parsers CustomOutputParser)."""

    def __init__(self, udf):
        self.udf = udf

    def __call__(self, resp: HTTPResponseData):
        return self.udf(resp)



class SimpleHTTPTransformer(HasErrorCol, Transformer):
    """JSON-in / JSON-out service call per row
    (reference: SimpleHTTPTransformer.scala:65): selected input columns
    become the JSON body; the JSON response lands in ``outputCol``.
    The shared :class:`HasErrorCol` mixin collects the status line for
    failed rows (``errorCol``, default ``"errors"``) — and under
    ``handleInvalid='skip'/'quarantine'`` those rows leave the output via
    the row guard instead of flowing downstream."""

    inputCols = ListParam(doc="columns forming the JSON request body")
    outputCol = StringParam(doc="parsed JSON output column", default="output")
    url = StringParam(doc="service endpoint")
    method = StringParam(doc="HTTP method", default="POST")
    headers = DictParam(doc="extra headers", default=None)
    concurrency = IntParam(doc="concurrent requests", default=1)
    retries = IntParam(doc="retry count", default=3)
    retryPolicy = PyObjectParam(doc="RetryPolicy overriding `retries`")
    breaker = PyObjectParam(doc="CircuitBreaker for this endpoint")
    inputParser = UDFParam(doc="custom row -> HTTPRequestData")
    outputParser = UDFParam(doc="custom HTTPResponseData -> value")

    def _transform(self, ds: Dataset) -> Dataset:
        in_cols = self.inputCols or [c for c in ds.columns]
        parser = self.get("inputParser") or JSONInputParser(
            self.url, self.method, self.get("headers"))
        out_parser = self.get("outputParser") or JSONOutputParser()

        reqs = np.empty(ds.num_rows, dtype=object)
        for i in range(ds.num_rows):
            reqs[i] = parser({c: ds[c][i] for c in in_cols})
        http = HTTPTransformer(
            inputCol="_req", outputCol="_resp",
            concurrency=int(self.concurrency), retries=int(self.retries),
            retryPolicy=self.get("retryPolicy"), breaker=self.get("breaker"))
        scored = http.transform(ds.with_column("_req", reqs))
        out = np.empty(ds.num_rows, dtype=object)
        errors = np.empty(ds.num_rows, dtype=object)
        for i, resp in enumerate(scored["_resp"]):
            out[i] = out_parser(resp)
            errors[i] = self.response_error(resp)
        return ds.with_columns({self.outputCol: out, self.errorCol: errors})
