"""Port forwarding helpers (reference: io/http/PortForwarding.scala).

The reference uses JSch to open a REVERSE ssh tunnel (remote cluster
port → the driver's local serving port) so cloud notebooks can reach a
serving endpoint behind NAT.  The analogue here drives the system
``ssh`` binary (no JSch; zero extra dependencies) with the same
behavior: identity files, StrictHostKeyChecking disabled, and a retry
walk over a remote port range.  A pure-Python :class:`TcpRelay` covers
the local-forwarding/testing half without any ssh daemon.
"""

from __future__ import annotations

import socket
import subprocess
import threading
import time
from dataclasses import dataclass
from typing import List, Optional, Tuple

__all__ = ["ForwardSession", "TcpRelay", "build_ssh_command",
           "forward_port_to_remote"]


def build_ssh_command(username: str, ssh_host: str, ssh_port: int,
                      bind_address: str, remote_port: int,
                      local_host: str, local_port: int,
                      key_file: Optional[str] = None,
                      timeout_s: float = 10.0) -> List[str]:
    """The ``ssh -N -R`` command line for one reverse-forward attempt —
    split out so tests can pin the exact invocation without an sshd."""
    cmd = ["ssh", "-N", "-p", str(ssh_port),
           "-o", "StrictHostKeyChecking=no",
           "-o", "ExitOnForwardFailure=yes",
           "-o", f"ConnectTimeout={max(1, int(timeout_s))}",
           "-R", f"{bind_address}:{remote_port}:{local_host}:{local_port}"]
    if key_file:
        cmd += ["-i", key_file]
    cmd.append(f"{username}@{ssh_host}")
    return cmd


@dataclass
class ForwardSession:
    """A live reverse tunnel: the ssh child process + the remote port it
    bound.  ``close()`` tears the tunnel down."""
    process: subprocess.Popen
    remote_port: int

    def close(self) -> None:
        if self.process.poll() is None:
            self.process.terminate()
            try:
                self.process.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.process.kill()

    def __enter__(self) -> "ForwardSession":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def forward_port_to_remote(username: str, ssh_host: str,
                           remote_port_start: int, local_port: int,
                           ssh_port: int = 22, bind_address: str = "*",
                           local_host: str = "0.0.0.0",
                           key_file: Optional[str] = None,
                           max_retries: int = 3,
                           timeout_s: float = 10.0,
                           settle_s: float = 1.0) -> ForwardSession:
    """Open a reverse ssh tunnel ``remote:port → local_host:local_port``,
    walking ``remote_port_start + attempt`` like the reference until one
    binds (ExitOnForwardFailure makes a taken port exit immediately).

    An attempt counts as bound only after surviving the WHOLE
    ``timeout_s + settle_s`` window — a still-connecting ssh must not be
    mistaken for a live tunnel (the forward failure only surfaces after
    the connect completes).  Raises RuntimeError when no port binds."""
    last_err = ""
    for attempt in range(max_retries + 1):
        port = remote_port_start + attempt
        cmd = build_ssh_command(username, ssh_host, ssh_port, bind_address,
                                port, local_host, local_port, key_file,
                                timeout_s)
        try:
            proc = subprocess.Popen(cmd, stdout=subprocess.DEVNULL,
                                    stderr=subprocess.PIPE)
        except FileNotFoundError:
            raise RuntimeError(
                "port forwarding needs the system 'ssh' binary on PATH "
                "(none found); for a local relay without ssh use TcpRelay")
        # -N never exits on success; an exit inside the window means the
        # connect or the forward bind failed
        deadline = time.monotonic() + timeout_s + settle_s
        while proc.poll() is None and time.monotonic() < deadline:
            time.sleep(min(0.1, max(settle_s, 0.01)))
        if proc.poll() is None:
            # long-lived ssh with an undrained stderr PIPE blocks once
            # the OS buffer fills — drain it forever on a daemon thread,
            # discarding each chunk (no list that grows an element per
            # 64 KB for the tunnel's lifetime)
            def _drain(s=proc.stderr):
                for _ in iter(lambda: s.read(65536), b""):
                    pass
            threading.Thread(target=_drain, daemon=True).start()
            return ForwardSession(proc, port)
        last_err = (proc.stderr.read() or b"").decode(errors="replace")
    raise RuntimeError(
        f"could not bind a remote port in [{remote_port_start}, "
        f"{remote_port_start + max_retries}]: {last_err.strip()}")


class TcpRelay:
    """Pure-Python local port relay: listen on (host, port) and pipe
    every connection to ``target`` — the in-process stand-in for a
    forwarded port (and the testable half of the tunnel story: an ssh
    -L/-R hop is exactly this relay over a secure channel)."""

    def __init__(self, target: Tuple[str, int], host: str = "127.0.0.1",
                 port: int = 0):
        self.target = target
        self._srv = socket.create_server((host, port))
        self._srv.settimeout(0.2)
        self.address = self._srv.getsockname()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._open: List[socket.socket] = []     # live sockets, pruned
        self._accept_thread = threading.Thread(target=self._accept,
                                               daemon=True)
        self._accept_thread.start()

    @property
    def port(self) -> int:
        return self.address[1]

    def _accept(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            # connect + pipe on a per-connection thread so one slow
            # upstream cannot head-of-line-block new accepts
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        try:
            upstream = socket.create_connection(self.target, timeout=10)
        except OSError:
            conn.close()
            return
        with self._lock:
            if self._stop.is_set():
                conn.close()
                upstream.close()
                return
            self._open += [conn, upstream]
        t = threading.Thread(target=self._pipe, args=(upstream, conn),
                             daemon=True)
        t.start()
        self._pipe(conn, upstream)
        t.join()
        with self._lock:
            for s in (conn, upstream):
                if s in self._open:
                    self._open.remove(s)
                try:
                    s.close()
                except OSError:
                    pass

    @staticmethod
    def _pipe(src: socket.socket, dst: socket.socket) -> None:
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        finally:
            for s, how in ((dst, socket.SHUT_WR), (src, socket.SHUT_RD)):
                try:
                    s.shutdown(how)
                except OSError:
                    pass

    def close(self) -> None:
        """Stop accepting AND drop every live connection — a torn-down
        tunnel must revoke access, exactly like an ssh forward
        teardown."""
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass
        with self._lock:
            live, self._open = self._open, []
        for s in live:
            # shutdown BEFORE close: a bare close of a socket another
            # thread is blocked in recv() on neither wakes that thread
            # nor reliably sends the FIN
            for fn in (lambda: s.shutdown(socket.SHUT_RDWR), s.close):
                try:
                    fn()
                except OSError:
                    pass

    def __enter__(self) -> "TcpRelay":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
