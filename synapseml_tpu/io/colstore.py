"""Chunked, bounded-memory columnar source over SMLC column stores.

The reference never materializes a partition's rows: micro-batches stream
into a shared native dataset (reference: lightgbm/.../StreamingPartitionTask.
scala:101-422 — LGBM_DatasetCreateFromSampledColumn + per-batch
PushRowsWithMetadata), with per-partition row counts computed up front
(ClusterUtil.getNumRowsPerPartition, core/utils/ClusterUtil.scala:46).
This is the TPU-native equivalent: the on-disk SMLC column store (written
by the native loader, ``native/loader.cpp``) is memory-mapped and read in
row CHUNKS, so host memory stays O(chunk) while the consumer (GBDT
streaming train, DL minibatch iterators) assembles device-side state
incrementally.  ``shard(i, n)`` restricts a source to host ``i``'s
contiguous row range — the partition→host placement table for multi-host
input pipelines.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

_HEADER_BYTES = 4 + 4 + 8 + 8       # magic, version, rows, cols


def _open_colstore(path: str) -> Tuple[np.memmap, int, int]:
    with open(path, "rb") as f:
        if f.read(4) != b"SMLC":
            raise IOError(f"{path}: not an SMLC column store")
        np.frombuffer(f.read(4), np.uint32)          # version
        rows = int(np.frombuffer(f.read(8), np.int64)[0])
        cols = int(np.frombuffer(f.read(8), np.int64)[0])
    mm = np.memmap(path, np.float32, mode="r", offset=_HEADER_BYTES,
                   shape=(cols, rows))
    return mm, rows, cols


class ChunkedColumnSource:
    """Row-chunk iteration over an SMLC file with optional label/weight
    columns split out of the feature matrix.

    ``feature_cols``/``label_col``/``weight_col`` are column indices into
    the stored matrix; by default every column is a feature.  The memmap
    is the only handle on the data — a chunk read touches each feature
    column's contiguous slice, so resident memory is O(chunk_rows · F).
    """

    def __init__(self, path: str,
                 feature_cols: Optional[Sequence[int]] = None,
                 label_col: Optional[int] = None,
                 weight_col: Optional[int] = None,
                 chunk_rows: int = 65_536,
                 row_range: Optional[Tuple[int, int]] = None):
        self.path = path
        self._mm, total_rows, total_cols = _open_colstore(path)
        if feature_cols is None:
            excluded = {c for c in (label_col, weight_col) if c is not None}
            feature_cols = [c for c in range(total_cols) if c not in excluded]
        self.feature_cols = list(feature_cols)
        self.label_col = label_col
        self.weight_col = weight_col
        self.chunk_rows = int(chunk_rows)
        lo, hi = row_range if row_range is not None else (0, total_rows)
        if not 0 <= lo <= hi <= total_rows:
            raise ValueError(f"row_range {row_range} outside [0, {total_rows}]")
        self._lo, self._hi = lo, hi

    # -- shape -------------------------------------------------------------
    @property
    def num_rows(self) -> int:
        return self._hi - self._lo

    @property
    def num_features(self) -> int:
        return len(self.feature_cols)

    # -- placement (partition→host map analogue) ---------------------------
    def shard(self, index: int, count: int) -> "ChunkedColumnSource":
        """Host ``index``'s contiguous row range out of ``count`` hosts
        (deterministic balanced split: first ``rows % count`` shards carry
        one extra row — the same rule every host computes locally, no
        rendezvous required)."""
        if not 0 <= index < count:
            raise ValueError(f"shard index {index} outside [0, {count})")
        n = self.num_rows
        base, extra = divmod(n, count)
        lo = self._lo + index * base + min(index, extra)
        hi = lo + base + (1 if index < extra else 0)
        return ChunkedColumnSource(
            self.path, self.feature_cols, self.label_col, self.weight_col,
            self.chunk_rows, row_range=(lo, hi))

    # -- reads -------------------------------------------------------------
    def _rows(self, lo: int, hi: int) -> np.ndarray:
        out = np.empty((hi - lo, len(self.feature_cols)), np.float32)
        for j, c in enumerate(self.feature_cols):
            out[:, j] = self._mm[c, lo:hi]
        return out

    def _read_chunk(self, lo: int, hi: int) -> Tuple[np.ndarray,
                                                     Optional[np.ndarray],
                                                     Optional[np.ndarray]]:
        y = (np.asarray(self._mm[self.label_col, lo:hi], np.float32)
             if self.label_col is not None else None)
        w = (np.asarray(self._mm[self.weight_col, lo:hi], np.float32)
             if self.weight_col is not None else None)
        return self._rows(lo, hi), y, w

    def iter_chunks(self) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray],
                                            Optional[np.ndarray]]]:
        """Yield (X_chunk, y_chunk | None, w_chunk | None) row chunks."""
        for lo in range(self._lo, self._hi, self.chunk_rows):
            yield self._read_chunk(lo, min(lo + self.chunk_rows, self._hi))

    def read_labels(self) -> Optional[np.ndarray]:
        if self.label_col is None:
            return None
        return np.asarray(self._mm[self.label_col, self._lo:self._hi],
                          np.float32)

    def read_weights(self) -> Optional[np.ndarray]:
        if self.weight_col is None:
            return None
        return np.asarray(self._mm[self.weight_col, self._lo:self._hi],
                          np.float32)

    def sample_rows(self, k: int, seed: int = 0) -> np.ndarray:
        """Uniform row sample (same draw as fit_bin_mapper's in-memory
        sampling, so streamed and in-memory training bin identically)."""
        n = self.num_rows
        if n <= k:
            return self._rows(self._lo, self._hi)
        rng = np.random.default_rng(seed)
        idx = np.sort(rng.choice(n, k, replace=False)) + self._lo
        out = np.empty((k, len(self.feature_cols)), np.float32)
        for j, c in enumerate(self.feature_cols):
            out[:, j] = self._mm[c][idx]
        return out

    def iter_batches(self, batch_size: int,
                     rng: Optional[np.random.Generator] = None,
                     ) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray],
                                         Optional[np.ndarray]]]:
        """Fixed-size minibatches for DL training loops.  With ``rng``,
        chunk ORDER and intra-chunk rows are shuffled (bounded-memory
        approximate shuffle: exact within a chunk, chunk-granular across
        the file — the streaming-shuffle tradeoff every out-of-core loader
        makes); the tail partial batch is dropped.
        """
        starts = list(range(self._lo, self._hi, self.chunk_rows))
        if rng is not None:
            rng.shuffle(starts)
        leftovers: Optional[Tuple[np.ndarray, ...]] = None
        for lo in starts:
            X, y, w = self._read_chunk(lo, min(lo + self.chunk_rows,
                                               self._hi))
            if rng is not None:
                perm = rng.permutation(len(X))
                X = X[perm]
                y = y[perm] if y is not None else None
                w = w[perm] if w is not None else None
            if leftovers is not None:
                X = np.concatenate([leftovers[0], X])
                y = (np.concatenate([leftovers[1], y])
                     if y is not None else None)
                w = (np.concatenate([leftovers[2], w])
                     if w is not None else None)
            full = (len(X) // batch_size) * batch_size
            for s in range(0, full, batch_size):
                yield (X[s:s + batch_size],
                       y[s:s + batch_size] if y is not None else None,
                       w[s:s + batch_size] if w is not None else None)
            leftovers = (X[full:], y[full:] if y is not None else None,
                         w[full:] if w is not None else None)


def write_matrix(path: str, matrix: np.ndarray) -> None:
    """Write a float32 matrix as an SMLC column store (native fast path
    when the toolchain is available)."""
    from ..native import write_colstore
    write_colstore(path, np.asarray(matrix, np.float32))


def csv_to_colstore(csv_path: str, out_path: str,
                    delim: str = ",") -> Tuple[int, list]:
    """Parse a CSV with the native multithreaded loader and persist it as
    an SMLC column store; returns (rows, column_names)."""
    from ..native import read_csv_matrix, write_colstore
    mat, names = read_csv_matrix(csv_path, delim)
    write_colstore(out_path, mat)
    return mat.shape[0], names
