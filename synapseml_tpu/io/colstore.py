"""Chunked, bounded-memory columnar source over SMLC column stores.

The reference never materializes a partition's rows: micro-batches stream
into a shared native dataset (reference: lightgbm/.../StreamingPartitionTask.
scala:101-422 — LGBM_DatasetCreateFromSampledColumn + per-batch
PushRowsWithMetadata), with per-partition row counts computed up front
(ClusterUtil.getNumRowsPerPartition, core/utils/ClusterUtil.scala:46).
This is the TPU-native equivalent: the on-disk SMLC column store (written
by the native loader, ``native/loader.cpp``) is memory-mapped and read in
row CHUNKS, so host memory stays O(chunk) while the consumer (GBDT
streaming train, DL minibatch iterators) assembles device-side state
incrementally.  ``shard(i, n)`` restricts a source to host ``i``'s
contiguous row range — the partition→host placement table for multi-host
input pipelines.
"""

from __future__ import annotations

import os
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

_HEADER_BYTES = 4 + 4 + 8 + 8       # magic, version, rows, cols

#: SMLC payload dtype by header version: v1 is the native loader's f32;
#: v2 stores bf16 (uint16 bit pattern) — half the ingest traffic of the
#: GBDT streaming path for one bf16 rounding of the feature values
#: (binning is quantile-based, so split quality is AUC-pinned, not
#: bit-pinned; see docs/api/perf.md "GBDT fused bf16 ingest")
_VERSION_F32 = 1
_VERSION_BF16 = 2


def f32_to_bf16_bits(arr: np.ndarray) -> np.ndarray:
    """float32 → bfloat16 bit patterns (uint16), round-to-nearest-even —
    the same rounding jax's ``astype(bfloat16)`` applies, implemented on
    the raw bits so the storage layer needs no ml_dtypes import."""
    bits = np.ascontiguousarray(arr, np.float32).view(np.uint32)
    # RNE: add 0x7FFF + lsb-of-kept-half, then truncate
    rounded = bits + 0x7FFF + ((bits >> 16) & 1)
    out = (rounded >> 16).astype(np.uint16)
    # NaN must stay NaN (the rounding above can carry into the exponent
    # and turn a NaN payload into inf): force the quiet-NaN pattern
    nan = np.isnan(arr)
    if nan.any():
        out[nan] = np.uint16(0x7FC0)
    return out


def bf16_bits_to_f32(bits: np.ndarray) -> np.ndarray:
    """bfloat16 bit patterns (uint16) → exact float32 values."""
    return (np.asarray(bits, np.uint16).astype(np.uint32) << 16) \
        .view(np.float32)


def _balanced_range(lo: int, hi: int, index: int,
                    count: int) -> Tuple[int, int]:
    """Host ``index``'s contiguous slice of [lo, hi) under the balanced
    placement rule (first ``n % count`` shards carry one extra row —
    ClusterUtil.getNumRowsPerPartition): ONE definition shared by the
    dense and sparse sources so nested sharding stays consistent."""
    if not 0 <= index < count:
        raise ValueError(f"shard index {index} outside [0, {count})")
    base, extra = divmod(hi - lo, count)
    s = lo + index * base + min(index, extra)
    return s, s + base + (1 if index < extra else 0)


def _open_colstore(path: str) -> Tuple[np.memmap, int, int, bool]:
    with open(path, "rb") as f:
        if f.read(4) != b"SMLC":
            raise IOError(f"{path}: not an SMLC column store")
        version = int(np.frombuffer(f.read(4), np.uint32)[0])
        rows = int(np.frombuffer(f.read(8), np.int64)[0])
        cols = int(np.frombuffer(f.read(8), np.int64)[0])
    if version not in (_VERSION_F32, _VERSION_BF16):
        raise IOError(f"{path}: unknown SMLC version {version}")
    bf16 = version == _VERSION_BF16
    mm = np.memmap(path, np.uint16 if bf16 else np.float32, mode="r",
                   offset=_HEADER_BYTES, shape=(cols, rows))
    return mm, rows, cols, bf16


class ChunkedColumnSource:
    """Row-chunk iteration over an SMLC file with optional label/weight
    columns split out of the feature matrix.

    ``feature_cols``/``label_col``/``weight_col`` are column indices into
    the stored matrix; by default every column is a feature.  The memmap
    is the only handle on the data — a chunk read touches each feature
    column's contiguous slice, so resident memory is O(chunk_rows · F).
    """

    def __init__(self, path: str,
                 feature_cols: Optional[Sequence[int]] = None,
                 label_col: Optional[int] = None,
                 weight_col: Optional[int] = None,
                 chunk_rows: int = 65_536,
                 row_range: Optional[Tuple[int, int]] = None):
        self.path = path
        self._mm, total_rows, total_cols, self._bf16 = _open_colstore(path)
        if feature_cols is None:
            excluded = {c for c in (label_col, weight_col) if c is not None}
            feature_cols = [c for c in range(total_cols) if c not in excluded]
        self.feature_cols = list(feature_cols)
        self.label_col = label_col
        self.weight_col = weight_col
        self.chunk_rows = int(chunk_rows)
        lo, hi = row_range if row_range is not None else (0, total_rows)
        if not 0 <= lo <= hi <= total_rows:
            raise ValueError(f"row_range {row_range} outside [0, {total_rows}]")
        self._lo, self._hi = lo, hi

    # -- shape -------------------------------------------------------------
    @property
    def num_rows(self) -> int:
        return self._hi - self._lo

    @property
    def num_features(self) -> int:
        return len(self.feature_cols)

    # -- placement (partition→host map analogue) ---------------------------
    def shard(self, index: int, count: int) -> "ChunkedColumnSource":
        """Host ``index``'s contiguous row range out of ``count`` hosts
        (deterministic balanced split: first ``rows % count`` shards carry
        one extra row — the same rule every host computes locally, no
        rendezvous required)."""
        lo, hi = _balanced_range(self._lo, self._hi, index, count)
        return ChunkedColumnSource(
            self.path, self.feature_cols, self.label_col, self.weight_col,
            self.chunk_rows, row_range=(lo, hi))

    # -- reads -------------------------------------------------------------
    def _col_slice(self, c: int, lo: int, hi: int) -> np.ndarray:
        """One column's [lo, hi) slice as f32 (exact bf16 upcast on v2
        stores — NEVER ``astype`` the raw uint16 bit patterns)."""
        raw = self._mm[c, lo:hi]
        return bf16_bits_to_f32(raw) if self._bf16 \
            else np.asarray(raw, np.float32)

    def _rows(self, lo: int, hi: int) -> np.ndarray:
        out = np.empty((hi - lo, len(self.feature_cols)), np.float32)
        for j, c in enumerate(self.feature_cols):
            out[:, j] = self._col_slice(c, lo, hi)
        return out

    def _read_chunk(self, lo: int, hi: int) -> Tuple[np.ndarray,
                                                     Optional[np.ndarray],
                                                     Optional[np.ndarray]]:
        y = (self._col_slice(self.label_col, lo, hi)
             if self.label_col is not None else None)
        w = (self._col_slice(self.weight_col, lo, hi)
             if self.weight_col is not None else None)
        return self._rows(lo, hi), y, w

    def iter_chunks(self) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray],
                                            Optional[np.ndarray]]]:
        """Yield (X_chunk, y_chunk | None, w_chunk | None) row chunks."""
        for lo in range(self._lo, self._hi, self.chunk_rows):
            yield self._read_chunk(lo, min(lo + self.chunk_rows, self._hi))

    def read_labels(self) -> Optional[np.ndarray]:
        if self.label_col is None:
            return None
        return self._col_slice(self.label_col, self._lo, self._hi)

    def read_weights(self) -> Optional[np.ndarray]:
        if self.weight_col is None:
            return None
        return self._col_slice(self.weight_col, self._lo, self._hi)

    def sample_rows(self, k: int, seed: int = 0) -> np.ndarray:
        """Uniform row sample (same draw as fit_bin_mapper's in-memory
        sampling, so streamed and in-memory training bin identically)."""
        n = self.num_rows
        if n <= k:
            return self._rows(self._lo, self._hi)
        rng = np.random.default_rng(seed)
        idx = np.sort(rng.choice(n, k, replace=False)) + self._lo
        out = np.empty((k, len(self.feature_cols)), np.float32)
        for j, c in enumerate(self.feature_cols):
            raw = self._mm[c][idx]
            out[:, j] = bf16_bits_to_f32(raw) if self._bf16 \
                else raw
        return out

    def iter_batches(self, batch_size: int,
                     rng: Optional[np.random.Generator] = None,
                     ) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray],
                                         Optional[np.ndarray]]]:
        """Fixed-size minibatches for DL training loops.  With ``rng``,
        chunk ORDER and intra-chunk rows are shuffled (bounded-memory
        approximate shuffle: exact within a chunk, chunk-granular across
        the file — the streaming-shuffle tradeoff every out-of-core loader
        makes); the tail partial batch is dropped.
        """
        starts = list(range(self._lo, self._hi, self.chunk_rows))
        if rng is not None:
            rng.shuffle(starts)
        leftovers: Optional[Tuple[np.ndarray, ...]] = None
        for lo in starts:
            X, y, w = self._read_chunk(lo, min(lo + self.chunk_rows,
                                               self._hi))
            if rng is not None:
                perm = rng.permutation(len(X))
                X = X[perm]
                y = y[perm] if y is not None else None
                w = w[perm] if w is not None else None
            if leftovers is not None:
                X = np.concatenate([leftovers[0], X])
                y = (np.concatenate([leftovers[1], y])
                     if y is not None else None)
                w = (np.concatenate([leftovers[2], w])
                     if w is not None else None)
            full = (len(X) // batch_size) * batch_size
            for s in range(0, full, batch_size):
                yield (X[s:s + batch_size],
                       y[s:s + batch_size] if y is not None else None,
                       w[s:s + batch_size] if w is not None else None)
            leftovers = (X[full:], y[full:] if y is not None else None,
                         w[full:] if w is not None else None)


def write_matrix(path: str, matrix: np.ndarray,
                 dtype: str = "f32") -> None:
    """Write a matrix as an SMLC column store.

    ``dtype="f32"`` is the native loader's v1 format; ``dtype="bf16"``
    writes the v2 bf16 colstore — half the bytes on disk AND half the
    ingest traffic of every later streamed read (the GBDT histogram
    byte-diet's storage half: values round once to bf16, reads upcast
    exactly to f32, bin boundaries move by at most one rounding ulp)."""
    if dtype == "f32":
        from ..native import write_colstore
        write_colstore(path, np.asarray(matrix, np.float32))
        return
    if dtype != "bf16":
        raise ValueError(f"dtype={dtype!r}: expected 'f32' or 'bf16'")
    matrix = np.ascontiguousarray(matrix, np.float32)
    rows, cols = matrix.shape
    with open(path, "wb") as f:
        f.write(b"SMLC")
        f.write(np.uint32(_VERSION_BF16).tobytes())
        f.write(np.int64(rows).tobytes())
        f.write(np.int64(cols).tobytes())
        # column-major like the native writer: one column = one
        # contiguous run, which is what chunk reads slice
        f.write(np.ascontiguousarray(
            f32_to_bf16_bits(matrix).T).tobytes())


def csv_to_colstore(csv_path: str, out_path: str,
                    delim: str = ",") -> Tuple[int, list]:
    """Parse a CSV with the native multithreaded loader and persist it as
    an SMLC column store; returns (rows, column_names)."""
    from ..native import read_csv_matrix, write_colstore
    mat, names = read_csv_matrix(csv_path, delim)
    write_colstore(out_path, mat)
    return mat.shape[0], names


# --------------------------------------------------------------------------
# sparse (CSR) out-of-core source
# --------------------------------------------------------------------------

_SPARSE_HEADER = 4 + 4 + 8 + 8 + 8 + 1 + 1   # magic, ver, rows, cols, nnz,
                                             # has_label, has_weight


def write_csr(path: str, indptr: np.ndarray, indices: np.ndarray,
              data: np.ndarray, num_cols: int,
              labels: Optional[np.ndarray] = None,
              weights: Optional[np.ndarray] = None) -> None:
    """Write a CSR matrix as an SMLS sparse store.

    Layout: header | indptr int64 (rows+1) | indices int32 (nnz) |
    data f32 (nnz) | labels f32 (rows)? | weights f32 (rows)?.  Row-major
    CSR keeps any row RANGE contiguous in indices/data, which is what
    makes ``shard``/chunk reads O(chunk nnz).
    """
    indptr = np.asarray(indptr, np.int64)
    indices = np.asarray(indices, np.int32)
    data = np.asarray(data, np.float32)
    rows = len(indptr) - 1
    if rows < 0:
        raise ValueError("indptr must have at least one entry")
    if len(indices) != len(data) or int(indptr[-1]) != len(data):
        raise ValueError(
            f"inconsistent CSR: len(indices)={len(indices)}, "
            f"len(data)={len(data)}, indptr[-1]={int(indptr[-1])}")
    if int(indptr[0]) != 0 or np.any(np.diff(indptr) < 0):
        raise ValueError("indptr must start at 0 and be non-decreasing")
    if len(indices) and (indices.min() < 0 or indices.max() >= num_cols):
        raise ValueError("column index out of range")
    for name, arr in (("labels", labels), ("weights", weights)):
        if arr is not None and len(arr) != rows:
            raise ValueError(f"{name} has {len(arr)} entries for "
                             f"{rows} rows")
    with open(path, "wb") as f:
        f.write(b"SMLS")
        f.write(np.uint32(1).tobytes())
        f.write(np.int64(rows).tobytes())
        f.write(np.int64(num_cols).tobytes())
        f.write(np.int64(len(data)).tobytes())
        f.write(np.uint8(0 if labels is None else 1).tobytes())
        f.write(np.uint8(0 if weights is None else 1).tobytes())
        f.write(indptr.tobytes())
        f.write(indices.tobytes())
        f.write(data.tobytes())
        if labels is not None:
            f.write(np.asarray(labels, np.float32).tobytes())
        if weights is not None:
            f.write(np.asarray(weights, np.float32).tobytes())


class SparseChunkedSource:
    """CSR micro-batch source with the same protocol as
    :class:`ChunkedColumnSource` (``num_rows``/``num_features``/
    ``iter_chunks``/``sample_rows``/``read_labels``/``read_weights``/
    ``shard``), so GBDT streaming train consumes it unchanged.

    The reference streams sparse micro-batches into the shared native
    dataset (reference: StreamingPartitionTask.scala:264
    ``pushMicroBatches`` sparse path over LGBM_DatasetPushRowsByCSR...).
    Here each chunk densifies ONLY its own rows (O(chunk_rows · F) host,
    memset + nnz scatter) before binning and EFB bundling — the FULL
    matrix never exists densely on the host, which is the point for
    one-hot matrices whose dense form is hundreds of times their nnz.
    """

    def __init__(self, path: str, chunk_rows: int = 65_536,
                 _range: Optional[Tuple[int, int]] = None):
        self.path = path
        self.chunk_rows = int(chunk_rows)
        with open(path, "rb") as f:
            if f.read(4) != b"SMLS":
                raise IOError(f"{path}: not an SMLS sparse store")
            np.frombuffer(f.read(4), np.uint32)
            self._rows_total = int(np.frombuffer(f.read(8), np.int64)[0])
            self._cols = int(np.frombuffer(f.read(8), np.int64)[0])
            self._nnz = int(np.frombuffer(f.read(8), np.int64)[0])
            self._has_label = bool(np.frombuffer(f.read(1), np.uint8)[0])
            self._has_weight = bool(np.frombuffer(f.read(1), np.uint8)[0])
        off = _SPARSE_HEADER
        self._indptr = np.memmap(path, np.int64, "r", offset=off,
                                 shape=(self._rows_total + 1,))
        off += (self._rows_total + 1) * 8
        self._indices = np.memmap(path, np.int32, "r", offset=off,
                                  shape=(self._nnz,))
        off += self._nnz * 4
        self._data = np.memmap(path, np.float32, "r", offset=off,
                               shape=(self._nnz,))
        off += self._nnz * 4
        self._labels = None
        if self._has_label:
            self._labels = np.memmap(path, np.float32, "r", offset=off,
                                     shape=(self._rows_total,))
            off += self._rows_total * 4
        self._weights = None
        if self._has_weight:
            self._weights = np.memmap(path, np.float32, "r", offset=off,
                                      shape=(self._rows_total,))
        self._lo, self._hi = _range or (0, self._rows_total)

    @property
    def num_rows(self) -> int:
        return self._hi - self._lo

    @property
    def num_features(self) -> int:
        return self._cols

    def shard(self, index: int, count: int) -> "SparseChunkedSource":
        """Contiguous row-range restriction for host ``index`` of
        ``count`` — nests: sharding a shard subdivides ITS range."""
        lo, hi = _balanced_range(self._lo, self._hi, index, count)
        return SparseChunkedSource(self.path, self.chunk_rows,
                                   _range=(lo, hi))

    def _dense_rows(self, row_idx: np.ndarray) -> np.ndarray:
        """Densify an arbitrary row set: memset + one scatter of its nnz."""
        out = np.zeros((len(row_idx), self._cols), np.float32)
        starts = self._indptr[row_idx]
        ends = self._indptr[row_idx + 1]
        for i, (s, e) in enumerate(zip(starts, ends)):
            out[i, self._indices[s:e]] = self._data[s:e]
        return out

    def _dense_range(self, lo: int, hi: int) -> np.ndarray:
        """Densify a contiguous row range with ONE vectorized scatter over
        the range's nnz slice (no per-row python loop)."""
        out = np.zeros((hi - lo, self._cols), np.float32)
        s, e = int(self._indptr[lo]), int(self._indptr[hi])
        if e > s:
            counts = np.diff(self._indptr[lo:hi + 1]).astype(np.int64)
            rows = np.repeat(np.arange(hi - lo), counts)
            out[rows, self._indices[s:e]] = self._data[s:e]
        return out

    def iter_chunks(self) -> Iterator[Tuple[np.ndarray, Optional[np.ndarray],
                                            Optional[np.ndarray]]]:
        for lo in range(self._lo, self._hi, self.chunk_rows):
            hi = min(lo + self.chunk_rows, self._hi)
            y = (np.asarray(self._labels[lo:hi], np.float32)
                 if self._labels is not None else None)
            w = (np.asarray(self._weights[lo:hi], np.float32)
                 if self._weights is not None else None)
            yield self._dense_range(lo, hi), y, w

    def read_labels(self) -> Optional[np.ndarray]:
        if self._labels is None:
            return None
        return np.asarray(self._labels[self._lo:self._hi], np.float32)

    def read_weights(self) -> Optional[np.ndarray]:
        if self._weights is None:
            return None
        return np.asarray(self._weights[self._lo:self._hi], np.float32)

    def sample_rows(self, k: int, seed: int = 0) -> np.ndarray:
        n = self.num_rows
        if n <= k:
            return self._dense_range(self._lo, self._hi)
        rng = np.random.default_rng(seed)
        idx = np.sort(rng.choice(n, k, replace=False)) + self._lo
        return self._dense_rows(idx)


def dense_to_csr(matrix: np.ndarray):
    """(indptr, indices, data) of a dense matrix — test/convert helper."""
    matrix = np.asarray(matrix, np.float32)
    mask = matrix != 0.0
    counts = mask.sum(axis=1)
    indptr = np.zeros(len(matrix) + 1, np.int64)
    np.cumsum(counts, out=indptr[1:])
    rows, cols = np.nonzero(mask)
    return indptr, cols.astype(np.int32), matrix[rows, cols]
