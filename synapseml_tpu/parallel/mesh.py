"""Device-mesh construction and sharding helpers.

The one communication substrate for the whole framework (replacing the
reference's three backends — LightGBM driver-socket rendezvous + native ring
NetworkManager.scala:55-205, VW spanning tree VowpalWabbitClusterUtil.scala:16-40,
and Horovod dl/utils.py:31-46).  Axis conventions:

- ``data``    — batch/row sharding (DP); every trainer uses it
- ``model``   — tensor-parallel weight sharding (TP)
- ``seq``     — sequence/context parallelism for long-context attention
- ``expert``  — expert parallelism (MoE)
- ``pipe``    — pipeline stages

Meshes are built so ``data`` varies slowest across hosts (DCN-friendly) and
``model``/``seq`` ride ICI within a host, per the standard TPU scaling
recipe.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SEQ_AXIS = "seq"
EXPERT_AXIS = "expert"
PIPE_AXIS = "pipe"


def make_mesh(axis_sizes: Optional[Dict[str, int]] = None,
              devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """Build a Mesh from named axis sizes. ``-1`` for at most one axis means
    "use all remaining devices". Default: pure data-parallel over all devices.
    """
    devs = list(devices) if devices is not None else jax.devices()
    n = len(devs)
    if not axis_sizes:
        axis_sizes = {DATA_AXIS: n}
    names = list(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if sizes.count(-1) > 1:
        raise ValueError("at most one axis may be -1")
    if -1 in sizes:
        fixed = math.prod(s for s in sizes if s != -1)
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes {fixed}")
        sizes[sizes.index(-1)] = n // fixed
    total = math.prod(sizes)
    if total > n:
        raise ValueError(f"mesh {dict(zip(names, sizes))} needs {total} devices, have {n}")
    grid = np.array(devs[:total]).reshape(sizes)
    return Mesh(grid, axis_names=tuple(names))


def data_parallel_mesh(num_devices: Optional[int] = None) -> Mesh:
    devs = jax.devices()
    if num_devices is not None:
        devs = devs[:num_devices]
    return make_mesh({DATA_AXIS: len(devs)}, devs)


def dp_tp_mesh(tp: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """(data, model) mesh with model innermost so TP rides ICI."""
    return make_mesh({DATA_AXIS: -1, MODEL_AXIS: tp}, devices)


def dp_sp_tp_mesh(sp: int, tp: int,
                  devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """(data, seq, model) mesh for long-context training."""
    return make_mesh({DATA_AXIS: -1, SEQ_AXIS: sp, MODEL_AXIS: tp}, devices)


def dp_ep_mesh(ep: int, devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """(data, expert) mesh: MoE dispatch all_to_alls ride the expert axis."""
    return make_mesh({DATA_AXIS: -1, EXPERT_AXIS: ep}, devices)


def batch_sharding(mesh: Mesh, ndim: int = 2, axis: str = DATA_AXIS) -> NamedSharding:
    """Shard dim 0 along the data axis, replicate the rest."""
    return NamedSharding(mesh, P(axis, *([None] * (ndim - 1))))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(mesh: Mesh, x, axis: str = DATA_AXIS):
    """Device-put a host array batch-sharded over ``axis`` (pads rows to a
    multiple of the axis size — TPUs want static, divisible shapes)."""
    x = np.asarray(x)
    size = mesh.shape[axis]
    n = x.shape[0]
    rem = n % size
    if rem:
        pad = size - rem
        x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)], axis=0)
    return jax.device_put(x, batch_sharding(mesh, x.ndim, axis)), n


def pad_to_multiple(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def local_mesh_devices(mesh: Mesh) -> List[jax.Device]:
    pid = jax.process_index()
    return [d for d in mesh.devices.flat if d.process_index == pid]
