"""Cluster/device topology discovery — the ``ClusterUtil`` analogue.

The reference discovers Spark executors, tasks-per-executor and driver host
to size its training topology (reference: core/utils/ClusterUtil.scala:22-141,
getNumTasksPerExecutor/getNumRowsPerPartition/getDriverHost/getExecutors).
On TPU the topology is the JAX process/device mesh: hosts are TPU-VM
workers, "tasks" are chips, and placement is mesh coordinates instead of
executor ids.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence

import jax


@dataclasses.dataclass(frozen=True)
class HostInfo:
    """One host (TPU-VM worker) — the 'executor' analogue."""
    process_index: int
    device_ids: List[int]

    @property
    def num_devices(self) -> int:
        return len(self.device_ids)


@dataclasses.dataclass(frozen=True)
class Topology:
    """Snapshot of the cluster topology."""
    num_processes: int
    process_index: int
    num_devices: int
    num_local_devices: int
    platform: str
    hosts: List[HostInfo]

    def devices_per_host(self) -> int:
        return self.num_devices // max(1, self.num_processes)


def get_topology(devices: Optional[Sequence[jax.Device]] = None) -> Topology:
    """Discover hosts/chips (ClusterUtil.getExecutors analogue)."""
    devs = list(devices) if devices is not None else jax.devices()
    by_proc: Dict[int, List[int]] = {}
    for d in devs:
        by_proc.setdefault(d.process_index, []).append(d.id)
    hosts = [HostInfo(p, sorted(ids)) for p, ids in sorted(by_proc.items())]
    return Topology(
        num_processes=jax.process_count(),
        process_index=jax.process_index(),
        num_devices=len(devs),
        num_local_devices=jax.local_device_count(),
        platform=devs[0].platform if devs else jax.default_backend(),
        hosts=hosts,
    )


def get_num_rows_per_partition(ds, num_partitions: Optional[int] = None) -> List[int]:
    """Per-partition row counts (ClusterUtil.getNumRowsPerPartition,
    ClusterUtil.scala:46 — there a Spark job; here arithmetic)."""
    if num_partitions is not None:
        ds = ds.repartition(num_partitions)
    return [b - a for a, b in ds.partition_bounds()]
