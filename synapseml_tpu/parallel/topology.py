"""Cluster/device topology discovery — the ``ClusterUtil`` analogue.

The reference discovers Spark executors, tasks-per-executor and driver host
to size its training topology (reference: core/utils/ClusterUtil.scala:22-141,
getNumTasksPerExecutor/getNumRowsPerPartition/getDriverHost/getExecutors).
On TPU the topology is the JAX process/device mesh: hosts are TPU-VM
workers, "tasks" are chips, and placement is mesh coordinates instead of
executor ids.

Beyond the host/chip counts, the snapshot now carries the ICI/DCN
*structure* the collective planner (:mod:`synapseml_tpu.parallel.planner`)
routes by: per-device mesh ``coords`` and ``slice_index`` where the
backend exposes them, ``None`` where it does not (the CPU container, older
jaxlibs) — no fabricated topology, the same honesty contract as the
roofline spec tables (``telemetry.roofline``: unknown backend ⇒ claim
nothing).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

import jax


@dataclasses.dataclass(frozen=True)
class HostInfo:
    """One host (TPU-VM worker) — the 'executor' analogue."""
    process_index: int
    device_ids: List[int]

    @property
    def num_devices(self) -> int:
        return len(self.device_ids)


@dataclasses.dataclass(frozen=True)
class Topology:
    """Snapshot of the cluster topology.

    ``coords`` / ``slice_indices`` are per-device, in ``jax.devices()``
    order, and ``None``-valued on backends that do not expose them
    (CPU/host platform) — consumers must treat ``None`` as "link
    structure unknown", never substitute a guess.
    """
    num_processes: int
    process_index: int
    num_devices: int
    num_local_devices: int
    platform: str
    hosts: List[HostInfo]
    #: per-device chip mesh coordinates (e.g. ``(x, y, z)`` on TPU), or
    #: ``None`` per device where the backend has no coords
    coords: List[Optional[Tuple[int, ...]]] = dataclasses.field(
        default_factory=list)
    #: per-device pod-slice index (DCN boundary marker on multi-slice
    #: deployments), or ``None`` per device where unexposed
    slice_indices: List[Optional[int]] = dataclasses.field(
        default_factory=list)

    def devices_per_host(self) -> int:
        return self.num_devices // max(1, self.num_processes)

    @property
    def coords_known(self) -> bool:
        """True only when EVERY device reported mesh coordinates."""
        return bool(self.coords) and all(c is not None for c in self.coords)

    def num_slices(self) -> Optional[int]:
        """Distinct pod slices, or ``None`` when the backend does not
        expose slice indices (no fabricated DCN structure)."""
        if not self.slice_indices or any(s is None
                                         for s in self.slice_indices):
            return None
        return len(set(self.slice_indices))


def _device_coords(d) -> Optional[Tuple[int, ...]]:
    """A device's chip coords as a tuple, ``None`` when unexposed (CPU
    devices have no ``coords``; some backends raise on access)."""
    try:
        coords = getattr(d, "coords", None)
        if coords is None:
            return None
        return tuple(int(c) for c in coords)
    except Exception:
        return None


def _device_slice_index(d) -> Optional[int]:
    try:
        s = getattr(d, "slice_index", None)
        return int(s) if s is not None else None
    except Exception:
        return None


def get_topology(devices: Optional[Sequence[jax.Device]] = None) -> Topology:
    """Discover hosts/chips (ClusterUtil.getExecutors analogue)."""
    devs = list(devices) if devices is not None else jax.devices()
    by_proc: Dict[int, List[int]] = {}
    for d in devs:
        by_proc.setdefault(d.process_index, []).append(d.id)
    hosts = [HostInfo(p, sorted(ids)) for p, ids in sorted(by_proc.items())]
    return Topology(
        num_processes=jax.process_count(),
        process_index=jax.process_index(),
        num_devices=len(devs),
        num_local_devices=jax.local_device_count(),
        platform=devs[0].platform if devs else jax.default_backend(),
        hosts=hosts,
        coords=[_device_coords(d) for d in devs],
        slice_indices=[_device_slice_index(d) for d in devs],
    )


def get_num_rows_per_partition(ds, num_partitions: Optional[int] = None) -> List[int]:
    """Per-partition row counts (ClusterUtil.getNumRowsPerPartition,
    ClusterUtil.scala:46 — there a Spark job; here arithmetic)."""
    if num_partitions is not None:
        ds = ds.repartition(num_partitions)
    return [b - a for a, b in ds.partition_bounds()]
