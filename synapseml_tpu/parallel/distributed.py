"""Multi-host rendezvous — the NetworkManager replacement.

The reference rendezvouses workers through a driver ServerSocket handshake
(status:host:port:partition:executor messages, machine-list broadcast —
reference: NetworkManager.scala:55-80,123-169,294-440).  On TPU the
rendezvous is ``jax.distributed.initialize`` against a coordinator address;
after it, every process sees the global device set and collectives need no
further setup.  Retry semantics mirror the reference's exponential backoff
around ``LGBM_NetworkInit`` (NetworkManager.scala:182-205).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Optional

import jax

logger = logging.getLogger("synapseml_tpu")


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Rendezvous parameters (the machine-list analogue)."""
    coordinator_address: Optional[str] = None   # "host:port"
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    init_timeout_s: float = 300.0
    #: force a backend ("cpu" for the simulated multi-host slice; None keeps
    #: the ambient platform — on a TPU pod the runtime picks the TPU backend)
    platform: Optional[str] = None
    #: virtual devices per process (CPU backend only; a TPU host's chip
    #: count is fixed by hardware)
    local_device_count: Optional[int] = None


_initialized = False


def _configure_backend(cfg: ClusterConfig) -> None:
    """Apply platform/device-count config BEFORE the JAX backend exists.

    The CPU backend only joins cross-process collectives when its gloo
    implementation is selected at client-creation time, so this must run
    before anything touches ``jax.devices()``.  The image's sitecustomize
    force-registers the TPU tunnel platform, hence the explicit
    ``jax_platforms`` override rather than the JAX_PLATFORMS env var.
    """
    if cfg.platform is not None:
        jax.config.update("jax_platforms", cfg.platform)
    if cfg.platform == "cpu":
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        if cfg.local_device_count:
            try:
                jax.config.update("jax_num_cpu_devices",
                                  cfg.local_device_count)
            except AttributeError:
                # jax 0.4.x has no jax_num_cpu_devices; the XLA flag does
                # the same job as long as it lands before backend creation
                # (we are before it — that is this function's contract).
                # An inherited count (the driver's test harness sets one)
                # must be REPLACED, not kept — this process's share of the
                # mesh is cfg.local_device_count, nothing else.
                import os
                import re
                flags = re.sub(
                    r"--xla_force_host_platform_device_count=\d+", "",
                    os.environ.get("XLA_FLAGS", ""))
                os.environ["XLA_FLAGS"] = (
                    f"{flags} --xla_force_host_platform_device_count="
                    f"{cfg.local_device_count}").strip()


def initialize_cluster(config: Optional[ClusterConfig] = None,
                       max_retries: int = 5,
                       base_delay_s: float = 1.0) -> None:
    """Join the cluster; idempotent; no-op when single-process (the local[*]
    analogue) or when running under a managed TPU runtime that already
    initialized. Retries with exponential backoff like the reference's
    NetworkInit (NetworkManager.scala:182-205)."""
    global _initialized
    if _initialized:
        return
    cfg = config or ClusterConfig()
    if cfg.coordinator_address is None and cfg.num_processes in (None, 1):
        _initialized = True   # single host: nothing to rendezvous
        return
    _configure_backend(cfg)
    delay = base_delay_s
    last: Optional[BaseException] = None
    for attempt in range(max_retries):
        try:
            jax.distributed.initialize(
                coordinator_address=cfg.coordinator_address,
                num_processes=cfg.num_processes,
                process_id=cfg.process_id,
                initialization_timeout=int(cfg.init_timeout_s),
            )
            _initialized = True
            logger.info("joined cluster: process %d/%d",
                        jax.process_index(), jax.process_count())
            return
        except Exception as e:
            last = e
            logger.warning("rendezvous attempt %d failed: %s", attempt, e)
            # jax.distributed.initialize sets global state before connecting;
            # clear it or every retry raises "should only be called once"
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            time.sleep(delay)
            delay *= 2
    raise RuntimeError(f"cluster rendezvous failed after {max_retries} attempts") from last


def shutdown_cluster() -> None:
    global _initialized
    if _initialized and jax.process_count() > 1:
        jax.distributed.shutdown()
    _initialized = False
