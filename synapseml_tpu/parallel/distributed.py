"""Multi-host rendezvous — the NetworkManager replacement.

The reference rendezvouses workers through a driver ServerSocket handshake
(status:host:port:partition:executor messages, machine-list broadcast —
reference: NetworkManager.scala:55-80,123-169,294-440).  On TPU the
rendezvous is ``jax.distributed.initialize`` against a coordinator address;
after it, every process sees the global device set and collectives need no
further setup.  Retry semantics mirror the reference's exponential backoff
around ``LGBM_NetworkInit`` (NetworkManager.scala:182-205).
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Optional

import jax

logger = logging.getLogger("synapseml_tpu")


@dataclasses.dataclass(frozen=True)
class ClusterConfig:
    """Rendezvous parameters (the machine-list analogue)."""
    coordinator_address: Optional[str] = None   # "host:port"
    num_processes: Optional[int] = None
    process_id: Optional[int] = None
    init_timeout_s: float = 300.0


_initialized = False


def initialize_cluster(config: Optional[ClusterConfig] = None,
                       max_retries: int = 5,
                       base_delay_s: float = 1.0) -> None:
    """Join the cluster; idempotent; no-op when single-process (the local[*]
    analogue) or when running under a managed TPU runtime that already
    initialized. Retries with exponential backoff like the reference's
    NetworkInit (NetworkManager.scala:182-205)."""
    global _initialized
    if _initialized:
        return
    cfg = config or ClusterConfig()
    if cfg.coordinator_address is None and cfg.num_processes in (None, 1):
        _initialized = True   # single host: nothing to rendezvous
        return
    delay = base_delay_s
    last: Optional[BaseException] = None
    for attempt in range(max_retries):
        try:
            jax.distributed.initialize(
                coordinator_address=cfg.coordinator_address,
                num_processes=cfg.num_processes,
                process_id=cfg.process_id,
                initialization_timeout=int(cfg.init_timeout_s),
            )
            _initialized = True
            logger.info("joined cluster: process %d/%d",
                        jax.process_index(), jax.process_count())
            return
        except Exception as e:
            last = e
            logger.warning("rendezvous attempt %d failed: %s", attempt, e)
            # jax.distributed.initialize sets global state before connecting;
            # clear it or every retry raises "should only be called once"
            try:
                jax.distributed.shutdown()
            except Exception:
                pass
            time.sleep(delay)
            delay *= 2
    raise RuntimeError(f"cluster rendezvous failed after {max_retries} attempts") from last


def shutdown_cluster() -> None:
    global _initialized
    if _initialized and jax.process_count() > 1:
        jax.distributed.shutdown()
    _initialized = False
