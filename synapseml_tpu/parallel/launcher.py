"""Local multi-process launcher — the driver half of the rendezvous.

The reference's driver opens a ServerSocket, waits for every worker task to
phone home with ``status:host:port:partition:executor``, then broadcasts the
machine list so the native ring can form (reference:
lightgbm/src/main/scala/com/microsoft/azure/synapse/ml/lightgbm/
NetworkManager.scala:294-440).  The TPU analogue needs no machine list —
``jax.distributed.initialize`` against a coordinator address gives every
process the global device table — so the driver's remaining job is exactly
what this module does: pick the coordinator endpoint, start one OS process
per host, watch them, and collect their results.

This is how multi-host tests and the distributed-serving harness execute for
real on one machine: N processes x M virtual CPU devices per process form a
genuine cross-process mesh (gloo collectives), the same code path a multi-host
TPU pod takes (PJRT collectives over ICI/DCN).

Supervision: every worker emits ``SMLMP_HB`` heartbeat lines on the same
pipe as ``RESULT_MARKER``; the driver's watch loop feeds them to a
:class:`~synapseml_tpu.parallel.supervisor.HeartbeatMonitor` so a dead OR
hung rank is declared failed in O(heartbeat interval), not O(global
timeout).  A failed attempt tears the whole gang down (SIGTERM → grace →
SIGKILL) and raises :class:`WorkerFailure` with a structured per-rank
cause map (``timeout`` / ``exit <code>`` / ``no result`` / ``hang at step
N`` / ``no heartbeat`` / advisory ``straggler``) plus every rank's
ring-buffered log tail.  Pass a :class:`~synapseml_tpu.resilience.
RetryPolicy` and the whole launch relaunches elastically (fresh
coordinator port, fresh processes) via :class:`~synapseml_tpu.parallel.
supervisor.GangSupervisor` — with ``checkpoint_dir`` threaded through,
checkpointing trainers resume from the last complete step instead of
step 0, since a partial cluster cannot be patched rank-by-rank once
``jax.distributed`` has formed.
"""

from __future__ import annotations

import collections
import json
import os
import signal
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ..resilience import RetryPolicy, get_faults
from ..telemetry import get_registry
from ..telemetry.gangplane import (OBS_DIR_ENV, TM_INTERVAL_ENV,
                                   parse_telemetry)
from .heartbeat import HB_INTERVAL_ENV, parse_heartbeat

#: marker the worker prints in front of its JSON result line
RESULT_MARKER = "SMLMP_RESULT:"

#: ring-buffer depth of retained log lines per rank (a chatty rank must
#: not grow the driver without bound; failures surface only the tail)
DEFAULT_TAIL_LINES = 400
#: per-line retention cap — one enormous line must not defeat the ring
_MAX_LINE_CHARS = 4096

#: env var carrying the checkpoint directory to every worker
CKPT_DIR_ENV = "SMLTPU_CKPT_DIR"
#: env var carrying the worker-side rendezvous watchdog deadline
RENDEZVOUS_TIMEOUT_ENV = "SMLTPU_RENDEZVOUS_TIMEOUT_S"


class ReservedPort:
    """A free TCP port that STAYS bound until :meth:`release`.

    The old ``find_free_port`` close-then-rebind dance had a race: between
    the driver closing its probe socket and rank 0's ``jax.distributed``
    service binding the port, any other process could grab it.  Holding
    the socket (``SO_REUSEADDR`` + ``SO_REUSEPORT`` where available)
    keeps the kernel from handing the port to anyone else for the whole
    spawn window; the driver releases it only after every worker process
    exists, leaving just the unavoidable sliver between release and the
    coordinator's own bind (rank 0 still has its multi-second interpreter
    + jax import ahead of it at that point)."""

    def __init__(self, host: str = "127.0.0.1"):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        if hasattr(socket, "SO_REUSEPORT"):
            try:
                self._sock.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEPORT, 1)
            except OSError:
                pass
        self._sock.bind((host, 0))
        self.host = host
        self.port = self._sock.getsockname()[1]

    @property
    def held(self) -> bool:
        return self._sock is not None

    def release(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def __enter__(self) -> "ReservedPort":
        return self

    def __exit__(self, *exc) -> None:
        self.release()


def find_free_port() -> int:
    """Ask the kernel for a free TCP port.  Kept for compatibility;
    prefer :class:`ReservedPort`, which holds the bind open instead of
    close-then-rebind (the race this function cannot avoid)."""
    with ReservedPort() as rp:
        return rp.port


def _rank_causes(returncodes: Dict[int, Optional[int]],
                 timed_out: Sequence[int],
                 missing_result: Sequence[int],
                 extra: Optional[Dict[int, str]] = None) -> Dict[int, str]:
    """Structured per-rank failure causes (only failed ranks appear).
    ``extra`` (heartbeat verdicts / straggler advisories) wins over the
    generic exit-code causes — 'hang at step 3' beats 'exit -9'."""
    causes: Dict[int, str] = dict(extra or {})
    for r in timed_out:
        causes.setdefault(r, "timeout")
    for r, rc in returncodes.items():
        if r not in causes and rc not in (0, None):
            causes[r] = f"exit {rc}"
    for r in missing_result:
        causes.setdefault(r, "no result")
    return causes


class WorkerFailure(RuntimeError):
    """A worker exited non-zero, timed out, hung, or produced no result.

    ``causes`` maps failed rank → cause string; ``logs`` maps every rank
    → its captured output tail (ring-buffered)."""

    def __init__(self, msg: str, logs: Dict[int, str],
                 causes: Optional[Dict[int, str]] = None):
        self.causes = dict(causes or {})
        if self.causes:
            msg += "\nper-rank causes: " + ", ".join(
                f"rank {r}: {c}" for r, c in sorted(self.causes.items()))
        super().__init__(msg + "\n" + "\n".join(
            f"--- rank {r} log (tail) ---\n{t[-4000:]}" for r, t in logs.items()))
        self.logs = logs


class GangInterrupted(RuntimeError):
    """The DRIVER tore a healthy gang down on purpose (an elastic
    ``GangSupervisor.resize()`` request between checkpoints) — not a
    worker failure: it burns no retry, writes no post-mortem, and the
    relaunch resumes from the last durable checkpoint exactly like a
    recovered crash."""


class _RankReader(threading.Thread):
    """Per-rank pipe drain: parses heartbeat/result markers on the fly
    and retains only a bounded tail of raw lines.

    A rank that fills the OS pipe buffer mid-collective would deadlock
    the whole cluster if nobody read its pipe, and on failure we want
    EVERY rank's tail, not just the first one waited on — but a chatty
    rank streaming millions of lines must not grow the driver without
    limit, hence the ring buffer."""

    def __init__(self, rank: int, proc: subprocess.Popen,
                 monitor=None, plane=None,
                 tail_lines: int = DEFAULT_TAIL_LINES):
        super().__init__(name=f"rank-reader-{rank}", daemon=True)
        self.rank = rank
        self.proc = proc
        self.monitor = monitor
        self.plane = plane
        self.tail: "collections.deque[str]" = collections.deque(
            maxlen=max(1, tail_lines))
        self.result_line: Optional[str] = None
        self.dropped = 0

    def run(self) -> None:
        stream = self.proc.stdout
        if stream is None:
            return
        for line in stream:
            line = line.rstrip("\n")
            hb = parse_heartbeat(line)
            if hb is not None:
                if self.monitor is not None:
                    self.monitor.observe(self.rank, step=hb.get("step"),
                                         ts=hb.get("ts"))
                continue                       # beats never enter the tail
            tm = parse_telemetry(line)
            if tm is not None:
                # telemetry batches feed the gang plane and never enter
                # the tail (one batch can be tens of KB of metrics/spans)
                if self.plane is not None:
                    self.plane.ingest(self.rank, tm)
                continue
            if line.startswith(RESULT_MARKER):
                # the result must survive any amount of later chatter,
                # so it is captured out-of-band from the ring
                self.result_line = line
            if len(self.tail) == self.tail.maxlen:
                self.dropped += 1
            self.tail.append(line[:_MAX_LINE_CHARS])

    def text(self) -> str:
        head = (f"... ({self.dropped} earlier lines dropped)\n"
                if self.dropped else "")
        return head + "\n".join(self.tail)


def _teardown_gang(procs: List[subprocess.Popen],
                   term_grace_s: float = 2.0) -> None:
    """SIGTERM every live rank, give the gang ``term_grace_s`` to unwind
    (flush logs, run finally blocks), then SIGKILL whatever remains — a
    rank blocked inside a native collective never sees the SIGTERM, which
    is exactly why the KILL follows."""
    faults = get_faults()
    alive = [p for p in procs if p.poll() is None]
    for p in alive:
        try:
            p.send_signal(signal.SIGTERM)
            faults.note("gang.teardown", pid=p.pid, sig="SIGTERM")
        except OSError:
            pass
    deadline = time.monotonic() + max(0.0, term_grace_s)
    while alive and time.monotonic() < deadline:
        alive = [p for p in alive if p.poll() is None]
        if alive:
            time.sleep(0.02)
    for p in alive:
        if p.poll() is None:
            try:
                p.kill()
                faults.note("gang.teardown", pid=p.pid, sig="SIGKILL")
            except OSError:
                pass


def _launch_once(task: str, n_processes: int, devices_per_process: int,
                 task_args: Any, timeout_s: float,
                 env_extra: Optional[Dict[str, str]], *,
                 monitor=None, heartbeat_interval_s: float = 0.0,
                 checkpoint_dir: Optional[str] = None,
                 term_grace_s: float = 2.0,
                 tail_lines: int = DEFAULT_TAIL_LINES,
                 plane=None, tm_interval_s: float = 0.0,
                 obs_dir: Optional[str] = None,
                 interrupt: Optional[threading.Event] = None) -> List[Any]:
    """One rendezvous attempt: spawn, watch (heartbeats + exits + global
    deadline), collect (or tear down and raise WorkerFailure).

    ``interrupt`` (set by another thread) tears the healthy gang down at
    the next watch poll and raises :class:`GangInterrupted` — the
    supervisor's elastic-resize boundary."""
    # fault site: an armed rule here stands in for a failed rendezvous
    # without burning real subprocess spawns in tests
    if get_faults().check("launcher.attempt") is not None:
        raise WorkerFailure("injected rendezvous failure", {},
                            causes={r: "injected" for r in range(n_processes)})
    reserved = ReservedPort()
    coordinator = f"{reserved.host}:{reserved.port}"
    procs: List[subprocess.Popen] = []
    readers: List[_RankReader] = []
    args_json = json.dumps(task_args)
    pythonpath = os.pathsep.join(
        [p for p in sys.path if p and os.path.isdir(p)])
    reg = get_registry()
    g_hb_age = reg.gauge("rank_heartbeat_age_seconds",
                         "seconds since each rank's last heartbeat "
                         "(live gang attempts only)", ("rank",))
    try:
        try:
            for rank in range(n_processes):
                env = dict(os.environ)
                env.update(env_extra or {})
                env.update({
                    "SMLTPU_COORDINATOR": coordinator,
                    "SMLTPU_NUM_PROCESSES": str(n_processes),
                    "SMLTPU_PROCESS_ID": str(rank),
                    "SMLTPU_PLATFORM": "cpu",
                    "SMLTPU_LOCAL_DEVICES": str(devices_per_process),
                    "SMLTPU_TASK": task,
                    "SMLTPU_TASK_ARGS": args_json,
                    "PYTHONPATH": pythonpath,
                })
                if heartbeat_interval_s > 0:
                    env[HB_INTERVAL_ENV] = str(heartbeat_interval_s)
                    env.setdefault(RENDEZVOUS_TIMEOUT_ENV, str(timeout_s))
                if checkpoint_dir:
                    env[CKPT_DIR_ENV] = str(checkpoint_dir)
                if tm_interval_s > 0:
                    env[TM_INTERVAL_ENV] = str(tm_interval_s)
                if obs_dir:
                    env[OBS_DIR_ENV] = str(obs_dir)
                p = subprocess.Popen(
                    [sys.executable, "-m", "synapseml_tpu.parallel.worker"],
                    stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                    text=True, env=env)
                procs.append(p)
                r = _RankReader(rank, p, monitor=monitor, plane=plane,
                                tail_lines=tail_lines)
                r.start()
                readers.append(r)
        finally:
            # the port stays reserved for the whole spawn window; only
            # once every worker exists (each still facing its multi-second
            # jax import before rank 0 binds) is it handed over
            reserved.release()

        deadline = time.monotonic() + timeout_s
        poll_s = (min(0.25, heartbeat_interval_s / 4.0)
                  if heartbeat_interval_s > 0 else 0.05)
        timed_out: List[int] = []
        hb_causes: Dict[int, str] = {}
        interrupted = False
        while True:
            if interrupt is not None and interrupt.is_set():
                # driver-requested teardown (elastic resize): not a
                # failure — tear down NOW so the relaunch at the new
                # size starts from the last durable checkpoint
                interrupted = True
                break
            running = []
            failed_exit = False
            for rank, p in enumerate(procs):
                rc = p.poll()
                if rc is None:
                    running.append(rank)
                elif rc == 0:
                    if monitor is not None:
                        monitor.mark_done(rank)
                else:
                    failed_exit = True
            if failed_exit:
                # one dead rank wedges every peer inside its blocked
                # collectives: fail the gang NOW, not at the timeout
                break
            if not running:
                break
            if monitor is not None:
                for rank, age in monitor.ages().items():
                    g_hb_age.set(age, rank=str(rank))
                hb_causes = monitor.verdicts()
                if hb_causes:
                    break
            if time.monotonic() >= deadline:
                timed_out = running
                break
            time.sleep(poll_s)

        # snapshot exits BEFORE tearing down: a rank WE kill must not be
        # blamed with its teardown signal in the cause map
        returncodes = {rank: p.poll() for rank, p in enumerate(procs)}
        if interrupted or timed_out or hb_causes or any(
                rc not in (0, None) for rc in returncodes.values()):
            _teardown_gang(procs, term_grace_s=term_grace_s)
        for r in readers:
            r.join(timeout=10.0)
        logs = {r.rank: r.text() for r in readers}

        if interrupted:
            raise GangInterrupted(
                "gang torn down by driver request (elastic resize)")

        stragglers = monitor.stragglers() if monitor is not None else {}

        def _with_steps(causes: Dict[int, str]) -> Dict[int, str]:
            # every verdict carries the rank's last-known step, so the
            # relaunch decision (and the human) knows how much work died
            if monitor is None:
                return causes
            steps = monitor.last_steps()
            return {r: (c if "step" in c or steps.get(r) is None
                        else f"{c} (last step {steps[r]})")
                    for r, c in causes.items()}

        if hb_causes:
            raise WorkerFailure(
                f"ranks {sorted(hb_causes)} declared failed by the "
                "heartbeat detector", logs,
                causes=_with_steps(_rank_causes(
                    returncodes, [], [],
                    extra={**stragglers, **hb_causes})))
        if timed_out:
            raise WorkerFailure(
                f"ranks {timed_out} timed out after {timeout_s:.0f}s", logs,
                causes=_with_steps(_rank_causes(returncodes, timed_out, [],
                                                extra=stragglers)))
        # rc None = still running at snapshot time (torn down by us, not
        # a failure of its own)
        failed = [r for r, rc in returncodes.items() if rc not in (0, None)]
        if failed:
            raise WorkerFailure(
                f"ranks {failed} exited non-zero", logs,
                causes=_with_steps(_rank_causes(returncodes, [], [],
                                                extra=stragglers)))
        results: List[Any] = []
        missing: List[int] = []
        for r in readers:
            if r.result_line is None:
                missing.append(r.rank)
                results.append(None)
            else:
                results.append(json.loads(
                    r.result_line[len(RESULT_MARKER):]))
        if missing:
            raise WorkerFailure(
                f"ranks {missing} produced no result", logs,
                causes=_rank_causes(returncodes, [], missing))
        return results
    finally:
        reserved.release()
        _teardown_gang(procs, term_grace_s=0.0)
        if monitor is not None:
            # REMOVE the per-rank series rather than zeroing them: after
            # an elastic shrink the departed ranks must not linger on
            # /metrics as phantom age-0 rows (the replica-probe
            # _Metric.remove() fix, applied to the heartbeat gauge)
            for rank in range(n_processes):
                g_hb_age.remove(rank=str(rank))


def run_on_local_cluster(task: str,
                         n_processes: int = 2,
                         devices_per_process: int = 2,
                         task_args: Any = None,
                         timeout_s: float = 300.0,
                         env_extra: Optional[Dict[str, str]] = None,
                         retry_policy: Optional[RetryPolicy] = None,
                         heartbeat_interval_s: float = 1.0,
                         hang_intervals: float = 3.0,
                         startup_grace_s: float = 120.0,
                         straggler_lag_steps: Optional[int] = None,
                         checkpoint_dir: Optional[Any] = None,
                         term_grace_s: float = 2.0,
                         tail_lines: int = DEFAULT_TAIL_LINES,
                         observability_dir: Optional[str] = None,
                         tm_interval_s: Optional[float] = None,
                         min_ranks: Optional[int] = None,
                         shrink_after: int = 2,
                         resize_cooldown_s: float = 0.0,
                         max_resizes: int = 8,
                         capacity_fn=None,
                         ) -> List[Any]:
    """Run ``module:function`` on a real N-process JAX cluster; return the
    per-rank results (rank order).

    Each rank is an OS process that rendezvouses through
    ``initialize_cluster`` (parallel/distributed.py) against a localhost
    coordinator, sees the global ``n_processes * devices_per_process``-device
    table, and runs ``function(task_args)`` with collectives live across
    process boundaries.  The function must return something JSON-serializable.

    Supervision is on by default (``heartbeat_interval_s=1.0``): every
    rank emits heartbeats, and a dead/hung rank fails the attempt within
    ``hang_intervals`` beats.  ``retry_policy``: on :class:`WorkerFailure`
    the WHOLE launch retries (fresh port, fresh processes) under the
    policy's backoff — a formed ``jax.distributed`` cluster cannot
    re-admit a replacement rank, so whole-gang restart is the only sound
    retry unit.  ``checkpoint_dir`` (a path or ``CheckpointManager``)
    reaches every worker as ``SMLTPU_CKPT_DIR`` so checkpointing trainers
    resume instead of restarting.  The raised failure (when retries
    exhaust) is the LAST attempt's, with per-rank causes.

    Observability (see :mod:`synapseml_tpu.telemetry.gangplane`):
    ``observability_dir`` turns the gang-wide plane on — workers export
    metric/span/flight batches over the ``SMLMP_TM:`` wire (mirrored
    into the coordinator's ``/metrics`` with a ``rank`` label), dump
    their flight rings there on teardown, and a dead attempt leaves a
    schema-checked ``postmortem.json`` bundle plus a stitched multi-lane
    ``gang_trace.json``.  ``tm_interval_s`` overrides the export cadence
    (defaults to the heartbeat interval).

    Elastic resize (see :class:`~synapseml_tpu.parallel.supervisor.
    GangSupervisor`): ``min_ranks < n_processes`` lets the job SHRINK to
    the largest healthy size ≥ ``min_ranks`` when the same rank keeps
    failing ``shrink_after`` consecutive attempts (degraded mode, under
    ``resize_cooldown_s`` + ``max_resizes``), and ``capacity_fn``
    (→ placeable rank count) grows a degraded gang back toward
    ``n_processes`` at the next relaunch boundary.  Keep a reference to
    a :class:`GangSupervisor` instead if you need mid-run
    ``resize(n)`` requests.
    """
    from .supervisor import GangSupervisor
    return GangSupervisor(
        task, n_processes=n_processes,
        devices_per_process=devices_per_process, task_args=task_args,
        timeout_s=timeout_s, env_extra=env_extra, retry_policy=retry_policy,
        heartbeat_interval_s=heartbeat_interval_s,
        hang_intervals=hang_intervals, startup_grace_s=startup_grace_s,
        straggler_lag_steps=straggler_lag_steps,
        checkpoint_dir=checkpoint_dir, term_grace_s=term_grace_s,
        tail_lines=tail_lines, observability_dir=observability_dir,
        tm_interval_s=tm_interval_s, min_ranks=min_ranks,
        shrink_after=shrink_after, resize_cooldown_s=resize_cooldown_s,
        max_resizes=max_resizes, capacity_fn=capacity_fn).run()
