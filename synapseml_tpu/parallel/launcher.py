"""Local multi-process launcher — the driver half of the rendezvous.

The reference's driver opens a ServerSocket, waits for every worker task to
phone home with ``status:host:port:partition:executor``, then broadcasts the
machine list so the native ring can form (reference:
lightgbm/src/main/scala/com/microsoft/azure/synapse/ml/lightgbm/
NetworkManager.scala:294-440).  The TPU analogue needs no machine list —
``jax.distributed.initialize`` against a coordinator address gives every
process the global device table — so the driver's remaining job is exactly
what this module does: pick the coordinator endpoint, start one OS process
per host, watch them, and collect their results.

This is how multi-host tests and the distributed-serving harness execute for
real on one machine: N processes x M virtual CPU devices per process form a
genuine cross-process mesh (gloo collectives), the same code path a multi-host
TPU pod takes (PJRT collectives over ICI/DCN).

Failure handling: a failed attempt raises :class:`WorkerFailure` carrying a
structured per-rank cause map (``timeout`` / ``exit <code>`` / ``no
result``) with every rank's log tail — the reference's NetworkManager
retries its rendezvous socket (NetworkManager.scala:294-340) and so does
this driver: pass a :class:`~synapseml_tpu.resilience.RetryPolicy` and the
whole launch (fresh coordinator port, fresh processes) retries under its
backoff, since a partial cluster cannot be patched rank-by-rank once
``jax.distributed`` has formed.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

from ..resilience import RetryPolicy, get_faults
from ..telemetry import get_registry

#: marker the worker prints in front of its JSON result line
RESULT_MARKER = "SMLMP_RESULT:"


def find_free_port() -> int:
    """Ask the kernel for a free TCP port (the driver's ServerSocket bind,
    NetworkManager.scala:299 — there the socket is kept open; here the
    coordinator re-binds it immediately so a race is possible but unlikely)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _rank_causes(returncodes: Dict[int, Optional[int]],
                 timed_out: Sequence[int],
                 missing_result: Sequence[int]) -> Dict[int, str]:
    """Structured per-rank failure causes (only failed ranks appear)."""
    causes: Dict[int, str] = {}
    for r in timed_out:
        causes[r] = "timeout"
    for r, rc in returncodes.items():
        if r not in causes and rc not in (0, None):
            causes[r] = f"exit {rc}"
    for r in missing_result:
        causes.setdefault(r, "no result")
    return causes


class WorkerFailure(RuntimeError):
    """A worker exited non-zero, timed out, or produced no result.

    ``causes`` maps failed rank → cause string; ``logs`` maps every rank
    → its captured output."""

    def __init__(self, msg: str, logs: Dict[int, str],
                 causes: Optional[Dict[int, str]] = None):
        self.causes = dict(causes or {})
        if self.causes:
            msg += "\nper-rank causes: " + ", ".join(
                f"rank {r}: {c}" for r, c in sorted(self.causes.items()))
        super().__init__(msg + "\n" + "\n".join(
            f"--- rank {r} log (tail) ---\n{t[-4000:]}" for r, t in logs.items()))
        self.logs = logs


def _launch_once(task: str, n_processes: int, devices_per_process: int,
                 task_args: Any, timeout_s: float,
                 env_extra: Optional[Dict[str, str]]) -> List[Any]:
    """One rendezvous attempt: spawn, wait, collect (or WorkerFailure)."""
    # fault site: an armed rule here stands in for a failed rendezvous
    # without burning real subprocess spawns in tests
    if get_faults().check("launcher.attempt") is not None:
        raise WorkerFailure("injected rendezvous failure", {},
                            causes={r: "injected" for r in range(n_processes)})
    port = find_free_port()
    coordinator = f"127.0.0.1:{port}"
    procs: List[subprocess.Popen] = []
    logs: Dict[int, str] = {}
    args_json = json.dumps(task_args)
    pythonpath = os.pathsep.join(
        [p for p in sys.path if p and os.path.isdir(p)])
    try:
        for rank in range(n_processes):
            env = dict(os.environ)
            env.update(env_extra or {})
            env.update({
                "SMLTPU_COORDINATOR": coordinator,
                "SMLTPU_NUM_PROCESSES": str(n_processes),
                "SMLTPU_PROCESS_ID": str(rank),
                "SMLTPU_PLATFORM": "cpu",
                "SMLTPU_LOCAL_DEVICES": str(devices_per_process),
                "SMLTPU_TASK": task,
                "SMLTPU_TASK_ARGS": args_json,
                "PYTHONPATH": pythonpath,
            })
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "synapseml_tpu.parallel.worker"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env))
        # drain every rank's pipe on its own thread: a rank that fills the
        # OS pipe buffer mid-collective would otherwise deadlock the whole
        # cluster, and on failure we want EVERY rank's log, not just the
        # first one waited on
        readers = []
        for rank, p in enumerate(procs):
            t = threading.Thread(
                target=lambda r=rank, pr=p: logs.__setitem__(
                    r, pr.stdout.read() or ""),
                daemon=True)
            t.start()
            readers.append(t)
        deadline = time.monotonic() + timeout_s
        timed_out = []
        for rank, p in enumerate(procs):
            remaining = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                timed_out.append(rank)
        if timed_out:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for t in readers:
            t.join(timeout=10.0)
        returncodes = {r: p.returncode for r, p in enumerate(procs)}
        if timed_out:
            raise WorkerFailure(
                f"ranks {timed_out} timed out after {timeout_s:.0f}s", logs,
                causes=_rank_causes(returncodes, timed_out, []))
        failed = [r for r, rc in returncodes.items() if rc != 0]
        if failed:
            raise WorkerFailure(
                f"ranks {failed} exited non-zero", logs,
                causes=_rank_causes(returncodes, [], []))
        results: List[Any] = []
        missing: List[int] = []
        for rank, p in enumerate(procs):
            lines = [ln for ln in logs[rank].splitlines()
                     if ln.startswith(RESULT_MARKER)]
            if not lines:
                missing.append(rank)
                results.append(None)
            else:
                results.append(json.loads(lines[-1][len(RESULT_MARKER):]))
        if missing:
            raise WorkerFailure(
                f"ranks {missing} produced no result", logs,
                causes=_rank_causes(returncodes, [], missing))
        return results
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def run_on_local_cluster(task: str,
                         n_processes: int = 2,
                         devices_per_process: int = 2,
                         task_args: Any = None,
                         timeout_s: float = 300.0,
                         env_extra: Optional[Dict[str, str]] = None,
                         retry_policy: Optional[RetryPolicy] = None,
                         ) -> List[Any]:
    """Run ``module:function`` on a real N-process JAX cluster; return the
    per-rank results (rank order).

    Each rank is an OS process that rendezvouses through
    ``initialize_cluster`` (parallel/distributed.py) against a localhost
    coordinator, sees the global ``n_processes * devices_per_process``-device
    table, and runs ``function(task_args)`` with collectives live across
    process boundaries.  The function must return something JSON-serializable.

    ``retry_policy``: on :class:`WorkerFailure` the WHOLE launch retries
    (fresh port, fresh processes) under the policy's backoff — a formed
    ``jax.distributed`` cluster cannot re-admit a replacement rank, so
    whole-gang restart is the only sound retry unit.  The raised failure
    (when retries exhaust) is the LAST attempt's, with per-rank causes.
    """
    attempts = 1 + (retry_policy.max_retries if retry_policy else 0)
    reg = get_registry()
    m_retries = reg.counter("launcher_rendezvous_retries_total",
                            "whole-gang launch retries", ("task",))
    last: Optional[WorkerFailure] = None
    for attempt in range(attempts):
        try:
            return _launch_once(task, n_processes, devices_per_process,
                                task_args, timeout_s, env_extra)
        except WorkerFailure as e:
            last = e
            if retry_policy is None or attempt >= attempts - 1 \
                    or not retry_policy.acquire_retry():
                raise
            m_retries.inc(1, task=task)
            retry_policy.sleep(retry_policy.backoff_s(attempt),
                               site="launcher.backoff")
    raise last  # pragma: no cover — loop always returns or raises
