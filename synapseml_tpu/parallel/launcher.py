"""Local multi-process launcher — the driver half of the rendezvous.

The reference's driver opens a ServerSocket, waits for every worker task to
phone home with ``status:host:port:partition:executor``, then broadcasts the
machine list so the native ring can form (reference:
lightgbm/src/main/scala/com/microsoft/azure/synapse/ml/lightgbm/
NetworkManager.scala:294-440).  The TPU analogue needs no machine list —
``jax.distributed.initialize`` against a coordinator address gives every
process the global device table — so the driver's remaining job is exactly
what this module does: pick the coordinator endpoint, start one OS process
per host, watch them, and collect their results.

This is how multi-host tests and the distributed-serving harness execute for
real on one machine: N processes x M virtual CPU devices per process form a
genuine cross-process mesh (gloo collectives), the same code path a multi-host
TPU pod takes (PJRT collectives over ICI/DCN).
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

#: marker the worker prints in front of its JSON result line
RESULT_MARKER = "SMLMP_RESULT:"


def find_free_port() -> int:
    """Ask the kernel for a free TCP port (the driver's ServerSocket bind,
    NetworkManager.scala:299 — there the socket is kept open; here the
    coordinator re-binds it immediately so a race is possible but unlikely)."""
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


class WorkerFailure(RuntimeError):
    """A worker exited non-zero or produced no result."""

    def __init__(self, msg: str, logs: Dict[int, str]):
        super().__init__(msg + "\n" + "\n".join(
            f"--- rank {r} log (tail) ---\n{t[-4000:]}" for r, t in logs.items()))
        self.logs = logs


def run_on_local_cluster(task: str,
                         n_processes: int = 2,
                         devices_per_process: int = 2,
                         task_args: Any = None,
                         timeout_s: float = 300.0,
                         env_extra: Optional[Dict[str, str]] = None,
                         ) -> List[Any]:
    """Run ``module:function`` on a real N-process JAX cluster; return the
    per-rank results (rank order).

    Each rank is an OS process that rendezvouses through
    ``initialize_cluster`` (parallel/distributed.py) against a localhost
    coordinator, sees the global ``n_processes * devices_per_process``-device
    table, and runs ``function(task_args)`` with collectives live across
    process boundaries.  The function must return something JSON-serializable.

    This mirrors the reference driver's role in every local multi-task test
    (NetworkManager.scala:294-340): spawn workers, hand them the coordinator,
    wait, surface failures with worker logs attached.
    """
    port = find_free_port()
    coordinator = f"127.0.0.1:{port}"
    procs: List[subprocess.Popen] = []
    logs: Dict[int, str] = {}
    args_json = json.dumps(task_args)
    pythonpath = os.pathsep.join(
        [p for p in sys.path if p and os.path.isdir(p)])
    try:
        for rank in range(n_processes):
            env = dict(os.environ)
            env.update(env_extra or {})
            env.update({
                "SMLTPU_COORDINATOR": coordinator,
                "SMLTPU_NUM_PROCESSES": str(n_processes),
                "SMLTPU_PROCESS_ID": str(rank),
                "SMLTPU_PLATFORM": "cpu",
                "SMLTPU_LOCAL_DEVICES": str(devices_per_process),
                "SMLTPU_TASK": task,
                "SMLTPU_TASK_ARGS": args_json,
                "PYTHONPATH": pythonpath,
            })
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "synapseml_tpu.parallel.worker"],
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                text=True, env=env))
        # drain every rank's pipe on its own thread: a rank that fills the
        # OS pipe buffer mid-collective would otherwise deadlock the whole
        # cluster, and on failure we want EVERY rank's log, not just the
        # first one waited on
        readers = []
        for rank, p in enumerate(procs):
            t = threading.Thread(
                target=lambda r=rank, pr=p: logs.__setitem__(
                    r, pr.stdout.read() or ""),
                daemon=True)
            t.start()
            readers.append(t)
        deadline = time.monotonic() + timeout_s
        timed_out = []
        for rank, p in enumerate(procs):
            remaining = max(0.1, deadline - time.monotonic())
            try:
                p.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                timed_out.append(rank)
        if timed_out:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for t in readers:
            t.join(timeout=10.0)
        if timed_out:
            raise WorkerFailure(
                f"ranks {timed_out} timed out after {timeout_s:.0f}s", logs)
        results: List[Any] = []
        for rank, p in enumerate(procs):
            if p.returncode != 0:
                raise WorkerFailure(
                    f"rank {rank} exited {p.returncode}", logs)
            lines = [ln for ln in logs[rank].splitlines()
                     if ln.startswith(RESULT_MARKER)]
            if not lines:
                raise WorkerFailure(f"rank {rank} produced no result", logs)
            results.append(json.loads(lines[-1][len(RESULT_MARKER):]))
        return results
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
