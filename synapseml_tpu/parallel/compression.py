"""Compressed + sharded collectives: quantized allreduce with error
feedback, behind the :mod:`~synapseml_tpu.parallel.collectives` dispatch.

BENCH_r05 put the f32 gradient allreduce at the top of the BERT
fine-tune StepProfiler decomposition and GBDT's per-iteration histogram
psum is pure bandwidth — both move 4 bytes per value when far fewer
carry the signal.  This module implements the two levers:

- **Quantized allreduce codecs** (EQuARX, arXiv:2506.17615): ``bf16``
  (cast, reduce in bf16, cast back — 2x wire) and ``int8`` (chunked
  symmetric quantization with one f32 scale per ``chunk`` values —
  ~3.9x wire at chunk=256).  int8 reduces as reduce-scatter +
  all-gather of QUANTIZED shards: an ``all_to_all`` ships each rank its
  shard's quantized copies, the shard sums in f32 locally, and the
  re-quantized result all-gathers back — both wire phases ride int8.
- **Error feedback** (1-bit SGD lineage): the per-leaf quantization
  error is carried in a persistent residual and added to the next
  step's gradient instead of lost, so compressed SGD tracks the f32
  trajectory (pinned in tests/test_collectives_compression.py).
- **Sharded weight update** (Xu et al., arXiv:2004.13336): gradients
  reduce-scatter, each rank updates its 1/N shard of params/moments,
  updated params all-gather back — the N-way replicated optimizer work
  disappears (see :mod:`~synapseml_tpu.models.dl.training`).

Everything here is trace-time jax: the codecs run INSIDE jit/shard_map
bodies, so the compressed collective is part of the compiled step.

Non-finite policy (chunk-granular pass-through): an int8 chunk holding
any NaN/Inf decodes to all-NaN on every rank — gradient-overflow
detection still trips, at chunk granularity instead of element
granularity.  bf16 casts non-finites through natively.

Determinism: every rank decodes the SAME gathered bytes in the SAME
order, so compressed reductions are replicated exactly like ``psum`` —
the property GBDT's identical-tree-on-every-rank growth relies on.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..telemetry import get_registry
from .mesh import DATA_AXIS

#: codecs understood by :class:`CollectiveConfig.compression`
CODECS = ("none", "bf16", "int8")


@dataclasses.dataclass(frozen=True)
class CollectiveConfig:
    """Per-estimator collective compression/sharding policy.

    Frozen + hashable on purpose: it joins jit/lru static keys (the
    GBDT ``_make_step`` cache, the grower jit signatures), so two fits
    with different codecs compile distinct programs.
    """
    #: "none" | "bf16" | "int8" — wire codec for eligible reductions
    compression: str = "none"
    #: reduce-scatter gradients, update the local 1/N shard, all-gather
    #: params back (DL train path only; GBDT histograms have no
    #: optimizer state to shard)
    sharded_update: bool = False
    #: carry quantization error into the next step's gradient
    #: (DL gradient sync only — GBDT histograms are re-derived per
    #: split, so there is no stream to feed an error into)
    error_feedback: bool = False
    #: leaves with fewer elements stay f32 (compression overhead beats
    #: the wire win on tiny tensors; biases/scalars also carry
    #: outsized signal per byte)
    min_size: int = 2048
    #: values sharing one f32 scale in the int8 codec
    chunk: int = 256
    #: force the manual data-parallel shard_map step even with
    #: ``compression='none'`` — a measurement pin, not a perf knob: a
    #: compressed-vs-f32 pair where the f32 leg rides pjit would
    #: conflate the codec with the execution-mode change, so the bench
    #: pins BOTH legs to the manual mode (the bench_obs_overhead
    #: same-dispatch-mode methodology)
    manual: bool = False
    #: reduction ROUTE (:mod:`~synapseml_tpu.parallel.planner`):
    #: 'auto' (default — per-payload planner choice; resolves 'flat'
    #: wherever the topology is unknown, so defaults trace byte-
    #: identically to the pre-planner dispatch) | 'flat' (whatever
    #: jax.lax emits — today's path, pinned) | 'ring' | 'tree' |
    #: 'hierarchical' (intra-host f32, inter-host through the codec).
    #: A non-auto routing strategy also engages the manual dispatch
    #: paths (the route must be ours to schedule).
    strategy: str = "auto"

    def __post_init__(self):
        if self.compression not in CODECS:
            raise ValueError(
                f"compression={self.compression!r}: must be one of {CODECS}")
        if self.chunk < 8:
            raise ValueError(f"chunk={self.chunk}: must be >= 8")
        from .planner import STRATEGIES
        if self.strategy not in STRATEGIES:
            raise ValueError(
                f"strategy={self.strategy!r}: must be one of {STRATEGIES}")

    @property
    def enabled(self) -> bool:
        return (self.compression != "none" or self.sharded_update
                or self.manual or self.routes)

    @property
    def routes(self) -> bool:
        """An EXPLICIT routing request ('auto' alone does not enable a
        config — on unknown topology it is indistinguishable from flat,
        and on known topology it engages wherever the codec/manual
        knobs already put dispatch in our hands)."""
        return self.strategy in ("ring", "tree", "hierarchical")

    @property
    def compresses(self) -> bool:
        return self.compression != "none"


def resolve_collective_config(value: Any) -> Optional[CollectiveConfig]:
    """The one parser for estimator-level ``collectiveCompression``
    params and ``BoostingConfig.collective_compression``: accepts
    ``None``/``"none"`` (off), a codec shorthand (``"bf16"``/``"int8"``
    — error feedback ON, the right default for gradient streams; GBDT
    ignores the flag), a full :class:`CollectiveConfig`, or its
    ``dataclasses.asdict`` form (checkpointed configs)."""
    if value is None:
        return None
    if isinstance(value, CollectiveConfig):
        return value if value.enabled else None
    if isinstance(value, dict):
        # a checkpointed BoostingConfig round-trips a CollectiveConfig
        # through dataclasses.asdict — rebuild it (unknown keys from a
        # newer build are dropped, matching Booster.from_dict's policy)
        fields = {f.name for f in dataclasses.fields(CollectiveConfig)}
        return resolve_collective_config(CollectiveConfig(
            **{k: v for k, v in value.items() if k in fields}))
    if isinstance(value, str):
        if value == "none" or value == "":
            return None
        if value not in CODECS:
            raise ValueError(
                f"collectiveCompression={value!r}: must be one of {CODECS} "
                "or a CollectiveConfig")
        cfg = CollectiveConfig(compression=value, error_feedback=True)
        if value == "int8":
            tuned = _tuned_int8_chunk()
            if tuned is not None:
                cfg = dataclasses.replace(cfg, chunk=tuned)
        return cfg
    raise TypeError(
        f"collectiveCompression accepts a str codec or CollectiveConfig, "
        f"got {type(value).__name__}")


def _tuned_int8_chunk() -> Optional[int]:
    """The ``int8_chunk`` tuning-table winner for this device, or None
    (keep the 256 default).  Only the codec SHORTHAND consults the
    table: an explicit ``CollectiveConfig`` (or its checkpointed dict
    form) is the caller's decision and passes through untouched."""
    try:
        from ..telemetry.tunetable import geometry_key, get_tuneplane
        winner = get_tuneplane().consult(
            "resolve_collective_config", "int8_chunk",
            geometry_key(numel=1 << 18),
            validate=lambda w: (isinstance(w.get("chunk"), int)
                                and not isinstance(w["chunk"], bool)
                                and w["chunk"] >= 8))
    except Exception:
        return None
    return int(winner["chunk"]) if winner is not None else None


def stream_eligible(shape, dtype,
                    config: Optional[CollectiveConfig]) -> bool:
    """The size/dtype half of the eligibility predicate: does a payload
    of this shape/dtype belong to the big flat stream at all (large
    float, ``min_size`` or more elements) — before asking whether the
    codec engages on it?  The routing-only stream
    (:func:`compressed_tree_sync` under an explicit strategy with
    ``compression='none'``) partitions leaves by THIS, so the big/small
    split can never disagree between compressing and routing-only
    configs."""
    return (config is not None
            and int(np.prod(shape)) >= config.min_size
            and jnp.issubdtype(dtype, jnp.floating))


def codec_eligible(shape, dtype, config: Optional[CollectiveConfig]) -> bool:
    """THE eligibility predicate — does the codec engage for a payload of
    this shape/dtype under ``config``?  One implementation on purpose:
    the traced reductions (:func:`compressed_psum`,
    :func:`compressed_tree_sync`), the wire accounting
    (:func:`wire_nbytes`), and the host-side codec labels
    (``collectives.allreduce_fn``) must all agree, or metrics report
    int8 wire for ops that really reduced in f32."""
    return (config is not None and config.compresses
            and stream_eligible(shape, dtype, config))


# -- wire accounting ---------------------------------------------------------

def logical_nbytes(x) -> int:
    """Bytes the values occupy at their LOGICAL dtype (what an
    uncompressed collective would move per shard)."""
    n = 0
    for leaf in jax.tree_util.tree_leaves(x):
        size, dtype = getattr(leaf, "size", None), getattr(leaf, "dtype",
                                                           None)
        if size is not None and dtype is not None:
            n += int(size) * np.dtype(dtype).itemsize
    return n


def wire_nbytes(x, config: Optional[CollectiveConfig],
                channel_major: bool = False) -> int:
    """Bytes the codec actually puts on the wire for ``x``: bf16 halves
    every eligible f32; int8 ships 1 byte/value + one f32 scale per
    ``chunk`` — INCLUDING the zero-pad values the layout adds (with
    ``channel_major``, each trailing channel pads to a chunk multiple —
    the :func:`compressed_psum` layout; the flat int8 stream then rounds
    up to a whole chunk).  The final pad to an ``n_ranks * chunk``
    multiple depends on the axis size, which this accounting cannot see;
    the ≤ ``(n-1) * chunk`` values it omits are noise against real
    payloads.  ``config=None``/"none" → logical bytes."""
    if config is None or not config.compresses:
        return logical_nbytes(x)
    total = 0
    int8_vals = 0
    for leaf in jax.tree_util.tree_leaves(x):
        size, dtype = getattr(leaf, "size", None), getattr(leaf, "dtype",
                                                           None)
        if size is None or dtype is None:
            continue
        size = int(size)
        shape = tuple(getattr(leaf, "shape", ()))
        if not codec_eligible((size,), dtype, config):
            total += size * np.dtype(dtype).itemsize
        elif config.compression == "bf16":
            total += size * 2
        elif channel_major and len(shape) >= 2:
            C = shape[-1]
            per = size // C
            int8_vals += C * (-(-per // config.chunk) * config.chunk)
        else:
            int8_vals += size
    if int8_vals:
        int8_vals = -(-int8_vals // config.chunk) * config.chunk
        total += int8_vals + (int8_vals // config.chunk) * 4
    return total


def record_compressed(op: str, axis, x,
                      config: Optional[CollectiveConfig],
                      channel_major: bool = False,
                      strategy: str = "flat",
                      codec: Optional[str] = None,
                      wire: Optional[int] = None) -> None:
    """Trace-time wire/logical accounting for a compressed collective —
    the codec-aware counterpart of ``collectives._record`` (which
    assumed logical dtype size for every op and would double-count and
    mis-rank codecs).  ``strategy`` is the planner route the bytes take
    (ISSUE 14: every strategy choice attributable), 'flat' for the
    direct dispatch.  ``codec``/``wire`` override the config-derived
    label and byte model for routed dispatches whose wire differs from
    the flat one (a tree that demoted int8 ships f32; hierarchical adds
    intra-host f32 legs — see ``ReductionPlan.wire_nbytes``).
    Telemetry must never break a trace."""
    try:
        if codec is None:
            codec = config.compression if config is not None else "none"
        logical = logical_nbytes(x)
        if wire is None:
            wire = wire_nbytes(x, config, channel_major=channel_major)
        reg = get_registry()
        labels = dict(op=op, axis=str(axis), codec=codec, strategy=strategy)
        reg.counter(
            "collective_wire_bytes_total",
            "per-shard bytes collectives actually put on the wire, by "
            "op, mesh axis, codec and routing strategy",
            ("op", "axis", "codec", "strategy")).inc(wire, **labels)
        reg.gauge(
            "collective_compression_ratio",
            "logical / wire bytes of the last traced collective, by op, "
            "mesh axis, codec and routing strategy",
            ("op", "axis", "codec", "strategy")).set(
                (logical / wire) if wire else 1.0, **labels)
    except Exception:
        pass


# -- codecs ------------------------------------------------------------------

def bf16_encode(x):
    return x.astype(jnp.bfloat16)


def bf16_decode(q):
    return q.astype(jnp.float32)


def int8_encode(flat, chunk: int) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Chunked symmetric int8 quantization of a flat f32 vector whose
    length is a (static) multiple of ``chunk``.

    → ``(q int8 (n_chunks, chunk), scales f32 (n_chunks,))`` with
    ``scale = max|finite x| / 127`` per chunk.  A chunk containing any
    non-finite value gets a NaN scale, so the whole chunk decodes to
    NaN — the documented pass-through policy (overflow detection trips
    at chunk granularity)."""
    xc = flat.reshape(-1, chunk)
    finite = jnp.isfinite(xc)
    amax = jnp.max(jnp.where(finite, jnp.abs(xc), 0.0), axis=1)
    scale = amax / 127.0
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(xc / safe[:, None]), -127, 127).astype(jnp.int8)
    scale = jnp.where(jnp.all(finite, axis=1), scale, jnp.nan)
    return q, scale.astype(jnp.float32)


def int8_decode(q, scales) -> jnp.ndarray:
    """Inverse of :func:`int8_encode` → flat f32 (NaN-scale chunks decode
    to all-NaN)."""
    return (q.astype(jnp.float32) * scales[:, None]).reshape(-1)


@functools.partial(jax.jit, static_argnames=("chunk",))
def int8_roundtrip_jit(flat: jnp.ndarray, chunk: int) -> jnp.ndarray:
    """Jitted encode→decode round trip of a flat f32 vector — the int8
    codec's standalone entry point: the ``int8_chunk`` autotune space
    times it per candidate chunk, and it is registered with the warmup
    lattice (``REGISTERED_ENTRY_POINTS``) like every other tunable
    program.  The in-collective codec runs inside larger jitted bodies;
    this isolates the quantization cost itself."""
    return int8_decode(*int8_encode(flat, chunk))


def _channel_major_padded(x, chunk: int):
    """Channel-major flatten with each channel zero-padded to a
    ``chunk`` multiple → ``(flat, per, per_padded)``.

    Histogram-style arrays carry heterogeneous channels on the LAST
    axis (grad/hess/count for GBDT — counts are ~1e3x gradients); a
    C-order flatten would interleave them into shared int8 chunks and
    the small channel would quantize to zero.  Moving the channel axis
    leading is not enough on its own: a channel whose element count is
    not a chunk multiple (28 features x 64 bins = 1792, say) leaves a
    BOUNDARY chunk spanning two channels, where the big channel's amax
    scale flattens the small one.  Padding every channel to a chunk
    multiple keeps each chunk strictly single-channel.  Pure layout —
    inverted exactly by :func:`_channel_major_padded_inv`."""
    if getattr(x, "ndim", 0) >= 2:
        C = x.shape[-1]
        moved = jnp.moveaxis(x, -1, 0).reshape(C, -1)
        per = moved.shape[1]
        per_p = -(-per // chunk) * chunk
        if per_p != per:
            moved = jnp.pad(moved, ((0, 0), (0, per_p - per)))
        return moved.reshape(-1), per, per_p
    return x.reshape(-1), None, None


def _channel_major_padded_inv(flat, shape, per, per_p):
    if len(shape) >= 2:
        C = shape[-1]
        out = flat.reshape(C, per_p)[:, :per]
        return jnp.moveaxis(out.reshape((C,) + tuple(shape[:-1])), 0, -1)
    return flat.reshape(shape)


def _pad_to(flat, unit: int):
    """Zero-pad a flat vector to a multiple of ``unit`` (static)."""
    n = flat.shape[0]
    padded = -(-n // unit) * unit
    if padded != n:
        flat = jnp.pad(flat, (0, padded - n))
    return flat


def int8_reduce_scatter(flat, axis: str, chunk: int) -> jnp.ndarray:
    """Quantized reduce-scatter of a flat f32 vector whose length is a
    (static) multiple of ``n_ranks * chunk``: each rank quantizes its
    full vector per-chunk, an ``all_to_all`` ships shard ``r``'s
    quantized copies to rank ``r``, and the shard sums in f32 locally.

    → this rank's f32 shard of the SUM (length ``len / n``).  The wire
    carries int8 + per-chunk f32 scales — the reduce-scatter phase of
    the EQuARX-style quantized allreduce, and directly the gradient
    half of the sharded weight update."""
    n = lax.axis_size(axis)
    if n == 1:
        # single rank: same quantize→dequantize the wire would apply,
        # so 1-device runs surface the identical numeric policy the
        # gang sees (and the error-feedback tests exercise it locally)
        q, s = int8_encode(flat, chunk)
        return int8_decode(q, s)
    shard = flat.shape[0] // n
    q, s = int8_encode(flat, chunk)                   # (C, chunk), (C,)
    q = q.reshape(n, shard // chunk, chunk)
    s = s.reshape(n, shard // chunk)
    q_x = lax.all_to_all(q, axis, split_axis=0, concat_axis=0, tiled=False)
    s_x = lax.all_to_all(s, axis, split_axis=0, concat_axis=0, tiled=False)
    # decode each peer's copy of MY shard and sum in f32 (fixed 0..n-1
    # order → replicated-deterministic result after the gather below)
    vals = q_x.astype(jnp.float32) * s_x[..., None]   # (n, shard/chunk, chunk)
    return jnp.sum(vals, axis=0).reshape(-1)


def int8_all_gather(shard, axis: str, chunk: int) -> jnp.ndarray:
    """Quantized all-gather of equal f32 shards (length a static
    multiple of ``chunk``) → the concatenated f32 vector, identical on
    every rank.  The all-gather phase of the quantized allreduce."""
    n = lax.axis_size(axis)
    q, s = int8_encode(shard, chunk)
    if n == 1:
        return int8_decode(q, s)
    qg = lax.all_gather(q, axis_name=axis)            # (n, C, chunk)
    sg = lax.all_gather(s, axis_name=axis)            # (n, C)
    return (qg.astype(jnp.float32) * sg[..., None]).reshape(-1)


# -- in-jit compressed reductions -------------------------------------------

def compressed_psum(x, axis: Optional[str],
                    config: Optional[CollectiveConfig],
                    op: str = "compressed_psum", record: bool = True):
    """Drop-in ``psum`` with the config's codec on the wire.

    The GBDT histogram-allreduce replacement: stateless (no error
    feedback — each node's histogram is an independent quantity, not a
    stream), sum semantics, identical result on every rank.  Arrays
    with a trailing channel axis are re-laid out channel-major before
    chunking (see :func:`_channel_major_padded`).  Falls back to plain
    ``lax.psum`` for ``config=None``/"none"/too-small payloads, so the
    default path traces byte-identically to today's."""
    if axis is None:
        return x
    if not codec_eligible(x.shape, x.dtype, config):
        # record under the CALLER's op (not the psum wrapper's): a
        # too-small/non-float payload of the same logical collective
        # must not split into a different metric series — and with
        # record=False the caller accounts the op itself (allreduce_fn's
        # host wrapper), so recording here would double-count
        if record:
            from .collectives import _record
            _record(op, axis, x)
        return lax.psum(x, axis_name=axis)
    if record:
        # record=False for callers that already account the op at their
        # own level (allreduce_fn's host wrapper) — one op, one series
        from .collectives import _record
        _record(op, axis, x, config=config, channel_major=True)
    shape = x.shape
    orig_dtype = x.dtype
    if config.compression == "bf16":
        out = lax.psum(bf16_encode(x), axis_name=axis)
        return bf16_decode(out).astype(orig_dtype)
    flat, per, per_p = _channel_major_padded(x.astype(jnp.float32),
                                             config.chunk)
    size = flat.shape[0]
    # axis size is static inside shard_map tracing (it comes from the
    # mesh), so the padding below stays shape-static
    n = lax.axis_size(axis)
    flat = _pad_to(flat, int(n) * config.chunk)
    shard = int8_reduce_scatter(flat, axis, config.chunk)
    total = int8_all_gather(shard, axis, config.chunk)
    return _channel_major_padded_inv(total[:size], shape, per,
                                     per_p).astype(orig_dtype)


def flatten_with_residuals(leaves, big, res_leaves, padded: int):
    """Concatenate the ``big`` leaves (f32, plus their error-feedback
    residuals when carried) into one zero-padded flat stream of length
    ``padded`` — the ONE pack step shared by
    :func:`compressed_tree_sync` and the DL sharded weight update (the
    EF recursion lives here once; a hardening applied to one path
    cannot silently miss the other)."""
    eff = []
    for i in big:
        g = leaves[i].astype(jnp.float32)
        if res_leaves is not None:
            g = g + res_leaves[i].reshape(g.shape)
        eff.append(g.reshape(-1))
    flat = jnp.concatenate(eff) if eff else jnp.zeros((0,), jnp.float32)
    return jnp.pad(flat, (0, padded - flat.shape[0]))


def unpack_residuals(err, big, leaves, res_leaves):
    """Scatter the flat quantization error back into the per-rank
    residual leaves (``e' = (g+e) - Q(g+e)``) — the inverse of
    :func:`flatten_with_residuals`' packing order."""
    new_res = list(res_leaves)
    offset = 0
    for i in big:
        sz = leaves[i].size
        new_res[i] = err[offset:offset + sz].reshape(new_res[i].shape)
        offset += sz
    return new_res


# -- world-size-independent re-sharding (elastic gang resize) ---------------
#
# Two pieces of training state are laid out by WORLD SIZE: the per-rank
# error-feedback residuals (stacked ``(n, *leaf.shape)``) and the
# sharded-update flat moment stream (padded to an ``n * unit`` multiple).
# An N-rank checkpoint restoring on M ranks goes through a CANONICAL
# (world-size-free) form first — gather-to-canonical-then-reshard — so
# the restored state is a pure function of the checkpoint, identical
# whichever size reads it.

def canonical_residuals(stacked):
    """Stacked per-rank EF residuals ``(n, *shape)`` → the canonical
    ``(*shape,)`` TOTAL carried error.

    The EF recursion is additive in SUM units: each rank transmits
    ``Q(g_r + e_r)`` and keeps ``e_r' = (g_r + e_r) - Q(g_r + e_r)``, so
    the quantity the compressed stream owes the true gradient trajectory
    is ``sum_r e_r`` — the per-rank decomposition is an artifact of who
    computed what, not training state.  Summation order is the stacked
    rank order (0..n-1), deterministic on every reader."""
    return np.asarray(stacked, dtype=np.float32).sum(axis=0)


def reshard_residuals(canonical, n: int):
    """Canonical total error ``(*shape,)`` → ``(n, *shape)`` stacked
    per-rank residuals: rank 0 carries the whole total, ranks 1.. carry
    zeros.  Exact (no divide — splitting ``e / n`` would round) and
    preserves the EF invariant ``sum_r e_r == canonical``; the
    decomposition re-balances itself within one step (each rank's next
    error is its own quantization error)."""
    canonical = np.asarray(canonical, dtype=np.float32)
    out = np.zeros((int(n),) + canonical.shape, dtype=np.float32)
    out[0] = canonical
    return out


def reshard_flat_stream(buf, total: int, new_padded: int):
    """A flat padded per-stream vector (sharded-update moments) laid out
    for one world size → the same stream re-padded for another: trim to
    the ``total`` real values (pad positions hold zeros — pad gradients
    are structurally zero, so their moments never grow), re-pad to
    ``new_padded``."""
    buf = np.asarray(buf)
    if total > buf.shape[0] or new_padded < total:
        raise ValueError(
            f"cannot re-lay stream of {buf.shape[0]} values to "
            f"{new_padded} keeping {total} real values")
    out = np.zeros((int(new_padded),), dtype=buf.dtype)
    out[:total] = buf[:total]
    return out


def compressed_tree_sync(tree, axis: Optional[str],
                         config: CollectiveConfig,
                         residuals=None, mean: bool = True,
                         op: str = "grad_sync"):
    """Gradient-tree allreduce with compression + per-leaf error
    feedback: → ``(reduced_tree, new_residuals)``.

    Large float leaves concatenate into one flat buffer (the
    ``tree_psum_bucketed`` fusion idea, applied to the compressed
    stream), ride the quantized reduce-scatter + all-gather, and unpack;
    small/non-float leaves ride a plain bucketed psum.  With
    ``residuals`` (a pytree matching ``tree``, each leaf stacked
    ``(1, *leaf.shape)`` per-rank under shard_map), each rank transmits
    ``Q(g + e)`` and keeps ``e' = (g + e) - Q(g + e)`` — the classic
    error-feedback recursion, in SUM units (the mean divide applies to
    the reduced total only).
    """
    from .collectives import tree_psum_bucketed, _record
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    if axis is not None:
        n = lax.axis_size(axis)
    else:
        n = 1
    # the big-leaf stream: codec-eligible leaves, plus — for a
    # routing-only config (strategy set, compression 'none') — the same
    # large-float class routed at f32, so an explicit ring/tree/
    # hierarchical request still schedules the gradient stream
    big = [i for i, lf in enumerate(leaves)
           if stream_eligible(lf.shape, lf.dtype, config)
           and (config.compresses or config.routes)]
    small = [i for i in range(len(leaves)) if i not in big]

    out = list(leaves)
    new_res = None
    if residuals is not None:
        new_res = list(jax.tree_util.tree_leaves(residuals))
    if small and axis is not None:
        small_tree = [leaves[i] for i in small]
        summed = tree_psum_bucketed(small_tree, axis=axis)
        for j, i in enumerate(small):
            out[i] = summed[j] / n if mean else summed[j]
    if big:
        # the planner resolves the gradient stream's route at trace
        # time (flat everywhere topology is unknown — the pre-planner
        # jaxpr, byte-identical); non-flat routes reduce through
        # ReductionPlan.reduce_flat with the SAME per-leaf EF contract
        plan = None
        if axis is not None and getattr(config, "strategy",
                                        "flat") != "flat":
            from .planner import get_planner
            size_est = int(sum(leaves[i].size for i in big)) * 4
            plan = get_planner().plan(size_est, int(n), config,
                                      axis=str(axis), op=op)
        routed = plan is not None and plan.strategy != "flat"
        size = int(sum(leaves[i].size for i in big))
        big_leaves = [leaves[i] for i in big]
        codec = (plan.wire_codec((size,), jnp.float32) if routed
                 else None)
        if routed:
            # calls/logical series, then the strategy-labeled wire
            # series at the codec and bytes the resolved route REALLY
            # ships (a tree route demotes int8 to the f32 wire;
            # hierarchical counts its intra-host f32 legs plus the
            # 1/inner codec shard) — flat-model accounting here would
            # claim int8 wire for a route that ships f32
            _record(op, axis, big_leaves)
            record_compressed(op, axis, big_leaves,
                              config if codec != "none" else None,
                              strategy=plan.strategy, codec=codec,
                              wire=plan.wire_nbytes(big_leaves, codec))
        else:
            _record(op, axis, big_leaves, config=config, strategy="flat")
        flat = flatten_with_residuals(leaves, big, new_res, size)
        want_err = new_res is not None and config.error_feedback
        if routed:
            flat_p = _pad_to(flat, plan.pad_unit(codec))
            total_p, err_p = plan.reduce_flat(flat_p, axis, codec,
                                              want_err=want_err)
            total = total_p[:size]
            if want_err:
                new_res = unpack_residuals(err_p[:size], big, leaves,
                                           new_res)
        elif not config.compresses:
            # a routing-only stream whose plan resolved flat (unknown
            # topology / structural fallback): plain f32 psum — the
            # same wire the small-leaf path rides
            total = (lax.psum(flat, axis_name=axis)
                     if axis is not None else flat)
        elif config.compression == "bf16":
            sent = bf16_decode(bf16_encode(flat))
            if axis is not None:
                total = bf16_decode(lax.psum(bf16_encode(flat),
                                             axis_name=axis))
            else:
                total = sent
            if want_err:
                new_res = unpack_residuals(flat - sent[:size], big,
                                           leaves, new_res)
        else:
            flat_p = _pad_to(flat, int(n) * config.chunk)
            q, s = int8_encode(flat_p, config.chunk)
            sent = int8_decode(q, s)[:size]
            if axis is not None and int(n) > 1:
                shard = int8_reduce_scatter(flat_p, axis, config.chunk)
                total = int8_all_gather(shard, axis, config.chunk)[:size]
            else:
                total = sent
            if want_err:
                new_res = unpack_residuals(flat - sent[:size], big,
                                           leaves, new_res)
        offset = 0
        for i in big:
            sz = leaves[i].size
            shp = leaves[i].shape
            red = total[offset:offset + sz].reshape(shp)
            out[i] = (red / n if mean else red).astype(leaves[i].dtype)
            offset += sz
    # no big leaves (config doesn't compress, or nothing eligible):
    # the small-leaf branch above already rode the whole tree through
    # the plain bucketed psum — the f32 wire, one traced reduce
    reduced = jax.tree_util.tree_unflatten(treedef, out)
    if residuals is not None:
        new_res = jax.tree_util.tree_unflatten(
            jax.tree_util.tree_structure(residuals), new_res)
    return reduced, new_res
