"""Cluster bring-up self-check — prove the rendezvous actually works.

The reference validates its ring during bring-up: every worker phones home,
the driver broadcasts the machine list, and ``LGBM_NetworkInit`` fails
loudly when a peer is unreachable (NetworkManager.scala:182-205,294-440).
The TPU analogue below is run on EVERY rank of a freshly initialized
cluster and returns facts that only come out right when the rendezvous is
real: the global device table (with owning process per device), a
deterministic partition placement computed independently on each rank, and
a cross-process ``psum`` whose result requires data from every process.
"""

from __future__ import annotations

from typing import Any, Dict

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import DATA_AXIS


def cluster_report(args: Any = None) -> Dict[str, Any]:
    """Return rendezvous evidence from this rank (JSON-serializable)."""
    n_partitions = int((args or {}).get("n_partitions", 12))
    devs = jax.devices()
    mesh = Mesh(np.array(devs), (DATA_AXIS,))

    # deterministic placement, computed independently per rank: every rank
    # must derive the identical partition->device map from the global table
    from .placement import place_partitions
    pm = place_partitions(n_partitions, mesh)
    placement = {str(p): r for p, r in sorted(pm.partition_to_rank.items())}

    # cross-process psum: shard i carries value i; the sum over all shards
    # is only correct when every process's devices contribute
    n = len(devs)
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    garr = jax.make_array_from_callback(
        (n,), sharding,
        lambda idx: np.asarray([idx[0].start or 0], dtype=np.float32))
    from .collectives import all_gather, psum, shard_map_over
    summed = jax.jit(shard_map_over(mesh, P(DATA_AXIS), P(DATA_AXIS))(psum))(garr)
    local = [float(np.asarray(s.data)[0]) for s in summed.addressable_shards]

    # a second collective with direction: all_gather preserves order, so the
    # result also proves the device order is the same global order everywhere.
    # out_specs keeps the device axis so no replication proof is needed:
    # each shard of the (n*n,) result holds the full gathered order
    gathered = jax.jit(shard_map_over(mesh, P(DATA_AXIS), P(DATA_AXIS))(
        lambda x: all_gather(x, tiled=True)))(garr)
    gathered_host = [float(v) for v in
                     np.asarray(jax.device_get(gathered.addressable_shards[0].data))]

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "global_devices": len(devs),
        "local_devices": len(jax.local_devices()),
        "device_table": [[d.id, d.process_index] for d in devs],
        "placement": placement,
        "psum_local": local,
        "psum_expected": float(sum(range(n))),
        "all_gather": gathered_host,
    }
