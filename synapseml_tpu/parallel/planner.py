"""Topology-aware collective planner: per-payload reduction routing.

The PR 6 codecs decide *what bytes* ride a reduction; nothing decided
*what route* they take — every reduction was whatever ``jax.lax`` emits,
whether the gang spans one ICI-connected host or many DCN-separated
ones.  This module synthesizes a :class:`ReductionPlan` per payload —
**ring** (bandwidth-optimal reduce-scatter + all-gather around the
axis), **tree** (recursive-doubling exchange, ``log2(n)`` rounds —
latency-optimal for small payloads, Horovod's size-dependent selection,
arXiv:1802.05799), or **two-level hierarchical** (intra-host
reduce-scatter in f32, inter-host allreduce through the PR 6 int8/bf16
codecs, intra-host all-gather back — EQuARX, arXiv:2506.17615) — chosen
from payload bytes × world size × link class, behind the existing
:class:`~synapseml_tpu.parallel.compression.CollectiveConfig`
(``strategy='auto'|'flat'|'ring'|'tree'|'hierarchical'``).

Honesty contract (the roofline spec-table pattern): the ``auto``
decision table only routes away from ``flat`` when the topology is
actually KNOWN — device mesh coords discovered from the backend, or an
explicitly injected :class:`TopologySpec` (CPU-container tests and
bench).  An unknown topology plans ``flat``, byte-identical to the
pre-planner dispatch; nothing is fabricated.

Plans bind at TRACE time (the planner runs while jit traces, like the
``_record`` accounting), are cached in size buckets keyed like jit
statics ``(payload bucket, world, config, spec, epoch)``, and the cache
is invalidated at every :class:`~synapseml_tpu.parallel.supervisor.
GangSupervisor` relaunch/resize boundary (world size changed → topology
snapshot refreshed → plans rebuilt; already-compiled programs keep
their traced route — gang workers are fresh processes, so the refresh
lands with the relaunch).

Telemetry: ``collective_plans_total{strategy,reason,model}`` per
synthesized plan (``model`` names what priced the auto decision —
``fitted`` a measured α-β fit from the tuning table, ``spec`` the
hardcoded cutoff constants, ``fallback`` no cost model consulted at
all: forced strategies, single rank, unknown topology),
``plan_decide``/``plan_invalidate`` flight events, the
``collective_wire_bytes_total{op,axis,codec,strategy}`` strategy label,
and the StepProfiler collective segment split by strategy — every
routing choice is attributable in /metrics, flight rings and bench.
"""

from __future__ import annotations

import dataclasses
import math
import threading
from typing import Any, Dict, Optional, Tuple

from ..resilience.faults import get_faults
from ..telemetry import get_registry
from ..telemetry.flight import record as flight_record

__all__ = ["TopologySpec", "ReductionPlan", "CollectivePlanner",
           "STRATEGIES", "TREE_CUTOFF_BYTES", "get_planner", "set_planner",
           "planned_psum", "PLANNER_METRICS"]

#: strategies a :class:`~synapseml_tpu.parallel.compression.
#: CollectiveConfig` may request ('auto' resolves per payload)
STRATEGIES = ("auto", "flat", "ring", "tree", "hierarchical")

#: payloads at or below this ride the latency-optimal tree under 'auto'
#: (the Horovod ring-vs-tree crossover class: log2(n) full-payload sends
#: beat 2(n-1) chunked hops only while the per-hop latency dominates)
TREE_CUTOFF_BYTES = 256 << 10

#: planner-level metric names (held to the docs bar by
#: tests/test_collective_planner.py, the GANG_METRICS pattern)
PLANNER_METRICS = frozenset({"collective_plans_total"})

#: aggregate per-chip ICI bytes/s by device kind (public spec sheets) —
#: carried on discovered specs for bench/telemetry context and link-class
#: RANKING only (the decision table is structural); absent kinds stay
#: None: unknown backend ⇒ claim nothing (telemetry.roofline pattern)
CHIP_ICI_BW = {
    "TPU v4": 300e9,
    "TPU v5 lite": 200e9,    # v5e
    "TPU v5": 600e9,         # v5p
    "TPU v6 lite": 450e9,    # v6e / Trillium
}


@dataclasses.dataclass(frozen=True)
class TopologySpec:
    """The link structure plans are routed by.

    Frozen + hashable on purpose: it joins the plan-cache key exactly
    like a jit static.  ``source='discovered'`` specs are built from the
    live :func:`~synapseml_tpu.parallel.topology.get_topology` snapshot;
    ``'injected'`` specs are explicit overrides (CPU-container tests,
    bench synthetic topologies) and are always trusted.
    """
    n_hosts: int = 1
    devices_per_host: int = 1
    platform: str = "unknown"
    #: every device reported chip mesh coords (real ICI structure seen)
    coords_known: bool = False
    #: link-class context (bytes/s); None = unknown, never guessed
    ici_bytes_per_s: Optional[float] = None
    dcn_bytes_per_s: Optional[float] = None
    source: str = "injected"

    def __post_init__(self):
        if self.n_hosts < 1 or self.devices_per_host < 1:
            raise ValueError(
                f"TopologySpec needs n_hosts >= 1 and devices_per_host "
                f">= 1, got {self.n_hosts}x{self.devices_per_host}")
        if self.source not in ("injected", "discovered"):
            raise ValueError(f"source={self.source!r}")

    @property
    def world(self) -> int:
        return self.n_hosts * self.devices_per_host

    @property
    def multi_host(self) -> bool:
        return self.n_hosts > 1

    @property
    def trusted(self) -> bool:
        """May 'auto' route on this spec?  Injected specs always;
        discovered ones only when the backend really exposed coords —
        a CPU/host-platform snapshot stays untrusted so every default
        path keeps planning ``flat`` (no fabricated topology)."""
        return self.source == "injected" or self.coords_known


def discover_spec() -> TopologySpec:
    """Build a ``source='discovered'`` spec from the live jax topology
    (imports jax — call only where jax is already the runtime)."""
    from .topology import get_topology
    import jax
    topo = get_topology()
    ici = None
    try:
        from ..telemetry.roofline import chip_lookup
        ici = chip_lookup(jax.devices()[0], CHIP_ICI_BW)
    except Exception:
        ici = None
    n_slices = topo.num_slices()
    n_hosts = max(topo.num_processes, n_slices or 1)
    return TopologySpec(
        n_hosts=n_hosts,
        devices_per_host=max(1, topo.num_devices // max(1, n_hosts)),
        platform=topo.platform,
        coords_known=topo.coords_known,
        ici_bytes_per_s=ici,
        dcn_bytes_per_s=None,
        source="discovered")


def _is_pow2(n: int) -> bool:
    return n >= 1 and (n & (n - 1)) == 0


def _bucket(nbytes: int) -> int:
    """Size bucket of a payload: next power of two (plans for 1.1 MB and
    1.9 MB share one cache entry — the prefill-bucket idiom applied to
    the plan cache)."""
    nbytes = max(1, int(nbytes))
    return 1 << (nbytes - 1).bit_length()


@dataclasses.dataclass(frozen=True)
class ReductionPlan:
    """One resolved route for one (payload bucket, world, config).

    Frozen + hashable (it rides trace-time closures and cache keys).
    ``execute`` has ``psum`` semantics — per-shard value in, replicated
    sum out — and MUST run inside shard_map tracing over ``axis``;
    ``reduce_flat`` is the gradient-stream form the DL sync uses
    (padded flat f32 in, (total, this-rank's-quantization-error) out).
    """
    strategy: str                 # resolved: flat | ring | tree | hierarchical
    reason: str                   # why the decision table chose it
    world: int
    inner: int                    # intra-host group size (hierarchical; else world)
    payload_bucket: int
    config: Any = None            # the CollectiveConfig (or None = bare flat)

    @property
    def outer(self) -> int:
        return self.world // max(1, self.inner)

    def wire_codec(self, shape, dtype) -> str:
        """The codec THIS plan puts on its quantized leg for a payload
        of this shape — 'none' when the config doesn't compress it, and
        for ``tree`` routes (latency-bound payloads ride the logical
        dtype; chunked int8 would add two codec passes to save bytes
        that don't matter at this size — bf16 still composes)."""
        from .compression import codec_eligible
        cfg = self.config
        if cfg is None or not codec_eligible(shape, dtype, cfg):
            return "none"
        if self.strategy == "tree" and cfg.compression == "int8":
            return "none"
        return cfg.compression

    def pad_unit(self, codec: str) -> int:
        """Flat-stream padding multiple the route needs (static)."""
        if self.strategy == "ring":
            return (self.world * self.config.chunk if codec == "int8"
                    else self.world)
        if self.strategy == "hierarchical":
            return (self.inner * self.config.chunk if codec == "int8"
                    else self.inner)
        if self.strategy == "flat" and codec == "int8":
            return self.world * self.config.chunk
        return 1

    def wire_nbytes(self, x, codec: str,
                    channel_major: bool = False) -> int:
        """Per-shard bytes THIS route actually puts on the wire for
        ``x``.  flat/ring/tree follow the one-payload-traversal
        convention the flat accounting already uses (at the route's
        EFFECTIVE codec — a tree that demoted int8 reports f32 wire,
        not int8 wire that never existed).  hierarchical counts its
        real legs: two intra-host f32 passes (reduce-scatter +
        all-gather, ``(inner-1)/inner`` of the payload each) plus the
        ``1/inner`` inter-host shard at codec width — pricing the whole
        payload at int8 width would claim a ~4x wire win the f32
        intra-host legs don't deliver."""
        from .compression import logical_nbytes, wire_nbytes
        live = self.config if codec != "none" else None
        if self.strategy != "hierarchical":
            return wire_nbytes(x, live, channel_major=channel_major)
        logical = logical_nbytes(x)
        intra = 2 * (self.inner - 1) * logical // self.inner
        inter = wire_nbytes(x, live,
                            channel_major=channel_major) // self.inner
        return intra + inter

    def phases(self, codec: str = "none") -> Tuple[str, ...]:
        """The wire legs a dispatch under this plan comprises — attached
        to :class:`~synapseml_tpu.parallel.collectives.CollectiveTimeout`
        payloads so a watchdogged hierarchical leg names what it was
        executing instead of one opaque op name."""
        if self.strategy == "hierarchical":
            return ("intra_reduce_scatter@f32",
                    f"inter_allreduce@{codec}",
                    "intra_all_gather@f32")
        if self.strategy == "ring":
            return (f"ring_reduce_scatter@{codec if codec != 'none' else 'f32'}",
                    f"ring_all_gather@{codec if codec != 'none' else 'f32'}")
        if self.strategy == "tree":
            return (f"tree_exchange@{codec if codec != 'none' else 'f32'}",)
        if codec == "int8":
            return ("reduce_scatter@int8", "all_gather@int8")
        return (f"psum@{codec if codec != 'none' else 'f32'}",)

    # -- execution (trace-time jax; imports deferred so the planner is
    # importable driver-side without jax) --------------------------------

    def execute(self, x, axis, op: str = "planned_psum",
                record: bool = True):
        """``psum`` semantics under this plan's route.  ``flat``
        delegates verbatim to :func:`~synapseml_tpu.parallel.
        compression.compressed_psum` — byte-identical tracing to the
        pre-planner dispatch, by construction."""
        from .compression import compressed_psum
        if self.strategy == "flat":
            return compressed_psum(x, axis, self.config, op=op,
                                   record=record)
        import jax.numpy as jnp
        from .compression import (_channel_major_padded,
                                  _channel_major_padded_inv, _pad_to)
        codec = self.wire_codec(x.shape, x.dtype)
        if record:
            _record_routed(op, axis, x, self, codec)
        shape, orig_dtype = x.shape, x.dtype
        if codec == "none":
            # route at the input dtype (ints stay ints; addition is the
            # reduction either way — a detour through f32 would round
            # int payloads past 2^24)
            flat = x.reshape(-1)
            size = flat.shape[0]
            flat = _pad_to(flat, self.pad_unit(codec))
            total, _ = self.reduce_flat(flat, axis, codec, want_err=False)
            return total[:size].reshape(shape)
        # codec legs run f32 like compressed_psum; int8 chunks are laid
        # out channel-major so heterogeneous trailing channels (GBDT
        # grad/hess/count) never share a scale
        cm = codec == "int8"
        if cm:
            flat, per, per_p = _channel_major_padded(
                x.astype(jnp.float32), self.config.chunk)
        else:
            flat, per, per_p = x.astype(jnp.float32).reshape(-1), None, None
        size = flat.shape[0]
        flat = _pad_to(flat, self.pad_unit(codec))
        total, _ = self.reduce_flat(flat, axis, codec, want_err=False)
        total = total[:size]
        if cm:
            return _channel_major_padded_inv(total, shape, per,
                                             per_p).astype(orig_dtype)
        return total.reshape(shape).astype(orig_dtype)

    def reduce_flat(self, flat, axis, codec: str, want_err: bool = False):
        """Sum a padded flat stream over ``axis`` along this route →
        ``(total, err)``.

        ``err`` (only materialized when ``want_err``) is THIS rank's
        share of the wire quantization error, in the stream's
        coordinates — the error-feedback recursion's input.  The EF
        invariant is the SUM across ranks: for flat/ring codecs each
        rank keeps its own payload's error; for hierarchical each rank
        keeps the error of the intra-host shard it owned on the
        quantized inter-host leg (zero elsewhere), so
        ``sum_r err_r == total quantization error`` exactly — per-leaf
        error feedback composes unchanged.
        """
        import jax.numpy as jnp
        from jax import lax
        from .compression import (bf16_decode, bf16_encode, int8_all_gather,
                                  int8_decode, int8_encode,
                                  int8_reduce_scatter)
        from .collectives import _ring_core
        cfg = self.config
        n = self.world
        zeros = (lambda: jnp.zeros_like(flat)) if want_err else (lambda: None)

        if self.strategy == "hierarchical":
            return self._hier_reduce_flat(flat, axis, codec, want_err)

        if codec == "int8":
            # flat AND ring: the chunked int8 reduce-scatter +
            # all-gather IS the bandwidth-optimal ring schedule — the
            # 'ring' label names the route it already takes
            total = int8_all_gather(
                int8_reduce_scatter(flat, axis, cfg.chunk), axis, cfg.chunk)
            if want_err:
                err = flat - int8_decode(*int8_encode(flat, cfg.chunk))
                return total, err
            return total, None
        if codec == "bf16":
            enc = bf16_encode(flat)
            if self.strategy == "ring":
                total = bf16_decode(_ring_core(enc, axis, n))
            elif self.strategy == "tree":
                total = bf16_decode(self._tree_core(enc, axis))
            else:
                total = bf16_decode(lax.psum(enc, axis_name=axis))
            if want_err:
                return total, flat - bf16_decode(enc)
            return total, None
        # f32 / logical-dtype routes (lossless: err stays zero)
        if self.strategy == "ring":
            return _ring_core(flat, axis, n), zeros()
        if self.strategy == "tree":
            return self._tree_core(flat, axis), zeros()
        return lax.psum(flat, axis_name=axis), zeros()

    def _tree_core(self, v, axis):
        """Recursive-doubling allreduce: log2(world) pairwise
        exchange-and-add rounds (partner = rank XOR 2^k).  Every rank
        sums the same balanced tree shape (operand order differs only
        commutatively), so the result is replicated bit-identically."""
        from jax import lax
        n = self.world
        k = 1
        while k < n:
            perm = [(i, i ^ k) for i in range(n)]
            v = v + lax.ppermute(v, axis, perm=perm)
            k <<= 1
        return v

    def _groups(self):
        """Intra-host rank blocks + the transposed inter-host groups,
        carved by the same assignment core that places data partitions
        (:func:`~synapseml_tpu.parallel.placement.partition_assignment`
        — placement and reduction grouping cannot drift apart)."""
        from .placement import partition_assignment
        pm = partition_assignment(self.world, self.outer, strategy="block")
        intra = [pm.rank_to_partitions[h] for h in range(self.outer)]
        inter = [[intra[h][i] for h in range(self.outer)]
                 for i in range(self.inner)]
        return intra, inter

    def _hier_reduce_flat(self, flat, axis, codec: str, want_err: bool):
        """Two-level allreduce over one gang axis via grouped
        collectives: intra-host reduce-scatter in f32 (ICI), inter-host
        allreduce through the codec (DCN — the only leg that crosses
        hosts ships 1/inner of the payload, quantized), intra-host
        all-gather back in f32."""
        import jax.numpy as jnp
        from jax import lax
        from .compression import (bf16_decode, bf16_encode, int8_decode,
                                  int8_encode)
        intra, inter = self._groups()
        shard = lax.psum_scatter(flat, axis, scatter_dimension=0,
                                 tiled=True, axis_index_groups=intra)
        err_shard = None
        if codec == "int8":
            q, s = int8_encode(shard, self.config.chunk)
            qg = lax.all_gather(q, axis_name=axis, axis_index_groups=inter)
            sg = lax.all_gather(s, axis_name=axis, axis_index_groups=inter)
            total_shard = jnp.sum(
                qg.astype(jnp.float32) * sg[..., None], axis=0).reshape(-1)
            if want_err:
                err_shard = shard - int8_decode(q, s)
        elif codec == "bf16":
            enc = bf16_encode(shard)
            total_shard = bf16_decode(
                lax.psum(enc, axis_name=axis, axis_index_groups=inter))
            if want_err:
                err_shard = shard - bf16_decode(enc)
        else:
            total_shard = lax.psum(shard, axis_name=axis,
                                   axis_index_groups=inter)
        out = lax.all_gather(total_shard, axis_name=axis, tiled=True,
                             axis_index_groups=intra)
        if not want_err:
            return out, None
        if err_shard is None:
            return out, jnp.zeros_like(flat)
        # this rank owned shard (me % inner) of its host's sum on the
        # quantized leg: keep exactly that error, zero elsewhere —
        # summing residuals across the gang reproduces the total error
        me = lax.axis_index(axis)
        shard_len = flat.shape[0] // self.inner
        err = lax.dynamic_update_slice(
            jnp.zeros_like(flat), err_shard,
            ((me % self.inner) * shard_len,))
        return out, err


def _record_routed(op: str, axis, x, plan: "ReductionPlan",
                   codec: str) -> None:
    """Trace-time accounting for a routed (non-flat) collective: the
    plain calls/logical series plus the strategy-labeled wire series at
    the bytes the ROUTE really ships (:meth:`ReductionPlan.wire_nbytes`
    — codec='none' routes report wire == logical, hierarchical counts
    its intra-host f32 legs), so the per-strategy wire histogram in
    bench covers uncompressed routes too.  Telemetry must never break a
    trace."""
    try:
        from .collectives import _record
        from .compression import record_compressed
        _record(op, axis, x)            # collective_{calls,bytes}_total
        cm = codec == "int8"
        record_compressed(op, axis, x,
                          plan.config if codec != "none" else None,
                          channel_major=cm, strategy=plan.strategy,
                          codec=codec,
                          wire=plan.wire_nbytes(x, codec,
                                                channel_major=cm))
    except Exception:
        pass


class CollectivePlanner:
    """Process-global plan synthesizer + size-bucketed cache.

    Thread-safe.  The cache key is ``(payload bucket, world, config,
    spec, epoch)`` — every component hashable, exactly the jit-statics
    discipline, so a topology refresh (epoch bump) or a spec swap can
    never serve a stale route to a NEW trace."""

    def __init__(self, spec: Optional[TopologySpec] = None):
        self._lock = threading.RLock()
        self._injected = spec
        self._discovered: Optional[TopologySpec] = None
        self._discovery_failed = False
        self._epoch = 0
        self._plans: Dict[Tuple, ReductionPlan] = {}
        self._c_plans = get_registry().counter(
            "collective_plans_total",
            "reduction plans synthesized, by resolved strategy, decision "
            "reason and the cost model that priced the auto decision "
            "(fitted|spec|fallback)", ("strategy", "reason", "model"))
        #: resolved once per epoch: a measured α-β fit from the tuning
        #: table when one matches this device, else the spec-constant
        #: model (byte-identical decisions to the hardcoded cutoff)
        self._cost_model: Optional[Any] = None

    # -- topology ----------------------------------------------------------
    def spec(self) -> Optional[TopologySpec]:
        """The spec plans route by: the injected override when set, else
        a lazily discovered snapshot (None when discovery fails — e.g.
        planner used driver-side before jax initializes)."""
        with self._lock:
            if self._injected is not None:
                return self._injected
            if self._discovered is None and not self._discovery_failed:
                try:
                    self._discovered = discover_spec()
                except Exception:
                    self._discovery_failed = True
            return self._discovered

    def set_spec(self, spec: Optional[TopologySpec],
                 reason: str = "injected") -> None:
        """Inject (or with ``None`` clear) the topology override;
        invalidates every cached plan."""
        with self._lock:
            self._injected = spec
            self._invalidate(reason)

    def refresh(self, reason: str, world_size: Optional[int] = None) -> None:
        """The relaunch/resize hook: drop the discovered topology
        snapshot (next plan re-discovers) and every cached plan.  An
        injected spec survives — it is an explicit operator/test
        override, not a snapshot.  Records the invalidation in the
        fault call log and the flight ring so resize tests can pin
        that a resize really re-planned."""
        with self._lock:
            self._discovered = None
            self._discovery_failed = False
            self._invalidate(reason, world_size=world_size)

    def _invalidate(self, reason: str,
                    world_size: Optional[int] = None) -> None:
        dropped = len(self._plans)
        self._plans.clear()
        self._cost_model = None          # re-consult the table next plan
        self._epoch += 1
        get_faults().note("plan.refresh", reason=reason,
                          world_size=world_size, dropped_plans=dropped,
                          epoch=self._epoch)
        try:
            flight_record("plan_invalidate", reason=reason,
                          world_size=world_size, dropped_plans=dropped,
                          epoch=self._epoch)
        except Exception:
            pass

    # -- cost model --------------------------------------------------------
    def cost_model(self):
        """The :class:`~synapseml_tpu.telemetry.autotune.
        CollectiveCostModel` pricing this planner's 'auto' decisions:
        a measured α-β fit when the tuning table holds one for this
        device's link class, else the spec-constant model whose cutoff
        IS ``TREE_CUTOFF_BYTES`` (decisions byte-identical to the
        pre-model planner).  Resolved lazily, re-resolved after every
        :meth:`refresh`/:meth:`set_spec` epoch bump."""
        with self._lock:
            if self._cost_model is None:
                self._cost_model = _resolve_cost_model()
            return self._cost_model

    def set_cost_model(self, model):
        """Inject a cost model (tests) → the previous one; ``None``
        restores lazy table resolution at the next plan."""
        with self._lock:
            prev = self._cost_model
            self._cost_model = model
            return prev

    # -- planning ----------------------------------------------------------
    def cache_size(self) -> int:
        with self._lock:
            return len(self._plans)

    def epoch(self) -> int:
        with self._lock:
            return self._epoch

    def plan(self, payload_bytes: int, world: int, config,
             axis: str = "data", op: Optional[str] = None) -> ReductionPlan:
        """Resolve (and cache) the route for one payload class."""
        world = int(world)
        bucket = _bucket(payload_bytes)
        with self._lock:
            spec = None
            if config is not None and getattr(config, "strategy",
                                              "flat") != "flat":
                spec = self.spec()
            key = (bucket, world, config, spec, self._epoch)
            plan = self._plans.get(key)
            if plan is not None:
                return plan
            if self._cost_model is None:
                self._cost_model = _resolve_cost_model()
            strategy, reason, inner, model = _decide(
                payload_bytes, world, spec, config,
                cost_model=self._cost_model)
            plan = ReductionPlan(strategy=strategy, reason=reason,
                                 world=world, inner=inner,
                                 payload_bucket=bucket, config=config)
            self._plans[key] = plan
            self._c_plans.inc(1, strategy=strategy, reason=reason,
                              model=model)
        try:
            flight_record("plan_decide", strategy=strategy, reason=reason,
                          world=world, inner=inner,
                          payload_bucket=bucket, op=op, model=model,
                          codec=(config.compression if config is not None
                                 else "none"))
        except Exception:
            pass
        return plan

    def resolved_routing(self, config,
                         world: Optional[int] = None) -> str:
        """'flat' when every plan under this config is the flat
        dispatch — no config, ``strategy='flat'``, ``'auto'`` with no
        trusted topology (the default everywhere topology is unknown),
        or an EXPLICIT strategy whose structural preconditions fail so
        :func:`_decide` falls back to flat anyway ('hierarchical'
        without a trusted multi-host topology, 'tree' on a non-pow2
        world, any route at world 1) — else the config's strategy
        field.  The checkpoint guards key on THIS, so pre-planner
        checkpoints (no strategy recorded) resume freely under default
        configs, a real routing switch refuses loudly, and a stamp can
        never name a route the sync didn't run (a 'hierarchical'
        request that actually synced flat must not poison resume on a
        cluster where it WOULD route).  Pass ``world`` (the fit's mesh
        size) where known — both checkpoint guards do; without it the
        hierarchical divisibility check falls back to the spec's own
        world and the tree pow2 check is skipped (tree needs no
        topology, so there is nothing to fall back to)."""
        if config is None:
            return "flat"
        s = getattr(config, "strategy", "flat")
        if s == "flat":
            return "flat"
        if world is not None and int(world) <= 1:
            return "flat"
        if s in ("auto", "hierarchical"):
            spec = self.spec()
            if spec is None or not spec.trusted:
                return "flat"
            if s == "hierarchical":
                inner = spec.devices_per_host
                w = int(world) if world is not None else spec.world
                if not (spec.multi_host and 1 <= inner < w
                        and w % inner == 0):
                    return "flat"
        if s == "tree":
            w = int(world) if world is not None else None
            if w is not None and not _is_pow2(w):
                return "flat"
        return s


def _resolve_cost_model():
    """The planner's cost model: a measured α-β fit when the tuning
    table holds one for this device's ICI link class (honesty: the fit
    was recorded from real watched-dispatch timings on a matching
    ``device_kind``), else :meth:`CollectiveCostModel.spec` whose
    cutoff is exactly ``TREE_CUTOFF_BYTES`` — no table, byte-identical
    decisions.  Never raises (planning must not break on a torn table
    or an import cycle during teardown)."""
    try:
        from ..telemetry.autotune import (COST_MODEL_GEOMETRY,
                                          COST_MODEL_SPACE,
                                          CollectiveCostModel)
        from ..telemetry.tunetable import get_tuneplane

        def _gate(w):
            a, b = w.get("alpha_s"), w.get("beta_s_per_byte")

            def num(v):
                return (isinstance(v, (int, float))
                        and not isinstance(v, bool)
                        and math.isfinite(v))

            return num(a) and num(b) and a >= 0.0 and b > 0.0

        won = get_tuneplane().consult(
            "CollectivePlanner", COST_MODEL_SPACE, COST_MODEL_GEOMETRY,
            validate=_gate)
        if won is not None:
            return CollectiveCostModel(
                alpha_s=float(won["alpha_s"]),
                beta_s_per_byte=float(won["beta_s_per_byte"]),
                source="fitted")
        return CollectiveCostModel.spec(TREE_CUTOFF_BYTES)
    except Exception:
        return None


def _decide(payload_bytes: int, world: int,
            spec: Optional[TopologySpec], config, cost_model=None):
    """The decision table → ``(strategy, reason, inner, model)``.

    Structural rules over payload bytes × world size × link class.
    The ONE numeric threshold — the 'auto' tree-vs-ring payload
    crossover — routes through ``cost_model.tree_cutoff_bytes(world)``:
    a measured α-β fit when the tuning table holds one (``model=
    'fitted'``), else the spec-constant model whose cutoff is the
    hardcoded ``TREE_CUTOFF_BYTES`` (``model='spec'``, decisions
    byte-identical to the pre-model planner).  Paths that consult no
    cost model at all — forced strategies, single rank, unknown
    topology — label ``model='fallback'``: unknown topology still
    plans flat and nothing is ever priced from fabricated numbers."""
    requested = getattr(config, "strategy", "flat") if config is not None \
        else "flat"
    if requested == "flat":
        return "flat", "forced", world, "fallback"
    if world <= 1:
        return "flat", "single_rank", world, "fallback"
    known = spec is not None and spec.trusted
    inner = spec.devices_per_host if known else world
    hier_ok = (known and spec.multi_host and 1 <= inner < world
               and world % inner == 0)
    if requested == "ring":
        return "ring", "forced", world, "fallback"
    if requested == "tree":
        if _is_pow2(world):
            return "tree", "forced", world, "fallback"
        return "flat", "non_pow2_world", world, "fallback"
    if requested == "hierarchical":
        if hier_ok:
            return "hierarchical", "forced", inner, "fallback"
        return "flat", ("no_topology" if not known
                        else "indivisible_world"), world, "fallback"
    if requested != "auto":
        raise ValueError(f"strategy={requested!r}: must be one of "
                         f"{STRATEGIES}")
    # -- auto --------------------------------------------------------------
    if not known:
        return "flat", "unknown_topology", world, "fallback"
    cutoff, mlabel = TREE_CUTOFF_BYTES, "spec"
    if cost_model is not None:
        try:
            cutoff = cost_model.tree_cutoff_bytes(world)
            mlabel = cost_model.source
        except Exception:
            cutoff, mlabel = TREE_CUTOFF_BYTES, "spec"
    if payload_bytes <= cutoff:
        if _is_pow2(world):
            return "tree", "latency_bound", world, mlabel
        return "flat", "non_pow2_world", world, mlabel
    compresses_here = (config is not None and config.compresses
                       and payload_bytes >= config.min_size * 4)
    if hier_ok and compresses_here:
        return "hierarchical", "multi_host_codec", inner, mlabel
    if hier_ok:
        return "hierarchical", "multi_host", inner, mlabel
    return "ring", "bandwidth_bound", world, mlabel


_default_planner = CollectivePlanner()
_planner_lock = threading.Lock()


def get_planner() -> CollectivePlanner:
    """The process-wide planner every dispatch plans through."""
    return _default_planner


def set_planner(planner: CollectivePlanner) -> CollectivePlanner:
    """Swap the process planner (tests) → the previous one."""
    global _default_planner
    with _planner_lock:
        prev = _default_planner
        _default_planner = planner
        return prev


def planned_psum(x, axis: Optional[str], config,
                 op: str = "compressed_psum", record: bool = True):
    """The planner-routed ``psum``: resolve a :class:`ReductionPlan` for
    this payload (trace-time; shapes and the axis size are static under
    shard_map tracing) and execute it.  ``config=None`` — no policy at
    all — bypasses the planner and traces exactly as
    :func:`~synapseml_tpu.parallel.compression.compressed_psum` always
    has, as does any plan that resolves ``flat``."""
    if axis is None:
        return x
    from .compression import compressed_psum
    if config is None:
        return compressed_psum(x, axis, None, op=op, record=record)
    if getattr(config, "strategy", "flat") == "flat":
        return compressed_psum(x, axis, config, op=op, record=record)
    import numpy as np
    from jax import lax
    world = int(lax.axis_size(axis))
    nbytes = int(np.prod(x.shape)) * np.dtype(x.dtype).itemsize
    plan = get_planner().plan(nbytes, world, config, axis=str(axis), op=op)
    return plan.execute(x, axis, op=op, record=record)
