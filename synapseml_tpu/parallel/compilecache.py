"""Persistent XLA compilation cache + compile attribution.

Two halves of the compile plane's substrate (the serving-side lattice
warmup lives in :mod:`synapseml_tpu.models.llm.warmup`; this module is
workload-agnostic — the DL/GBDT training steps reuse cached artifacts
through the same knob):

- **persistent cache** — :func:`enable_compilation_cache` wires
  ``jax_compilation_cache_dir`` (plus the min-size/min-time thresholds,
  floored so even this CPU container's sub-second programs land in the
  cache) so a relaunched or resized gang re-loads compiled executables
  from disk instead of re-running XLA.  The directory threads through
  :class:`~synapseml_tpu.parallel.supervisor.GangSupervisor` to every
  worker as ``SMLTPU_COMPILE_CACHE_DIR``; workers call
  :func:`enable_from_env` before their task compiles anything.

- **attribution** — :func:`install_compile_listeners` registers
  ``jax.monitoring`` listeners once per process: every backend compile
  lands in the ``llm_compile_seconds{program}`` histogram (labelled by
  the thread's current :func:`compile_label`, ``unattributed``
  otherwise) and the ``xla_compiles_total{program}`` counter; the
  persistent cache's own hit/miss events land in
  ``xla_compile_cache_hits_total`` / ``xla_compile_cache_misses_total``
  — so "how long did this replica spend in XLA, on which program, and
  did the cache help" is answerable from ``/metrics`` alone.

Everything degrades to a no-op when the running jax predates an API
(monitoring, a cache threshold option): the plane loses attribution or
cache coverage, never correctness.
"""

from __future__ import annotations

import contextlib
import os
import threading
from typing import Dict, Iterator, Optional

from ..telemetry import get_registry

__all__ = [
    "COMPILE_CACHE_ENV", "cache_stats", "compile_label",
    "enable_compilation_cache", "enable_from_env",
    "install_compile_listeners",
]

#: env var carrying the persistent compilation cache directory to every
#: gang worker (the ``SMLTPU_CKPT_DIR`` idiom)
COMPILE_CACHE_ENV = "SMLTPU_COMPILE_CACHE_DIR"

#: the jax.monitoring event one backend (XLA) compile emits
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
#: persistent-cache verdict events (one per cacheable compile request)
_CACHE_HIT_EVENT = "/jax/compilation_cache/cache_hits"
_CACHE_MISS_EVENT = "/jax/compilation_cache/cache_misses"

#: histogram buckets for compile durations: CPU-container programs sit
#: in the 10ms-1s decades, real TPU serving programs in the 1-100s ones
_COMPILE_SECONDS_BUCKETS = (0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0,
                            100.0, 300.0)

_lock = threading.Lock()
_listeners_installed = False
_cache_dir: Optional[str] = None
#: thread-local compile attribution label (see :func:`compile_label`)
_tls = threading.local()
#: process-wide raw tallies, readable without the registry (the bench
#: children and the gang cache-reuse pin read these)
_counts = {"compiles": 0, "cache_hits": 0, "cache_misses": 0}


def current_label() -> str:
    return getattr(_tls, "label", None) or "unattributed"


@contextlib.contextmanager
def compile_label(label: str) -> Iterator[None]:
    """Attribute any backend compile on THIS thread inside the block to
    ``label`` (nests; the innermost label wins) — the warmup lattice and
    the engine's step dispatch wrap their jitted calls in this so
    ``llm_compile_seconds{program}`` names the program that compiled."""
    prev = getattr(_tls, "label", None)
    _tls.label = label
    try:
        yield
    finally:
        _tls.label = prev


def install_compile_listeners() -> bool:
    """Register the process-wide jax.monitoring listeners (idempotent).
    Returns False when this jax has no monitoring API — attribution is
    lost, nothing else."""
    global _listeners_installed
    with _lock:
        if _listeners_installed:
            return True
        try:
            from jax import monitoring
        except Exception:  # noqa: BLE001 — jax too old / stripped
            return False
        reg = get_registry()
        h_seconds = reg.histogram(
            "llm_compile_seconds",
            "backend (XLA) compile seconds per compiled program, "
            "labelled by the compile plane's program key "
            "(unattributed: a compile outside any labelled region)",
            ("program",), buckets=_COMPILE_SECONDS_BUCKETS)
        c_compiles = reg.counter(
            "xla_compiles_total", "backend (XLA) compiles run by this "
            "process", ("program",))
        c_hits = reg.counter(
            "xla_compile_cache_hits_total",
            "compile requests served from the persistent compilation "
            "cache", ())
        c_misses = reg.counter(
            "xla_compile_cache_misses_total",
            "compile requests the persistent compilation cache could "
            "not serve (compiled then stored)", ())

        def on_duration(event: str, duration: float, **kw) -> None:
            if event != _COMPILE_EVENT:
                return
            label = current_label()
            _counts["compiles"] += 1
            h_seconds.observe(duration, program=label)
            c_compiles.inc(1, program=label)

        def on_event(event: str, **kw) -> None:
            if event == _CACHE_HIT_EVENT:
                _counts["cache_hits"] += 1
                c_hits.inc(1)
            elif event == _CACHE_MISS_EVENT:
                _counts["cache_misses"] += 1
                c_misses.inc(1)

        try:
            monitoring.register_event_duration_secs_listener(on_duration)
            monitoring.register_event_listener(on_event)
        except Exception:  # noqa: BLE001 — listener API drift
            return False
        _listeners_installed = True
        return True


def cache_stats() -> Dict[str, int]:
    """Raw process tallies: ``compiles`` / ``cache_hits`` /
    ``cache_misses`` (zeros until :func:`install_compile_listeners` —
    which every enable path runs — has been called)."""
    return dict(_counts)


def compilation_cache_dir() -> Optional[str]:
    """The directory this process enabled, or None."""
    return _cache_dir


def enable_compilation_cache(cache_dir: str) -> bool:
    """Point jax's persistent compilation cache at ``cache_dir`` and
    floor the entry thresholds so every program caches (XLA's defaults
    skip sub-second compiles — exactly the CPU-container regime, and
    pointless filtering on TPU where the multi-second programs dominate
    anyway).  Installs the attribution listeners as a side effect.
    Idempotent per process; returns False (cache off, process fine)
    when this jax has no persistent-cache support."""
    global _cache_dir
    install_compile_listeners()
    try:
        import jax
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        for opt, val in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                         ("jax_persistent_cache_min_entry_size_bytes", -1)):
            try:
                jax.config.update(opt, val)
            except Exception:  # noqa: BLE001 — older jax: coarser cache
                pass
        # jax latches the cache state at the FIRST compile: a process
        # that already compiled anything before this call (an engine
        # constructed, then the knob turned on) has the cache pinned
        # "disabled" and ignores the config update — reset so the next
        # compile re-initializes against the new dir.  Private API,
        # best-effort: without it, only enable-before-first-compile
        # processes (the worker path) get the cache.
        try:
            from jax._src import compilation_cache as _jcc
            _jcc.reset_cache()
        except Exception:  # noqa: BLE001
            pass
    except Exception:  # noqa: BLE001 — no jax / no cache support
        return False
    with _lock:
        _cache_dir = str(cache_dir)
    try:
        from ..telemetry.flight import record as flight_record
        flight_record("compile_cache", dir=str(cache_dir))
    except Exception:  # noqa: BLE001 — flight is advisory
        pass
    return True


def enable_from_env() -> Optional[str]:
    """Worker-side: enable the cache when the supervisor threaded
    ``SMLTPU_COMPILE_CACHE_DIR`` through (returns the dir), else just
    install the attribution listeners (returns None)."""
    cache_dir = os.environ.get(COMPILE_CACHE_ENV)
    if cache_dir:
        return cache_dir if enable_compilation_cache(cache_dir) else None
    install_compile_listeners()
    return None
