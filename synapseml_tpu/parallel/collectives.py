"""Collective-communication wrappers over the mesh.

The single allreduce stack replacing: LightGBM's native socket ring
(``LGBM_NetworkInit`` + in-C++ histogram allreduce, reference:
NetworkManager.scala:182-205), VW's spanning-tree AllReduce
(VowpalWabbitClusterUtil.scala:16-40) and Horovod's NCCL/Gloo
(dl/utils.py:31-46).  Everything is an XLA collective over ICI/DCN inside
jit — no sockets, no coordinator processes.

Use inside ``shard_map``/``pjit`` bodies with the axis names from
:mod:`synapseml_tpu.parallel.mesh`.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Callable, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ..resilience.faults import get_faults
from ..telemetry import get_registry
from ..telemetry.flight import record as flight_record
from ..telemetry.gangplane import observe_collective
from .mesh import DATA_AXIS


class CollectiveTimeout(RuntimeError):
    """A host-dispatched collective (or the cluster rendezvous) blocked
    past its deadline.

    A rank stuck in an allreduce whose peer died would otherwise freeze
    silently until the gang's global timeout; this converts the freeze
    into a structured failure carrying enough to diagnose it — the op,
    the mesh axis, the per-shard payload, the deadline that expired,
    and (for planner-routed dispatches) the ROUTE: the resolved
    strategy plus the wire phases the compiled program comprises, so a
    watchdogged hierarchical leg names what it was executing
    (``intra_reduce_scatter@f32 | inter_allreduce@int8 | ...``) instead
    of one opaque op name — and the gang supervisor treats it as a
    whole-gang failure (the blocked native dispatch itself cannot be
    cancelled; the raising process exits and the supervisor
    relaunches)."""

    def __init__(self, op: str, axis, timeout_s: float,
                 payload_bytes: Optional[int] = None,
                 strategy: Optional[str] = None,
                 phases: Optional[Sequence[str]] = None):
        extra = (f", {payload_bytes} payload bytes"
                 if payload_bytes is not None else "")
        route = ""
        if strategy is not None:
            route = f" [strategy={strategy}"
            if phases:
                route += " phases=" + " | ".join(phases)
            route += "]"
        super().__init__(
            f"collective {op!r} over axis {axis!r} still blocked after "
            f"{timeout_s:.3f}s{extra}{route}")
        self.op = op
        self.axis = str(axis)
        self.timeout_s = float(timeout_s)
        self.payload_bytes = payload_bytes
        self.strategy = strategy
        self.phases = tuple(phases) if phases else None


class _ShapeOnly:
    """A shape/dtype stand-in leaf for byte accounting — lets the host
    wrapper account S copies of the per-shard LOCAL layout without
    materializing them (``wire_nbytes``/``logical_nbytes`` read only
    ``shape``/``size``/``dtype``)."""
    __slots__ = ("shape", "size", "dtype")

    def __init__(self, shape, dtype):
        self.shape = tuple(shape)
        self.size = int(np.prod(self.shape)) if self.shape else 1
        self.dtype = dtype


def _payload_bytes(x, config=None, channel_major: bool = False) -> int:
    """Per-shard bytes the op actually moves: WIRE bytes when a
    compression config is in play, logical dtype bytes otherwise (the
    pre-codec behavior assumed logical size for every op, which
    double-counted compressed payloads and mis-ranked codecs in
    /metrics and flight events)."""
    from .compression import logical_nbytes, wire_nbytes
    if config is not None and config.compresses:
        return wire_nbytes(x, config, channel_major=channel_major)
    return logical_nbytes(x)


def dispatch_watchdog(fn: Callable, *args, op: str, axis=DATA_AXIS,
                      deadline=None, timeout_s: Optional[float] = None,
                      payload_bytes: Optional[int] = None,
                      codec: str = "none",
                      logical_bytes: Optional[int] = None,
                      strategy: Optional[str] = None,
                      phases: Optional[Sequence[str]] = None, **kw):
    """Run a blocking dispatch under a host-side watchdog timer.

    ``deadline`` (a :class:`~synapseml_tpu.resilience.Deadline`) and/or
    ``timeout_s`` bound the wait; with neither, the call runs inline
    (zero overhead — no thread).  On expiry the caller gets a
    :class:`CollectiveTimeout` and ``collective_timeouts_total{op,axis}``
    ticks; the worker thread stays parked on the un-cancellable native
    call (daemon — it dies with the process, which is the supervisor's
    next move anyway).

    The ``collective.dispatch`` fault site fires INSIDE the watched
    thread, so an armed ``hang`` rule wedges the dispatch exactly where
    a lost peer would.
    """
    # compressed ops tag their flight events with the codec and BOTH
    # byte counts (``nbytes`` is what moved on the wire, ``logical_nbytes``
    # what it represents); planner-routed ops additionally carry the
    # resolved strategy; the bare "none" path emits the identical event
    # payload it always did
    extra = ({"codec": codec, "logical_nbytes": logical_bytes}
             if codec != "none" else {})
    if strategy is not None and strategy != "flat":
        extra["strategy"] = strategy
    seg_strategy = strategy or "flat"
    if deadline is not None:
        timeout_s = deadline.limit(timeout_s)
    if timeout_s is None:
        flight_record("collective.begin", op=op, axis=str(axis),
                      nbytes=payload_bytes, **extra)
        get_faults().raise_point("collective.dispatch", op=op,
                                 axis=str(axis))
        t0 = time.perf_counter()
        out = fn(*args, **kw)
        dt = time.perf_counter() - t0
        flight_record("collective.end", op=op, axis=str(axis),
                      nbytes=payload_bytes, seconds=round(dt, 6), **extra)
        observe_collective(dt, payload_bytes or 0, strategy=seg_strategy)
        return out
    box: dict = {}
    done = threading.Event()

    def _run():
        try:
            get_faults().raise_point("collective.dispatch", op=op,
                                     axis=str(axis))
            box["value"] = fn(*args, **kw)
        except BaseException as e:      # surfaced on the caller's thread
            box["error"] = e
        finally:
            done.set()

    flight_record("collective.begin", op=op, axis=str(axis),
                  nbytes=payload_bytes, timeout_s=float(timeout_s), **extra)
    t0 = time.perf_counter()
    t = threading.Thread(target=_run, daemon=True,
                         name=f"collective-{op}")
    t.start()
    if not done.wait(timeout=max(0.0, float(timeout_s))):
        get_registry().counter(
            "collective_timeouts_total",
            "host-dispatched collectives that blocked past their "
            "deadline", ("op", "axis")).inc(1, op=op, axis=str(axis))
        flight_record("collective.timeout", op=op, axis=str(axis),
                      nbytes=payload_bytes, timeout_s=float(timeout_s),
                      **extra)
        raise CollectiveTimeout(op, axis, float(timeout_s),
                                payload_bytes=payload_bytes,
                                strategy=strategy, phases=phases)
    dt = time.perf_counter() - t0
    if "error" in box:
        # failed collectives leave the `begin` unpaired, matching the
        # inline leg — a paired `end` means the op completed
        raise box["error"]
    flight_record("collective.end", op=op, axis=str(axis),
                  nbytes=payload_bytes, seconds=round(dt, 6), **extra)
    observe_collective(dt, payload_bytes or 0, strategy=seg_strategy)
    return box["value"]


def _record(op: str, axis, x, config=None, channel_major: bool = False,
            strategy: str = "flat") -> None:
    """EQuARX-style per-collective accounting (arXiv:2506.17615): count +
    payload bytes per (op, axis) into the process metrics registry.
    ``collective_bytes_total`` stays LOGICAL bytes (the signal the op
    reduces); compressed ops additionally land their WIRE bytes +
    compression ratio via :func:`~synapseml_tpu.parallel.compression.
    record_compressed` so codecs rank correctly in /metrics.

    These wrappers run under jit TRACING, so for compiled code each
    series counts collectives per traced program, weighted by the
    per-shard payload the op moves — the number that answers "how many
    bytes does this step's program hand to the ICI" — not per execution.
    Telemetry must never break a trace, hence the blanket except."""
    try:
        from .compression import logical_nbytes, record_compressed
        nbytes = logical_nbytes(x)
        reg = get_registry()
        labels = dict(op=op, axis=str(axis))
        reg.counter("collective_calls_total",
                    "collective ops traced, by op and mesh axis",
                    ("op", "axis")).inc(1, **labels)
        reg.counter("collective_bytes_total",
                    "per-shard LOGICAL payload bytes handed to "
                    "collectives, by op and mesh axis", ("op", "axis")).inc(
                        nbytes, **labels)
        if config is not None and config.compresses:
            record_compressed(op, axis, x, config,
                              channel_major=channel_major,
                              strategy=strategy)
    except Exception:
        pass


def psum(x, axis: str = DATA_AXIS):
    _record("psum", axis, x)
    return lax.psum(x, axis_name=axis)


def pmean(x, axis: str = DATA_AXIS):
    _record("pmean", axis, x)
    return lax.pmean(x, axis_name=axis)

def pmax(x, axis: str = DATA_AXIS):
    _record("pmax", axis, x)
    return lax.pmax(x, axis_name=axis)


def pmin(x, axis: str = DATA_AXIS):
    _record("pmin", axis, x)
    return lax.pmin(x, axis_name=axis)


def all_gather(x, axis: str = DATA_AXIS, *, tiled: bool = False):
    _record("all_gather", axis, x)
    return lax.all_gather(x, axis_name=axis, tiled=tiled)


def reduce_scatter(x, axis: str = DATA_AXIS, *, scatter_dimension: int = 0):
    _record("reduce_scatter", axis, x)
    return lax.psum_scatter(x, axis_name=axis,
                            scatter_dimension=scatter_dimension, tiled=True)


def ppermute(x, perm: Sequence[tuple], axis: str = DATA_AXIS):
    _record("ppermute", axis, x)
    return lax.ppermute(x, axis_name=axis, perm=list(perm))


def ring_shift(x, axis: str = DATA_AXIS, *, reverse: bool = False):
    """Send to the next rank on the ring (the ring-attention building block)."""
    _record("ring_shift", axis, x)
    n = lax.axis_size(axis)
    if reverse:
        perm = [(i, (i - 1) % n) for i in range(n)]
    else:
        perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: str = DATA_AXIS):
    return lax.axis_index(axis)


def barrier(x, axis: str = DATA_AXIS):
    """Gang sync inside a mapped computation — the
    ``BarrierTaskContext.barrier()`` analogue (NetworkManager.scala:150-156).

    Returns ``x`` data-dependent on a cross-replica collective, so XLA cannot
    reorder work on ``x`` before the sync or dead-code-eliminate the
    collective (a bare unused psum would be DCE'd)."""
    _record("barrier", axis, jnp.ones((), jnp.int32))
    token = lax.psum(jnp.ones((), jnp.int32), axis_name=axis)
    gated, _ = lax.optimization_barrier((x, token))
    return gated


def shard_map_over(mesh: Mesh, in_specs, out_specs,
                   check_vma: bool = False) -> Callable:
    """Decorator: shard_map a function over ``mesh`` with the given specs."""
    def wrap(fn):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    return wrap


def ring_allreduce(x, axis: str = DATA_AXIS):
    """Explicit bandwidth-optimal ring allreduce: reduce-scatter around the
    ring then all-gather back, each step moving 1/n of the payload to the
    next neighbor — the algorithm LightGBM's socket ring implements in C++
    (the native allreduce behind LGBM_NetworkInit, NetworkManager.scala:188)
    and the schedule XLA itself lowers ``psum`` to on a 1-D link.  Exposed
    explicitly for (a) parity tests pinning our semantics to the
    reference's, and (b) composing with compute between the 2(n-1) steps
    (latency hiding) where a monolithic psum could not.

    ``x``: equal-shape per-rank value whose leading dim is divisible by the
    axis size.  Returns the SUM over ranks, replicated (== lax.psum).
    """
    _record("ring_allreduce", axis, x)
    return _ring_core(x, axis, int(lax.axis_size(axis)))


def _ring_core(x, axis, n: int):
    """The unrecorded ring schedule :func:`ring_allreduce` documents —
    shared with the collective planner's ``ring`` strategy
    (:mod:`~synapseml_tpu.parallel.planner`), which does its own
    strategy-labeled accounting."""
    if n == 1:
        return x
    me = lax.axis_index(axis)
    parts = jnp.stack(jnp.split(x, n, axis=0))         # (n, chunk, ...)
    to_next = [(i, (i + 1) % n) for i in range(n)]

    # reduce-scatter: after n-1 steps rank r owns the full sum of part
    # (r+1) mod n
    def rs_step(s, acc):
        # send the partial we just finished accumulating
        idx = (me - s) % n
        sending = acc[idx]
        received = lax.ppermute(sending, axis_name=axis, perm=to_next)
        return acc.at[(me - s - 1) % n].add(received)

    acc = lax.fori_loop(0, n - 1, rs_step, parts)
    own = (me + 1) % n

    # all-gather: circulate each finished part the rest of the way round
    def ag_step(s, st):
        acc, moving = st
        received = lax.ppermute(moving, axis_name=axis, perm=to_next)
        acc = acc.at[(own - s - 1) % n].set(received)
        return acc, received

    acc, _ = lax.fori_loop(0, n - 1, ag_step, (acc, acc[own]))
    return jnp.concatenate(list(acc), axis=0)


def hierarchical_psum(x, inner_axis: str, outer_axis: str):
    """Two-level allreduce for multi-slice meshes: reduce-scatter over the
    fast ``inner_axis`` (ICI within a slice), psum the 1/n-sized shard over
    the slow ``outer_axis`` (DCN between slices), then all-gather back over
    ICI — cross-DCN traffic shrinks by the inner axis size versus a flat
    psum over both axes.  Leading dim must divide the inner axis size.
    Returns the global sum, replicated on both axes (== psum over both)."""
    _record("hierarchical_psum", f"{inner_axis}+{outer_axis}", x)
    scattered = lax.psum_scatter(x, axis_name=inner_axis,
                                 scatter_dimension=0, tiled=True)
    scattered = lax.psum(scattered, axis_name=outer_axis)
    return lax.all_gather(scattered, axis_name=inner_axis, tiled=True)


def tree_psum_bucketed(tree, axis: str = DATA_AXIS,
                       bucket_bytes: int = 4 << 20):
    """psum a pytree (gradients) in size-bucketed fusion groups: leaves are
    packed into ~``bucket_bytes`` flat buffers so small tensors ride one
    collective (latency-bound regime) while huge ones keep their own
    (bandwidth-bound regime) — Horovod's tensor-fusion strategy
    (the NCCL path behind dl/utils.py:31-46) expressed in XLA."""
    _record("tree_psum_bucketed", axis, tree)
    leaves, treedef = jax.tree.flatten(tree)
    # buckets are per-dtype so the fused buffer sums at each leaf's OWN
    # precision — a float32 detour would silently round f64/int leaves
    buckets: list = []
    cur: list = []
    cur_bytes = 0
    cur_dtype = None
    for i, leaf in enumerate(leaves):
        nbytes = leaf.size * leaf.dtype.itemsize
        if cur and (cur_bytes + nbytes > bucket_bytes
                    or leaf.dtype != cur_dtype):
            buckets.append(cur)
            cur, cur_bytes = [], 0
        cur.append(i)
        cur_bytes += nbytes
        cur_dtype = leaf.dtype
    if cur:
        buckets.append(cur)
    out = list(leaves)
    for bucket in buckets:
        if len(bucket) == 1:
            i = bucket[0]
            out[i] = lax.psum(leaves[i], axis_name=axis)
            continue
        flat = jnp.concatenate([leaves[i].reshape(-1) for i in bucket])
        summed = lax.psum(flat, axis_name=axis)
        offset = 0
        for i in bucket:
            size = leaves[i].size
            out[i] = summed[offset:offset + size].reshape(leaves[i].shape)
            offset += size
    return jax.tree.unflatten(treedef, out)


def allreduce_fn(mesh: Mesh, axis: str = DATA_AXIS,
                 config=None) -> Callable:
    """jitted allreduce over the data axis: input is per-rank values stacked
    on dim 0 (shape (num_ranks, *H)), output is their sum (shape (*H)).
    The LightGBM histogram-allreduce replacement.

    ``config`` (a :class:`~synapseml_tpu.parallel.compression.
    CollectiveConfig`) selects the wire codec: the reduce runs as the
    compressed :func:`~synapseml_tpu.parallel.compression.
    compressed_psum`, and every metric/flight event reports WIRE bytes
    with the codec attached (``None``/"none" keeps today's f32 path and
    event payloads byte-identical).

    The returned callable is host-dispatched (unlike the in-jit wrappers
    above), so each call ALSO lands one sample in the
    ``collective_latency_seconds`` histogram — dispatch latency under
    async execution, true op latency when the caller synchronizes.

    Hang-proofing: pass ``deadline=`` (a :class:`~synapseml_tpu.
    resilience.Deadline`) or ``timeout_s=`` per call and an
    indefinitely-blocked dispatch raises :class:`CollectiveTimeout`
    instead of freezing the rank (see :func:`dispatch_watchdog`)."""
    from .compression import codec_eligible, record_compressed
    from .planner import planned_psum
    compresses = config is not None and config.compresses
    codec = config.compression if compresses else "none"

    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=P(axis), out_specs=P())
    def _allreduce(x):
        # x.sum(0) handles both one and several stacked values per shard.
        # record=False: the host wrapper below accounts this op once
        # (per call, on the full stacked payload) — recording the
        # traced inner reduce too would double-count the series.
        # planned_psum resolves the route at trace time; config=None and
        # strategy-flat configs delegate to the exact pre-planner
        # dispatch (compressed_psum / bare lax.psum), byte-identically.
        local = x.sum(0)
        return planned_psum(local, axis, config, op="allreduce_fn",
                            record=False)

    latency = get_registry().histogram(
        "collective_latency_seconds",
        "host-observed latency of host-dispatched collectives",
        ("op", "axis"))
    #: payload signature -> ReductionPlan (or None), resolved at the
    #: FIRST dispatch of each signature — exactly when jit traces it —
    #: and pinned, so the host labels keep naming the route the
    #: already-compiled program runs even after a planner refresh or
    #: set_spec re-routes plans for signatures not yet traced
    plans: dict = {}

    @functools.wraps(_allreduce)
    def timed(x, *, deadline=None, timeout_s=None):
        # codec accounting shares the traced compressed_psum's
        # eligibility predicate: the codec applies to the locally summed
        # (*H,) payload, so a stacked input whose inner size is below
        # min_size (or non-float) really reduces in f32 and must be
        # reported that way — not as int8 wire that never existed
        inner = getattr(x, "shape", ())[1:]
        dtype = getattr(x, "dtype", jnp.float32)
        # the planner resolves the ROUTE the traced body takes for this
        # payload class — same planner, same cache key as the traced
        # planned_psum, resolved once per payload signature (the jit
        # cache key) and pinned in ``plans``, so the host-side labels
        # (strategy on metrics, flight events, StepProfiler segment,
        # CollectiveTimeout phases) name the route the compiled program
        # really runs even after a mid-life planner refresh/set_spec
        sig = (tuple(getattr(x, "shape", ())), str(np.dtype(dtype)))
        if sig in plans:
            plan = plans[sig]
        else:
            plan = None
            if config is not None and config.strategy != "flat":
                from .planner import get_planner
                nbytes = (int(np.prod(inner)) if inner else 1) \
                    * np.dtype(dtype).itemsize
                plan = get_planner().plan(nbytes, int(mesh.shape[axis]),
                                          config, axis=str(axis),
                                          op="allreduce_fn")
            plans[sig] = plan
        routed = plan is not None and plan.strategy != "flat"
        strategy = plan.strategy if routed else "flat"
        active = codec_eligible(inner, dtype, config)
        # a routed plan may demote the codec for its route (tree runs
        # latency-bound payloads at the logical dtype)
        eff_codec = (plan.wire_codec(tuple(inner), dtype) if routed
                     else (codec if active else "none"))
        wire_active = eff_codec != "none"
        # the traced compressed_psum lays the ndim>=2 LOCAL (*H) out
        # channel-major (per-channel chunk padding), so the stacked
        # account is S x the padded local — padding the stacked array
        # itself would miscount the pad bytes the wire really ships
        cm = len(inner) >= 2
        if wire_active:
            S = int(getattr(x, "shape", (1,))[0])
            payload = [_ShapeOnly(inner, dtype)] * S
        else:
            payload = x
        if routed:
            # calls/logical series, then the strategy-labeled wire
            # series at the codec and bytes the route REALLY ships
            # (uncompressed routes land wire == logical so the
            # per-strategy wire histogram covers f32 routes too;
            # hierarchical counts its intra-host f32 legs — see
            # ReductionPlan.wire_nbytes)
            wire = plan.wire_nbytes(payload, eff_codec,
                                    channel_major=cm)
            _record("allreduce_fn", axis, payload)
            record_compressed("allreduce_fn", axis, payload,
                              config if wire_active else None,
                              channel_major=cm, strategy=strategy,
                              codec=eff_codec, wire=wire)
        else:
            _record("allreduce_fn", axis, payload,
                    config=config if wire_active else None,
                    channel_major=cm, strategy=strategy)
            wire = _payload_bytes(payload,
                                  config if wire_active else None,
                                  channel_major=cm)
        extra = ({"codec": eff_codec, "logical_nbytes": _payload_bytes(x)}
                 if wire_active else {})
        if routed:
            extra["strategy"] = strategy
        t0 = time.perf_counter()
        if deadline is None and timeout_s is None:
            out = _allreduce(x)
            # host-observed dispatch latency feeds the open train step's
            # collective segment + the flight ring (the watched leg below
            # goes through dispatch_watchdog, which does both itself)
            dt = time.perf_counter() - t0
            observe_collective(dt, wire, strategy=strategy)
            flight_record("collective.end", op="allreduce_fn",
                          axis=str(axis), nbytes=wire,
                          seconds=round(dt, 6), **extra)
        else:
            # the watched leg must SYNCHRONIZE: under async dispatch the
            # bare call returns before the ring moves a byte, and a hung
            # collective would block some later consumer instead of here
            out = dispatch_watchdog(
                lambda v: jax.block_until_ready(_allreduce(v)), x,
                op="allreduce_fn", axis=axis,
                deadline=deadline, timeout_s=timeout_s,
                payload_bytes=wire, codec=eff_codec,
                logical_bytes=_payload_bytes(x),
                strategy=strategy if routed else None,
                phases=plan.phases(eff_codec) if routed else None)
        latency.observe(time.perf_counter() - t0, op="allreduce_fn",
                        axis=str(axis))
        return out

    return timed
