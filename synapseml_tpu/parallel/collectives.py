"""Collective-communication wrappers over the mesh.

The single allreduce stack replacing: LightGBM's native socket ring
(``LGBM_NetworkInit`` + in-C++ histogram allreduce, reference:
NetworkManager.scala:182-205), VW's spanning-tree AllReduce
(VowpalWabbitClusterUtil.scala:16-40) and Horovod's NCCL/Gloo
(dl/utils.py:31-46).  Everything is an XLA collective over ICI/DCN inside
jit — no sockets, no coordinator processes.

Use inside ``shard_map``/``pjit`` bodies with the axis names from
:mod:`synapseml_tpu.parallel.mesh`.
"""

from __future__ import annotations

import functools
from typing import Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from .mesh import DATA_AXIS


def psum(x, axis: str = DATA_AXIS):
    return lax.psum(x, axis_name=axis)


def pmean(x, axis: str = DATA_AXIS):
    return lax.pmean(x, axis_name=axis)

def pmax(x, axis: str = DATA_AXIS):
    return lax.pmax(x, axis_name=axis)


def pmin(x, axis: str = DATA_AXIS):
    return lax.pmin(x, axis_name=axis)


def all_gather(x, axis: str = DATA_AXIS, *, tiled: bool = False):
    return lax.all_gather(x, axis_name=axis, tiled=tiled)


def reduce_scatter(x, axis: str = DATA_AXIS, *, scatter_dimension: int = 0):
    return lax.psum_scatter(x, axis_name=axis,
                            scatter_dimension=scatter_dimension, tiled=True)


def ppermute(x, perm: Sequence[tuple], axis: str = DATA_AXIS):
    return lax.ppermute(x, axis_name=axis, perm=list(perm))


def ring_shift(x, axis: str = DATA_AXIS, *, reverse: bool = False):
    """Send to the next rank on the ring (the ring-attention building block)."""
    n = lax.axis_size(axis)
    if reverse:
        perm = [(i, (i - 1) % n) for i in range(n)]
    else:
        perm = [(i, (i + 1) % n) for i in range(n)]
    return lax.ppermute(x, axis_name=axis, perm=perm)


def axis_index(axis: str = DATA_AXIS):
    return lax.axis_index(axis)


def barrier(x, axis: str = DATA_AXIS):
    """Gang sync inside a mapped computation — the
    ``BarrierTaskContext.barrier()`` analogue (NetworkManager.scala:150-156).

    Returns ``x`` data-dependent on a cross-replica collective, so XLA cannot
    reorder work on ``x`` before the sync or dead-code-eliminate the
    collective (a bare unused psum would be DCE'd)."""
    token = lax.psum(jnp.ones((), jnp.int32), axis_name=axis)
    gated, _ = lax.optimization_barrier((x, token))
    return gated


def shard_map_over(mesh: Mesh, in_specs, out_specs,
                   check_vma: bool = False) -> Callable:
    """Decorator: shard_map a function over ``mesh`` with the given specs."""
    def wrap(fn):
        return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    return wrap


def allreduce_fn(mesh: Mesh, axis: str = DATA_AXIS) -> Callable:
    """jitted allreduce over the data axis: input is per-rank values stacked
    on dim 0 (shape (num_ranks, *H)), output is their sum (shape (*H)).
    The LightGBM histogram-allreduce replacement."""
    @jax.jit
    @functools.partial(jax.shard_map, mesh=mesh,
                       in_specs=P(axis), out_specs=P())
    def _allreduce(x):
        # x.sum(0) handles both one and several stacked values per shard
        return lax.psum(x.sum(0), axis_name=axis)
    return _allreduce
