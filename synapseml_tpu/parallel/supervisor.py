"""Gang supervision: missed-heartbeat failure detection and elastic,
checkpoint-resumed relaunch.

The reference's NetworkManager treats worker loss as a whole-job event —
retry the rendezvous socket, rebuild the ring from scratch
(NetworkManager.scala:294-340) — and a HUNG worker is not even noticed
until the global timeout expires.  This module closes both gaps,
Horovod-elastic / TPU-pod style (preemption is the common case):

- :class:`HeartbeatMonitor` — a phi-accrual-flavored missed-heartbeat
  detector over the per-rank ``SMLMP_HB`` beats the launcher's reader
  threads feed it.  Suspicion for a rank is ``elapsed / expected
  interval`` where *expected* adapts to the observed mean inter-arrival
  (a loaded host stretches everyone's cadence together, so the detector
  stretches with it instead of false-positiving); a rank is declared
  failed at ``hang_intervals`` (default 3) missed beats, i.e. in
  O(heartbeat interval) rather than O(global timeout).  Verdicts are
  structured: ``hang at step N``, ``no heartbeat``, and advisory
  ``straggler`` for ranks whose step lags the gang leader.

- :class:`GangSupervisor` — the elastic relaunch driver.  One attempt =
  one whole gang (a formed ``jax.distributed`` cluster cannot re-admit a
  replacement rank); on failure the launcher has already torn every rank
  down (SIGTERM → grace → SIGKILL) and the supervisor relaunches under
  the caller's :class:`~synapseml_tpu.resilience.RetryPolicy` with a
  FRESH coordinator port.  A ``checkpoint_dir`` threads through to every
  worker (``SMLTPU_CKPT_DIR``), so trainers that checkpoint (GBDT/DL)
  resume from the last *complete* step — a retry costs seconds, not the
  job.  ``last_recovery_s`` clocks kill-to-resumed-step wall time (the
  ``bench_gang_recovery`` probe's number).

- **Elastic resize** (this PR): a permanently lost rank no longer kills
  the job.  With ``min_ranks`` set, repeated failure of the same rank
  shrinks the next relaunch to the largest healthy size ≥ ``min_ranks``
  (degraded mode, resumed from the last durable checkpoint);
  :meth:`GangSupervisor.resize` / ``capacity_fn`` grow it back when
  capacity returns.  Checkpoints are world-size-independent by contract
  (DL state re-shards on restore; the booster is its own state), so an
  N-rank checkpoint resumes on M ranks.

Telemetry: ``gang_restarts_total{task}``, ``gang_failures_total{task,
cause}``, ``gang_resizes_total{task,direction}``,
``rank_heartbeat_age_seconds{rank}`` (updated live by the launcher's
watch loop; departed ranks' series are removed).  The fault registry's
call log records observed beats (``gang.heartbeat``), teardown signals
(``gang.teardown``), restarts (``gang.restart``) and resizes
(``gang.resize``) when ``record_calls`` is set, so chaos tests assert
the supervision schedule itself.
"""

from __future__ import annotations

import os
import threading
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, List, Optional

from ..resilience import RetryPolicy
from ..resilience.faults import get_faults
from ..telemetry import get_registry
from ..telemetry.gangplane import GangPlane, write_postmortem

__all__ = ["HeartbeatMonitor", "GangSupervisor", "RankHealth"]


@dataclass
class RankHealth:
    """Per-rank liveness state (driver side)."""
    rank: int
    started: float
    beats: int = 0
    last_beat: Optional[float] = None
    last_step: Optional[int] = None
    #: EWMA of inter-arrival seconds (None until two beats)
    mean_interval: Optional[float] = None
    done: bool = False

    def snapshot(self) -> Dict[str, Any]:
        return {"rank": self.rank, "beats": self.beats,
                "last_step": self.last_step,
                "mean_interval": self.mean_interval, "done": self.done}


class HeartbeatMonitor:
    """Phi-style missed-heartbeat detector for one gang attempt.

    Thread-safe: the launcher's per-rank reader threads call
    :meth:`observe` while the watch loop polls :meth:`verdicts`.
    ``clock`` is injectable so tests drive time deterministically.
    """

    #: EWMA weight of the newest inter-arrival sample
    EWMA_ALPHA = 0.25

    def __init__(self, n_ranks: int, interval_s: float,
                 hang_intervals: float = 3.0,
                 startup_grace_s: float = 120.0,
                 straggler_lag_steps: Optional[int] = None,
                 clock: Callable[[], float] = time.monotonic,
                 on_observe: Optional[Callable[[int, Optional[int]], None]]
                 = None,
                 ranks: Optional[Iterable[int]] = None):
        if interval_s <= 0:
            raise ValueError("interval_s must be > 0")
        self.interval_s = float(interval_s)
        self.hang_intervals = float(hang_intervals)
        self.startup_grace_s = float(startup_grace_s)
        self.straggler_lag_steps = straggler_lag_steps
        self._clock = clock
        self._on_observe = on_observe
        self._lock = threading.Lock()
        now = clock()
        # the watched rank set comes from the LIVE attempt: the
        # supervisor rebuilds the monitor per attempt at its (post-
        # resize) world size, so verdicts/ages/stragglers never
        # reference a departed rank.  ``ranks`` additionally lets a
        # caller watch a sparse/explicit id set (gang ranks always
        # renumber 0..n-1, so the supervisor itself never needs it).
        rank_ids = (list(ranks) if ranks is not None
                    else list(range(n_ranks)))
        self.ranks: Dict[int, RankHealth] = {
            r: RankHealth(rank=r, started=now) for r in rank_ids}

    # -- feeding -----------------------------------------------------------
    def observe(self, rank: int, step: Optional[int] = None,
                ts: Optional[float] = None) -> None:
        """One received beat (``ts`` is the sender's wall clock, carried
        for logs; detection uses the driver's own monotonic clock)."""
        now = self._clock()
        with self._lock:
            h = self.ranks.get(rank)
            if h is None:
                return
            if h.last_beat is not None:
                d = now - h.last_beat
                h.mean_interval = (d if h.mean_interval is None else
                                   (1 - self.EWMA_ALPHA) * h.mean_interval
                                   + self.EWMA_ALPHA * d)
            h.last_beat = now
            h.beats += 1
            if step is not None and (h.last_step is None
                                     or step >= h.last_step):
                h.last_step = step
        get_faults().note("gang.heartbeat", rank=rank, step=step)
        if self._on_observe is not None:
            self._on_observe(rank, step)

    def mark_done(self, rank: int) -> None:
        """Rank exited cleanly: stop watching it (a finished rank is not
        a hung rank)."""
        with self._lock:
            h = self.ranks.get(rank)
            if h is not None:
                h.done = True

    # -- reading -----------------------------------------------------------
    def age(self, rank: int) -> float:
        """Seconds since this rank's last beat (since start when none)."""
        now = self._clock()
        with self._lock:
            h = self.ranks[rank]
            return now - (h.last_beat if h.last_beat is not None
                          else h.started)

    def ages(self) -> Dict[int, float]:
        now = self._clock()
        with self._lock:
            return {r: now - (h.last_beat if h.last_beat is not None
                              else h.started)
                    for r, h in self.ranks.items() if not h.done}

    def last_steps(self) -> Dict[int, Optional[int]]:
        with self._lock:
            return {r: h.last_step for r, h in self.ranks.items()}

    def max_step(self) -> Optional[int]:
        with self._lock:
            steps = [h.last_step for h in self.ranks.values()
                     if h.last_step is not None]
        return max(steps) if steps else None

    def _expected_interval(self, h: RankHealth) -> float:
        """The adaptive beat period: never tighter than the configured
        interval, stretched by the observed mean when the host is slow."""
        if h.mean_interval is None:
            return self.interval_s
        return max(self.interval_s, h.mean_interval)

    def suspicion(self, rank: int) -> float:
        """phi-style suspicion: elapsed beats-worth of silence (0 when
        the rank just beat; >= ``hang_intervals`` ⇒ declared failed)."""
        now = self._clock()
        with self._lock:
            h = self.ranks[rank]
            if h.done:
                return 0.0
            if h.last_beat is None:
                return 0.0
            return (now - h.last_beat) / self._expected_interval(h)

    def verdicts(self) -> Dict[int, str]:
        """rank → structured failure cause, for every rank the detector
        declares failed NOW (empty dict: gang looks alive)."""
        now = self._clock()
        out: Dict[int, str] = {}
        with self._lock:
            for r, h in self.ranks.items():
                if h.done:
                    continue
                if h.last_beat is None:
                    silent = now - h.started
                    if silent > self.startup_grace_s:
                        out[r] = f"no heartbeat (none in {silent:.1f}s)"
                    continue
                silent = now - h.last_beat
                phi = silent / self._expected_interval(h)
                if phi >= self.hang_intervals:
                    step = ("?" if h.last_step is None else h.last_step)
                    out[r] = (f"hang at step {step} (no heartbeat for "
                              f"{silent:.1f}s, {phi:.1f} intervals)")
        return out

    def stragglers(self) -> Dict[int, str]:
        """Advisory rank → cause for ranks alive but lagging the gang
        leader by more than ``straggler_lag_steps`` (empty when the
        feature is off or nobody lags)."""
        lag = self.straggler_lag_steps
        if lag is None:
            return {}
        with self._lock:
            steps = {r: h.last_step for r, h in self.ranks.items()
                     if not h.done and h.last_step is not None}
            if len(steps) < 2:
                return {}
            lead = max(steps.values())
            return {r: f"straggler at step {s} (leader at step {lead})"
                    for r, s in steps.items() if lead - s > lag}


class GangSupervisor:
    """Elastic whole-gang launcher: detect fast, tear down, relaunch,
    resume from the last complete checkpoint — and, with a resize
    policy, RESIZE the gang instead of dying with it.

    One instance supervises one logical job; :meth:`run` returns the
    per-rank results of the first attempt that completes.  State left on
    the instance afterward: ``restarts`` (relaunch count),
    ``last_failure`` (the last :class:`~synapseml_tpu.parallel.launcher.
    WorkerFailure`), ``last_recovery_s`` (seconds from failure detection
    to the relaunched gang re-reaching the failed attempt's best step —
    the elastic-resume cost), ``monitor`` (the live attempt's detector),
    ``plane`` (the attempt's merged cross-rank telemetry when the
    observability plane is on), ``last_postmortem`` (path of the bundle
    the last dead attempt left in ``observability_dir``),
    ``world_size`` (the live attempt's rank count — ``n_processes``
    until a resize), ``resize_history`` (every applied resize).

    Elastic resize (Horovod-elastic shrink-to-survive semantics,
    arXiv:1802.05799): ``min_ranks < n_processes`` arms the shrink
    policy — when the SAME rank is blamed for ``shrink_after``
    consecutive failed attempts (a really-lost TPU host keeps failing
    however often the gang relaunches at the same size), the next
    relaunch drops to the largest healthy size ≥ ``min_ranks`` and
    resumes from the last durable checkpoint in DEGRADED mode.  Growth:
    :meth:`resize` requests a new size (a running healthy attempt is
    torn down at the next watch poll and relaunched — resume from the
    last durable checkpoint makes that a between-checkpoints boundary),
    and ``capacity_fn`` (→ currently placeable rank count) lets a
    degraded gang grow back toward ``n_processes`` automatically at the
    next relaunch boundary.  Resizes ride the caller's
    :class:`~synapseml_tpu.resilience.RetryPolicy` (failure-driven
    shrinks consume a retry + its backoff exactly like a same-size
    relaunch) plus their own brake: ``resize_cooldown_s`` between
    automatic shrinks and a ``max_resizes`` budget.  Checkpoints must be
    world-size-independent for this to be sound — GBDT boosters are
    (the model is the state), DL TrainStates re-shard on restore (see
    ``docs/api/gang.md`` "Elastic resize").
    """

    def __init__(self, task: str, n_processes: int = 2,
                 devices_per_process: int = 2, task_args: Any = None,
                 timeout_s: float = 300.0,
                 env_extra: Optional[Dict[str, str]] = None,
                 retry_policy: Optional[RetryPolicy] = None,
                 heartbeat_interval_s: float = 1.0,
                 hang_intervals: float = 3.0,
                 startup_grace_s: float = 120.0,
                 straggler_lag_steps: Optional[int] = None,
                 checkpoint_dir: Optional[Any] = None,
                 term_grace_s: float = 2.0,
                 tail_lines: int = 400,
                 observability_dir: Optional[str] = None,
                 tm_interval_s: Optional[float] = None,
                 min_ranks: Optional[int] = None,
                 shrink_after: int = 2,
                 resize_cooldown_s: float = 0.0,
                 max_resizes: int = 8,
                 capacity_fn: Optional[Callable[[], int]] = None,
                 compile_cache_dir: Optional[str] = None,
                 tune_table_dir: Optional[str] = None):
        self.task = task
        self.n_processes = int(n_processes)
        self.devices_per_process = int(devices_per_process)
        self.task_args = task_args
        self.timeout_s = float(timeout_s)
        self.env_extra = dict(env_extra or {})
        self.retry_policy = retry_policy
        self.heartbeat_interval_s = float(heartbeat_interval_s)
        self.hang_intervals = float(hang_intervals)
        self.startup_grace_s = float(startup_grace_s)
        self.straggler_lag_steps = straggler_lag_steps
        # a CheckpointManager (or anything with .directory) passes its
        # directory; plain strings pass through
        if checkpoint_dir is not None and not isinstance(checkpoint_dir, str):
            checkpoint_dir = getattr(checkpoint_dir, "directory",
                                     checkpoint_dir)
        self.checkpoint_dir = checkpoint_dir
        # persistent XLA compilation cache (ISSUE 15): the dir threads
        # to every worker as SMLTPU_COMPILE_CACHE_DIR (the CKPT_DIR
        # idiom) so relaunched AND resized gangs load compiled
        # executables from disk instead of re-running XLA — the
        # recompile-from-scratch tax was a visible slice of
        # resize_recovery_seconds.  World-size-dependent programs
        # (sharded train steps) key on their new shapes and simply
        # miss; everything shape-stable hits.
        self.compile_cache_dir = (str(compile_cache_dir)
                                  if compile_cache_dir else None)
        if self.compile_cache_dir:
            from .compilecache import COMPILE_CACHE_ENV
            self.env_extra.setdefault(COMPILE_CACHE_ENV,
                                      self.compile_cache_dir)
        # persisted autotune tuning tables (ISSUE 20): same threading as
        # the compile cache — every worker (and every relaunch/resize
        # generation) resolves its TunePlane against the shared dir, so
        # a winner measured once serves the whole gang's lifetime
        self.tune_table_dir = str(tune_table_dir) if tune_table_dir else None
        if self.tune_table_dir:
            from ..telemetry.tunetable import TUNE_TABLE_ENV
            self.env_extra.setdefault(TUNE_TABLE_ENV, self.tune_table_dir)
        self.term_grace_s = float(term_grace_s)
        self.tail_lines = int(tail_lines)
        # the gang-wide observability plane: an obs dir turns wire export
        # on (cadence defaulting to the heartbeat interval), collects
        # flight dumps, and receives postmortem.json / gang_trace.json
        self.observability_dir = observability_dir
        if tm_interval_s is None:
            tm_interval_s = (self.heartbeat_interval_s
                             if observability_dir else 0.0)
        self.tm_interval_s = float(tm_interval_s)

        # -- elastic resize policy ----------------------------------------
        if min_ranks is not None:
            min_ranks = int(min_ranks)
            if not 1 <= min_ranks <= self.n_processes:
                raise ValueError(
                    f"min_ranks={min_ranks}: must be in "
                    f"[1, n_processes={self.n_processes}]")
        self.min_ranks = min_ranks
        self.shrink_after = max(1, int(shrink_after))
        self.resize_cooldown_s = float(resize_cooldown_s)
        self.max_resizes = int(max_resizes)
        self.capacity_fn = capacity_fn

        self.restarts = 0
        self.last_failure: Optional[BaseException] = None
        self.last_recovery_s: Optional[float] = None
        self.monitor: Optional[HeartbeatMonitor] = None
        #: the live (or last) attempt's merged cross-rank telemetry
        self.plane: Optional[GangPlane] = None
        #: path of the last written post-mortem bundle, if any
        self.last_postmortem: Optional[str] = None
        #: rank count of the live (or next) attempt
        self.world_size = self.n_processes
        #: applied resizes: [{"attempt", "from", "to", "direction",
        #: "cause"}] — also lands in post-mortem bundles
        self.resize_history: List[Dict[str, Any]] = []
        self._max_world = self.n_processes
        self._fail_streak: Dict[int, int] = {}
        self._resizes_done = 0
        self._last_shrink_at: Optional[float] = None
        self._resize_lock = threading.Lock()
        self._requested_size: Optional[int] = None
        self._interrupt = threading.Event()
        #: callables invoked with each applied-resize event dict (the
        #: same record appended to :attr:`resize_history`) — how a
        #: budget holder (the serving CapacityArbiter) keeps its chip
        #: accounting honest when the gang resizes for its OWN reasons
        #: (failure-driven shrink, capacity probe), not just when asked
        self._resize_listeners: List[Any] = []

        reg = get_registry()
        self._c_restarts = reg.counter(
            "gang_restarts_total",
            "elastic whole-gang relaunches", ("task",))
        self._c_failures = reg.counter(
            "gang_failures_total",
            "gang attempts that failed, by first-listed cause kind",
            ("task", "cause"))
        self._c_resizes = reg.counter(
            "gang_resizes_total",
            "applied elastic gang resizes, by direction",
            ("task", "direction"))
        self._g_world = reg.gauge(
            "gang_world_size",
            "rank count of the live (or next) gang attempt", ("task",))
        self._g_world.set(self.world_size, task=self.task)

    def _new_monitor(self, watermark: Optional[int],
                     failed_at: Optional[float]) -> Optional[HeartbeatMonitor]:
        if self.heartbeat_interval_s <= 0:
            return None

        recovered = {"done": watermark is None or failed_at is None}
        # surfaced so run() can close the clock at gang COMPLETION when
        # no beat ever re-reached the watermark (the dead attempt's best
        # step was the last step — the relaunch restores it and has
        # nothing left to replay)
        self._recovery_pending = recovered

        def on_observe(rank: int, step: Optional[int]) -> None:
            # kill-to-resumed-step clock: first beat of the relaunched
            # gang that re-reaches the failed attempt's best step
            if recovered["done"] or step is None or step < watermark:
                return
            recovered["done"] = True
            self.last_recovery_s = time.monotonic() - failed_at

        # rank set from the LIVE attempt (post-resize size), never the
        # fixed construction-time n_processes
        return HeartbeatMonitor(
            self.world_size, self.heartbeat_interval_s,
            hang_intervals=self.hang_intervals,
            startup_grace_s=self.startup_grace_s,
            straggler_lag_steps=self.straggler_lag_steps,
            on_observe=on_observe)

    #: verdict-prefix → metric label for gang_failures_total{cause}
    _CAUSE_KINDS = (("hang", "hang"), ("no heartbeat", "no_heartbeat"),
                    ("exit", "exit"), ("timeout", "timeout"),
                    ("no result", "no_result"), ("straggler", "straggler"),
                    ("injected", "injected"))

    @classmethod
    def _cause_kind(cls, causes: Dict[int, str]) -> str:
        if not causes:
            return "unknown"
        first = causes[sorted(causes)[0]]
        for prefix, kind in cls._CAUSE_KINDS:
            if first.startswith(prefix):
                return kind
        return "other"

    def _clear_flight_dumps(self) -> None:
        """Remove a previous attempt's (or run's) on-disk flight rings
        before launching: flight ``seq`` counters restart per process, so
        a stale dump with a high ``last_seq`` would outrank the NEW
        attempt's wire tail in the post-mortem gather and attribute the
        wrong events to a dead rank."""
        obs = self.observability_dir
        if not obs or not os.path.isdir(obs):
            return
        for r in range(self._max_world):
            try:
                os.unlink(os.path.join(obs, f"flight-rank{r}.json"))
            except OSError:
                pass

    def _write_postmortem(self, attempt: int, failure) -> None:
        """One dead attempt → schema-checked
        ``postmortem-attempt<N>.json`` in the obs dir, with
        ``postmortem.json`` always the LATEST attempt's bundle (plus the
        stitched multi-lane trace of whatever spans the wire delivered
        before the gang died).  Per-attempt files mean an early
        attempt's verdict — often the root cause — survives later
        retries.  Never raises: bundling evidence must not mask the
        failure being bundled."""
        obs = self.observability_dir
        if not obs:
            return
        try:
            os.makedirs(obs, exist_ok=True)
            last_steps = (self.monitor.last_steps()
                          if self.monitor is not None else {})
            bundle = write_postmortem(
                os.path.join(obs, f"postmortem-attempt{attempt}.json"),
                task=self.task, causes=dict(failure.causes),
                attempt=attempt, n_ranks=self.world_size,
                plane=self.plane, last_steps=last_steps, obs_dir=obs,
                resize_history=list(self.resize_history))
            from ..telemetry.artifact import write_json
            from ..telemetry.gangplane import check_postmortem
            latest = os.path.join(obs, "postmortem.json")
            write_json(latest, bundle, schema=check_postmortem)
            # only after the write lands: a swallowed failure must not
            # leave this pointing at a missing/stale file
            self.last_postmortem = latest
            if self.plane is not None:
                self.plane.export_chrome(os.path.join(obs,
                                                      "gang_trace.json"))
        except Exception:
            pass

    def _export_trace(self) -> None:
        obs = self.observability_dir
        if obs and self.plane is not None:
            try:
                os.makedirs(obs, exist_ok=True)
                self.plane.export_chrome(os.path.join(obs,
                                                      "gang_trace.json"))
            except Exception:
                pass

    # -- elastic resize ----------------------------------------------------
    def resize(self, n: int) -> None:
        """Request the gang run at ``n`` ranks from the next attempt on.

        Thread-safe and callable mid-run: a running healthy attempt is
        torn down at the next watch poll (SIGTERM → grace → SIGKILL, the
        normal teardown) and the relaunch at the new size resumes from
        the last durable checkpoint — so the request lands *between
        checkpoints*, never inside one.  An explicit request is an
        operator action: it bypasses the automatic ``max_resizes``
        budget and the shrink cooldown — but NOT the validity floor:
        ``n <= 0`` and ``n < min_ranks`` are caller errors rejected
        here, loudly, instead of entering the relaunch path with a gang
        shape the policy forbids."""
        n = int(n)
        if n < 1:
            raise ValueError(
                f"resize({n}): a gang needs at least one rank — to stop "
                "the gang, let the task finish or tear the supervisor "
                "down; resize only changes a LIVE gang's shape")
        if self.min_ranks is not None and n < self.min_ranks:
            raise ValueError(
                f"resize({n}): below this supervisor's elastic floor "
                f"min_ranks={self.min_ranks} — shrink requests must stay "
                f"in [{self.min_ranks}, ...]; raise min_ranks at "
                "construction if the floor itself is wrong")
        with self._resize_lock:
            if n == self.world_size:
                # already there: a no-op request must not tear down a
                # healthy running gang — it only CANCELS any pending
                # request for a different size (and its wakeup; the
                # event is set nowhere else)
                self._requested_size = None
                self._interrupt.clear()
                return
            # set the wakeup under the SAME lock that consumes the
            # request: setting it after release races
            # _plan_before_launch (request consumed, event cleared, THEN
            # set) into tearing down the next healthy, correctly-sized
            # attempt for nothing
            self._requested_size = n
            self._interrupt.set()

    def add_resize_listener(self, fn) -> None:
        """Register ``fn(event_dict)`` to run on every APPLIED resize
        (requested, failure-driven, or capacity-driven) — the
        budget-aware hook: an external chip-budget holder stays
        consistent with resizes it did not initiate.  Listener errors
        are swallowed: accounting must not break the relaunch path."""
        with self._resize_lock:
            self._resize_listeners.append(fn)

    def _apply_resize(self, attempt: int, new_size: int, cause: str,
                      automatic: bool) -> None:
        # the world_size write happens under the SAME lock resize()'s
        # no-op comparison reads it under — otherwise a request racing
        # the application of a capacity/failure resize compares against
        # a stale size and needlessly tears down the next attempt
        with self._resize_lock:
            old = self.world_size
            if new_size == old:
                return
            direction = "shrink" if new_size < old else "grow"
            self.world_size = new_size
        self._max_world = max(self._max_world, new_size)
        if automatic:
            self._resizes_done += 1
            if direction == "shrink":
                self._last_shrink_at = time.monotonic()
        # rank indices renumber 0..new-1 on relaunch: stale streaks
        # would blame the wrong process
        self._fail_streak.clear()
        event = {"attempt": int(attempt), "from": old, "to": new_size,
                 "direction": direction, "cause": cause}
        self.resize_history.append(event)
        self._c_resizes.inc(1, task=self.task, direction=direction)
        self._g_world.set(new_size, task=self.task)
        with self._resize_lock:
            listeners = list(self._resize_listeners)
        for fn in listeners:
            try:
                fn(dict(event))
            except Exception:
                pass
        get_faults().note("gang.resize", **event)
        try:
            from ..telemetry.flight import record as flight_record
            flight_record("gang_resize", task=self.task, **event)
        except Exception:
            pass
        # world size changed → topology snapshot refreshed → reduction
        # plan cache invalidated (ISSUE 14: the collective planner
        # re-plans at every resize boundary; workers are fresh
        # processes, so their planners rebuild at relaunch — this keeps
        # the DRIVER-side planner honest too)
        self._replan(f"resize_{direction}", new_size)

    def _replan(self, reason: str, world_size: int) -> None:
        """Invalidate the process collective-plan cache (recorded in the
        fault call log + flight ring as ``plan.refresh`` /
        ``plan_invalidate``).  Never raises: re-planning is advisory —
        a failed refresh must not take the supervisor down with it."""
        try:
            from .planner import get_planner
            get_planner().refresh(reason, world_size=int(world_size))
        except Exception:
            pass

    def _resize_budget_ok(self) -> bool:
        return self._resizes_done < self.max_resizes

    def _shrink_cooled_down(self) -> bool:
        """THE cooldown gate for every AUTOMATIC shrink — failure-driven
        and capacity-driven alike, so a flapping capacity probe cannot
        sidestep the brake the operator configured."""
        return (self._last_shrink_at is None
                or time.monotonic() - self._last_shrink_at
                >= self.resize_cooldown_s)

    def _plan_after_failure(self, causes: Dict[int, str]) -> Optional[int]:
        """Shrink-to-survive decision for one failed attempt → target
        size, or None.  A rank is *persistently* failing once it is
        blamed (non-advisory cause) in ``shrink_after`` consecutive
        failed attempts — the permanent-loss signature (a transient
        crash resumes fine at the same size; a cordoned host fails
        every relaunch).  Target: largest healthy size ≥ ``min_ranks``.
        """
        blamed = {r for r, c in causes.items()
                  if not str(c).startswith("straggler")}
        for r in list(self._fail_streak):
            if r not in blamed:
                del self._fail_streak[r]
        for r in blamed:
            self._fail_streak[r] = self._fail_streak.get(r, 0) + 1
        if self.min_ranks is None:
            return None
        persistent = [r for r in blamed
                      if self._fail_streak[r] >= self.shrink_after]
        if not persistent:
            return None
        target = max(self.min_ranks, self.world_size - len(persistent))
        if target >= self.world_size or not self._resize_budget_ok() \
                or not self._shrink_cooled_down():
            return None
        return target

    def _plan_before_launch(self, attempt: int) -> None:
        """Attempt-boundary resize decisions: consume an explicit
        :meth:`resize` request, then let ``capacity_fn`` shrink a gang
        whose capacity left or grow a degraded gang back toward
        ``n_processes`` when capacity returned."""
        with self._resize_lock:
            req = self._requested_size
            self._requested_size = None
            # a request set while no attempt ran left the event set;
            # consuming the request consumes the wakeup too
            self._interrupt.clear()
        if req is not None:
            self._apply_resize(attempt, req, cause="requested",
                               automatic=False)
            return
        if self.capacity_fn is None:
            return
        try:
            cap = int(self.capacity_fn())
        except Exception:
            return                      # a flaky probe must not kill the job
        floor = self.min_ranks if self.min_ranks is not None else 1
        if cap < self.world_size:
            target = max(floor, cap)
            if (target < self.world_size and self._resize_budget_ok()
                    and self._shrink_cooled_down()):
                self._apply_resize(attempt, target,
                                   cause=f"capacity {cap}", automatic=True)
        elif self.world_size < self.n_processes and cap > self.world_size:
            target = min(self.n_processes, cap)
            if self._resize_budget_ok():
                self._apply_resize(attempt, target,
                                   cause=f"capacity {cap}", automatic=True)

    def run(self) -> List[Any]:
        """Launch (and relaunch/resize) until a gang completes; per-rank
        results in rank order (length = the completing attempt's
        ``world_size``), or the LAST attempt's failure when retries
        exhaust."""
        from .launcher import GangInterrupted, WorkerFailure, _launch_once

        policy = self.retry_policy
        retries_left = policy.max_retries if policy else 0
        watermark: Optional[int] = None
        failed_at: Optional[float] = None
        attempt = 0
        while True:
            self._plan_before_launch(attempt)
            self.monitor = self._new_monitor(watermark, failed_at)
            self.plane = (GangPlane(self.world_size)
                          if (self.tm_interval_s > 0
                              or self.observability_dir) else None)
            self._clear_flight_dumps()
            try:
                results = _launch_once(
                    self.task, self.world_size, self.devices_per_process,
                    self.task_args, self.timeout_s, self.env_extra,
                    monitor=self.monitor,
                    heartbeat_interval_s=self.heartbeat_interval_s,
                    checkpoint_dir=self.checkpoint_dir,
                    term_grace_s=self.term_grace_s,
                    tail_lines=self.tail_lines,
                    plane=self.plane, tm_interval_s=self.tm_interval_s,
                    obs_dir=self.observability_dir,
                    interrupt=self._interrupt)
                if (failed_at is not None
                        and not getattr(self, "_recovery_pending",
                                        {"done": True})["done"]):
                    # the relaunched gang completed without ever beating
                    # a step ≥ watermark (everything durable was already
                    # done): completion IS the recovery
                    self.last_recovery_s = time.monotonic() - failed_at
                self._export_trace()
                return results
            except GangInterrupted:
                # a deliberate resize teardown: no retry burned, no
                # post-mortem — but the recovery clock starts, so
                # resize_recovery_seconds covers requested grows too
                failed_at = time.monotonic()
                if self.monitor is not None:
                    step = self.monitor.max_step()
                    if step is not None and (watermark is None
                                             or step > watermark):
                        watermark = step
                self.restarts += 1
                self._c_restarts.inc(1, task=self.task)
                # ``attempt`` is the FAILURE index (postmortem naming)
                # and does not advance here; ``restart`` is the
                # monotonic launch counter both restart paths share, so
                # fault-log consumers can order the timeline
                get_faults().note("gang.restart", attempt=attempt,
                                  restart=self.restarts, causes={},
                                  watermark=watermark, resize=True)
                # every relaunch boundary re-plans (a resize teardown
                # already refreshed in _apply_resize when the size
                # changes; this covers same-size interrupts too)
                self._replan("relaunch", self.world_size)
                continue
            except WorkerFailure as e:
                self.last_failure = e
                failed_at = time.monotonic()
                if self.monitor is not None:
                    step = self.monitor.max_step()
                    if step is not None and (watermark is None
                                             or step > watermark):
                        watermark = step
                self._c_failures.inc(1, task=self.task,
                                     cause=self._cause_kind(e.causes))
                self._write_postmortem(attempt, e)
                target = self._plan_after_failure(e.causes)
                if policy is None or retries_left <= 0 \
                        or not policy.acquire_retry():
                    raise
                retries_left -= 1
                if target is not None:
                    self._apply_resize(attempt, target,
                                       cause=self._cause_kind(e.causes),
                                       automatic=True)
                self.restarts += 1
                self._c_restarts.inc(1, task=self.task)
                get_faults().note("gang.restart", attempt=attempt + 1,
                                  restart=self.restarts,
                                  causes=dict(e.causes),
                                  watermark=watermark)
                # relaunch boundary: the failed attempt's topology may
                # be gone (that is often WHY it failed) — re-plan
                self._replan("relaunch", self.world_size)
                policy.sleep(policy.backoff_s(attempt),
                             site="launcher.backoff")
                attempt += 1
