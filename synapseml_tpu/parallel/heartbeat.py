"""Worker-side heartbeat channel — the liveness half of gang supervision.

Each worker process of a launched gang emits a periodic

    SMLMP_HB:{"rank": r, "step": s, "ts": t}

line on stdout — the SAME pipe that carries ``RESULT_MARKER`` — and the
driver's per-rank reader threads feed every beat into the
:class:`~synapseml_tpu.parallel.supervisor.HeartbeatMonitor`.  A dead OR
hung rank is therefore declared failed in O(heartbeat interval) instead
of O(global timeout): a crashed process closes the pipe, a wedged one
(GIL held by a stuck extension, a collective blocked forever) stops
producing beats, and both look identical to the detector.

The emitter is a daemon thread started by ``worker.main`` before the
cluster rendezvous, so "no heartbeat at all" cleanly separates
boot/rendezvous failures from mid-task hangs.  Training code reports
progress through :func:`beat` (the GBDT checkpoint writer calls it after
every published step), which rides the next emitted line as the rank's
last-known step — the supervisor uses it for ``hang at step N`` verdicts
and for the kill-to-resumed-step recovery clock.

Stdlib-only: importable before (and without) jax, from any layer.
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from typing import Optional

__all__ = ["HB_MARKER", "HB_INTERVAL_ENV", "HeartbeatEmitter", "beat",
           "current_step", "parse_heartbeat", "start_emitter"]

#: marker in front of the heartbeat JSON line (the RESULT_MARKER sibling)
HB_MARKER = "SMLMP_HB:"
#: env var the launcher sets to enable emission (seconds; 0/unset = off)
HB_INTERVAL_ENV = "SMLTPU_HB_INTERVAL_S"

_state_lock = threading.Lock()
_state = {"step": None}


def beat(step: Optional[int] = None) -> None:
    """Report training progress: the emitted heartbeat carries the most
    recent step so the driver knows each rank's last durable position.
    Free when no emitter runs (one lock + dict store)."""
    if step is None:
        return
    with _state_lock:
        prev = _state["step"]
        if prev is None or step >= prev:
            _state["step"] = step


def current_step() -> Optional[int]:
    with _state_lock:
        return _state["step"]


def reset_step() -> None:
    """Forget the reported step (a worker process never needs this — it
    dies with its gang attempt; in-process tests do)."""
    with _state_lock:
        _state["step"] = None


def parse_heartbeat(line: str) -> Optional[dict]:
    """``SMLMP_HB:{...}`` line → dict (None for non-heartbeat lines or
    garbage — a chatty task must not crash the driver's reader)."""
    if not line.startswith(HB_MARKER):
        return None
    try:
        d = json.loads(line[len(HB_MARKER):])
        return d if isinstance(d, dict) else None
    except ValueError:
        return None


class HeartbeatEmitter(threading.Thread):
    """Daemon thread printing one heartbeat line every ``interval_s``.

    Each emission passes the ``heartbeat.emit`` fault site, so tests make
    a rank go silent (kind ``hang`` wedges this thread → beats stop while
    the process lives) or die (kind ``kill_rank``) deterministically.
    """

    def __init__(self, rank: int, interval_s: float, stream=None):
        super().__init__(name=f"hb-emitter-r{rank}", daemon=True)
        self.rank = int(rank)
        self.interval_s = float(interval_s)
        self._stream = stream
        # NB: not named _stop — threading.Thread owns that name internally
        self._halt = threading.Event()

    def stop(self) -> None:
        self._halt.set()

    def _emit(self) -> None:
        from ..resilience.faults import get_faults
        from ..telemetry.flight import record as flight_record
        step = current_step()
        faults = get_faults()
        # the silent-rank fault site: ``hang`` blocks right here
        faults.raise_point("heartbeat.emit", rank=self.rank, step=step)
        faults.note("heartbeat.emit", rank=self.rank, step=step)
        flight_record("heartbeat", rank=self.rank, step=step)
        line = HB_MARKER + json.dumps(
            {"rank": self.rank, "step": step, "ts": time.time()})
        # ONE write call: print()'s text+newline pair could interleave
        # with the main thread's result-marker write on shared stdout
        stream = self._stream if self._stream is not None else sys.stdout
        stream.write(line + "\n")
        stream.flush()

    def run(self) -> None:
        while not self._halt.is_set():
            try:
                self._emit()
            except Exception:
                # an injected raise kind (or a closed pipe at teardown)
                # silences this rank — exactly what the detector watches
                return
            self._halt.wait(self.interval_s)


def start_emitter(rank: int,
                  interval_s: Optional[float] = None) -> Optional[HeartbeatEmitter]:
    """Start the emitter when heartbeats are enabled (``interval_s`` or
    the ``SMLTPU_HB_INTERVAL_S`` env var > 0); returns it, or None."""
    if interval_s is None:
        try:
            interval_s = float(os.environ.get(HB_INTERVAL_ENV, "0") or 0)
        except ValueError:
            interval_s = 0.0
    if interval_s <= 0:
        return None
    emitter = HeartbeatEmitter(rank, interval_s)
    emitter.start()
    return emitter
