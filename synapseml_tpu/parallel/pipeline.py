"""GPipe-style pipeline parallelism over a ``pipe`` mesh axis.

The reference has no pipeline parallelism (SURVEY §2.3: PP — "No"); this is
TPU-native capability.  The formulation is SPMD pipelining under
``shard_map``: every stage runs the SAME program, holding its own slice of
a stage-stacked parameter pytree (leading dim = ``pipe`` axis), and
activations rotate one hop per tick with ``lax.ppermute`` — the collective
rides ICI neighbors, which is exactly the physical topology a pipeline
wants.  A microbatch enters at stage 0 each tick; after ``S-1`` fill ticks
the pipe is full and every tick retires one microbatch at the last stage.
Total ticks ``T = M + S - 1`` for M microbatches over S stages; bubble
fraction ``(S-1)/T`` shrinks as M grows, as in GPipe.

Everything is differentiable (``ppermute`` transposes to the reverse
permutation), so ``jax.grad`` through :func:`pipeline_apply` yields the
1F1B-equivalent backward schedule automatically from XLA's scheduling of
the transposed loop.
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .mesh import PIPE_AXIS

__all__ = ["pipeline_apply", "pipeline_loss", "stack_stage_params",
           "PIPE_AXIS"]


def stack_stage_params(per_stage_params: Sequence[Any]):
    """Stack S per-stage pytrees into one pytree with a leading stage dim
    (shard it over the ``pipe`` axis; each rank then sees its own slice)."""
    return jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs, axis=0), *per_stage_params)


def _run_schedule(stage_fn, stacked_params, microbatches, axis_name,
                  collect=None):
    """The tick loop; returns (outputs valid on last stage, stage, S).

    ``microbatches`` may be a single (M, mb, ...) array OR a pytree of
    them (e.g. ``{"x": ..., "mask": ...}``) — transformer stages carry
    the attention mask alongside the activations; pass-through leaves
    simply rotate unchanged.  ``collect`` (state pytree → output pytree,
    default identity) selects which leaves land in the outputs buffer —
    pass-through leaves the caller discards should not pay the output
    carry or the closing psum."""
    collect = collect if collect is not None else (lambda s: s)
    tmap = jax.tree_util.tree_map
    params = tmap(lambda a: a[0], stacked_params)
    M = jax.tree_util.tree_leaves(microbatches)[0].shape[0]
    stage = lax.axis_index(axis_name)
    S = lax.psum(1, axis_name)
    T = M + S - 1
    perm = [(i, (i + 1) % S) for i in range(S)]

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (clamped gather keeps shapes static;
        # ingested garbage for t >= M never reaches an output slot)
        inp = tmap(lambda mbs: lax.dynamic_index_in_dim(
            mbs, jnp.minimum(t, M - 1), axis=0, keepdims=False),
            microbatches)
        state = tmap(lambda i, s: jnp.where(stage == 0, i, s), inp, state)
        out = stage_fn(params, state)
        # last stage retires microbatch t-(S-1) at tick t
        retire = t - (S - 1)
        outputs = tmap(
            lambda os, o: jnp.where(
                (stage == S - 1) & (retire >= 0),
                lax.dynamic_update_index_in_dim(
                    os, o, jnp.maximum(retire, 0), axis=0),
                os),
            outputs, collect(out))
        state = tmap(lambda o: lax.ppermute(o, axis_name, perm), out)
        return (state, outputs), None

    state0 = tmap(lambda mbs: jnp.zeros(mbs.shape[1:], mbs.dtype),
                  microbatches)
    outputs0 = tmap(jnp.zeros_like, collect(microbatches))
    (_, outputs), _ = lax.scan(tick, (state0, outputs0), jnp.arange(T))
    return outputs, stage, S


def pipeline_apply(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                   stacked_params: Any,
                   microbatches: jnp.ndarray,
                   axis_name: str = PIPE_AXIS,
                   collect: Callable[[Any], Any] = None) -> jnp.ndarray:
    """Run microbatches through the S-stage pipeline.  MUST be called
    inside ``shard_map`` with ``axis_name`` bound and ``stacked_params``
    sharded so each rank's slice has leading dim 1.

    stage_fn: (params_of_one_stage, activation (mb, ...)) -> activation.
    microbatches: (M, mb, ...) — the same array on every stage (stage 0 is
    the only consumer; replicating it avoids a scatter).
    Returns (M, mb, ...) outputs, valid on every stage (broadcast from the
    last stage by the closing psum).

    For TRAINING use :func:`pipeline_loss`: differentiating through this
    broadcast with an identical per-rank loss inflates gradients by S
    (every rank seeds the same cotangent into the psum transpose).

    ``collect`` (state pytree → output pytree) selects the leaves worth
    retiring and broadcasting — pass-through leaves (e.g. an attention
    mask riding the pipeline) should not pay the outputs carry/psum.
    """
    outputs, stage, S = _run_schedule(stage_fn, stacked_params,
                                      microbatches, axis_name, collect)
    return jax.tree_util.tree_map(
        lambda o: lax.psum(jnp.where(stage == S - 1, o,
                                     jnp.zeros_like(o)), axis_name),
        outputs)


def pipeline_loss(stage_fn: Callable[[Any, jnp.ndarray], jnp.ndarray],
                  stacked_params: Any,
                  microbatches: jnp.ndarray,
                  loss_fn: Callable[[jnp.ndarray], jnp.ndarray],
                  axis_name: str = PIPE_AXIS) -> jnp.ndarray:
    """Pipeline forward + scalar loss: ``loss_fn`` (outputs (M, mb, ...) →
    scalar) is evaluated on the broadcast outputs, identically on every
    rank — never on another stage's zero-filled buffer, so losses with
    singular derivatives at 0 (log, sqrt, 1/x) stay NaN-free.

    Differentiate by taking ``jax.grad`` OUTSIDE the shard_map wrapping
    this function (out_specs ``P()``) — that seeds one cotangent for the
    replicated scalar and the transposed ppermute schedule delivers exact
    per-stage gradients.  ``jax.grad`` INSIDE the shard_map would seed once
    per rank and inflate every gradient by the stage count.
    """
    return loss_fn(pipeline_apply(stage_fn, stacked_params, microbatches,
                                  axis_name))
