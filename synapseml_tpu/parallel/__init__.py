from .collectives import (CollectiveTimeout, all_gather, allreduce_fn,
                          axis_index, barrier, dispatch_watchdog,
                          hierarchical_psum, pmax, pmean, pmin, ppermute,
                          psum, reduce_scatter, ring_allreduce, ring_shift,
                          shard_map_over, tree_psum_bucketed)
from .compression import (CollectiveConfig, compressed_psum,
                          compressed_tree_sync, resolve_collective_config)
from .distributed import ClusterConfig, initialize_cluster, shutdown_cluster
from .launcher import (GangInterrupted, ReservedPort, WorkerFailure,
                       find_free_port, run_on_local_cluster)
from .selfcheck import cluster_report
from .supervisor import GangSupervisor, HeartbeatMonitor
from .mesh import (DATA_AXIS, EXPERT_AXIS, MODEL_AXIS, PIPE_AXIS, SEQ_AXIS,
                   batch_sharding, data_parallel_mesh, dp_ep_mesh, dp_sp_tp_mesh,
                   dp_tp_mesh, local_mesh_devices, make_mesh, pad_to_multiple,
                   replicated, shard_batch)
from .placement import (PlacementMap, partition_assignment,
                        place_partitions, rows_for_rank)
from .planner import (CollectivePlanner, ReductionPlan, TopologySpec,
                      get_planner, planned_psum, set_planner)
from .topology import Topology, get_num_rows_per_partition, get_topology
