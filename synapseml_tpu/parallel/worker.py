"""Worker entry point for the multi-process launcher.

One OS process per cluster rank: configure the backend BEFORE it
initializes, rendezvous through ``initialize_cluster``, run the task, print
the JSON result behind a marker the driver greps for.  This is the worker
half of the reference's handshake (NetworkManager.scala:123-169 — there the
worker phones the driver's ServerSocket and blocks on the machine-list
reply; here ``jax.distributed.initialize`` is both legs).

Gang supervision hooks (all driver-controlled via env):

- ``SMLTPU_HB_INTERVAL_S`` > 0 starts the heartbeat emitter thread FIRST,
  so the driver distinguishes "still importing jax" (beats flowing, no
  step) from "process wedged" (beats stopped) from "boot failure" (no
  beat at all).
- ``SMLTPU_RENDEZVOUS_TIMEOUT_S`` arms a host-side watchdog around the
  blocking ``initialize_cluster`` call: a coordinator that never answers
  becomes a structured :class:`~synapseml_tpu.parallel.collectives.
  CollectiveTimeout` (op ``rendezvous``) and a fast non-zero exit, not an
  indefinitely-hung rank.
- ``SMLTPU_CKPT_DIR`` names the gang's checkpoint directory; tasks read
  it to resume elastically after a relaunch.
- ``SMLTPU_COMPILE_CACHE_DIR`` points jax's persistent compilation
  cache at a shared directory (enabled before the rendezvous, so even
  rendezvous-time programs cache): a relaunched or resized gang loads
  compiled executables from disk instead of re-running XLA.

Gang observability hooks (see :mod:`synapseml_tpu.telemetry.gangplane`):

- ``SMLTPU_TM_INTERVAL_S`` > 0 starts the telemetry wire emitter beside
  the heartbeat thread: one ``SMLMP_TM:`` line per interval carrying the
  cumulative metric snapshot plus incremental completed spans and flight
  events.  A FINAL batch flushes synchronously before the result marker,
  so a clean exit drops no spans or metrics (satisfying the contract
  that ``shutdown_cluster`` loses nothing a crash wouldn't).
- ``SMLTPU_OBS_DIR`` names the observability directory: the flight
  recorder's ring dumps there SIGKILL-atomically (``flight-rank<r>.json``)
  on SIGTERM — the teardown signal a failing gang's healthy peers
  receive — and again on clean exit, giving the driver's post-mortem
  gather the full ring instead of the bounded wire tail.

Run as ``python -m synapseml_tpu.parallel.worker`` with the SMLTPU_* env
set by ``launcher.run_on_local_cluster``.
"""

from __future__ import annotations

import importlib
import json
import os
import signal
import sys


def _flight_dump_path(obs_dir: str, rank: int) -> str:
    return os.path.join(obs_dir, f"flight-rank{rank}.json")


def _install_flight_dump(rank: int):
    """SIGTERM → dump the flight ring, then exit 143 without unwinding
    (the rank may be parked in a dead collective no ``finally`` block
    would ever reach).  Returns ``(dump, install)`` — the dump callable
    for the clean path and the installer for re-arming — or None when no
    obs dir is configured.  Re-arming matters: ``jax.distributed``'s
    rendezvous registers XLA's own SIGTERM preemption notifier, which
    would silently replace this handler, so the worker installs once
    early (covers a teardown DURING rendezvous) and again right after
    the cluster forms."""
    from synapseml_tpu.telemetry.gangplane import OBS_DIR_ENV
    obs_dir = os.environ.get(OBS_DIR_ENV)
    if not obs_dir:
        return None
    from synapseml_tpu.telemetry.flight import get_flight

    def dump() -> None:
        try:
            get_flight().dump(_flight_dump_path(obs_dir, rank), rank=rank)
        except BaseException:
            pass                # a failed dump must not mask the teardown

    def on_term(signum, frame):  # pragma: no cover - signal path
        dump()
        os._exit(143)

    def install() -> None:
        try:
            signal.signal(signal.SIGTERM, on_term)
        except (ValueError, OSError):   # non-main thread / exotic platform
            pass

    install()
    return dump, install


def main() -> int:
    coordinator = os.environ["SMLTPU_COORDINATOR"]
    n_procs = int(os.environ["SMLTPU_NUM_PROCESSES"])
    rank = int(os.environ["SMLTPU_PROCESS_ID"])
    platform = os.environ.get("SMLTPU_PLATFORM") or None
    local_devices = int(os.environ.get("SMLTPU_LOCAL_DEVICES", "0")) or None
    task = os.environ["SMLTPU_TASK"]
    task_args = json.loads(os.environ.get("SMLTPU_TASK_ARGS", "null"))

    # heartbeats first: the gang supervisor must see this rank alive
    # before (and during) the slow rendezvous below
    from synapseml_tpu.parallel import heartbeat
    emitter = heartbeat.start_emitter(rank)
    # telemetry wire export + the crash flight dump ride the same early
    # start: the driver holds a near-current tail even for a rank that
    # dies during the rendezvous
    from synapseml_tpu.telemetry import gangplane
    tm_emitter = gangplane.start_emitter(rank)
    flight_hooks = _install_flight_dump(rank)
    flight_dump = flight_hooks[0] if flight_hooks else None

    # persistent XLA compilation cache: enabled BEFORE the rendezvous
    # (and therefore before anything compiles) when the supervisor
    # threaded SMLTPU_COMPILE_CACHE_DIR through — a relaunched or
    # resized gang loads its compiled executables from disk.  Also
    # installs the compile/cache-hit attribution listeners either way.
    from synapseml_tpu.parallel.compilecache import enable_from_env
    enable_from_env()

    from synapseml_tpu.parallel.distributed import (ClusterConfig,
                                                    initialize_cluster,
                                                    shutdown_cluster)
    cfg = ClusterConfig(
        coordinator_address=coordinator,
        num_processes=n_procs,
        process_id=rank,
        platform=platform,
        local_device_count=local_devices,
    )
    rdv_timeout = float(
        os.environ.get("SMLTPU_RENDEZVOUS_TIMEOUT_S", "0") or 0)
    if rdv_timeout > 0:
        from synapseml_tpu.parallel.collectives import dispatch_watchdog
        dispatch_watchdog(initialize_cluster, cfg,
                          op="rendezvous", axis="-",
                          timeout_s=rdv_timeout)
    else:
        initialize_cluster(cfg)
    heartbeat.beat(step=0)        # rendezvoused: step 0 is reachable
    if flight_hooks is not None:
        flight_hooks[1]()         # re-arm: the rendezvous installed XLA's
        #                           SIGTERM notifier over our dump handler

    mod_name, fn_name = task.split(":", 1)
    fn = getattr(importlib.import_module(mod_name), fn_name)
    result = fn(task_args)
    # the final telemetry batch flushes BEFORE the result marker: clean
    # exits must drop no spans or metrics (the periodic loop stops first
    # so the flush cannot interleave with a concurrent emission)
    if tm_emitter is not None:
        tm_emitter.stop()
        tm_emitter.emit_now(final=True)
    # marker line is the contract with launcher.run_on_local_cluster —
    # a single write call so the heartbeat thread's lines cannot land
    # between the result text and its newline
    sys.stdout.write("SMLMP_RESULT:" + json.dumps(result) + "\n")
    sys.stdout.flush()
    # keep beating THROUGH the distributed shutdown: it can take longer
    # than the hang threshold, and a rank finishing cleanly must not be
    # declared hung in its last second
    shutdown_cluster()
    if emitter is not None:
        emitter.stop()
    if flight_dump is not None:
        flight_dump()             # clean-path dump: the full on-disk ring
    return 0


if __name__ == "__main__":
    sys.exit(main())
