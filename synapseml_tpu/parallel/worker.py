"""Worker entry point for the multi-process launcher.

One OS process per cluster rank: configure the backend BEFORE it
initializes, rendezvous through ``initialize_cluster``, run the task, print
the JSON result behind a marker the driver greps for.  This is the worker
half of the reference's handshake (NetworkManager.scala:123-169 — there the
worker phones the driver's ServerSocket and blocks on the machine-list
reply; here ``jax.distributed.initialize`` is both legs).

Gang supervision hooks (all driver-controlled via env):

- ``SMLTPU_HB_INTERVAL_S`` > 0 starts the heartbeat emitter thread FIRST,
  so the driver distinguishes "still importing jax" (beats flowing, no
  step) from "process wedged" (beats stopped) from "boot failure" (no
  beat at all).
- ``SMLTPU_RENDEZVOUS_TIMEOUT_S`` arms a host-side watchdog around the
  blocking ``initialize_cluster`` call: a coordinator that never answers
  becomes a structured :class:`~synapseml_tpu.parallel.collectives.
  CollectiveTimeout` (op ``rendezvous``) and a fast non-zero exit, not an
  indefinitely-hung rank.
- ``SMLTPU_CKPT_DIR`` names the gang's checkpoint directory; tasks read
  it to resume elastically after a relaunch.

Run as ``python -m synapseml_tpu.parallel.worker`` with the SMLTPU_* env
set by ``launcher.run_on_local_cluster``.
"""

from __future__ import annotations

import importlib
import json
import os
import sys


def main() -> int:
    coordinator = os.environ["SMLTPU_COORDINATOR"]
    n_procs = int(os.environ["SMLTPU_NUM_PROCESSES"])
    rank = int(os.environ["SMLTPU_PROCESS_ID"])
    platform = os.environ.get("SMLTPU_PLATFORM") or None
    local_devices = int(os.environ.get("SMLTPU_LOCAL_DEVICES", "0")) or None
    task = os.environ["SMLTPU_TASK"]
    task_args = json.loads(os.environ.get("SMLTPU_TASK_ARGS", "null"))

    # heartbeats first: the gang supervisor must see this rank alive
    # before (and during) the slow rendezvous below
    from synapseml_tpu.parallel import heartbeat
    emitter = heartbeat.start_emitter(rank)

    from synapseml_tpu.parallel.distributed import (ClusterConfig,
                                                    initialize_cluster,
                                                    shutdown_cluster)
    cfg = ClusterConfig(
        coordinator_address=coordinator,
        num_processes=n_procs,
        process_id=rank,
        platform=platform,
        local_device_count=local_devices,
    )
    rdv_timeout = float(
        os.environ.get("SMLTPU_RENDEZVOUS_TIMEOUT_S", "0") or 0)
    if rdv_timeout > 0:
        from synapseml_tpu.parallel.collectives import dispatch_watchdog
        dispatch_watchdog(initialize_cluster, cfg,
                          op="rendezvous", axis="-",
                          timeout_s=rdv_timeout)
    else:
        initialize_cluster(cfg)
    heartbeat.beat(step=0)        # rendezvoused: step 0 is reachable

    mod_name, fn_name = task.split(":", 1)
    fn = getattr(importlib.import_module(mod_name), fn_name)
    result = fn(task_args)
    # marker line is the contract with launcher.run_on_local_cluster —
    # a single write call so the heartbeat thread's lines cannot land
    # between the result text and its newline
    sys.stdout.write("SMLMP_RESULT:" + json.dumps(result) + "\n")
    sys.stdout.flush()
    # keep beating THROUGH the distributed shutdown: it can take longer
    # than the hang threshold, and a rank finishing cleanly must not be
    # declared hung in its last second
    shutdown_cluster()
    if emitter is not None:
        emitter.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main())
