"""Worker entry point for the multi-process launcher.

One OS process per cluster rank: configure the backend BEFORE it
initializes, rendezvous through ``initialize_cluster``, run the task, print
the JSON result behind a marker the driver greps for.  This is the worker
half of the reference's handshake (NetworkManager.scala:123-169 — there the
worker phones the driver's ServerSocket and blocks on the machine-list
reply; here ``jax.distributed.initialize`` is both legs).

Run as ``python -m synapseml_tpu.parallel.worker`` with the SMLTPU_* env
set by ``launcher.run_on_local_cluster``.
"""

from __future__ import annotations

import importlib
import json
import os
import sys


def main() -> int:
    coordinator = os.environ["SMLTPU_COORDINATOR"]
    n_procs = int(os.environ["SMLTPU_NUM_PROCESSES"])
    rank = int(os.environ["SMLTPU_PROCESS_ID"])
    platform = os.environ.get("SMLTPU_PLATFORM") or None
    local_devices = int(os.environ.get("SMLTPU_LOCAL_DEVICES", "0")) or None
    task = os.environ["SMLTPU_TASK"]
    task_args = json.loads(os.environ.get("SMLTPU_TASK_ARGS", "null"))

    from synapseml_tpu.parallel.distributed import (ClusterConfig,
                                                    initialize_cluster,
                                                    shutdown_cluster)
    initialize_cluster(ClusterConfig(
        coordinator_address=coordinator,
        num_processes=n_procs,
        process_id=rank,
        platform=platform,
        local_device_count=local_devices,
    ))

    mod_name, fn_name = task.split(":", 1)
    fn = getattr(importlib.import_module(mod_name), fn_name)
    result = fn(task_args)
    # marker line is the contract with launcher.run_on_local_cluster
    print("SMLMP_RESULT:" + json.dumps(result), flush=True)
    shutdown_cluster()
    return 0


if __name__ == "__main__":
    sys.exit(main())
