"""Deterministic partition→chip placement.

The reference maps Spark partitions onto LightGBM ranks with a deterministic
ordering — machines sorted by (host, min partition id), executor→partition
map broadcast from the driver (reference: NetworkManager.scala:171-180,
309-315; PartitionTaskContext offsets BasePartitionTask.scala:105-112).
Here the same contract maps Dataset partitions onto mesh coordinates:
partition ids are assigned in CONTIGUOUS BLOCKS over the data axis in
device order (like Spark's executor→partition grouping; the device order
is itself deterministic — mesh device grid order), or round-robin when a
caller asks for ``strategy="round_robin"`` interleaving.  The same
assignment core (:func:`partition_assignment`) groups gang ranks into
intra-host blocks for the collective planner's hierarchical strategies
(:mod:`synapseml_tpu.parallel.planner`), so placement and reduction
grouping cannot drift apart.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

from jax.sharding import Mesh

from .mesh import DATA_AXIS

#: accepted :func:`place_partitions` strategies
PLACEMENT_STRATEGIES = ("block", "round_robin")


@dataclasses.dataclass(frozen=True)
class PlacementMap:
    """partition id -> (data-axis rank, device id); the machine-list analogue."""
    partition_to_rank: Dict[int, int]
    rank_to_partitions: Dict[int, List[int]]
    num_ranks: int

    def partitions_for_rank(self, rank: int) -> List[int]:
        return self.rank_to_partitions.get(rank, [])


def partition_assignment(num_partitions: int, num_ranks: int,
                         strategy: str = "block") -> PlacementMap:
    """The mesh-free assignment core behind :func:`place_partitions`.

    ``"block"``: rank r gets the contiguous run ``[r*k, (r+1)*k)`` with
    the remainder spread over the first ranks.  ``"round_robin"``:
    partition p goes to rank ``p % num_ranks``.  Both are stable across
    runs for a given ``(num_partitions, num_ranks)``.  Also used by the
    collective planner to carve gang ranks into intra-host groups
    (partitions = global ranks, ranks = hosts).
    """
    if strategy not in PLACEMENT_STRATEGIES:
        raise ValueError(f"strategy={strategy!r}: must be one of "
                         f"{PLACEMENT_STRATEGIES}")
    num_ranks = int(num_ranks)
    p2r: Dict[int, int] = {}
    r2p: Dict[int, List[int]] = {r: [] for r in range(num_ranks)}
    if strategy == "round_robin":
        for pid in range(num_partitions):
            r = pid % num_ranks
            p2r[pid] = r
            r2p[r].append(pid)
    else:
        base, rem = divmod(num_partitions, num_ranks)
        pid = 0
        for r in range(num_ranks):
            count = base + (1 if r < rem else 0)
            for _ in range(count):
                p2r[pid] = r
                r2p[r].append(pid)
                pid += 1
    return PlacementMap(p2r, r2p, num_ranks)


def place_partitions(num_partitions: int, mesh: Mesh,
                     axis: str = DATA_AXIS,
                     strategy: str = "block") -> PlacementMap:
    """Deterministically assign partitions to data-axis ranks.

    Default ``strategy="block"`` is contiguous block assignment (like
    Spark's executor→partition grouping) — the layout
    :func:`rows_for_rank` relies on to return one contiguous row range.
    ``strategy="round_robin"`` interleaves partitions over ranks
    instead (load-levelling when partition sizes trend — the ordering
    the module docstring historically promised; now it is a knob, not a
    misdescription).
    """
    return partition_assignment(num_partitions, mesh.shape[axis], strategy)


def rows_for_rank(ds, placement: PlacementMap, rank: int) -> Tuple[int, int]:
    """Row range [start, end) owned by a data-axis rank, following the
    contiguous partition blocks (requires a ``"block"`` placement —
    round-robin ranks own non-contiguous partitions, so a single range
    cannot describe them)."""
    parts = placement.partitions_for_rank(rank)
    bounds = ds.partition_bounds()
    if not parts:
        return (0, 0)
    if parts != list(range(parts[0], parts[-1] + 1)):
        raise ValueError(
            f"rank {rank} owns non-contiguous partitions {parts} "
            "(round_robin placement?) — rows_for_rank needs block "
            "placement")
    return (bounds[parts[0]][0], bounds[parts[-1]][1])
