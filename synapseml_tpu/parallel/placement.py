"""Deterministic partition→chip placement.

The reference maps Spark partitions onto LightGBM ranks with a deterministic
ordering — machines sorted by (host, min partition id), executor→partition
map broadcast from the driver (reference: NetworkManager.scala:171-180,
309-315; PartitionTaskContext offsets BasePartitionTask.scala:105-112).
Here the same contract maps Dataset partitions onto mesh coordinates:
partition ids are assigned round-robin over the data axis in device order,
which is itself deterministic (mesh device grid order).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

import numpy as np
from jax.sharding import Mesh

from .mesh import DATA_AXIS


@dataclasses.dataclass(frozen=True)
class PlacementMap:
    """partition id -> (data-axis rank, device id); the machine-list analogue."""
    partition_to_rank: Dict[int, int]
    rank_to_partitions: Dict[int, List[int]]
    num_ranks: int

    def partitions_for_rank(self, rank: int) -> List[int]:
        return self.rank_to_partitions.get(rank, [])


def place_partitions(num_partitions: int, mesh: Mesh,
                     axis: str = DATA_AXIS) -> PlacementMap:
    """Deterministically assign partitions to data-axis ranks.

    Contiguous block assignment (like Spark's executor→partition grouping):
    rank r gets partitions [r*k, (r+1)*k) with the remainder spread over the
    first ranks — stable across runs for a given (num_partitions, mesh).
    """
    num_ranks = mesh.shape[axis]
    base, rem = divmod(num_partitions, num_ranks)
    p2r: Dict[int, int] = {}
    r2p: Dict[int, List[int]] = {r: [] for r in range(num_ranks)}
    pid = 0
    for r in range(num_ranks):
        count = base + (1 if r < rem else 0)
        for _ in range(count):
            p2r[pid] = r
            r2p[r].append(pid)
            pid += 1
    return PlacementMap(p2r, r2p, num_ranks)


def rows_for_rank(ds, placement: PlacementMap, rank: int) -> Tuple[int, int]:
    """Row range [start, end) owned by a data-axis rank, following the
    contiguous partition blocks."""
    parts = placement.partitions_for_rank(rank)
    bounds = ds.partition_bounds()
    if not parts:
        return (0, 0)
    return (bounds[parts[0]][0], bounds[parts[-1]][1])
