"""Image pipeline stages.

Re-designs the reference's per-row OpenCV stage pipeline
(reference: opencv/.../ImageTransformer.scala:643-675 — a list of
ImageTransformerStage specs applied row-by-row through JNI) as ONE
batched XLA program: equally-sized images are stacked to (N, H, W, C)
and every stage runs on the whole batch; ragged batches are grouped by
shape first.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from ..core.dataset import Dataset
from ..core.params import (BoolParam, IntParam, ListParam, PyObjectParam,
                           StringParam)
from ..core.pipeline import Transformer
from . import ops


class ImageTransformer(Transformer):
    """Chained image ops (reference: opencv ImageTransformer stage list:
    resize/crop/colorFormat/blur/threshold/gaussianKernel/flip).

    Use the fluent helpers::

        ImageTransformer(inputCol="image").resize(224, 224).blur(5, 1.5)

    Stage specs serialize as plain dicts (the reference serializes stage
    name + params the same way).
    """

    inputCol = StringParam(doc="image column (H,W,C arrays)", default="image")
    outputCol = StringParam(doc="output image column", default="out_image")
    stages = ListParam(doc="ordered op specs", default=None)
    toTensor = BoolParam(doc="emit float32 CHW tensor (toTensor param)",
                         default=False)
    normalizeMean = ListParam(doc="per-channel mean for tensor output")
    normalizeStd = ListParam(doc="per-channel std for tensor output")
    colorScaleFactor = PyObjectParam(doc="scalar scale before normalize")

    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None, **kw):
        super().__init__(**kw)
        if inputCol is not None:
            self.set("inputCol", inputCol)
        if outputCol is not None:
            self.set("outputCol", outputCol)

    # -- fluent builders (reference ImageTransformer setters) --------------
    def _append(self, spec: Dict[str, Any]) -> "ImageTransformer":
        cur = list(self.get_or_default("stages") or [])
        cur.append(spec)
        self.set("stages", cur)
        return self

    def resize(self, height: int, width: int) -> "ImageTransformer":
        return self._append({"op": "resize", "height": height, "width": width})

    def crop(self, x: int, y: int, height: int, width: int) -> "ImageTransformer":
        return self._append({"op": "crop", "x": x, "y": y,
                             "height": height, "width": width})

    def center_crop(self, height: int, width: int) -> "ImageTransformer":
        """Crop centered on the image midpoint, clamped to the image size
        (reference: CenterCropImage, opencv/.../ImageTransformer.scala:139)."""
        return self._append({"op": "centercrop", "height": int(height),
                             "width": int(width)})

    def color_format(self, mode: str) -> "ImageTransformer":
        return self._append({"op": "color", "mode": mode})

    def blur(self, aperture: int, sigma: float) -> "ImageTransformer":
        return self._append({"op": "blur", "aperture": int(aperture),
                             "sigma": float(sigma)})

    def threshold(self, thresh: float, max_val: float = 255.0) -> "ImageTransformer":
        return self._append({"op": "threshold", "threshold": float(thresh),
                             "maxVal": float(max_val)})

    def gaussian_kernel(self, aperture: int, sigma: float) -> "ImageTransformer":
        return self._append({"op": "gaussian", "aperture": int(aperture),
                             "sigma": float(sigma)})

    def flip(self, flip_code: int = 1) -> "ImageTransformer":
        return self._append({"op": "flip", "flipCode": int(flip_code)})

    def normalize(self, mean: Sequence[float], std: Sequence[float],
                  color_scale_factor: float = 1 / 255.0) -> "ImageTransformer":
        self.set("toTensor", True)
        self.set("normalizeMean", [float(m) for m in mean])
        self.set("normalizeStd", [float(s) for s in std])
        self.set("colorScaleFactor", float(color_scale_factor))
        return self

    # -- execution ---------------------------------------------------------
    def _apply_batch(self, batch: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        x = jnp.asarray(batch, jnp.float32)
        for spec in self.get_or_default("stages") or []:
            op = spec["op"]
            if op == "resize":
                x = ops.resize_bilinear(x, spec["height"], spec["width"])
            elif op == "crop":
                x = ops.center_crop(x, spec["x"], spec["y"],
                                    spec["width"], spec["height"])
            elif op == "centercrop":
                h, w = int(x.shape[1]), int(x.shape[2])
                ch = min(spec["height"], h)
                cw = min(spec["width"], w)
                x = ops.center_crop(x, w // 2 - cw // 2, h // 2 - ch // 2,
                                    cw, ch)
            elif op == "color":
                x = ops.color_convert(x, spec["mode"])
            elif op in ("blur", "gaussian"):
                x = ops.gaussian_blur(x, spec["aperture"], spec["sigma"])
            elif op == "threshold":
                x = ops.threshold(x, spec["threshold"], spec["maxVal"])
            elif op == "flip":
                x = ops.flip(x, spec["flipCode"])
            else:
                raise ValueError(f"unknown image op {op!r}")
        out = np.asarray(x)
        if self.toTensor:
            scale = float(self.get_or_default("colorScaleFactor") or 1.0)
            out = out * scale
            mean = self.get_or_default("normalizeMean")
            std = self.get_or_default("normalizeStd")
            if mean is not None:
                out = (out - np.asarray(mean, np.float32)) / \
                    np.asarray(std, np.float32)
            out = np.moveaxis(out, -1, 1)  # NHWC -> NCHW tensor convention
        return out

    def _transform(self, ds: Dataset) -> Dataset:
        col = ds[self.inputCol]
        imgs = [np.asarray(v) for v in col]
        # group equal shapes so each group is one batched XLA call
        by_shape: Dict[tuple, List[int]] = {}
        for i, im in enumerate(imgs):
            by_shape.setdefault(im.shape, []).append(i)
        results: List[Optional[np.ndarray]] = [None] * len(imgs)
        for shape, idxs in by_shape.items():
            batch = np.stack([imgs[i] for i in idxs]).astype(np.float32)
            if batch.ndim == 3:  # grayscale H,W -> H,W,1
                batch = batch[..., None]
            out = self._apply_batch(batch)
            for k, i in enumerate(idxs):
                results[i] = out[k]
        out_col = np.empty(len(imgs), dtype=object)
        for i, r in enumerate(results):
            out_col[i] = r
        return ds.with_column(self.outputCol, out_col)


class UnrollImage(Transformer):
    """Flatten an image column into a numeric vector column
    (reference: image/UnrollImage.scala:169 — OpenCV-channel-order aware)."""

    inputCol = StringParam(doc="image column", default="image")
    outputCol = StringParam(doc="vector output", default="unrolled")

    def __init__(self, inputCol: Optional[str] = None,
                 outputCol: Optional[str] = None, **kw):
        super().__init__(**kw)
        if inputCol is not None:
            self.set("inputCol", inputCol)
        if outputCol is not None:
            self.set("outputCol", outputCol)

    def _transform(self, ds: Dataset) -> Dataset:
        col = ds[self.inputCol]
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(col):
            out[i] = np.asarray(v, np.float64).ravel()
        return ds.with_column(self.outputCol, out)


class UnrollBinaryImage(UnrollImage):
    """Parity alias (reference: image/UnrollBinaryImage.scala) — binary
    payloads are decoded by the IO layer before reaching this stage."""


class ImageSetAugmenter(Transformer):
    """Supplement a training set with flipped copies of its images
    (reference: opencv/.../ImageSetAugmenter.scala:20-67 — identity rows
    plus a left-right and/or up-down flipped union, other columns kept)."""

    inputCol = StringParam(doc="image column", default="image")
    outputCol = StringParam(doc="augmented image column", default="augmented")
    flipLeftRight = BoolParam(doc="add left-right flipped copies",
                              default=True)
    flipUpDown = BoolParam(doc="add up-down flipped copies", default=False)

    def _transform(self, ds: Dataset) -> Dataset:
        out = ds.with_column(self.outputCol, ds[self.inputCol])
        # OpenCV flip codes (ImageTransformer.flip): 1 = left-right, 0 = up-down
        for enabled, code in ((self.flipLeftRight, 1), (self.flipUpDown, 0)):
            if not enabled:
                continue
            flipped = (ImageTransformer(inputCol=self.inputCol,
                                        outputCol=self.outputCol)
                       .flip(code).transform(ds))
            # keep the augmented column dtype-homogeneous with the identity
            # rows (ImageTransformer computes in float32)
            col = flipped[self.outputCol]
            src = ds[self.inputCol]
            cast = np.empty(len(col), object)
            for i in range(len(col)):
                cast[i] = np.asarray(col[i]).astype(
                    np.asarray(src[i]).dtype)
            out = out.union(flipped.with_column(self.outputCol, cast))
        return out
