"""Batched jnp image kernels.

Each op is the XLA re-design of one reference OpenCV stage
(reference: opencv/.../ImageTransformer.scala — ResizeImage:68,
CropImage:109, ColorFormat:148, Blur:171, Threshold:196,
GaussianKernel:221, Flip:252): all take (N, H, W, C) float32 batches so
convolutions map onto the MXU and elementwise ops fuse.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax


@partial(jax.jit, static_argnames=("out_h", "out_w"))
def resize_bilinear(images: jnp.ndarray, out_h: int, out_w: int) -> jnp.ndarray:
    """(N,H,W,C) -> (N,out_h,out_w,C); XLA's optimized resize."""
    n, _, _, c = images.shape
    return jax.image.resize(images, (n, out_h, out_w, c), method="bilinear")


@partial(jax.jit, static_argnames=("x", "y", "w", "h"))
def center_crop(images: jnp.ndarray, x: int, y: int, w: int, h: int) -> jnp.ndarray:
    """CropImage analogue: fixed rectangle (static under jit)."""
    return lax.slice(images, (0, y, x, 0),
                     (images.shape[0], y + h, x + w, images.shape[3]))


def gaussian_kernel(aperture: int, sigma: float) -> np.ndarray:
    """Separable 1-D gaussian taps (GaussianKernel stage analogue)."""
    half = aperture // 2
    xs = np.arange(-half, half + 1, dtype=np.float64)
    k = np.exp(-0.5 * (xs / max(sigma, 1e-9)) ** 2)
    return (k / k.sum()).astype(np.float32)


@partial(jax.jit, static_argnames=("aperture",))
def gaussian_blur(images: jnp.ndarray, aperture: int, sigma: float) -> jnp.ndarray:
    """Separable gaussian blur as two depthwise convs (Blur analogue —
    the reference calls cv2.GaussianBlur per row)."""
    k = _gauss_taps(aperture, sigma)
    n, h, w, c = images.shape
    x = jnp.moveaxis(images, -1, 1).reshape(n * c, 1, h, w)
    kh = k.reshape(1, 1, aperture, 1)
    kw = k.reshape(1, 1, 1, aperture)
    x = lax.conv_general_dilated(x, kh, (1, 1), padding="SAME")
    x = lax.conv_general_dilated(x, kw, (1, 1), padding="SAME")
    return jnp.moveaxis(x.reshape(n, c, h, w), 1, -1)


def _gauss_taps(aperture: int, sigma):
    half = aperture // 2
    xs = jnp.arange(-half, half + 1, dtype=jnp.float32)
    k = jnp.exp(-0.5 * (xs / jnp.maximum(sigma, 1e-9)) ** 2)
    return k / k.sum()


@partial(jax.jit, static_argnames=("flip_code",))
def flip(images: jnp.ndarray, flip_code: int = 1) -> jnp.ndarray:
    """OpenCV flip codes: 0 = vertical (up/down), >0 horizontal, <0 both."""
    if flip_code == 0:
        return images[:, ::-1, :, :]
    if flip_code > 0:
        return images[:, :, ::-1, :]
    return images[:, ::-1, ::-1, :]


@jax.jit
def threshold(images: jnp.ndarray, thresh: float, max_val: float) -> jnp.ndarray:
    """Binary threshold (Threshold stage, cv2.THRESH_BINARY)."""
    return jnp.where(images > thresh, max_val, 0.0)


_BGR_TO_GRAY = jnp.asarray([0.114, 0.587, 0.299], jnp.float32)


@partial(jax.jit, static_argnames=("mode",))
def color_convert(images: jnp.ndarray, mode: str) -> jnp.ndarray:
    """ColorFormat analogue; modes: gray (BGR weights), rgb<->bgr swap."""
    if mode == "gray":
        g = (images * _BGR_TO_GRAY).sum(-1, keepdims=True)
        return g
    if mode in ("bgr2rgb", "rgb2bgr"):
        return images[..., ::-1]
    raise ValueError(f"unknown color mode {mode!r}")
