"""SLIC-style superpixel clustering.

Re-designs the reference's superpixel support (reference:
image/Superpixel.scala:147 — SLIC-ish cluster growth used by image
explainers; image/SuperpixelTransformer.scala:37).  The clustering is a
fixed-iteration-count SLIC: k-means in (color, position) space with
centers initialized on a grid — all distance updates are batched jnp so
the per-image cost is a handful of fused XLA ops.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..core.dataset import Dataset
from ..core.params import FloatParam, IntParam, StringParam
from ..core.pipeline import Transformer


@partial(jax.jit, static_argnames=("gh", "gw", "iters"))
def _slic(img, yy, xx, gh: int, gw: int, iters: int, spatial_weight):
    """img (H,W,C) float32; returns (H,W) int32 segment labels."""
    h, w, c = img.shape
    # grid-initialized centers: color mean at grid point + position
    cy = (jnp.arange(gh) + 0.5) * (h / gh)
    cx = (jnp.arange(gw) + 0.5) * (w / gw)
    centers_pos = jnp.stack(jnp.meshgrid(cy, cx, indexing="ij"),
                            -1).reshape(-1, 2)                  # (K, 2)
    ci = jnp.clip(centers_pos[:, 0].astype(jnp.int32), 0, h - 1)
    cj = jnp.clip(centers_pos[:, 1].astype(jnp.int32), 0, w - 1)
    centers_col = img[ci, cj]                                   # (K, C)

    pix_col = img.reshape(-1, c)                                # (P, C)
    pix_pos = jnp.stack([yy.ravel(), xx.ravel()], -1)           # (P, 2)

    def step(_, carry):
        centers_col, centers_pos = carry
        d_col = ((pix_col[:, None, :] - centers_col[None]) ** 2).sum(-1)
        d_pos = ((pix_pos[:, None, :] - centers_pos[None]) ** 2).sum(-1)
        d = d_col + spatial_weight * d_pos                      # (P, K)
        assign = jnp.argmin(d, axis=1)
        onehot = jax.nn.one_hot(assign, centers_col.shape[0],
                                dtype=jnp.float32)              # (P, K)
        counts = onehot.sum(0)[:, None] + 1e-6
        new_col = (onehot.T @ pix_col) / counts
        new_pos = (onehot.T @ pix_pos) / counts
        return (new_col, new_pos)

    centers_col, centers_pos = jax.lax.fori_loop(
        0, iters, step, (centers_col, centers_pos))
    d_col = ((pix_col[:, None, :] - centers_col[None]) ** 2).sum(-1)
    d_pos = ((pix_pos[:, None, :] - centers_pos[None]) ** 2).sum(-1)
    assign = jnp.argmin(d_col + spatial_weight * d_pos, axis=1)
    return assign.reshape(h, w).astype(jnp.int32)


def slic_segments(img: np.ndarray, cell_size: float = 16.0,
                  modifier: float = 130.0, iters: int = 5) -> np.ndarray:
    """(H, W, C) image -> (H, W) int32 superpixel labels, contiguous from 0.

    ``cell_size`` and ``modifier`` mirror the reference's Superpixel params
    (cellSize ≈ target superpixel side; modifier ≈ compactness: larger =
    more color-driven boundaries)."""
    img = np.asarray(img, np.float32)
    if img.ndim == 2:
        img = img[..., None]
    h, w = img.shape[:2]
    gh = max(1, int(round(h / cell_size)))
    gw = max(1, int(round(w / cell_size)))
    yy, xx = np.meshgrid(np.arange(h, dtype=np.float32),
                         np.arange(w, dtype=np.float32), indexing="ij")
    # compactness: color range / modifier scales the spatial term
    spatial_weight = np.float32((max(modifier, 1e-3) / cell_size) ** 2) / 255.0
    seg = np.asarray(_slic(jnp.asarray(img), jnp.asarray(yy), jnp.asarray(xx),
                           gh, gw, iters, jnp.float32(spatial_weight)))
    # relabel contiguous (empty clusters removed)
    uniq, inv = np.unique(seg, return_inverse=True)
    return inv.reshape(h, w).astype(np.int32)


class SuperpixelTransformer(Transformer):
    """Attach superpixel assignments to an image column
    (reference: image/SuperpixelTransformer.scala:37)."""

    inputCol = StringParam(doc="image column", default="image")
    outputCol = StringParam(doc="segment-label output", default="superpixels")
    cellSize = FloatParam(doc="target superpixel side length", default=16.0)
    modifier = FloatParam(doc="compactness", default=130.0)

    def __init__(self, inputCol: Optional[str] = None, **kw):
        super().__init__(**kw)
        if inputCol is not None:
            self.set("inputCol", inputCol)

    def _transform(self, ds: Dataset) -> Dataset:
        col = ds[self.inputCol]
        out = np.empty(len(col), dtype=object)
        for i, v in enumerate(col):
            out[i] = slic_segments(np.asarray(v), self.cellSize, self.modifier)
        return ds.with_column(self.outputCol, out)
