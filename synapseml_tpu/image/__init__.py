"""Image ops — the OpenCV-module and core-image equivalents.

The reference ships two image layers: JNI OpenCV stages
(opencv/.../ImageTransformer.scala:68-283 — Resize/Crop/ColorFormat/Blur/
Threshold/GaussianKernel/Flip applied per row) and pure-Scala helpers
(image/UnrollImage.scala:169, image/SuperpixelTransformer.scala:37).
Here every pixel op is a jnp/XLA kernel over a stacked (N, H, W, C)
batch — no per-row JNI, one fused program per pipeline.
"""

from .ops import (gaussian_kernel, gaussian_blur, resize_bilinear,
                  center_crop, flip, threshold, color_convert)
from .stages import (ImageSetAugmenter, ImageTransformer, UnrollImage,
                     UnrollBinaryImage)
from .superpixel import SuperpixelTransformer, slic_segments

__all__ = [
    "gaussian_kernel", "gaussian_blur", "resize_bilinear", "center_crop",
    "flip", "threshold", "color_convert",
    "ImageSetAugmenter", "ImageTransformer", "UnrollImage", "UnrollBinaryImage",
    "SuperpixelTransformer", "slic_segments",
]
