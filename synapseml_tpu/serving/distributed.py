"""Distributed serving: one listener per host of a multi-host mesh with a
shared routing table.

The reference ships one HTTP server per executor JVM and a driver-held
service registry so a front door can reach every partition's server
(reference: DistributedHTTPSource.scala:88,203, HTTPSourceV2 ServiceInfo).
The TPU-native analogue: every PROCESS of the cluster starts a local
:class:`~synapseml_tpu.serving.server.ServingServer`, and the routing
table is rendezvoused over the mesh itself — each process contributes its
``(ip, port)`` through an ``all_gather`` over the data axis, so the same
collective fabric that carries training gradients also publishes the
serving topology.  Any rank (or an external balancer) can then route
requests to every host.

Failover: the gathered table is a *topology*, not a liveness claim — a
replica can die or drain at any time.  :class:`ReplicaRouter` layers the
PR-2 health contract on top: per-replica ``/healthz``+``/readyz`` probes,
per-replica circuit breakers (``breaker_for``), and a :meth:`~
ReplicaRouter.route` that round-robins over replicas while skipping
dead/draining ones and NEVER returning a replica whose breaker is open.
After an elastic gang restart OR RESIZE, :meth:`DistributedServingServer.
refresh_routing_table` re-gathers the table over the re-formed mesh and
rebuilds the router — a shrink/grow is just a shorter/longer table: the
round-robin cursor clamps, departed endpoints release their process-wide
breakers (``drop_breaker``) and probe-gauge rows, and a departing
replica flushes its in-flight exchanges through :meth:`
DistributedServingServer.leave` (the PR-2 zero-drop ``drain()`` path),
so a resize drops nothing.  Health is exported as
``serving_replicas_healthy{router}``.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
import urllib.error
import urllib.request
from collections import OrderedDict
from typing import Dict, List, NamedTuple, Optional, Tuple

import numpy as np

from ..resilience import breaker_for, drop_breaker
from ..resilience.faults import get_faults
from ..telemetry import get_registry
from ..telemetry.flight import record as flight_record
from .server import ServingServer

#: replica probe states.  WARMING is the compile plane's pre-ready
#: window (readyz 503 with status "warming"): routable exactly like
#: DRAINING — skipped without a breaker signal — so a resized-in
#: replica absorbs no traffic until its program lattice is warm, and
#: nobody's breaker opens over a replica that is merely compiling.
HEALTHY, DRAINING, DEAD, WARMING = ("healthy", "draining", "dead",
                                    "warming")

#: replica roles a routing table can carry (disaggregated serving:
#: decode replicas hold slots and stream tokens, prefill replicas are
#: compute-bound batch prefillers that hand their K/V off).  The index
#: of a name here is what rides the routing-table collective.
ROLE_NAMES: Tuple[str, ...] = ("decode", "prefill")


def _role_index(role: str) -> int:
    try:
        return ROLE_NAMES.index(role)
    except ValueError:
        raise ValueError(f"unknown replica role {role!r} "
                         f"(expected one of {ROLE_NAMES})")


class RouteResult(NamedTuple):
    """One routing decision, named.  The positional tuples this
    replaces grew a field per PR (rank → url → addr → affinity outcome
    → trace headers) and broke arity-sensitive unpacking once already;
    every router surface now returns THIS shape and call sites read
    fields by name.  ``headers`` is only populated by
    :meth:`DistributedServingServer.route_request` (trace/tenant
    propagation) — plain :meth:`~ReplicaRouter.route` fills it with a
    fresh empty dict."""
    #: table index of the routed replica (valid until the next refresh)
    rank: int
    #: the routed ``(host, port)`` captured under the router lock —
    #: hand back to ``report(addr=)`` so the report survives renumbering
    addr: Tuple[str, int]
    #: full request url for the routed replica
    url: str
    #: session-affinity outcome: ``hit`` / ``miss`` / ``repin``
    #: (repin ⇒ the pinned replica was lost: engage failover-restore)
    outcome: str
    #: headers to attach to the forwarded request
    headers: Dict[str, str]


class NoHealthyReplicaError(RuntimeError):
    """Every replica is dead, draining, or breaker-open."""

    def __init__(self, statuses: Dict[int, str]):
        super().__init__(
            "no routable replica: " + ", ".join(
                f"rank {r}: {s}" for r, s in sorted(statuses.items())))
        self.statuses = dict(statuses)


def _encode_addr(host: str, port: int) -> Tuple[int, int]:
    """(ip4 as uint32, port) — what rides the collective."""
    packed = struct.unpack("!I", socket.inet_aton(socket.gethostbyname(host)))
    return int(packed[0]), int(port)


def _decode_addr(ip_u32: int, port: int) -> Tuple[str, int]:
    return socket.inet_ntoa(struct.pack("!I", int(ip_u32) & 0xffffffff)), \
        int(port)


def exchange_routing_table(host: str, port: int,
                           deadline=None,
                           timeout_s: Optional[float] = None,
                           role: int = 0
                           ) -> Tuple[List[Tuple[str, int]], List[int]]:
    """All-gather this process's listener address over the global device
    mesh → ``([(host, port)], [role])`` indexed by process.  ``role`` is
    this process's :data:`ROLE_NAMES` index (0 = decode), gathered
    alongside the address so a disaggregated deployment publishes WHICH
    pool each listener belongs to through the same collective.
    Single-process: the local address and role alone (no collective).

    ``deadline``/``timeout_s`` bound the gather itself: when a peer died
    mid-restart the collective would block forever, and the bound turns
    that into a :class:`~synapseml_tpu.parallel.collectives.
    CollectiveTimeout` the gang supervisor handles."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if jax.process_count() == 1:
        return [(host, port)], [int(role)]
    from ..parallel.mesh import DATA_AXIS
    from ..parallel.collectives import (all_gather, dispatch_watchdog,
                                        shard_map_over)

    devs = jax.devices()
    mesh = Mesh(np.array(devs), (DATA_AXIS,))
    n = len(devs)
    ip_u32, port_i = _encode_addr(host, port)
    # each DEVICE row carries its owning process's
    # (ip, port, process_idx, role)
    my_proc = jax.process_index()
    local = np.array([[ip_u32, port_i, my_proc, int(role)]] *
                     jax.local_device_count(), dtype=np.int64)
    # int32 collective: the ip splits into 16-bit halves (each fits int32
    # unmasked — masking bit 31 would corrupt addresses >= 128.0.0.0)
    rows = np.stack([local[:, 0] >> 16, local[:, 0] & 0xffff,
                     local[:, 1], local[:, 2],
                     local[:, 3]], axis=1).astype(np.int32)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(DATA_AXIS)), rows, (n, 5))
    gathered_fn = jax.jit(shard_map_over(mesh, P(DATA_AXIS), P(DATA_AXIS))(
        lambda x: all_gather(x, tiled=True)))
    if deadline is not None or timeout_s is not None:
        gathered = dispatch_watchdog(
            lambda a: jax.block_until_ready(gathered_fn(a)), garr,
            op="all_gather", axis=DATA_AXIS, deadline=deadline,
            timeout_s=timeout_s, payload_bytes=int(rows.nbytes))
    else:
        gathered = gathered_fn(garr)
    table_rows = np.asarray(
        jax.device_get(gathered.addressable_shards[0].data))[:n]
    by_proc: Dict[int, Tuple[Tuple[str, int], int]] = {}
    for hi, lo, p_port, proc, p_role in table_rows:
        ip = (int(hi) << 16) | (int(lo) & 0xffff)
        by_proc[int(proc)] = (_decode_addr(ip, p_port), int(p_role))
    ordered = [by_proc[i] for i in sorted(by_proc)]
    return [addr for addr, _ in ordered], [r for _, r in ordered]


def probe_replica(host: str, port: int,
                  timeout_s: float = 1.0) -> str:
    """One replica's health, from its reserved paths: ``healthy`` (both
    ``/healthz`` and ``/readyz`` answer 200), ``warming`` (alive, but
    the compile plane is still AOT-compiling its program lattice —
    readyz 503 with body status ``"warming"``), ``draining`` (alive but
    readyz says stop routing — PR-2's drain/load-shed state), ``dead``
    (unreachable or healthz failing)."""
    base = f"http://{host}:{port}"
    fault = get_faults().http_fault("serving.probe", host=host, port=port)
    if fault is not None:
        return DEAD if fault[0] >= 500 else DRAINING
    try:
        with urllib.request.urlopen(base + "/healthz",
                                    timeout=timeout_s) as resp:
            if resp.status != 200:
                return DEAD
    except Exception:
        return DEAD
    try:
        with urllib.request.urlopen(base + "/readyz",
                                    timeout=timeout_s) as resp:
            return HEALTHY if resp.status == 200 else DRAINING
    except urllib.error.HTTPError as e:
        if e.code != 503:
            return DEAD
        try:
            status = json.loads(e.read().decode("utf-8")).get("status")
        except Exception:  # noqa: BLE001 — unparseable body: draining
            status = None
        return WARMING if status == "warming" else DRAINING
    except Exception:
        return DEAD


class ReplicaRouter:
    """Health-aware routing over a gathered replica table.

    One breaker per replica (shared process-wide through ``breaker_for``,
    keyed ``replica:<name>:<host>:<port>``): request failures reported via
    :meth:`report` trip it open, and :meth:`route` NEVER returns a
    replica whose breaker is open — an open replica only re-enters
    rotation through the breaker's own half-open probe admission.
    Probe results additionally mark replicas dead/draining so routing
    skips them before a single request is risked.  Thread-safe.
    """

    def __init__(self, table: List[Tuple[str, int]], name: str = "serving",
                 failure_threshold: int = 3, cooldown_s: float = 5.0,
                 probe_timeout_s: float = 1.0,
                 session_cache_size: int = 4096,
                 tenant_pin_cap: Optional[int] = None,
                 roles: Optional[List[str]] = None):
        self.name = name
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.probe_timeout_s = float(probe_timeout_s)
        self._lock = threading.Lock()
        self._rr = 0
        #: (tenant, session) -> (host, port) — keyed by ADDRESS, not
        #: rank, so an elastic resize renumbering the table cannot
        #: silently remap a session onto a stranger's prefix cache; and
        #: by TENANT, so two tenants reusing one session id can never
        #: share a pin.  Bounded LRU with per-tenant fairness: overflow
        #: evicts from the largest-pinning tenant (its own oldest pin),
        #: so one tenant's session churn cannot strip every other
        #: tenant's pins; ``tenant_pin_cap`` additionally hard-caps one
        #: tenant's pins (its cap overflow evicts only its own oldest).
        self._session_cap = int(session_cache_size)
        self._tenant_pin_cap = (int(tenant_pin_cap)
                                if tenant_pin_cap is not None else None)
        self._sessions: "OrderedDict[Tuple[str, str], Tuple[str, int]]" \
            = OrderedDict()
        self._tenant_pins: Dict[str, int] = {}
        self._g_healthy = get_registry().gauge(
            "serving_replicas_healthy",
            "replicas currently probed healthy with a non-open breaker",
            ("router",))
        # per-replica probe verdicts join the gang-level metric surface
        # (the coordinator's /metrics shows every replica's health beside
        # the rank-labeled worker metrics the gang plane mirrors)
        self._g_probe = get_registry().gauge(
            "serving_replica_probe_status",
            "last probe verdict per replica: 1 healthy, 0.5 draining, "
            "0 dead", ("router", "rank"))
        # session-affinity visibility: hit (pinned replica served),
        # miss (first route for a session — a cold pin), repin (pinned
        # replica unroutable, fell back to round-robin and re-pinned —
        # the prefix cache was lost).  A rising repin rate after a
        # resize is the router-side smoking gun for cold-prefill TTFT
        # regressions.
        self._m_affinity = get_registry().counter(
            "serving_affinity_total",
            "session-affinity routing outcomes", ("router", "outcome"))
        self._apply_table(table, roles=roles)

    def _breaker_key(self, host: str, port: int) -> str:
        return f"replica:{self.name}:{host}:{port}"

    def _apply_table(self, table: List[Tuple[str, int]],
                     roles: Optional[List[str]] = None) -> None:
        prev_table = list(getattr(self, "table", ()))
        prev = len(prev_table)
        self.table = [(h, int(p)) for h, p in table]
        # per-rank pool membership (disaggregated serving); a role-less
        # table is the colocated deployment — every replica decodes
        if roles is None:
            self.roles = ["decode"] * len(self.table)
        else:
            if len(roles) != len(self.table):
                raise ValueError(
                    f"roles ({len(roles)}) must match the table "
                    f"({len(self.table)})")
            self.roles = [str(r) for r in roles]
        # a shrunk table must not leave departed replicas' last verdicts
        # on /metrics as phantom healthy rows
        for r in range(len(self.table), prev):
            self._g_probe.remove(router=self.name, rank=str(r))
        # a shrunk table must also not leave the round-robin cursor
        # pointing past the end: route()'s modulo would still be safe,
        # but the cursor is a ROTATION POSITION and a stale one biases
        # the first post-resize pick — reset on shrink, keep on grow
        if self._rr >= len(self.table):
            self._rr = 0
        # optimistic until probed: a fresh table names live listeners
        self._status = {r: HEALTHY for r in range(len(self.table))}
        self._breakers = {
            r: breaker_for(self._breaker_key(h, p),
                           failure_threshold=self.failure_threshold,
                           cooldown_s=self.cooldown_s)
            for r, (h, p) in enumerate(self.table)}
        # departed ENDPOINTS release their process-wide breaker registry
        # entry (and its state gauge row) — an elastic gang resizing
        # every few minutes must not accumulate one breaker per address
        # it ever routed to.  Endpoints still in the table keep their
        # breaker (and its failure history) across the refresh.
        live = {self._breaker_key(h, p) for h, p in self.table}
        for h, p in prev_table:
            key = self._breaker_key(h, p)
            if key not in live:
                drop_breaker(key)
        # address -> rank for session-affinity lookups; sessions pinned
        # to a DEPARTED address fall back cleanly to round-robin (and
        # re-pin) on their next route — a resize loses the prefix cache
        # either way, never the request
        self._addr_rank = {addr: r for r, addr in enumerate(self.table)}
        for key in [s for s, addr in self._sessions.items()
                    if addr not in self._addr_rank]:
            self._drop_pin(key)
        self._update_gauge()

    # -- session-affinity pin bookkeeping (caller holds the lock) ----------
    def _drop_pin(self, key: Tuple[str, str]) -> None:
        if self._sessions.pop(key, None) is not None:
            n = self._tenant_pins.get(key[0], 0) - 1
            if n > 0:
                self._tenant_pins[key[0]] = n
            else:
                self._tenant_pins.pop(key[0], None)

    def _oldest_pin_of(self, tenant: str) -> Optional[Tuple[str, str]]:
        for key in self._sessions:          # LRU order: oldest first
            if key[0] == tenant:
                return key
        return None

    def _insert_pin(self, key: Tuple[str, str],
                    addr: Tuple[str, int]) -> None:
        tenant = key[0]
        if key not in self._sessions:
            cap = self._tenant_pin_cap
            if cap is not None and self._tenant_pins.get(tenant, 0) >= cap:
                # the tenant's own oldest pin makes room: a hard-capped
                # tenant's churn only ever evicts itself
                old = self._oldest_pin_of(tenant)
                if old is not None:
                    self._drop_pin(old)
            self._tenant_pins[tenant] = self._tenant_pins.get(tenant, 0) + 1
        self._sessions[key] = addr
        self._sessions.move_to_end(key)
        while len(self._sessions) > self._session_cap:
            # fairness at overflow: evict the LARGEST-pinning tenant's
            # oldest pin, not the global LRU head — one flooding
            # tenant's churn cannot strip every other tenant's pins
            big = max(self._tenant_pins,
                      key=lambda t: (self._tenant_pins[t], t))
            old = self._oldest_pin_of(big)
            self._drop_pin(old if old is not None
                           else next(iter(self._sessions)))

    def _update_gauge(self) -> None:
        healthy = sum(1 for r in self._status
                      if self._status[r] == HEALTHY
                      and self._breakers[r].state != "open")
        self._g_healthy.set(healthy, router=self.name)

    # -- probing -----------------------------------------------------------
    def probe(self, rank: int) -> str:
        with self._lock:
            if rank >= len(self.table):
                return DEAD            # refreshed away mid-probe-cycle
            h, p = self.table[rank]
        # network I/O outside the lock; writes re-validate the entry so a
        # concurrent refresh() cannot receive a stale rank's result
        status = probe_replica(h, p, timeout_s=self.probe_timeout_s)
        with self._lock:
            if rank < len(self.table) and self.table[rank] == (h, p):
                self._status[rank] = status
                b = self._breakers[rank]
                if status == HEALTHY:
                    # a health probe must not slam an OPEN breaker shut —
                    # request failures opened it, and only its own
                    # cooldown/half-open admission may reclose it.  Once
                    # the cooldown has elapsed (state half-open) a
                    # healthy probe counts as the reclosing success.
                    if b.state != "open":
                        b.record_success()
                elif status == DEAD:
                    b.record_failure()
                # draining is deliberate and warming is transient
                # startup work, not faults: no breaker signal for
                # either — a warming replica re-enters rotation the
                # first probe after its lattice finishes
                self._g_probe.set(
                    {HEALTHY: 1.0, WARMING: 0.75,
                     DRAINING: 0.5}.get(status, 0.0),
                    router=self.name, rank=str(rank))
                self._update_gauge()
        get_faults().note("serving.replica_probe", rank=rank, status=status)
        flight_record("replica_probe", router=self.name, rank=rank,
                      status=status)
        return status

    def probe_all(self) -> Dict[int, str]:
        with self._lock:
            ranks = list(range(len(self.table)))
        return {r: self.probe(r) for r in ranks}

    def statuses(self) -> Dict[int, str]:
        with self._lock:
            return dict(self._status)

    def warming_count(self) -> int:
        """Replicas last probed WARMING — capacity already in flight
        (the compile plane is AOT-warming a resized-in replica), which
        the autoscaler must count against demand instead of growing
        again while the previous grow is still becoming useful."""
        with self._lock:
            return sum(1 for s in self._status.values() if s == WARMING)

    def breaker(self, rank: int):
        return self._breakers[rank]

    # -- routing -----------------------------------------------------------
    def url_for(self, rank: int, path: str = "/") -> str:
        h, p = self.table[rank]
        path = path.rstrip("/") or "/"
        return f"http://{h}:{p}{'' if path == '/' else path}"

    def route(self, path: str = "/",
              session: Optional[str] = None,
              tenant: str = "default",
              role: Optional[str] = None) -> "RouteResult":
        """Next routable replica (round-robin) → :class:`RouteResult`.

        Skips replicas probed dead or draining and replicas whose
        breaker refuses the call (open, or half-open past its probe
        budget).  Raises :class:`NoHealthyReplicaError` with the full
        per-rank status map when nothing is routable.

        ``session`` pins SESSION AFFINITY: repeated routes for the same
        key land on the same replica while it stays routable — a
        multi-turn conversation keeps hitting the replica whose slotted
        KV cache still holds its prefix, so the follow-up turn's prompt
        prefills only its new tail.  When the pinned replica becomes
        unroutable (dead, draining, breaker-open, or dropped by an
        elastic resize), the session falls back to round-robin and
        RE-PINS to the replica it gets — a cold prefill, never a
        failure.  Pins are namespaced by ``tenant``: two tenants
        reusing one session id never share a replica pin.

        ``role`` restricts routing to one pool of a disaggregated
        table (``"decode"``/``"prefill"``); None routes over every
        replica (the colocated deployment)."""
        return self.route_addr(path, session=session, tenant=tenant,
                               role=role)

    def route_addr(self, path: str = "/",
                   session: Optional[str] = None,
                   tenant: str = "default",
                   role: Optional[str] = None) -> "RouteResult":
        """:meth:`route` plus the routed ``(host, port)`` captured under
        the same lock — hand that address back to :meth:`report` and the
        report survives a concurrent :meth:`refresh` renumbering the
        table (no lossy re-parse of the url, no racy
        ``router.table[rank]`` read) — plus the session-affinity
        OUTCOME: ``"hit"`` (pinned replica still routable — its KV
        prefix is warm), ``"miss"`` (first route for the session, or no
        session), ``"repin"`` (the pinned replica was LOST — the
        session's device prefix cache is gone, so the caller should
        engage a restore path instead of silently serving
        context-free).  A pinned replica whose role no longer matches
        the requested pool counts as LOST the same way: the session
        repins into the right pool and the repin outcome still fires
        the caller's failover-restore path."""
        with self._lock:
            n = len(self.table)
            pinned = False
            key = (str(tenant), str(session)) if session is not None \
                else None
            if key is not None:
                addr = self._sessions.get(key)
                pinned = addr is not None
                if addr is not None:
                    r = self._addr_rank.get(addr)
                    if (r is not None and self._status[r] == HEALTHY
                            and (role is None or self.roles[r] == role)
                            and self._breakers[r].allow()):
                        # affinity hit: round-robin cursor untouched —
                        # pinned traffic must not skew the rotation the
                        # unpinned traffic balances on
                        self._sessions.move_to_end(key)
                        self._m_affinity.inc(1, router=self.name,
                                             outcome="hit")
                        return RouteResult(r, addr, self.url_for(r, path),
                                           "hit", {})
            start = self._rr
            for i in range(n):
                r = (start + i) % n
                if role is not None and self.roles[r] != role:
                    continue
                if self._status[r] != HEALTHY:
                    continue
                if not self._breakers[r].allow():
                    continue
                self._rr = (r + 1) % n
                if key is not None:
                    self._insert_pin(key, self.table[r])
                    # a pinned session falling through to round-robin
                    # lost its replica (resize/death/breaker): that is a
                    # REPIN (prefix cache gone); a first-ever route for
                    # the session is a plain miss (cold by definition)
                    self._m_affinity.inc(
                        1, router=self.name,
                        outcome="repin" if pinned else "miss")
                return RouteResult(r, self.table[r], self.url_for(r, path),
                                   "repin" if pinned else "miss", {})
            statuses = {
                r: (f"role {self.roles[r]}" if role is not None
                    and self.roles[r] != role
                    else self._status[r] if self._status[r] != HEALTHY
                    else f"breaker {self._breakers[r].state}")
                for r in range(n)}
        raise NoHealthyReplicaError(statuses)

    def report(self, rank: int, ok: bool,
               addr: Optional[Tuple[str, int]] = None) -> None:
        """Outcome of a routed request — feeds the replica's breaker (a
        breaker fed only by probes would take a whole probe cycle to
        notice a flapping replica).

        A report for a rank a concurrent :meth:`refresh` dropped from
        the table is ignored (never a crash).  Pass ``addr`` — the
        ``(host, port)`` the request actually went to, recoverable from
        :meth:`route`'s url — and a report whose rank was RENUMBERED by
        the refresh (its index now names a different endpoint) is
        ignored too, instead of poisoning the new occupant's breaker;
        without ``addr`` an index-only report cannot detect renumbering
        and is applied to whatever endpoint now holds the index."""
        with self._lock:
            if addr is not None and (rank >= len(self.table)
                                     or self.table[rank] !=
                                     (addr[0], int(addr[1]))):
                return
            b = self._breakers.get(rank)
        if b is None:
            return
        if ok:
            b.record_success()
        else:
            b.record_failure()
        with self._lock:
            self._update_gauge()

    def refresh(self, table: List[Tuple[str, int]],
                roles: Optional[List[str]] = None) -> None:
        """Adopt a re-gathered table (after an elastic restart or
        resize): statuses reset optimistic; breakers persist per
        endpoint still IN the table (a replica that came back on the
        same address keeps its history until its cooldown admits a
        probe), departed endpoints release theirs; the round-robin
        cursor clamps so rotation never starts past the shrunk end.
        ``route()`` calls racing the refresh either route on the old
        table (their replica drains, it does not vanish) or the new —
        never a mix."""
        with self._lock:
            self._apply_table(table, roles=roles)


class DistributedServingServer:
    """One listener on THIS host plus the cluster-wide routing table.

    Start one per process of an initialized cluster; every instance knows
    every host's listener address (``routing_table``), so requests can be
    balanced across the whole mesh while each host's pipeline serves its
    local replica.  Matches the role of one-server-per-executor
    distributed serving (DistributedHTTPSource.scala:88).

    ``router`` (a :class:`ReplicaRouter` over the gathered table) adds
    failover: :meth:`route` skips dead/draining/breaker-open replicas,
    :meth:`probe_replicas` refreshes health from every replica's reserved
    paths, and :meth:`refresh_routing_table` re-gathers the table after
    an elastic gang restart."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", reply_timeout_s: float = 30.0,
                 max_queue: int = 1024,
                 max_body_bytes: int = 16 * 1024 * 1024,
                 gather_timeout_s: Optional[float] = None,
                 role: str = "decode"):
        self.local = ServingServer(host=host, port=port, api_path=api_path,
                                   reply_timeout_s=reply_timeout_s,
                                   max_queue=max_queue,
                                   max_body_bytes=max_body_bytes)
        lh, lp = self.local.address
        self._gather_timeout_s = gather_timeout_s
        #: this process's pool membership, published through the gather
        self.role = str(role)
        self.routing_table, role_ids = exchange_routing_table(
            lh, lp, timeout_s=gather_timeout_s,
            role=_role_index(self.role))
        self.routing_roles = [ROLE_NAMES[i] for i in role_ids]
        import jax
        self.router = ReplicaRouter(
            self.routing_table, name=f"dserv-p{jax.process_index()}",
            roles=self.routing_roles)

    @property
    def address(self) -> Tuple[str, int]:
        return self.local.address

    def url_for_rank(self, rank: int, path: str = "/") -> str:
        h, p = self.routing_table[rank]
        path = path.rstrip("/") or "/"
        return f"http://{h}:{p}{'' if path == '/' else path}"

    # -- failover ----------------------------------------------------------
    def route(self, path: str = "/",
              session: Optional[str] = None,
              tenant: str = "default",
              role: Optional[str] = None) -> "RouteResult":
        """Next healthy replica for a request; ``session`` pins
        multi-turn requests to the replica holding their prefix cache,
        namespaced by ``tenant`` (see :meth:`ReplicaRouter.route`);
        ``role`` restricts the route to one disaggregated pool."""
        return self.router.route(path, session=session, tenant=tenant,
                                 role=role)

    def route_addr(self, path: str = "/",
                   session: Optional[str] = None,
                   tenant: str = "default",
                   role: Optional[str] = None) -> "RouteResult":
        """:meth:`route` plus the routed ``(host, port)`` — pass it back
        through :meth:`report_result`'s ``addr=`` so the report survives
        a concurrent table refresh renumbering the ranks — plus the
        affinity outcome (see :meth:`ReplicaRouter.route_addr`)."""
        return self.router.route_addr(path, session=session, tenant=tenant,
                                      role=role)

    def route_request(self, path: str = "/",
                      session: Optional[str] = None,
                      trace_id: Optional[str] = None,
                      tenant: str = "default",
                      role: Optional[str] = None) -> "RouteResult":
        """:meth:`route_addr` plus request-trace propagation: mints a
        trace id at THIS hop when the caller has none, records the
        routing decision on the hop's flight recorder (trace id, rank,
        session, affinity outcome), and fills :attr:`RouteResult.
        headers` with what to attach to the forwarded request
        (``X-SML-Trace-Id``) — the replica's decode loop adopts the id
        (propagated ids are always sampled), so a session-affinity hop
        chain stays attributable end to end.

        ``outcome == "repin"`` is the failover-restore trigger: the
        session's pinned replica is GONE and with it the device prefix
        cache, so the caller marks the forwarded request ``resume`` —
        the new replica rebuilds the conversation from its session
        journal (or host arena) instead of silently serving it
        context-free."""
        from ..telemetry.tracing import mint_trace_id
        from .server import TENANT_HEADER, TRACE_HEADER
        tid = trace_id or mint_trace_id()
        res = self.router.route_addr(path, session=session, tenant=tenant,
                                     role=role)
        flight_record("route", router=self.router.name, trace_id=tid,
                      rank=res.rank, session=session, tenant=tenant,
                      affinity=res.outcome)
        headers = {TRACE_HEADER: tid}
        if tenant != "default":
            headers[TENANT_HEADER] = tenant
        return res._replace(headers=headers)

    def probe_replicas(self) -> Dict[int, str]:
        return self.router.probe_all()

    def report_result(self, rank: int, ok: bool,
                      addr: Optional[Tuple[str, int]] = None) -> None:
        self.router.report(rank, ok, addr=addr)

    def refresh_routing_table(
            self, timeout_s: Optional[float] = None) -> List[Tuple[str, int]]:
        """Re-gather the table over the (re-formed) mesh — call on every
        process after an elastic restart OR resize, collectively — and
        rebuild the router's view from it.  A resize is absorbed, not
        special-cased: the gathered table simply has a different length,
        the router clamps its rotation, departed endpoints release
        their breakers, and in-flight exchanges against a departing
        replica finish through its :meth:`leave` drain."""
        lh, lp = self.local.address
        self.routing_table, role_ids = exchange_routing_table(
            lh, lp, timeout_s=timeout_s or self._gather_timeout_s,
            role=_role_index(self.role))
        self.routing_roles = [ROLE_NAMES[i] for i in role_ids]
        self.router.refresh(self.routing_table, roles=self.routing_roles)
        return self.routing_table

    def leave(self, timeout_s: float = 30.0) -> bool:
        """This replica is departing (elastic shrink): stop admitting —
        probes flip to ``draining``, so every peer's router skips this
        rank before the table even refreshes — then flush EVERY accepted
        in-flight exchange through the PR-2 zero-drop ``drain()`` path
        and close.  Returns drain()'s verdict (True = nothing was
        dropped)."""
        return self.local.drain(timeout_s=timeout_s)

    # local-API passthroughs
    def register_api(self, *a, **kw):
        return self.local.register_api(*a, **kw)

    def get_batch(self, *a, **kw):
        return self.local.get_batch(*a, **kw)

    def reply(self, *a, **kw):
        return self.local.reply(*a, **kw)

    def close(self) -> None:
        self.local.close()
