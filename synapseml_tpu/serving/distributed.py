"""Distributed serving: one listener per host of a multi-host mesh with a
shared routing table.

The reference ships one HTTP server per executor JVM and a driver-held
service registry so a front door can reach every partition's server
(reference: DistributedHTTPSource.scala:88,203, HTTPSourceV2 ServiceInfo).
The TPU-native analogue: every PROCESS of the cluster starts a local
:class:`~synapseml_tpu.serving.server.ServingServer`, and the routing
table is rendezvoused over the mesh itself — each process contributes its
``(ip, port)`` through an ``all_gather`` over the data axis, so the same
collective fabric that carries training gradients also publishes the
serving topology.  Any rank (or an external balancer) can then route
requests to every host.
"""

from __future__ import annotations

import socket
import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from .server import ServingServer


def _encode_addr(host: str, port: int) -> Tuple[int, int]:
    """(ip4 as uint32, port) — what rides the collective."""
    packed = struct.unpack("!I", socket.inet_aton(socket.gethostbyname(host)))
    return int(packed[0]), int(port)


def _decode_addr(ip_u32: int, port: int) -> Tuple[str, int]:
    return socket.inet_ntoa(struct.pack("!I", int(ip_u32) & 0xffffffff)), \
        int(port)


def exchange_routing_table(host: str, port: int) -> List[Tuple[str, int]]:
    """All-gather this process's listener address over the global device
    mesh → ``[(host, port)]`` indexed by process.  Single-process: the
    local address alone (no collective)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    if jax.process_count() == 1:
        return [(host, port)]
    from ..parallel.mesh import DATA_AXIS
    from ..parallel.collectives import all_gather, shard_map_over

    devs = jax.devices()
    mesh = Mesh(np.array(devs), (DATA_AXIS,))
    n = len(devs)
    ip_u32, port_i = _encode_addr(host, port)
    # each DEVICE row carries its owning process's (ip, port, process_idx)
    my_proc = jax.process_index()
    local = np.array([[ip_u32, port_i, my_proc]] *
                     jax.local_device_count(), dtype=np.int64)
    # int32 collective: the ip splits into 16-bit halves (each fits int32
    # unmasked — masking bit 31 would corrupt addresses >= 128.0.0.0)
    rows = np.stack([local[:, 0] >> 16, local[:, 0] & 0xffff,
                     local[:, 1], local[:, 2]], axis=1).astype(np.int32)
    garr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P(DATA_AXIS)), rows, (n, 4))
    gathered = jax.jit(shard_map_over(mesh, P(DATA_AXIS), P(DATA_AXIS))(
        lambda x: all_gather(x, tiled=True)))(garr)
    table_rows = np.asarray(
        jax.device_get(gathered.addressable_shards[0].data))[:n]
    by_proc: Dict[int, Tuple[str, int]] = {}
    for hi, lo, p_port, proc in table_rows:
        ip = (int(hi) << 16) | (int(lo) & 0xffff)
        by_proc[int(proc)] = _decode_addr(ip, p_port)
    return [by_proc[i] for i in sorted(by_proc)]


class DistributedServingServer:
    """One listener on THIS host plus the cluster-wide routing table.

    Start one per process of an initialized cluster; every instance knows
    every host's listener address (``routing_table``), so requests can be
    balanced across the whole mesh while each host's pipeline serves its
    local replica.  Matches the role of one-server-per-executor
    distributed serving (DistributedHTTPSource.scala:88)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", reply_timeout_s: float = 30.0,
                 max_queue: int = 1024,
                 max_body_bytes: int = 16 * 1024 * 1024):
        self.local = ServingServer(host=host, port=port, api_path=api_path,
                                   reply_timeout_s=reply_timeout_s,
                                   max_queue=max_queue,
                                   max_body_bytes=max_body_bytes)
        lh, lp = self.local.address
        self.routing_table = exchange_routing_table(lh, lp)

    @property
    def address(self) -> Tuple[str, int]:
        return self.local.address

    def url_for_rank(self, rank: int, path: str = "/") -> str:
        h, p = self.routing_table[rank]
        path = path.rstrip("/") or "/"
        return f"http://{h}:{p}{'' if path == '/' else path}"

    # local-API passthroughs
    def register_api(self, *a, **kw):
        return self.local.register_api(*a, **kw)

    def get_batch(self, *a, **kw):
        return self.local.get_batch(*a, **kw)

    def reply(self, *a, **kw):
        return self.local.reply(*a, **kw)

    def close(self) -> None:
        self.local.close()
