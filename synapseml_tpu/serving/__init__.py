"""Model serving (reference: core Spark Serving layer)."""

from .continuous import ContinuousClient
from .distributed import DistributedServingServer, exchange_routing_table
from .server import (ApiHandle, MultiPipelineServer, PipelineServer,
                     ServingReply, ServingRequest, ServingServer)

__all__ = ["ApiHandle", "ContinuousClient", "DistributedServingServer",
           "MultiPipelineServer", "PipelineServer", "ServingReply",
           "ServingRequest", "ServingServer", "exchange_routing_table"]
