"""Model serving (reference: core Spark Serving layer)."""

from .server import PipelineServer, ServingReply, ServingRequest, ServingServer

__all__ = ["PipelineServer", "ServingReply", "ServingRequest",
           "ServingServer"]
