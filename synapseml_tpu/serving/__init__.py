"""Model serving (reference: core Spark Serving layer)."""

from .server import (ApiHandle, MultiPipelineServer, PipelineServer,
                     ServingReply, ServingRequest, ServingServer)

__all__ = ["ApiHandle", "MultiPipelineServer", "PipelineServer",
           "ServingReply", "ServingRequest", "ServingServer"]
