"""Model serving (reference: core Spark Serving layer)."""

from .continuous import ContinuousClient
from .distributed import (DistributedServingServer, NoHealthyReplicaError,
                          ReplicaRouter, exchange_routing_table,
                          probe_replica)
from .llm import LLMServer
from .server import (ApiHandle, MultiPipelineServer, PipelineServer,
                     ServingReply, ServingRequest, ServingServer)

__all__ = ["ApiHandle", "ContinuousClient", "DistributedServingServer",
           "LLMServer",
           "MultiPipelineServer", "NoHealthyReplicaError", "PipelineServer",
           "ReplicaRouter", "ServingReply", "ServingRequest",
           "ServingServer", "exchange_routing_table", "probe_replica"]
