"""Model serving (reference: core Spark Serving layer)."""

from .autoscaler import (AutoscalePolicy, Autoscaler, CapacityArbiter,
                         ScaleDecision, ServingReplicaSet, SupervisorPool,
                         sloz_signals)
from .continuous import ContinuousClient
from .disagg import PrefillPool, PrefillWorker
from .distributed import (ROLE_NAMES, DistributedServingServer,
                          NoHealthyReplicaError, ReplicaRouter,
                          RouteResult, exchange_routing_table,
                          probe_replica)
from .llm import LLMServer
from .qos import QosScheduler, TenantPolicy, jain_fairness
from .server import (ApiHandle, MultiPipelineServer, PipelineServer,
                     ServingReply, ServingRequest, ServingServer)

__all__ = ["ApiHandle", "AutoscalePolicy", "Autoscaler", "CapacityArbiter",
           "ContinuousClient", "DistributedServingServer",
           "LLMServer",
           "MultiPipelineServer", "NoHealthyReplicaError", "PipelineServer",
           "PrefillPool", "PrefillWorker",
           "QosScheduler", "ROLE_NAMES", "ReplicaRouter", "RouteResult",
           "ScaleDecision",
           "ServingReplicaSet", "ServingReply", "ServingRequest",
           "ServingServer", "SupervisorPool", "TenantPolicy",
           "exchange_routing_table", "jain_fairness",
           "probe_replica", "sloz_signals"]
