"""Disaggregated prefill/decode: a prefill pool with a crash-tolerant
KV handoff plane.

ROADMAP item 1(b), DistServe-style: prefill is compute-bound (one big
batched forward over the whole prompt), decode is memory-bound (one
token per step per slot) — colocating them makes each phase's latency
hostage to the other's load.  This module gives prefill its own pool of
dedicated replicas whose finished K/V ships to the decode replica as a
CRC-framed transfer (:func:`~synapseml_tpu.models.llm.kvtier.
pack_kv_transfer`) adopted through the decode engine's host arena, so
each phase scales off its own ``@phase=`` SLO plane.

The robustness contract — the reason this lives beside ``resilience/``
rather than being a plain RPC:

- every handoff runs under a **lease**: a :class:`~synapseml_tpu.
  resilience.policy.Deadline` bounds the whole attempt, so a dead (or
  wedged) prefill replica can never strand the decode slot waiting;
- the transfer carries (session, tenant, token-prefix hash, CRC per
  row): a flipped byte, a torn body, or a frame carrying the wrong
  prompt is detected BEFORE any K/V is adopted;
- worker calls run under :class:`~synapseml_tpu.resilience.policy.
  RetryPolicy` + one :class:`~synapseml_tpu.resilience.breaker.
  CircuitBreaker` per worker, so a flapping prefill replica is ejected
  from rotation instead of absorbing every lease;
- delivery is **idempotent**: adoption is ``arena.put()`` (supersede
  semantics), so a duplicated or re-sent transfer refreshes the entry
  instead of corrupting it;
- and every failure mode lands in the same place — **local colocated
  prefill on the decode replica** — counted by outcome in
  ``disagg_handoffs_total`` and flight-recorded.  A disaggregated turn
  is token-exact vs the colocated reference; the worst case is a cold
  local prefill, never a wrong token.

Degradation table (the tier-1-pinned outcomes):

==============  =========================================================
``ok``          K/V adopted into the decode arena; the decode engine's
                admit restores it token-exactly (warm TTFT)
``corrupt``     a row CRC / header CRC / prefix-hash check failed —
                nothing adopted, local prefill
``timeout``     the worker kept failing until the lease expired, or the
                transfer was dropped in flight (the receiver can only
                observe a drop as its deadline expiring)
``expired``     the transfer arrived after the lease deadline (a slow
                wire) — stale K/V is refused, local prefill
``fallback``    no pool / pool empty / every breaker open / prompt too
                short / retries exhausted inside the lease — handoff
                not attempted or abandoned early, local prefill
==============  =========================================================

Fault sites: ``disagg.prefill`` (the worker call — arm ``kill`` for the
replica-death chaos soak, ``error`` for retry/breaker paths) and
``disagg.transfer`` (the wire — arm ``corrupt``/``drop``/``delay``).
Both pass ``phase="prefill"`` so ``phase=``-gated rules target this
plane alone.  See docs/api/serving.md "Disaggregated prefill/decode".
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from ..resilience import Deadline, RetryPolicy, breaker_for, drop_breaker
from ..resilience.faults import get_faults
from ..telemetry import get_registry
from ..telemetry.flight import record as flight_record

__all__ = ["DISAGG_METRICS", "HANDOFF_OUTCOMES", "PrefillPool",
           "PrefillWorker"]

#: every handoff resolves to exactly one of these (no silent path)
HANDOFF_OUTCOMES = ("ok", "corrupt", "timeout", "expired", "fallback")

#: every metric this plane registers — held to the docs bar by the
#: metric-hygiene sweep, like GANG_METRICS / KVTIER_METRICS
DISAGG_METRICS = (
    "disagg_handoffs_total",
    "disagg_handoff_latency_seconds",
    "disagg_pool_replicas",
)


def _disagg_metrics():
    reg = get_registry()
    return (
        reg.counter(
            "disagg_handoffs_total",
            "prefill→decode KV handoffs by outcome (every non-ok "
            "outcome fell back to local colocated prefill)",
            ("pool", "outcome")),
        reg.histogram(
            "disagg_handoff_latency_seconds",
            "wall-clock of one handoff attempt, lease start to outcome",
            ("pool",),
            buckets=(0.001, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
                     1.0, 2.5, 5.0)),
        reg.gauge(
            "disagg_pool_replicas",
            "prefill workers currently in the pool", ("pool",)),
    )


class PrefillWorker:
    """One dedicated prefill replica: wraps a slot engine (typically a
    few big-bucket slots, built from the SAME model/variables as the
    decode engines) and turns a prompt into extractable K/V rows.

    ``prefill`` admits the prompt with ``max_new_tokens=1`` — the slot
    engine's admit path prefills the prompt, emits one token, and
    auto-retires, after which the slot's K/V rows still hold the
    prompt's span — then reads the per-layer rows out host-side in the
    cache-native dtype (the same shape ``HostKVArena.put`` stores)."""

    def __init__(self, engine: Any):
        self.engine = engine

    def prefill(self, ids, tenant: str = "default"
                ) -> List[Dict[str, np.ndarray]]:
        ids = [int(t) for t in ids]
        res = self.engine.admit(ids, 1, tenant=tenant)
        span = len(ids)
        slot = int(res.slot)
        return [{"k": np.asarray(layer["k"][slot, :span]),
                 "v": np.asarray(layer["v"][slot, :span])}
                for layer in self.engine.cache]


class PrefillPool:
    """The prefill side of the handoff plane (see module docstring).

    ``workers`` are :class:`PrefillWorker`-shaped objects (anything
    with ``prefill(ids, tenant=) -> rows``); ``factory`` (→ one new
    worker) arms :meth:`grow`, making the pool an autoscaler actuator
    with the ``ServingReplicaSet`` duck type (``replica_count`` /
    ``grow`` / ``shrink`` / ``warming_count``), so one
    :class:`~synapseml_tpu.serving.autoscaler.Autoscaler` per phase
    scales prefill and decode independently off their ``@phase=``
    planes.

    Call :meth:`bind` to attach the DECODE replica's arena (where
    adopted K/V lands) and the prefill-phase SLO plane; until bound,
    every handoff is a counted ``fallback``.
    """

    def __init__(self, workers: Optional[List[Any]] = None,
                 factory: Optional[Callable[[], Any]] = None,
                 name: str = "disagg",
                 lease_s: float = 5.0,
                 retry: Optional[RetryPolicy] = None,
                 failure_threshold: int = 3, cooldown_s: float = 5.0,
                 min_prompt: int = 1):
        self.name = str(name)
        self.lease_s = float(lease_s)
        self.retry = retry if retry is not None else RetryPolicy(
            max_retries=2, base_s=0.01, max_backoff_s=0.25)
        self.failure_threshold = int(failure_threshold)
        self.cooldown_s = float(cooldown_s)
        self.min_prompt = int(min_prompt)
        self.arena: Any = None
        self.slo: Any = None
        self._factory = factory
        self._lock = threading.Lock()
        self._workers: List[Any] = list(workers or [])
        self._rr = 0
        self._inflight = 0
        self._m_handoffs, self._m_latency, self._g_replicas = \
            _disagg_metrics()
        self._g_replicas.set(len(self._workers), pool=self.name)

    # -- pool membership (the autoscaler actuator surface) -----------------
    def _breaker_key(self, idx: int) -> str:
        return f"prefill:{self.name}:{idx}"

    def _breaker(self, idx: int):
        return breaker_for(self._breaker_key(idx),
                           failure_threshold=self.failure_threshold,
                           cooldown_s=self.cooldown_s)

    def replica_count(self) -> int:
        with self._lock:
            return len(self._workers)

    def warming_count(self) -> int:
        return 0

    def grow(self, n: int = 1) -> int:
        """Add up to ``n`` factory-built workers; returns how many."""
        if self._factory is None:
            return 0
        added = 0
        for _ in range(max(0, int(n))):
            worker = self._factory()
            with self._lock:
                self._workers.append(worker)
                count = len(self._workers)
            added += 1
        if added:
            self._g_replicas.set(count, pool=self.name)
            flight_record("disagg_pool", pool=self.name, op="grow",
                          replicas=count)
        return added

    def shrink(self, n: int = 1) -> int:
        """Retire up to ``n`` workers from the tail (their breakers are
        released — a pool resizing every few minutes must not leak one
        breaker per index it ever had)."""
        removed = 0
        with self._lock:
            for _ in range(max(0, int(n))):
                if not self._workers:
                    break
                self._workers.pop()
                drop_breaker(self._breaker_key(len(self._workers)))
                removed += 1
            count = len(self._workers)
            if self._rr >= max(count, 1):
                self._rr = 0
        if removed:
            self._g_replicas.set(count, pool=self.name)
            flight_record("disagg_pool", pool=self.name, op="shrink",
                          replicas=count)
        return removed

    # -- wiring ------------------------------------------------------------
    def bind(self, api_path: str, arena: Any,
             ttft_slo_s: Optional[float] = None,
             slo_store: Any = None) -> None:
        """Attach the decode replica's host arena (handoff destination)
        and create this pool's ``@phase=prefill`` SLO plane for
        ``api_path`` (``/sloz`` serves it; the prefill autoscaler scales
        off it).  ``ttft_slo_s`` declares the prefill-latency objective
        — for this plane "ttft" is the handoff wall-clock, prompt
        arrival to K/V adopted."""
        from ..telemetry.slo import get_slo_store, phase_plane_name
        self.arena = arena
        store = slo_store if slo_store is not None else get_slo_store()
        self.slo = store.window(phase_plane_name(api_path, "prefill"))
        if ttft_slo_s:
            self.slo.set_objective("ttft", float(ttft_slo_s))

    # -- the handoff -------------------------------------------------------
    def _pick(self) -> Optional[int]:
        """Next worker index whose breaker admits a call (None when the
        pool is empty or every breaker refuses)."""
        with self._lock:
            n = len(self._workers)
            for i in range(n):
                idx = (self._rr + i) % n
                if self._breaker(idx).allow():
                    self._rr = (idx + 1) % n
                    return idx
        return None

    def handoff(self, ids, session: Optional[str] = None,
                tenant: str = "default") -> str:
        """Run one prompt through the pool and adopt the K/V into the
        bound decode arena.  Returns the outcome (one of
        :data:`HANDOFF_OUTCOMES`) — NEVER raises: every failure mode is
        an attributed fallback to local prefill, and the caller admits
        the request into its own engine regardless (an ``ok`` outcome
        just means the admit will warm-restore instead of prefill)."""
        t0 = time.monotonic()
        with self._lock:
            self._inflight += 1
            inflight, n = self._inflight, len(self._workers)
        if self.slo is not None:
            self.slo.count("admitted")
            self.slo.observe_occupancy(min(1.0, inflight / max(1, n)))
        try:
            outcome = self._handoff(ids, session, tenant)
        except Exception:  # noqa: BLE001 — degrade, never break admission
            outcome = "fallback"
        finally:
            with self._lock:
                self._inflight -= 1
        dt = time.monotonic() - t0
        self._m_handoffs.inc(1, pool=self.name, outcome=outcome)
        self._m_latency.observe(dt, pool=self.name)
        if self.slo is not None:
            self.slo.observe_ttft(dt)
            self.slo.count("retired" if outcome == "ok" else "shed")
        flight_record("disagg_handoff", pool=self.name, outcome=outcome,
                      tenant=tenant, session=session,
                      tokens=int(len(ids)))
        return outcome

    def _handoff(self, ids, session: Optional[str],
                 tenant: str) -> str:
        ids = [int(t) for t in ids]
        if self.arena is None or len(ids) < self.min_prompt:
            return "fallback"
        from ..models.llm.kvtier import (ChecksumError, pack_kv_transfer,
                                         unpack_kv_transfer)
        faults = get_faults()
        deadline = Deadline.after(self.lease_s)
        blob: Optional[bytes] = None
        attempt = 0
        while blob is None:
            if deadline.expired:
                return "timeout"
            idx = self._pick()
            if idx is None:
                return "fallback"      # pool empty / all breakers open
            with self._lock:
                worker = self._workers[idx] \
                    if idx < len(self._workers) else None
            if worker is None:
                return "fallback"      # shrunk away under us
            brk = self._breaker(idx)
            try:
                # the worker-call fault site: ``kill`` is the prefill
                # replica dying mid-handoff, ``error``/``reset`` are the
                # transient failures the retry/breaker pair absorbs
                faults.kill_point("disagg.prefill", tenant=tenant,
                                  phase="prefill")
                rows = worker.prefill(ids, tenant=tenant)
                blob = pack_kv_transfer(ids, rows, session=session,
                                        tenant=tenant)
                brk.record_success()
            except Exception:  # noqa: BLE001 — any worker failure retries
                brk.record_failure()
                if deadline.expired:
                    return "timeout"
                if attempt >= self.retry.max_retries \
                        or not self.retry.acquire_retry():
                    return "fallback"  # retries exhausted inside the lease
                self.retry.sleep(
                    min(self.retry.backoff_s(attempt), deadline.remaining()),
                    site="disagg.retry")
                attempt += 1
        # the wire: corrupt flips a byte (caught below), drop loses the
        # frame (only the deadline observes it), delay holds it so the
        # lease can expire before adoption
        blob = faults.transfer_point("disagg.transfer", blob,
                                     tenant=tenant, phase="prefill")
        if blob is None:
            return "timeout"           # dropped in flight
        if deadline.expired:
            return "expired"           # arrived after the lease — refuse
        try:
            xfer = unpack_kv_transfer(blob)
        except (ChecksumError, ValueError):
            return "corrupt"
        # idempotent adoption: put() supersedes a shorter/equal resident
        # prefix, so a re-delivered transfer refreshes instead of tearing
        self.arena.put(xfer.ids, xfer.rows, kind="handoff",
                       tenant=xfer.tenant)
        return "ok"
