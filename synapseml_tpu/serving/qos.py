"""Multi-tenant QoS: the scheduling-policy core of the serving plane.

Millions of users are never one uniform queue.  The decode loop's
original FIFO admits whoever arrived first, so one flooding tenant
degrades every other tenant's TTFT and can starve the prefix/KV tier —
and the aggregate SLO percentiles hide exactly who did it.  This module
is the *policy* half ROADMAP item 2 names (the *mechanism* —
``SlotEngine.preempt()``/``resume()`` tickets through the kvtier arena —
shipped in PR 17 and is token-exact-pinned):

- **Priority classes** — each tenant (or request) carries an integer
  priority; higher admits first, and only a STRICTLY higher class may
  preempt a running slot.  Within one class, admission is weighted-fair.
- **Token-weighted deficit round robin** — each tenant holds a deficit
  counter refilled per admission round by its WEIGHT SHARE of the
  tokens the whole engine committed since the last round (virtual-time
  DRR: refills track real throughput, so a fast-ticking admission loop
  cannot re-top every tenant between token commits and erase the
  imbalance) and charged by COMMITTED tokens from the engine's
  per-slot accounting (token-weighted, not request-weighted: a
  speculative engine commits several tokens per slot per step, so
  request counts and token shares differ — charging committed tokens
  is what makes the share converge to the configured weights under
  spec decode too).  Deficits are clamped to ``±burst_quanta`` quanta
  of ``quantum_tokens x weight``, so an idle tenant cannot bank
  unbounded credit and a flooding one cannot dig an unbounded hole.
- **Preemption verdicts** — under queue pressure from a higher class,
  :meth:`QosScheduler.preemption_victim` names the lowest-priority,
  longest-remaining running slot; the decode loop evicts it through the
  PR 17 ticket path and auto-resumes it token-exactly when pressure
  clears.  Verdicts are rate-limited (``preempt_min_interval_s``) so a
  flapping queue cannot thrash the arena.
- **Per-tenant shed budgets** — a tenant's token rate rides the PR 2
  token-bucket :class:`~synapseml_tpu.resilience.policy.RetryBudget`;
  an over-budget tenant sheds 429-style with a computed ``Retry-After``
  while every other tenant is untouched.

Deliberately jax-free with an injectable monotonic ``clock`` — the
scheduler is pure bookkeeping and its tests (``tests/test_qos.py``)
drive admission rounds, budget refills, and preemption cooldowns on a
fake clock with no engine at all.

See docs/api/serving.md "Multi-tenant QoS".
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import (Any, Callable, Dict, Iterable, List, Optional,
                    Sequence, Tuple)

from ..resilience.policy import RetryBudget

__all__ = ["DEFAULT_TENANT", "DEFAULT_PRIORITY", "OVERFLOW_TENANT",
           "TenantPolicy", "QosScheduler", "jain_fairness", "QOS_METRICS"]

#: QoS-plane metric names (the metric-hygiene sweep holds every one to
#: the docs bar, like GANG/SLO/KVTIER_METRICS).  The per-tenant
#: ``tenant`` label additionally rides the existing ``llm_sheds_total``
#: / ``llm_admissions_total`` / ``llm_evictions_total`` counters.
QOS_METRICS = frozenset({"llm_qos_preemptions_total"})

#: the tenant every request without an explicit id belongs to — all
#: pre-QoS traffic lands here, so a single-tenant deployment behaves
#: exactly like the old FIFO (one tenant's DRR order IS arrival order)
DEFAULT_TENANT = "default"

#: the priority class of a request that declares none
DEFAULT_PRIORITY = 1

#: the attribution label a request rejected by the decode loop's
#: dynamic-tenant cap sheds under — tenant ids are client-controlled
#: and unauthenticated, so per-tenant planes/labels/budgets are only
#: materialised for registered tenants plus a bounded number of
#: dynamic ones; everything past the cap is rejected and counted here,
#: keeping metric/SLO cardinality bounded no matter how many ids a
#: client cycles through
OVERFLOW_TENANT = "~other"


@dataclasses.dataclass
class TenantPolicy:
    """One tenant's QoS contract.

    ``weight`` sets the tenant's fair share of committed tokens within
    its priority class; ``priority`` its class (higher admits first and
    may preempt strictly lower classes).  ``rate_tokens_per_s`` arms the
    PR 2 token-bucket shed budget (None = unlimited); ``burst_tokens``
    is the bucket capacity (default: 4 seconds of refill)."""
    weight: float = 1.0
    priority: int = DEFAULT_PRIORITY
    rate_tokens_per_s: Optional[float] = None
    burst_tokens: Optional[float] = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError("weight must be > 0")
        if self.rate_tokens_per_s is not None and self.rate_tokens_per_s <= 0:
            raise ValueError("rate_tokens_per_s must be > 0 (or None)")


class _ClockedBudget(RetryBudget):
    """The PR 2 token bucket, on the scheduler's injectable clock (the
    base class reads ``time.monotonic`` directly, which a fake-clock
    test cannot advance)."""

    def __init__(self, capacity: float, refill_per_s: float,
                 clock: Callable[[], float]):
        super().__init__(capacity, refill_per_s)
        self._clock = clock
        self._last = clock()

    def try_spend(self, n: float = 1.0) -> bool:
        with self._lock:
            self._refill(self._clock())
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False

    def tokens(self) -> float:
        with self._lock:
            self._refill(self._clock())
            return self._tokens


def jain_fairness(shares: Sequence[float]) -> float:
    """Jain's fairness index over per-tenant shares: 1.0 = perfectly
    even, 1/n = one tenant holds everything.  NaN-free: empty or
    all-zero input scores 1.0 (nothing was allocated unfairly)."""
    xs = [float(s) for s in shares if s >= 0]
    total = sum(xs)
    if not xs or total <= 0:
        return 1.0
    sq = sum(x * x for x in xs)
    return (total * total) / (len(xs) * sq) if sq > 0 else 1.0


class QosScheduler:
    """Token-weighted DRR + priority classes + shed budgets (see module
    docstring).  Thread-safe; every method is O(waiting) or better.

    Scheduled items are duck-typed: anything with ``.tenant`` (str) and
    ``.priority`` (int) attributes schedules; preemption candidates
    additionally need ``.remaining`` (tokens left in budget).  The
    decode loop's ``_DecodeSeq`` satisfies all three."""

    def __init__(self, policies: Optional[Dict[str, TenantPolicy]] = None,
                 default_policy: Optional[TenantPolicy] = None,
                 quantum_tokens: float = 32.0, burst_quanta: float = 8.0,
                 preempt_min_interval_s: float = 0.25,
                 clock: Callable[[], float] = time.monotonic):
        self._policies: Dict[str, TenantPolicy] = dict(policies or {})
        self.default_policy = default_policy or TenantPolicy()
        self.quantum_tokens = float(quantum_tokens)
        self.burst_quanta = float(burst_quanta)
        self.preempt_min_interval_s = float(preempt_min_interval_s)
        self.clock = clock
        self._lock = threading.Lock()
        self._deficit: Dict[str, float] = {}
        self._committed: Dict[str, int] = {}
        #: total committed tokens at the last admission round — the
        #: virtual-time anchor the per-round refill is computed from
        self._last_total = 0
        self._budgets: Dict[str, Optional[_ClockedBudget]] = {}
        self._last_preempt = float("-inf")
        #: total preemption verdicts issued (the bench reads this)
        self.preemptions = 0
        #: total budget sheds by tenant (attribution beside the metric)
        self.budget_sheds: Dict[str, int] = {}

    # -- policies ----------------------------------------------------------
    def policy(self, tenant: str) -> TenantPolicy:
        return self._policies.get(tenant, self.default_policy)

    def set_policy(self, tenant: str, policy: TenantPolicy) -> None:
        with self._lock:
            self._policies[tenant] = policy
            self._budgets.pop(tenant, None)   # re-arm from the new rate

    def is_registered(self, tenant: str) -> bool:
        """True when ``tenant`` carries an explicit :class:`TenantPolicy`
        (the decode loop's dynamic-tenant cap never applies to these)."""
        with self._lock:
            return tenant in self._policies

    def priority_of(self, item: Any) -> int:
        """The item's effective class: its own ``.priority`` when
        declared, else its tenant's policy."""
        p = getattr(item, "priority", None)
        return int(p) if p is not None else self.policy(item.tenant).priority

    def _cap(self, tenant: str) -> float:
        return self.quantum_tokens * self.policy(tenant).weight \
            * self.burst_quanta

    # -- deficit round robin -----------------------------------------------
    def admission_order(self, waiting: Sequence[Any],
                        cost: Optional[Callable[[Any], float]] = None
                        ) -> List[Any]:
        """One admission round: refill each waiting tenant's deficit by
        its weight share of the tokens committed SINCE THE LAST ROUND
        (virtual-time DRR — total refill equals total charge in steady
        state, so deficits measure each tenant's distance from its fair
        share instead of saturating at the burst cap when the loop
        ticks faster than tokens commit), clamp to the burst cap, then
        emit the round's admission order — priority classes strictly
        descending; within a class, a DRR interleave that repeatedly
        picks the tenant with the largest weight-normalized scratch
        deficit and debits it by the picked item's estimated cost
        (``cost(item)``, default ``item.max_new``), so one tenant
        cannot sweep every free slot in a single round.  FIFO order is
        preserved within a tenant; a single-tenant queue comes back in
        arrival order.  The REAL deficit is only ever charged by
        :meth:`charge` (committed tokens) — the scratch debit exists
        purely to interleave this round."""
        if not waiting:
            return []
        if cost is None:
            cost = lambda it: float(getattr(it, "max_new", 1) or 1)  # noqa: E731
        with self._lock:
            tenants = []
            for it in waiting:
                if it.tenant not in tenants:
                    tenants.append(it.tenant)
            total = sum(self._committed.values())
            delta = float(total - self._last_total)
            self._last_total = total
            wsum = sum(self.policy(t).weight for t in tenants)
            scratch: Dict[str, float] = {}
            for t in tenants:
                cap = self._cap(t)
                refilled = self._deficit.get(t, 0.0) \
                    + delta * self.policy(t).weight / wsum
                self._deficit[t] = max(-cap, min(cap, refilled))
                scratch[t] = self._deficit[t]
            tiers: Dict[int, Dict[str, deque]] = {}
            for i, it in enumerate(waiting):
                tiers.setdefault(self.priority_of(it), {}) \
                    .setdefault(it.tenant, deque()).append(it)
            order: List[Any] = []
            for prio in sorted(tiers, reverse=True):
                queues = tiers[prio]
                while queues:
                    t = max(queues,
                            key=lambda q: (scratch[q]
                                           / self.policy(q).weight, q))
                    item = queues[t].popleft()
                    scratch[t] -= cost(item)
                    order.append(item)
                    if not queues[t]:
                        del queues[t]
            return order

    def charge(self, tenant: str, tokens: int = 1) -> None:
        """Debit COMMITTED tokens against the tenant's deficit (the
        engine's per-slot accounting calls this once per step event —
        a speculative step charges every token it committed)."""
        with self._lock:
            cap = self._cap(tenant)
            self._deficit[tenant] = max(
                -cap, self._deficit.get(tenant, 0.0) - float(tokens))
            self._committed[tenant] = \
                self._committed.get(tenant, 0) + int(tokens)

    def deficit(self, tenant: str) -> float:
        with self._lock:
            return self._deficit.get(tenant, 0.0)

    def committed(self, tenant: str) -> int:
        with self._lock:
            return self._committed.get(tenant, 0)

    def committed_share(self) -> Dict[str, float]:
        """Each tenant's fraction of all committed tokens — the
        weighted-fairness convergence surface the bench pins."""
        with self._lock:
            total = sum(self._committed.values())
            if not total:
                return {t: 0.0 for t in self._committed}
            return {t: n / total for t, n in self._committed.items()}

    # -- shed budgets ------------------------------------------------------
    def _budget(self, tenant: str) -> Optional[_ClockedBudget]:
        if tenant not in self._budgets:
            pol = self.policy(tenant)
            if pol.rate_tokens_per_s is None:
                self._budgets[tenant] = None
            else:
                cap = pol.burst_tokens if pol.burst_tokens is not None \
                    else 4.0 * pol.rate_tokens_per_s
                self._budgets[tenant] = _ClockedBudget(
                    cap, pol.rate_tokens_per_s, self.clock)
        return self._budgets[tenant]

    def shed_verdict(self, tenant: str,
                     tokens: float = 1.0) -> Tuple[bool, float]:
        """Admission-time budget check: ``(admit, retry_after_s)``.
        ``admit=False`` means the tenant's token bucket cannot cover the
        request's budget — shed it 429-style; ``retry_after_s`` is when
        the bucket will have refilled enough (the server's own recovery
        estimate, exactly what ``Retry-After`` is for).

        A request costing MORE than the bucket's whole capacity is
        charged the capacity instead of its true cost: a full bucket
        admits it (draining to empty), so an oversized-but-legitimate
        request is throttled like everything else rather than 429'd
        forever with a Retry-After that can never come true (capacity
        is the most a refill can ever restore, so ``cost > capacity``
        would otherwise be permanently unadmittable)."""
        with self._lock:
            budget = self._budget(tenant)
        if budget is None:
            return True, 0.0
        want = min(float(tokens), budget.capacity)
        if budget.try_spend(want):
            return True, 0.0
        pol = self.policy(tenant)
        rate = pol.rate_tokens_per_s or 1.0
        retry_after = max(0.0, (want - budget.tokens()) / rate)
        with self._lock:
            self.budget_sheds[tenant] = self.budget_sheds.get(tenant, 0) + 1
        return False, retry_after

    # -- preemption --------------------------------------------------------
    def preemption_victim(self, demand_priority: int,
                          active: Iterable[Any]) -> Optional[Any]:
        """The slot to evict for a waiting class-``demand_priority``
        request: the LOWEST-priority, LONGEST-remaining active item
        whose class is STRICTLY below the demand — or None (nothing
        preemptible, or the anti-thrash cooldown has not elapsed).
        The caller routes the verdict through the PR 17 ticket path,
        flight-records it with the justifying pressure snapshot, and
        calls :meth:`commit_preemption` ONLY once the engine actually
        issued a ticket — a verdict the engine declined (``preempt``
        returned None) neither counts as a preemption nor burns the
        cooldown window, so a legitimate eviction is never delayed by
        a failed attempt."""
        now = self.clock()
        with self._lock:
            if now - self._last_preempt < self.preempt_min_interval_s:
                return None
        cands = [a for a in active
                 if self.priority_of(a) < int(demand_priority)]
        if not cands:
            return None
        return min(cands, key=lambda a: (self.priority_of(a),
                                         -float(getattr(a, "remaining",
                                                        0.0)),
                                         id(a)))

    def commit_preemption(self) -> None:
        """Confirm a :meth:`preemption_victim` verdict went through the
        engine (a ticket was issued): count it and arm the anti-thrash
        cooldown.  Kept separate from the verdict so an eviction the
        engine declined rolls back to 'never happened'."""
        with self._lock:
            self._last_preempt = self.clock()
            self.preemptions += 1

    # -- attribution -------------------------------------------------------
    def pressure_snapshot(self, waiting: Sequence[Any],
                          free_slots: int) -> Dict[str, Any]:
        """The justifying evidence a preemption verdict is
        flight-recorded with: who is waiting at which class, how many
        slots are free, and every known tenant's deficit."""
        by_prio: Dict[int, int] = {}
        for it in waiting:
            p = self.priority_of(it)
            by_prio[p] = by_prio.get(p, 0) + 1
        with self._lock:
            deficits = {t: round(d, 3) for t, d in self._deficit.items()}
        return {"free_slots": int(free_slots),
                "waiting": int(len(waiting)),
                "waiting_by_priority": {str(k): v for k, v
                                        in sorted(by_prio.items())},
                "deficits": deficits}

    def reset(self) -> None:
        with self._lock:
            self._deficit.clear()
            self._committed.clear()
            self._last_total = 0
            self._budgets.clear()
            self._last_preempt = float("-inf")
            self.preemptions = 0
            self.budget_sheds = {}
