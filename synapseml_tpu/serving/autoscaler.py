"""SLO-driven autoscaler: the control loop over the live chip budget.

PRs 7/13/15 built every sensor and actuator a serving control plane
needs — the schema-checked ``GET /sloz`` snapshot (designed as this
module's input contract), ``GangSupervisor.resize(n)`` recovering in
about a second, warming-aware routing, AOT warmup that makes a grown
replica useful in seconds — but nothing closed the loop.  This module
is the loop:

- :class:`Autoscaler` — a controller that polls a registered ``/sloz``
  source (an in-process :class:`~synapseml_tpu.telemetry.slo.SloStore`,
  an HTTP URL, or any callable returning a snapshot; every fetch is
  validated through :func:`~synapseml_tpu.telemetry.slo.check_sloz`,
  never trusted raw), derives one verdict per poll from windowed burn
  rate, shed ratio and occupancy — **grow** on sustained shed or TTFT
  burn > 1, **shrink** on persistently idle occupancy — and actuates
  through a replica pool (below).  A replica the ``/readyz`` plane
  still reports *warming* is capacity-in-flight: the controller holds
  instead of growing again while the previous grow is still compiling
  toward useful.
- :class:`CapacityArbiter` — ONE declared chip budget shared between a
  training gang and the serving replicas.  Serving growth beyond the
  free pool asks training to *yield* (an elastic shrink through
  ``GangSupervisor.resize``, never below the gang's ``min_ranks``
  floor); off-peak — no serving pressure for ``reclaim_after_s`` — the
  arbiter grows training back toward its preferred size.  Both sides
  move through the same elastic-resize machinery the PR-7 pins already
  hold to zero-drop / durable-step standards.
- Pools — :class:`ServingReplicaSet` (factory-spawned in-process
  replicas behind a shared :class:`~synapseml_tpu.serving.distributed.
  ReplicaRouter`: grow spawns, shrink removes the departing address
  from the table FIRST and then drains it, the PR-7 zero-drop order)
  and :class:`SupervisorPool` (gang-worker-hosted serving:
  ``GangSupervisor.resize(n)`` + ``DistributedServingServer.
  refresh_routing_table``).

Guard rails mirror the PR-7 resize brake: per-direction cooldowns, a
resize budget, sustain requirements (one hot window is noise, N
consecutive are a trend) and a hysteresis band — the shrink thresholds
(``burn_shrink``/``shed_shrink``) sit strictly below the grow
thresholds, so attainment oscillating around the objective parks the
controller at *hold* instead of flapping.  Every decision is
flight-recorded (``autoscale_decide``) and fault-log noted
(``autoscale.decide``) with the exact ``/sloz`` snapshot that justified
it, so a postmortem can replay why the controller acted.

Stdlib-only; importable before (and without) jax.
"""

from __future__ import annotations

import json
import math
import threading
import time
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Callable, Deque, Dict, List, Optional

from ..resilience.faults import get_faults
from ..telemetry import get_registry
from ..telemetry.flight import record as flight_record
from ..telemetry.slo import SloStore, check_sloz

__all__ = ["AutoscalePolicy", "Autoscaler", "CapacityArbiter",
           "ScaleDecision", "ServingReplicaSet", "SupervisorPool",
           "sloz_signals", "AUTOSCALE_METRICS"]

#: autoscaler metric names — held to the docs bar by the metric-hygiene
#: sweep, like GANG_METRICS / SLO_METRICS
AUTOSCALE_METRICS = frozenset({
    "autoscale_decisions_total", "autoscale_replicas",
    "autoscale_chips", "autoscale_arbiter_moves_total",
})


# ---------------------------------------------------------------------------
# /sloz input: fetch + signal extraction
# ---------------------------------------------------------------------------

def _fetch_sloz(source, timeout_s: float = 2.0) -> Dict[str, Any]:
    """One validated snapshot from any supported source: an
    :class:`SloStore`, an HTTP(S) URL serving ``GET /sloz``, or a
    callable returning the payload.  ``check_sloz`` is the only door —
    a malformed or foreign-versioned snapshot raises here, before any
    decision logic sees it."""
    if isinstance(source, SloStore):
        snap = source.snapshot()
    elif isinstance(source, str):
        with urllib.request.urlopen(source, timeout=timeout_s) as resp:
            snap = json.loads(resp.read().decode("utf-8"))
    elif callable(source):
        snap = source()
    else:
        raise TypeError(f"unsupported /sloz source: {type(source).__name__}")
    check_sloz(snap)
    return snap


def sloz_signals(snapshot: Dict[str, Any],
                 phase: Optional[str] = None) -> Dict[str, Any]:
    """The decision inputs, reduced across planes: worst (max) burn
    rate over every declared objective, worst (max) shed ratio, lowest
    (min) mean occupancy, and the total evidence count (latency
    observations + occupancy samples — zero means the windows are
    empty and no verdict has support).

    ``phase`` restricts the reduction to one disaggregated pool's
    planes (``<base>@phase=<prefill|decode>``) — two controllers each
    reducing their own phase scale the pools independently: prefill
    burn grows the prefill pool without touching decode, and vice
    versa."""
    from ..telemetry.slo import plane_phase
    max_burn = max_shed = min_occ = None
    samples = 0
    planes = snapshot.get("planes", {})
    if phase is not None:
        planes = {name: plane for name, plane in planes.items()
                  if plane_phase(name) == phase}
    for plane in planes.values():
        for block in plane.get("slo", {}).values():
            burn = block.get("burn_rate")
            if burn is not None:
                max_burn = burn if max_burn is None else max(max_burn, burn)
        shed = plane.get("rates", {}).get("shed_ratio")
        if shed is not None:
            max_shed = shed if max_shed is None else max(max_shed, shed)
        occ = plane.get("occupancy", {}).get("mean")
        if occ is not None:
            min_occ = occ if min_occ is None else min(min_occ, occ)
        samples += int(plane.get("occupancy", {}).get("samples") or 0)
        for sig in plane.get("signals", {}).values():
            samples += int(sig.get("count") or 0)
    return {"max_burn": max_burn, "max_shed": max_shed,
            "min_occupancy": min_occ, "samples": samples,
            "planes": len(planes)}


# ---------------------------------------------------------------------------
# policy + decision record
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class AutoscalePolicy:
    """Thresholds and guard rails for one controller.

    The hysteresis band is structural: ``burn_shrink < burn_grow`` and
    ``shed_shrink < shed_grow``, so a plane oscillating between the
    bands produces *hold*, never a grow/shrink flap.  ``sustain_polls``
    is the trend filter (one bursty window must not resize anything);
    the cooldowns and ``max_resizes`` budget mirror the PR-7 gang
    resize brake."""

    min_replicas: int = 1
    max_replicas: int = 8
    #: grow when windowed shed ratio exceeds this...
    shed_grow: float = 0.01
    #: ...or any declared objective burns error budget faster than
    #: sustainable (burn rate 1.0 = exactly sustainable)
    burn_grow: float = 1.0
    #: shrink only while mean occupancy sits below this...
    occ_shrink: float = 0.25
    #: ...AND the plane is quiet: burn/shed under the LOW edge of the
    #: hysteresis band (strictly below the grow thresholds)
    burn_shrink: float = 0.5
    shed_shrink: float = 0.0
    #: consecutive polls a signal must persist before acting
    sustain_polls: int = 3
    grow_cooldown_s: float = 15.0
    shrink_cooldown_s: float = 60.0
    #: lifetime resize budget (None = unlimited) — a runaway control
    #: loop stops moving chips long before it can thrash the gang
    max_resizes: Optional[int] = 64
    grow_step: int = 1
    shrink_step: int = 1

    def __post_init__(self):
        if self.min_replicas < 1 or self.max_replicas < self.min_replicas:
            raise ValueError(
                f"need 1 <= min_replicas <= max_replicas, got "
                f"[{self.min_replicas}, {self.max_replicas}]")
        if self.burn_shrink >= self.burn_grow:
            raise ValueError(
                f"hysteresis requires burn_shrink < burn_grow "
                f"({self.burn_shrink} >= {self.burn_grow}): equal bands "
                "make attainment oscillation flap the pool")
        if self.shed_shrink > self.shed_grow:
            raise ValueError(
                f"hysteresis requires shed_shrink <= shed_grow "
                f"({self.shed_shrink} > {self.shed_grow})")


@dataclass
class ScaleDecision:
    """One poll's verdict, with the evidence that justified it."""

    ts: float
    verdict: str                  # grow | shrink | hold | error
    reason: str
    replicas: int                 # pool size BEFORE any action
    target: Optional[int]         # pool size AFTER an action (else None)
    signals: Dict[str, Any] = field(default_factory=dict)
    snapshot: Optional[Dict[str, Any]] = None

    def as_dict(self) -> Dict[str, Any]:
        return {"ts": self.ts, "verdict": self.verdict,
                "reason": self.reason, "replicas": self.replicas,
                "target": self.target, "signals": dict(self.signals)}


# ---------------------------------------------------------------------------
# replica pools (the actuators)
# ---------------------------------------------------------------------------

class ServingReplicaSet:
    """In-process replica pool: ``factory()``-spawned serving replicas
    (anything with ``address``/``drain``/``close`` — a
    :class:`~synapseml_tpu.serving.server.ServingServer`, an
    :class:`~synapseml_tpu.serving.llm.LLMServer`, or a wrapper)
    behind an optional shared :class:`~synapseml_tpu.serving.
    distributed.ReplicaRouter`.

    Shrink follows the PR-7 zero-drop order: the departing replica
    leaves the routing table FIRST (no new routes can name it), then
    drains every accepted exchange, then closes — a controller-
    initiated shrink drops nothing."""

    def __init__(self, factory: Callable[[], Any], router=None,
                 drain_timeout_s: float = 30.0):
        self._factory = factory
        self.router = router
        self.drain_timeout_s = float(drain_timeout_s)
        self._lock = threading.Lock()
        self._replicas: List[Any] = []

    @staticmethod
    def _addr(replica):
        addr = getattr(replica, "address", None)
        if addr is None:
            addr = replica.server.address
        return addr

    @staticmethod
    def _health(replica):
        health = getattr(replica, "health", None)
        if health is None:
            server = getattr(replica, "server", None)
            health = getattr(server, "health", None)
        return health

    def addresses(self) -> List[Any]:
        with self._lock:
            return [self._addr(r) for r in self._replicas]

    def replicas(self) -> List[Any]:
        with self._lock:
            return list(self._replicas)

    def replica_count(self) -> int:
        with self._lock:
            return len(self._replicas)

    def warming_count(self) -> int:
        """Replicas whose compile plane still reports cold/warming —
        the in-process mirror of the router's probe-based count (no
        HTTP needed when the health object is reachable directly)."""
        count = 0
        for r in self.replicas():
            health = self._health(r)
            if health is not None and health.warming:
                count += 1
        return count

    def _refresh_router(self) -> None:
        if self.router is not None:
            self.router.refresh(self.addresses())

    def grow(self, n: int = 1) -> int:
        added = [self._factory() for _ in range(max(1, int(n)))]
        with self._lock:
            self._replicas.extend(added)
        self._refresh_router()
        return self.replica_count()

    def shrink(self, n: int = 1) -> int:
        with self._lock:
            n = min(max(1, int(n)), max(0, len(self._replicas) - 0))
            departing = self._replicas[len(self._replicas) - n:]
            del self._replicas[len(self._replicas) - n:]
        # departed addresses leave the table BEFORE the drain starts:
        # no route() issued after this refresh can name them, and the
        # drain flushes whatever they had already accepted
        self._refresh_router()
        for r in departing:
            drain = getattr(r, "leave", None) or getattr(r, "drain", None)
            if drain is not None:
                drain(timeout_s=self.drain_timeout_s)
            r.close()
        return self.replica_count()

    def close(self) -> None:
        with self._lock:
            replicas, self._replicas = list(self._replicas), []
        for r in replicas:
            try:
                r.close()
            except Exception:
                pass


class SupervisorPool:
    """Gang-worker-hosted serving replicas, one per rank: the pool's
    size IS the gang's world size, so grow/shrink actuate through
    ``GangSupervisor.resize(n)`` (the elastic relaunch the PR-7 pins
    hold to durable-step standards).  ``refresh_fn`` — typically every
    rank's collective :meth:`~synapseml_tpu.serving.distributed.
    DistributedServingServer.refresh_routing_table` — runs after each
    request so routers re-gather the resized table; ``router`` (any
    object with ``warming_count``) lends the warming visibility."""

    def __init__(self, supervisor, router=None,
                 refresh_fn: Optional[Callable[[], Any]] = None):
        self.supervisor = supervisor
        self.router = router
        self.refresh_fn = refresh_fn

    def replica_count(self) -> int:
        return int(self.supervisor.world_size)

    def warming_count(self) -> int:
        if self.router is None:
            return 0
        return int(self.router.warming_count())

    def _resize(self, n: int) -> int:
        self.supervisor.resize(n)
        if self.refresh_fn is not None:
            self.refresh_fn()
        return n

    def grow(self, n: int = 1) -> int:
        return self._resize(self.replica_count() + max(1, int(n)))

    def shrink(self, n: int = 1) -> int:
        return self._resize(self.replica_count() - max(1, int(n)))


# ---------------------------------------------------------------------------
# the chip-budget arbiter
# ---------------------------------------------------------------------------

class CapacityArbiter:
    """ONE declared chip budget shared between a training gang and the
    serving replicas.

    Accounting is in *entitlements*: ``training_chips`` tracks the rank
    count the arbiter last requested (adopted immediately — the elastic
    teardown is already in flight when ``resize`` returns), and a
    resize listener (:meth:`attach_training` registers it when the
    handle supports ``add_resize_listener``) reconciles the entitlement
    when the gang resizes for its OWN reasons — a failure-driven shrink
    returns its chips to the free pool instead of leaking them.

    Policy: serving acquisitions take free chips first; beyond that,
    training *yields* — an elastic shrink, never below the training
    floor (``min_ranks``).  :meth:`reclaim` (call it every poll) grows
    training back toward ``preferred`` once no serving pressure has
    been seen for ``reclaim_after_s`` — the off-peak reclaim."""

    def __init__(self, total_chips: int, *, chips_per_rank: int = 1,
                 chips_per_replica: int = 1, reclaim_after_s: float = 30.0,
                 name: str = "arbiter",
                 clock: Callable[[], float] = time.monotonic):
        if total_chips < 1:
            raise ValueError(f"total_chips={total_chips}: need >= 1")
        self.total_chips = int(total_chips)
        self.chips_per_rank = max(1, int(chips_per_rank))
        self.chips_per_replica = max(1, int(chips_per_replica))
        self.reclaim_after_s = float(reclaim_after_s)
        self.name = name
        self._clock = clock
        self._lock = threading.Lock()
        self._serving_chips = 0
        self._training = None          # (handle, preferred, min_ranks)
        self._training_ranks = 0
        self._last_pressure_at: Optional[float] = None
        reg = get_registry()
        self._g_chips = reg.gauge(
            "autoscale_chips", "chip entitlement by side of the shared "
            "budget (serving / training / free)", ("arbiter", "side"))
        self._c_moves = reg.counter(
            "autoscale_arbiter_moves_total",
            "training chip movements: yield (to serving) / reclaim "
            "(off-peak return)", ("arbiter", "direction"))
        self._export_locked()

    # -- wiring ------------------------------------------------------------
    def attach_training(self, handle, preferred_ranks: Optional[int] = None,
                        min_ranks: Optional[int] = None) -> None:
        """Declare the training side: ``handle`` needs ``resize(n)`` and
        ``world_size`` (a :class:`~synapseml_tpu.parallel.supervisor.
        GangSupervisor` fits).  ``preferred_ranks`` is the size training
        reclaims back to off-peak (default: its current size);
        ``min_ranks`` the yield floor (default: the handle's own
        ``min_ranks``, else 1)."""
        preferred = int(preferred_ranks if preferred_ranks is not None
                        else handle.world_size)
        floor = min_ranks if min_ranks is not None else \
            getattr(handle, "min_ranks", None)
        floor = max(1, int(floor if floor is not None else 1))
        if preferred < floor:
            raise ValueError(f"preferred_ranks={preferred} below "
                             f"min_ranks={floor}")
        with self._lock:
            self._training = (handle, preferred, floor)
            self._training_ranks = int(handle.world_size)
            self._export_locked()
        add = getattr(handle, "add_resize_listener", None)
        if add is not None:
            add(self._on_training_resize)

    def register_serving(self, chips: int) -> None:
        """Seed the serving entitlement (replicas already running when
        the arbiter comes up)."""
        with self._lock:
            self._serving_chips = max(0, int(chips))
            self._export_locked()

    def _on_training_resize(self, event: Dict[str, Any]) -> None:
        """Resize-listener reconciliation: a gang resize the arbiter
        did not request (failure-driven shrink, capacity probe) moves
        the training entitlement to the APPLIED size, so the freed (or
        consumed) chips show up in the free pool instead of leaking."""
        with self._lock:
            applied = int(event.get("to", self._training_ranks))
            if applied == self._training_ranks:
                return                   # confirmation of our own request
            self._training_ranks = applied
            self._export_locked()
        flight_record("arbiter_sync", arbiter=self.name,
                      training_ranks=applied,
                      cause=event.get("cause"))

    # -- accounting --------------------------------------------------------
    def serving_chips(self) -> int:
        with self._lock:
            return self._serving_chips

    def training_chips(self) -> int:
        with self._lock:
            return self._training_ranks * self.chips_per_rank

    def free_chips(self) -> int:
        with self._lock:
            return self._free_locked()

    def _free_locked(self) -> int:
        used = (self._serving_chips
                + self._training_ranks * self.chips_per_rank)
        return max(0, self.total_chips - used)

    def _export_locked(self) -> None:
        self._g_chips.set(self._serving_chips, arbiter=self.name,
                          side="serving")
        self._g_chips.set(self._training_ranks * self.chips_per_rank,
                          arbiter=self.name, side="training")
        self._g_chips.set(self._free_locked(), arbiter=self.name,
                          side="free")

    # -- the policy --------------------------------------------------------
    def acquire_serving(self, chips: int,
                        now: Optional[float] = None) -> bool:
        """Serving wants ``chips`` more: free pool first, then a
        training yield (elastic shrink toward — never below — the
        training floor).  False when the budget genuinely cannot cover
        the request; the caller holds instead of growing."""
        chips = max(1, int(chips))
        now = self._clock() if now is None else now
        with self._lock:
            self._last_pressure_at = now
            free = self._free_locked()
            if free >= chips:
                self._serving_chips += chips
                self._export_locked()
                flight_record("arbiter_acquire", arbiter=self.name,
                              chips=chips, source="free")
                return True
            if self._training is None:
                return False
            handle, _, floor = self._training
            need = chips - free
            yield_ranks = math.ceil(need / self.chips_per_rank)
            target = self._training_ranks - yield_ranks
            if target < floor:
                flight_record("arbiter_deny", arbiter=self.name,
                              chips=chips, training_ranks=
                              self._training_ranks, floor=floor)
                return False
            # adopt the entitlement BEFORE the resize, outside the lock:
            # the gang's resize listener re-enters _on_training_resize,
            # which must see a confirmation of OUR request (and must not
            # deadlock on this mutex)
            prev_ranks = self._training_ranks
            self._training_ranks = target
            self._serving_chips += chips
            self._export_locked()
        try:
            handle.resize(target)
        except Exception as exc:  # noqa: BLE001 — a refused resize
            #                       (validation, dead gang) denies the
            #                       grant, never crashes a poll
            with self._lock:
                self._training_ranks = prev_ranks
                self._serving_chips -= chips
                self._export_locked()
            flight_record("arbiter_deny", arbiter=self.name,
                          chips=chips, error=str(exc))
            return False
        self._c_moves.inc(1, arbiter=self.name, direction="yield")
        flight_record("arbiter_yield", arbiter=self.name, chips=chips,
                      yielded_ranks=yield_ranks, training_ranks=target)
        get_faults().note("autoscale.arbiter", direction="yield",
                          chips=chips, training_ranks=target)
        return True

    def release_serving(self, chips: int,
                        now: Optional[float] = None) -> None:
        """Serving shrank: its chips return to the free pool (training
        reclaims them later, through :meth:`reclaim`'s off-peak gate)."""
        chips = max(1, int(chips))
        with self._lock:
            self._serving_chips = max(0, self._serving_chips - chips)
            self._export_locked()
        flight_record("arbiter_release", arbiter=self.name, chips=chips)

    def reclaim(self, now: Optional[float] = None) -> int:
        """Off-peak reclaim: with no serving pressure for
        ``reclaim_after_s``, grow training back toward ``preferred``
        with whatever the free pool covers.  Returns ranks reclaimed
        (0 when gated).  Call once per controller poll."""
        now = self._clock() if now is None else now
        with self._lock:
            if self._training is None:
                return 0
            handle, preferred, _ = self._training
            if self._training_ranks >= preferred:
                return 0
            if (self._last_pressure_at is not None
                    and now - self._last_pressure_at < self.reclaim_after_s):
                return 0
            ranks = min(self._free_locked() // self.chips_per_rank,
                        preferred - self._training_ranks)
            if ranks < 1:
                return 0
            target = self._training_ranks + ranks
            # adopt first, resize outside the lock (see acquire_serving)
            prev_ranks = self._training_ranks
            self._training_ranks = target
            self._export_locked()
        try:
            handle.resize(target)
        except Exception:  # noqa: BLE001 — retried next poll
            with self._lock:
                self._training_ranks = prev_ranks
                self._export_locked()
            return 0
        self._c_moves.inc(1, arbiter=self.name, direction="reclaim")
        flight_record("arbiter_reclaim", arbiter=self.name,
                      reclaimed_ranks=ranks, training_ranks=target)
        get_faults().note("autoscale.arbiter", direction="reclaim",
                          reclaimed_ranks=ranks, training_ranks=target)
        return ranks


# ---------------------------------------------------------------------------
# the controller
# ---------------------------------------------------------------------------

class Autoscaler:
    """One control loop: ``/sloz`` source in, pool resizes out.

    :meth:`poll_once` is the whole step, deterministic under an
    explicit ``now`` (the decision tests drive synthetic snapshot feeds
    through fake clocks with zero real sleeps); :meth:`start` wraps it
    in a daemon thread for production use.  With an ``arbiter``
    attached, every grow first acquires chips (training yields under
    sustained pressure), every shrink releases them, and each poll
    gives the arbiter its off-peak reclaim chance."""

    def __init__(self, pool, source=None,
                 policy: Optional[AutoscalePolicy] = None,
                 arbiter: Optional[CapacityArbiter] = None,
                 name: str = "serving", poll_interval_s: float = 1.0,
                 clock: Callable[[], float] = time.monotonic,
                 fetch_timeout_s: float = 2.0,
                 keep_decisions: int = 256,
                 phase: Optional[str] = None):
        from ..telemetry.slo import get_slo_store
        self.pool = pool
        self.source = source if source is not None else get_slo_store()
        self.policy = policy or AutoscalePolicy()
        self.arbiter = arbiter
        self.name = name
        #: restrict decision inputs to one disaggregated pool's
        #: ``@phase=`` planes (None = reduce across every plane, the
        #: colocated deployment).  Two controllers — phase="prefill"
        #: over a PrefillPool, phase="decode" over a ServingReplicaSet
        #: — scale the pools independently off one shared /sloz.
        self.phase = phase
        self.poll_interval_s = float(poll_interval_s)
        self.fetch_timeout_s = float(fetch_timeout_s)
        self._clock = clock
        self._lock = threading.Lock()
        self._pressure_streak = 0
        self._idle_streak = 0
        self._last_grow_at: Optional[float] = None
        self._last_shrink_at: Optional[float] = None
        self._actions = 0
        #: recent decisions, newest last (each with its justifying
        #: snapshot) — the in-process postmortem surface
        self.decisions: Deque[ScaleDecision] = deque(maxlen=keep_decisions)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = get_registry()
        self._c_decisions = reg.counter(
            "autoscale_decisions_total",
            "controller verdicts per poll", ("scaler", "verdict"))
        self._g_replicas = reg.gauge(
            "autoscale_replicas", "serving replicas under autoscaler "
            "control", ("scaler",))

    # -- one deterministic step --------------------------------------------
    def poll_once(self, now: Optional[float] = None) -> ScaleDecision:
        now = self._clock() if now is None else now
        try:
            snapshot = _fetch_sloz(self.source,
                                   timeout_s=self.fetch_timeout_s)
        except Exception as exc:  # noqa: BLE001 — a broken source is a
            #                       recorded verdict, not a dead loop
            return self._finish(ScaleDecision(
                ts=now, verdict="error", reason=f"sloz fetch: {exc}",
                replicas=self._safe_count(), target=None))
        signals = sloz_signals(snapshot, phase=self.phase)
        decision = self._decide(now, signals, snapshot)
        if self.arbiter is not None:
            self.arbiter.reclaim(now)
        return self._finish(decision)

    def _safe_count(self) -> int:
        try:
            return int(self.pool.replica_count())
        except Exception:  # noqa: BLE001
            return -1

    def _decide(self, now: float, signals: Dict[str, Any],
                snapshot: Dict[str, Any]) -> ScaleDecision:
        p = self.policy
        replicas = int(self.pool.replica_count())
        warming = int(getattr(self.pool, "warming_count", lambda: 0)())

        def hold(reason):
            return ScaleDecision(ts=now, verdict="hold", reason=reason,
                                 replicas=replicas, target=None,
                                 signals=signals, snapshot=snapshot)

        if signals["samples"] == 0:
            with self._lock:
                self._pressure_streak = self._idle_streak = 0
            return hold("no_data: every window is empty")

        burn, shed = signals["max_burn"], signals["max_shed"]
        occ = signals["min_occupancy"]
        pressure = ((shed is not None and shed > p.shed_grow)
                    or (burn is not None and burn > p.burn_grow))
        quiet = ((burn is None or burn < p.burn_shrink)
                 and (shed is None or shed <= p.shed_shrink))
        idle = quiet and occ is not None and occ < p.occ_shrink
        with self._lock:
            if pressure:
                self._pressure_streak += 1
                self._idle_streak = 0
            elif idle:
                self._idle_streak += 1
                self._pressure_streak = 0
            else:
                self._pressure_streak = self._idle_streak = 0
            pressure_streak = self._pressure_streak
            idle_streak = self._idle_streak
            actions = self._actions
            last_grow, last_shrink = self._last_grow_at, self._last_shrink_at

        budget_left = (p.max_resizes is None or actions < p.max_resizes)
        if pressure:
            if pressure_streak < p.sustain_polls:
                return hold(f"sustaining_pressure "
                            f"{pressure_streak}/{p.sustain_polls}")
            if warming > 0:
                # PR-15 readyz semantics: a warming replica is capacity
                # already in flight, not a reason to grow again
                return hold(f"warming: {warming} replica(s) in flight")
            if replicas >= p.max_replicas:
                return hold(f"at_max: {replicas} replicas")
            if (last_grow is not None
                    and now - last_grow < p.grow_cooldown_s):
                return hold("grow_cooldown")
            if not budget_left:
                return hold(f"budget_spent: {actions} resizes")
            return self._actuate(now, "grow", replicas, signals, snapshot)
        if idle:
            if idle_streak < p.sustain_polls:
                return hold(f"sustaining_idle {idle_streak}/"
                            f"{p.sustain_polls}")
            if replicas <= p.min_replicas:
                return hold(f"at_min: {replicas} replicas")
            if warming > 0:
                return hold(f"warming: {warming} replica(s) in flight")
            if (last_shrink is not None
                    and now - last_shrink < p.shrink_cooldown_s):
                return hold("shrink_cooldown")
            if not budget_left:
                return hold(f"budget_spent: {actions} resizes")
            return self._actuate(now, "shrink", replicas, signals,
                                 snapshot)
        if occ is not None and occ < p.occ_shrink and not quiet:
            return hold("hysteresis: idle occupancy but burn/shed "
                        "between the bands")
        return hold("steady")

    def _actuate(self, now: float, direction: str, replicas: int,
                 signals: Dict[str, Any],
                 snapshot: Dict[str, Any]) -> ScaleDecision:
        p = self.policy
        if direction == "grow":
            step = min(p.grow_step, p.max_replicas - replicas)
            chips = step * (self.arbiter.chips_per_replica
                            if self.arbiter else 1)
            if self.arbiter is not None and \
                    not self.arbiter.acquire_serving(chips, now):
                return ScaleDecision(
                    ts=now, verdict="hold",
                    reason="no_chips: arbiter denied (training at floor)",
                    replicas=replicas, target=None, signals=signals,
                    snapshot=snapshot)
        else:
            step = min(p.shrink_step, replicas - p.min_replicas)
        try:
            if direction == "grow":
                target = int(self.pool.grow(step))
            else:
                target = int(self.pool.shrink(step))
        except Exception as exc:  # noqa: BLE001 — an actuation failure
            #                       is a recorded verdict; chips granted
            #                       for a failed grow go back
            if direction == "grow" and self.arbiter is not None:
                self.arbiter.release_serving(chips, now)
            return ScaleDecision(
                ts=now, verdict="error",
                reason=f"{direction} failed: {exc}", replicas=replicas,
                target=None, signals=signals, snapshot=snapshot)
        if direction == "shrink" and self.arbiter is not None:
            self.arbiter.release_serving(
                step * self.arbiter.chips_per_replica, now)
        with self._lock:
            self._actions += 1
            self._pressure_streak = self._idle_streak = 0
            if direction == "grow":
                self._last_grow_at = now
            else:
                self._last_shrink_at = now
        return ScaleDecision(ts=now, verdict=direction,
                             reason=f"{direction} {replicas}→{target}",
                             replicas=replicas, target=target,
                             signals=signals, snapshot=snapshot)

    def _finish(self, decision: ScaleDecision) -> ScaleDecision:
        self.decisions.append(decision)
        self._c_decisions.inc(1, scaler=self.name,
                              verdict=decision.verdict)
        count = self._safe_count()
        if count >= 0:
            self._g_replicas.set(count, scaler=self.name)
        # the postmortem contract: every decision rides the flight ring
        # and the fault call log WITH the /sloz snapshot that justified
        # it, so "why did the controller act" is replayable
        flight_record("autoscale_decide", scaler=self.name,
                      verdict=decision.verdict, reason=decision.reason,
                      replicas=decision.replicas, target=decision.target,
                      signals=dict(decision.signals),
                      sloz=decision.snapshot)
        get_faults().note("autoscale.decide", scaler=self.name,
                          verdict=decision.verdict,
                          reason=decision.reason,
                          replicas=decision.replicas,
                          target=decision.target,
                          sloz=decision.snapshot)
        return decision

    # -- the thread --------------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is not None:
            raise RuntimeError("autoscaler already started")
        self._stop.clear()

        def loop():
            while not self._stop.is_set():
                try:
                    self.poll_once()
                except Exception:  # noqa: BLE001 — the loop must outlive
                    pass           # any single poll's surprise
                self._stop.wait(self.poll_interval_s)

        self._thread = threading.Thread(
            target=loop, name=f"autoscaler-{self.name}", daemon=True)
        self._thread.start()
        return self

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout_s)
            self._thread = None
