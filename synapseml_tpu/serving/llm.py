"""Continuous-batching LLM serving: one listener + slotted decode loop.

:class:`LLMServer` is the LLM-shaped sibling of
:class:`~synapseml_tpu.serving.server.PipelineServer`: it wires a
:class:`~synapseml_tpu.serving.server.ServingServer` to a
:class:`~synapseml_tpu.models.llm.SlotEngine` through the
:class:`~synapseml_tpu.serving.server._DecodeLoop` scheduler, so
requests are admitted into KV-cache slots *every decode step* instead
of waiting for a full batch.

Request body (JSON, POST to the api path)::

    {"ids": [1, 2, 3], "max_new_tokens": 32}          # raw token ids
    {"prompt": "text", "stream": true}                 # with a tokenizer

Replies carry ``{"ids": [...]}`` (plus ``"completion"`` when a
tokenizer is configured); ``stream: true`` switches to a chunked body
with one ``{"token": id}`` JSON line per generated token and a final
``{"done": true, ...}`` line.  Load shedding, ``Retry-After``, drain
semantics, and ``/metrics``/``/healthz``/``/readyz`` are the standard
serving contract (see docs/api/serving.md).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from .server import ServingRequest, ServingServer, _DecodeLoop


class LLMServer:
    """Serve an LLM with continuous batching over a slotted KV cache.

    ``model``/``variables`` build a
    :class:`~synapseml_tpu.models.llm.SlotEngine` (or pass a prebuilt
    ``engine=``); ``tokenizer`` (optional, ``encode``/``decode``) lets
    requests carry ``"prompt"`` text instead of raw ``"ids"``.
    ``ttft_slo_s`` arms SLO-aware admission control: queued requests
    whose projected time-to-first-token exceeds it answer 503 +
    ``Retry-After`` — and it doubles as the windowed SLO plane's TTFT
    objective (``GET /sloz``; ``token_slo_s`` optionally declares a
    per-token one).  Every request is traced per-request at admission
    (sampling via ``trace_sample_every``; ``GET /tracez``) and the
    propagated ``X-SML-Trace-Id`` header keeps cross-replica hops
    attributable.  ``attention_backend`` selects the decode-step
    attention read (``'auto'`` = the Pallas paged kernel on TPU when
    the geometry fits VMEM, dense otherwise — see
    docs/api/serving.md "Paged decode attention").  ``spec_draft_len``
    turns on speculative decoding (greedy only): every slot advances
    by its accepted n-gram-drafted span per step and the SLO
    projection divides by the engine's accepted-tokens-per-step — see
    docs/api/serving.md "Speculative decoding".  ``warmup``
    (``'background'``/``'sync'``; default ``'off'``) arms the compile
    plane: the engine's full program lattice is AOT-compiled at
    construction and ``/readyz`` answers 503 ``"warming"`` until it
    finishes — see docs/api/serving.md "Warmup & compile plane"."""

    def __init__(self, model: Any = None, variables: Any = None, *,
                 engine: Any = None, tokenizer: Any = None,
                 n_slots: int = 16, max_len: Optional[int] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/generate",
                 max_new_tokens_default: int = 32,
                 ttft_slo_s: Optional[float] = None,
                 token_slo_s: Optional[float] = None,
                 eos_id: Optional[int] = None, pad_id: int = 0,
                 temperature: float = 0.0, top_k: int = 0,
                 top_p: float = 1.0, min_prefix: int = 8,
                 max_queue: int = 1024, reply_timeout_s: float = 30.0,
                 attention_backend: str = "auto",
                 spec_draft_len: int = 0, spec_ngram: int = 3,
                 trace_sample_every: Optional[int] = None,
                 warmup: str = "off",
                 kv_arena: Any = None,
                 kv_arena_bytes: Optional[int] = None,
                 journal: Any = None,
                 journal_dir: Optional[str] = None,
                 qos: Any = None,
                 tenant_policies: Optional[Dict[str, Any]] = None,
                 max_tenants: int = 256,
                 prefill_pool: Any = None,
                 engine_kwargs: Optional[Dict[str, Any]] = None):
        # session survivability plane (docs/api/serving.md "Session
        # survivability & KV tiering"): kv_arena / kv_arena_bytes
        # attach a host-RAM KV spill tier to the engine (retired slots
        # spill, warm conversations restore token-exactly instead of
        # cold-prefilling); journal / journal_dir arm the fsync'd
        # per-session journal so a killed replica's conversations
        # resume token-exactly here via {"session", "resume"} requests
        if kv_arena is None and kv_arena_bytes:
            from ..models.llm.kvtier import HostKVArena
            kv_arena = HostKVArena(int(kv_arena_bytes),
                                   name=api_path.strip("/") or "llm")
        if journal is None and journal_dir:
            from ..models.llm.kvtier import SessionJournal
            journal = SessionJournal(journal_dir,
                                     name=api_path.strip("/") or "llm")
        if engine is None:
            from ..models.llm import SlotEngine
            engine = SlotEngine(model, variables, n_slots=n_slots,
                                max_len=max_len, temperature=temperature,
                                top_k=top_k, top_p=top_p, eos_id=eos_id,
                                pad_id=pad_id, min_prefix=min_prefix,
                                attention_backend=attention_backend,
                                spec_draft_len=spec_draft_len,
                                spec_ngram=spec_ngram, warmup=warmup,
                                kv_arena=kv_arena,
                                **(engine_kwargs or {}))
        self.engine = engine
        self.kv_arena = getattr(engine, "kv_arena", kv_arena)
        self.journal = journal
        self.tokenizer = tokenizer
        self.server = ServingServer(host, port, api_path,
                                    reply_timeout_s=reply_timeout_s,
                                    max_queue=max_queue)
        # compile-plane readiness gate (ISSUE 15): with a warming
        # engine (warmup='background'/'sync', or a prebuilt engine
        # constructed with one), /readyz answers 503 "warming" — with
        # the plane's live snapshot in the payload — until the full
        # program lattice is AOT-compiled, so a balancer never routes
        # traffic this replica would stall on.  The listener itself
        # keeps accepting: direct requests queue and the decode loop
        # holds them compile-aware instead of shedding.
        plane = getattr(engine, "compile_plane", None)
        if plane is not None:
            self.server.health.set_warmup(plane.snapshot)
        # multi-tenant QoS (docs/api/serving.md "Multi-tenant QoS"):
        # pass a prebuilt QosScheduler via qos=, or just per-tenant
        # TenantPolicy contracts via tenant_policies= — requests carry
        # their tenant in the X-SML-Tenant header or "tenant" payload
        # field, and everything without one bills the default tenant.
        # max_tenants bounds how many DYNAMIC (unregistered) tenant
        # ids may materialise attribution planes — past the cap an
        # unknown tenant answers 429 (tenant ids are client-controlled;
        # unbounded ids would grow memory and /sloz without bound)
        if qos is None and tenant_policies is not None:
            from .qos import QosScheduler
            qos = QosScheduler(policies=dict(tenant_policies))
        self.qos = qos
        # disaggregated prefill/decode (docs/api/serving.md
        # "Disaggregated prefill/decode"): pass a serving.disagg.
        # PrefillPool and every fresh prompt is offered to the pool
        # before admission — its finished K/V ships into THIS replica's
        # host arena (a handoff needs one: pass kv_arena/kv_arena_bytes
        # too) and the admit warm-restores it token-exactly.  Every
        # handoff failure mode degrades to local colocated prefill,
        # counted in disagg_handoffs_total.  The pool is bound to this
        # server's api path so /sloz grows @phase=prefill|decode planes
        # the per-phase autoscalers consume.
        self.prefill_pool = prefill_pool
        if prefill_pool is not None:
            prefill_pool.bind(api_path, self.kv_arena,
                              ttft_slo_s=ttft_slo_s)
        self._loop = _DecodeLoop(
            self.server, self.server._default, engine,
            input_parser=self._parse,
            output_formatter=self._format,
            max_new_tokens_default=max_new_tokens_default,
            ttft_slo_s=ttft_slo_s, token_slo_s=token_slo_s,
            trace_sample_every=trace_sample_every,
            journal=journal, qos=qos, max_tenants=max_tenants,
            disagg=prefill_pool)
        # the loop constructs a default scheduler when none was given —
        # surface THAT one so callers can set policies/read attribution
        if self.qos is None:
            self.qos = self._loop.qos

    # -- request/reply shaping --------------------------------------------
    def _parse(self, req: ServingRequest) -> Dict[str, Any]:
        body = req.json()
        if "ids" in body:
            spec = dict(body)
        elif body.get("resume") and body.get("session") is not None \
                and self.journal is not None:
            # failover resume: the prompt + committed tokens come from
            # the session journal replay, not the request body
            spec = dict(body)
        elif "prompt" in body and self.tokenizer is not None:
            # budget prompt tokens against the engine window, leaving
            # room for the continuation (LLMTransformer's contract)
            budget = self.engine.max_len - int(
                body.get("max_new_tokens",
                         self._loop.max_new_tokens_default)) - 1
            rows = self.tokenizer.encode([str(body["prompt"])],
                                         max(budget, 1))[0]
            ids = [int(t) for t in rows[0] if t]
            spec = dict(body, ids=ids or [0])
        else:
            raise ValueError('request needs "ids" (or "prompt" with a '
                             "tokenizer configured)")
        return spec

    def _format(self, ids: List[int]) -> Dict[str, Any]:
        out: Dict[str, Any] = {"ids": [int(t) for t in ids]}
        if self.tokenizer is not None:
            out["completion"] = self.tokenizer.decode([ids])[0]
        return out

    # -- server surface ----------------------------------------------------
    @property
    def url(self) -> str:
        return self.server.url

    def drain(self, timeout_s: float = 30.0) -> bool:
        """Graceful shutdown with the serving zero-drop contract:
        the listener sheds NEW work immediately, the decode loop keeps
        running so every in-flight sequence decodes to completion (or
        answers a clean 503 + ``Retry-After`` when its projected TTFT is
        already past the SLO), and only then does the loop stop."""
        drained = self.server.drain(timeout_s)
        self._loop.stop()
        return drained

    def close(self) -> None:
        self._loop.stop()
        self.server.close()
