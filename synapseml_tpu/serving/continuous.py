"""Continuous-mode serving client: one persistent framed connection.

The reference's continuous server mode keeps the HTTP exchange machinery
out of the per-record path (reference: website/docs/features/
spark_serving/about.md:18,151-154 — "continuousServer", sub-millisecond
latency).  :class:`ContinuousClient` is the matching client for
:meth:`ServingServer`'s ``Upgrade: sml-frames`` mode: after one HTTP/1.1
upgrade handshake the connection carries length-prefixed binary frames
both ways, replies always in request order.

Pipelining is the point — ``request_many`` keeps a window of frames in
flight so the server batches them into one ``transform`` and the
per-record marginal cost is a 4-byte framed read, not an HTTP exchange.
"""

from __future__ import annotations

import socket
import struct
import time
from typing import Iterable, List, Optional, Tuple

from ..telemetry import get_registry


class ContinuousClient:
    """Persistent framed connection to one ServingServer API.

    >>> c = ContinuousClient(host, port, "/model")
    >>> status, body = c.request(b'{"x": 1.0}')
    >>> replies = c.request_many(payloads)      # pipelined, in order
    """

    def __init__(self, host: str, port: int, path: str = "/",
                 timeout_s: float = 30.0):
        reg = get_registry()
        self._m_records = reg.counter(
            "serving_continuous_client_records_total",
            "frames exchanged through ContinuousClient", ("path",))
        self._m_rps = reg.gauge(
            "serving_continuous_client_records_per_sec",
            "last request_many window's end-to-end records/sec", ("path",))
        self._path = path or "/"
        self._sock = socket.create_connection((host, port),
                                              timeout=timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rfile = self._sock.makefile("rb")
        self._in_flight = 0
        req = (f"GET {path or '/'} HTTP/1.1\r\n"
               f"Host: {host}:{port}\r\n"
               "Connection: Upgrade\r\n"
               "Upgrade: sml-frames\r\n\r\n").encode("latin1")
        self._sock.sendall(req)
        status_line = self._rfile.readline().decode("latin1")
        while True:                       # drain the handshake headers
            line = self._rfile.readline()
            if line in (b"\r\n", b"\n", b""):
                break
        if " 101 " not in status_line:
            self.close()
            raise ConnectionError(
                f"continuous upgrade refused: {status_line.strip()!r}")

    # -- framed protocol ---------------------------------------------------
    def send(self, payload: bytes) -> None:
        """Fire one request frame without waiting for its reply."""
        self._sock.sendall(struct.pack("<I", len(payload)) + payload)
        self._in_flight += 1

    def recv(self) -> Tuple[int, bytes]:
        """Next in-order reply → (status, body)."""
        hdr = self._rfile.read(4)
        if len(hdr) < 4:
            raise ConnectionError("continuous connection closed by server")
        (total,) = struct.unpack("<I", hdr)
        frame = self._rfile.read(total)
        if len(frame) < total or total < 2:
            raise ConnectionError("truncated continuous reply frame")
        (status,) = struct.unpack("<H", frame[:2])
        self._in_flight -= 1
        return status, frame[2:]

    def request(self, payload: bytes) -> Tuple[int, bytes]:
        """One synchronous round trip (send + recv)."""
        self.send(payload)
        reply = self.recv()
        self._m_records.inc(1, path=self._path)
        return reply

    def request_many(self, payloads: Iterable[bytes],
                     window: int = 64) -> List[Tuple[int, bytes]]:
        """Pipelined exchange: keep up to ``window`` frames in flight,
        collect every reply in request order."""
        t0 = time.perf_counter()
        out: List[Tuple[int, bytes]] = []
        for p in payloads:
            while self._in_flight >= max(1, window):
                out.append(self.recv())
            self.send(p)
        while self._in_flight:
            out.append(self.recv())
        dt = time.perf_counter() - t0
        self._m_records.inc(len(out), path=self._path)
        if out and dt > 0:
            self._m_rps.set(len(out) / dt, path=self._path)
        return out

    def close(self) -> None:
        try:
            self._sock.shutdown(socket.SHUT_WR)   # EOF ends the stream
        except OSError:
            pass
        try:
            self._rfile.close()
        finally:
            self._sock.close()

    def __enter__(self) -> "ContinuousClient":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self.close()
        return None
