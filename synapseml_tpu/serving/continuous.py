"""Continuous-mode serving client: one persistent framed connection.

The reference's continuous server mode keeps the HTTP exchange machinery
out of the per-record path (reference: website/docs/features/
spark_serving/about.md:18,151-154 — "continuousServer", sub-millisecond
latency).  :class:`ContinuousClient` is the matching client for
:meth:`ServingServer`'s ``Upgrade: sml-frames`` mode: after one HTTP/1.1
upgrade handshake the connection carries length-prefixed binary frames
both ways, replies always in request order.

Pipelining is the point — ``request_many`` keeps a window of frames in
flight so the server batches them into one ``transform`` and the
per-record marginal cost is a 4-byte framed read, not an HTTP exchange.

Resilience: a long-lived connection WILL break (server restart, LB idle
reset).  ``request``/``request_many`` transparently reconnect ONCE per
call on ``ECONNRESET``/broken pipe/server EOF — replies arrive in
request order, so every payload after the last received reply is known
to be unanswered and is resent on the fresh connection.  Reconnect
attempts back off under a :class:`~synapseml_tpu.resilience.RetryPolicy`
and the ``continuous.send``/``continuous.connect`` fault sites make the
whole path testable without killing a real server.
"""

from __future__ import annotations

import socket
import struct
import time
from typing import Iterable, List, Optional, Tuple

from ..resilience import RetryPolicy, get_faults
from ..telemetry import get_registry


class ContinuousClient:
    """Persistent framed connection to one ServingServer API.

    >>> with ContinuousClient(host, port, "/model") as c:
    ...     status, body = c.request(b'{"x": 1.0}')
    ...     replies = c.request_many(payloads)      # pipelined, in order
    """

    def __init__(self, host: str, port: int, path: str = "/",
                 timeout_s: float = 30.0,
                 reconnect_policy: Optional[RetryPolicy] = None):
        reg = get_registry()
        self._m_records = reg.counter(
            "serving_continuous_client_records_total",
            "frames exchanged through ContinuousClient", ("path",))
        self._m_rps = reg.gauge(
            "serving_continuous_client_records_per_sec",
            "last request_many window's end-to-end records/sec", ("path",))
        self._m_reconnects = reg.counter(
            "serving_continuous_client_reconnects_total",
            "transparent reconnects after a broken connection", ("path",))
        self._host, self._port = host, port
        self._path = path or "/"
        self._timeout_s = timeout_s
        self._reconnect_policy = reconnect_policy or RetryPolicy(
            max_retries=2, base_s=0.05, max_backoff_s=1.0)
        self._closed = False
        self._sock: Optional[socket.socket] = None
        self._rfile = None
        self._in_flight = 0
        self._connect()

    def _connect(self) -> None:
        """Dial + upgrade handshake (fault site ``continuous.connect``)."""
        get_faults().raise_point("continuous.connect")
        self._sock = socket.create_connection((self._host, self._port),
                                              timeout=self._timeout_s)
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._rfile = self._sock.makefile("rb")
        self._in_flight = 0
        req = (f"GET {self._path} HTTP/1.1\r\n"
               f"Host: {self._host}:{self._port}\r\n"
               "Connection: Upgrade\r\n"
               "Upgrade: sml-frames\r\n\r\n").encode("latin1")
        self._sock.sendall(req)
        status_line = self._rfile.readline().decode("latin1")
        while True:                       # drain the handshake headers
            line = self._rfile.readline()
            if line in (b"\r\n", b"\n", b""):
                break
        if " 101 " not in status_line:
            self._teardown()
            raise ConnectionError(
                f"continuous upgrade refused: {status_line.strip()!r}")

    def _reconnect(self) -> None:
        """Re-dial under the reconnect policy's backoff; in-flight frames
        on the dead connection are the caller's to resend."""
        self._teardown()
        policy = self._reconnect_policy
        last: Optional[Exception] = None
        for attempt in range(policy.max_retries + 1):
            try:
                self._connect()
                self._m_reconnects.inc(1, path=self._path)
                return
            except (ConnectionError, OSError) as e:
                last = e
                if attempt < policy.max_retries:
                    policy.sleep(policy.backoff_s(attempt),
                                 site="continuous.reconnect")
        raise ConnectionError(
            f"continuous reconnect to {self._host}:{self._port} failed: "
            f"{last}")

    # -- framed protocol ---------------------------------------------------
    def send(self, payload: bytes) -> None:
        """Fire one request frame without waiting for its reply."""
        get_faults().raise_point("continuous.send")
        self._sock.sendall(struct.pack("<I", len(payload)) + payload)
        self._in_flight += 1

    def recv(self) -> Tuple[int, bytes]:
        """Next in-order reply → (status, body)."""
        get_faults().raise_point("continuous.recv")
        hdr = self._rfile.read(4)
        if len(hdr) < 4:
            raise ConnectionError("continuous connection closed by server")
        (total,) = struct.unpack("<I", hdr)
        frame = self._rfile.read(total)
        if len(frame) < total or total < 2:
            raise ConnectionError("truncated continuous reply frame")
        (status,) = struct.unpack("<H", frame[:2])
        self._in_flight -= 1
        return status, frame[2:]

    def request(self, payload: bytes) -> Tuple[int, bytes]:
        """One synchronous round trip (send + recv), with one transparent
        reconnect-and-resend on a broken connection."""
        try:
            self.send(payload)
            reply = self.recv()
        except (ConnectionResetError, BrokenPipeError, ConnectionError):
            if self._closed:
                raise
            self._reconnect()
            self.send(payload)
            reply = self.recv()
        self._m_records.inc(1, path=self._path)
        return reply

    def request_many(self, payloads: Iterable[bytes],
                     window: int = 64) -> List[Tuple[int, bytes]]:
        """Pipelined exchange: keep up to ``window`` frames in flight,
        collect every reply in request order.

        On ``ECONNRESET``/broken pipe/server EOF mid-exchange the client
        reconnects ONCE and resends exactly the unanswered suffix
        (replies are in order, so everything after the last received
        reply is known-unanswered); a second break raises."""
        t0 = time.perf_counter()
        items = list(payloads)
        out: List[Tuple[int, bytes]] = []
        sent = 0
        reconnects_left = 1
        while len(out) < len(items):
            try:
                if sent < len(items) and self._in_flight < max(1, window):
                    self.send(items[sent])
                    sent += 1
                else:
                    out.append(self.recv())
            except (ConnectionResetError, BrokenPipeError, ConnectionError):
                if self._closed or reconnects_left <= 0:
                    raise
                reconnects_left -= 1
                self._reconnect()
                sent = len(out)          # resend the unanswered suffix
        dt = time.perf_counter() - t0
        self._m_records.inc(len(out), path=self._path)
        if out and dt > 0:
            self._m_rps.set(len(out) / dt, path=self._path)
        return out

    # -- lifecycle ---------------------------------------------------------
    def _teardown(self) -> None:
        """Close the socket + its makefile handle (both, or the fd leaks
        through the buffered reader), tolerating any prior state."""
        rfile, sock = self._rfile, self._sock
        self._rfile = self._sock = None
        self._in_flight = 0
        if rfile is not None:
            try:
                rfile.close()
            except OSError:
                pass
        if sock is not None:
            try:
                sock.close()
            except OSError:
                pass

    def close(self) -> None:
        """Idempotent: EOF the stream so queued server replies flush,
        then release the socket and makefile handle."""
        if self._closed:
            return
        self._closed = True
        if self._sock is not None:
            try:
                self._sock.shutdown(socket.SHUT_WR)   # EOF ends the stream
            except OSError:
                pass
        self._teardown()

    def __enter__(self) -> "ContinuousClient":
        return self

    def __exit__(self, *exc) -> Optional[bool]:
        self.close()
        return None

    def __del__(self):       # last-resort leak guard; close() is the API
        try:
            self.close()
        except Exception:
            pass
