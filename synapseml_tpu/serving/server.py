"""HTTP ⇄ Dataset serving.

Re-designs Spark Serving (reference: core/src/main/scala/org/apache/spark/
sql/execution/streaming/HTTPSourceV2.scala:56-90 — an HttpServer hosted in
a partition task turning requests into rows {id, request}; ServingUDFs.
scala:40-53 — ``sendReplyUDF`` routing response bytes back to the open
exchange by request id; DistributedHTTPSource.scala:88,203 — ONE server per
JVM hosting MULTIPLE named APIs).  Here the source/sink pair is explicit:

- :class:`ServingServer` hosts any number of registered APIs on one
  listener; each API owns a bounded micro-batch queue (backpressure: a
  full queue answers 503 immediately instead of parking the exchange) and
  a pending-exchange map keyed by request id.
- :class:`PipelineServer` is the continuous-serving loop for one API —
  batch → ``model.transform`` → reply — so the jitted model sees
  fixed-size batches instead of per-request calls.
- :class:`MultiPipelineServer` runs several named pipelines on one
  server, one serving loop per API (the multi-API routing of
  HTTPSourceV2's ServiceInfo registry).
"""

from __future__ import annotations

import json
import threading
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from queue import Empty, Full, Queue
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.dataset import Dataset
from ..core.pipeline import Transformer


@dataclass
class ServingRequest:
    """One pending request row (reference: HTTPSourceV2 row schema
    {id, request})."""
    id: str
    method: str
    path: str
    headers: Dict[str, str]
    body: bytes

    def json(self) -> Any:
        return json.loads(self.body.decode("utf-8"))


@dataclass
class ServingReply:
    status: int = 200
    body: bytes = b""
    headers: Dict[str, str] = field(default_factory=dict)


class _Exchange:
    __slots__ = ("request", "event", "reply")

    def __init__(self, request: ServingRequest):
        self.request = request
        self.event = threading.Event()
        self.reply: Optional[ServingReply] = None


class ApiHandle:
    """One named API's source/sink pair: bounded request queue + pending
    exchanges.  ``get_batch``/``reply`` mirror HTTPSourceV2 getBatch and
    ServingUDFs.sendReplyUDF for this API only."""

    def __init__(self, path: str, max_queue: int = 1024,
                 reply_timeout_s: float = 30.0):
        self.path = path
        self.reply_timeout_s = reply_timeout_s
        self._queue: "Queue[_Exchange]" = Queue(maxsize=max_queue)
        self._pending: Dict[str, _Exchange] = {}
        self._lock = threading.Lock()

    # -- server side -------------------------------------------------------
    def submit(self, req: ServingRequest) -> Optional[_Exchange]:
        """Enqueue; None ⇒ queue saturated (caller answers 503).

        Registered in ``_pending`` BEFORE the queue put: a fast pipeline
        can drain + reply the instant the exchange is visible, and a reply
        must find the registration or it would be silently dropped."""
        ex = _Exchange(req)
        with self._lock:
            self._pending[req.id] = ex
        try:
            self._queue.put_nowait(ex)
        except Full:
            with self._lock:
                self._pending.pop(req.id, None)
            return None
        return ex

    def forget(self, request_id: str) -> None:
        with self._lock:
            self._pending.pop(request_id, None)

    # -- source side (micro-batch pull; HTTPSourceV2 getBatch analogue) ----
    def get_batch(self, max_rows: int = 64,
                  timeout_s: float = 0.05) -> List[ServingRequest]:
        """Block up to ``timeout_s`` for the first request, then drain only
        what is already queued — continuous-mode semantics: a lone request
        is served immediately instead of waiting out the batch window,
        while a burst still rides one batched transform."""
        out: List[_Exchange] = []
        try:
            out.append(self._queue.get(timeout=timeout_s))
        except Empty:
            return []
        while len(out) < max_rows:
            try:
                out.append(self._queue.get_nowait())
            except Empty:
                break
        return [e.request for e in out]

    # -- sink side (ServingUDFs.sendReplyUDF analogue) ---------------------
    def reply(self, request_id: str, reply: ServingReply) -> bool:
        with self._lock:
            ex = self._pending.get(request_id)
        if ex is None:
            return False
        ex.reply = reply
        ex.event.set()
        return True


class ServingServer:
    """One HTTP listener per host hosting any number of named APIs (the
    DistributedHTTPSource model — one server per JVM, many sources;
    multi-host serving runs one per TPU-VM worker behind an external
    balancer).  The single-API constructor arguments keep the original
    one-endpoint usage working unchanged."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", reply_timeout_s: float = 30.0,
                 max_queue: int = 1024):
        self.api_path = api_path.rstrip("/") or "/"
        self._apis: Dict[str, ApiHandle] = {}
        self._apis_lock = threading.Lock()
        self._default = self.register_api(self.api_path, max_queue,
                                          reply_timeout_s)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _serve(self):
                api = outer._route(self.path)
                if api is None:
                    self.send_error(404, "no API registered at this path")
                    return
                length = int(self.headers.get("Content-Length", 0) or 0)
                body = self.rfile.read(length) if length else b""
                req = ServingRequest(
                    id=uuid.uuid4().hex, method=self.command,
                    path=self.path, headers=dict(self.headers), body=body)
                ex = api.submit(req)
                if ex is None:                       # backpressure
                    self.send_error(503, "serving queue saturated")
                    return
                ok = ex.event.wait(api.reply_timeout_s)
                api.forget(req.id)
                if not ok or ex.reply is None:
                    self.send_error(504, "serving pipeline timeout")
                    return
                rep = ex.reply
                self.send_response(rep.status)
                for k, v in rep.headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(rep.body)))
                self.end_headers()
                self.wfile.write(rep.body)

            do_GET = do_POST = do_PUT = _serve

        class _Server(ThreadingHTTPServer):
            # default listen backlog (5) RSTs bursts of concurrent connects
            request_queue_size = 128
            daemon_threads = True

        self._httpd = _Server((host, port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    # -- API registry (HTTPSourceV2 ServiceInfo analogue) ------------------
    def register_api(self, path: str, max_queue: int = 1024,
                     reply_timeout_s: float = 30.0) -> ApiHandle:
        path = path.rstrip("/") or "/"
        with self._apis_lock:
            if path in self._apis:
                return self._apis[path]
            handle = ApiHandle(path, max_queue, reply_timeout_s)
            self._apis[path] = handle
            return handle

    def _route(self, request_path: str) -> Optional[ApiHandle]:
        """Longest registered prefix wins ("/a/b" before "/a")."""
        with self._apis_lock:
            best = None
            for path, handle in self._apis.items():
                if path == "/" or request_path == path \
                        or request_path.startswith(path + "/") \
                        or request_path.startswith(path + "?"):
                    if best is None or len(path) > len(best.path):
                        best = handle
            return best

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        h, p = self.address
        return f"http://{h}:{p}{'' if self.api_path == '/' else self.api_path}"

    def url_for(self, path: str) -> str:
        h, p = self.address
        path = path.rstrip("/") or "/"
        return f"http://{h}:{p}{'' if path == '/' else path}"

    # -- default-API passthrough (original one-endpoint surface) -----------
    def get_batch(self, max_rows: int = 64,
                  timeout_s: float = 0.05) -> List[ServingRequest]:
        return self._default.get_batch(max_rows, timeout_s)

    def reply(self, request_id: str, reply: ServingReply) -> bool:
        # request ids are unique across APIs; try the owning handle first
        if self._default.reply(request_id, reply):
            return True
        with self._apis_lock:
            handles = list(self._apis.values())
        return any(h.reply(request_id, reply) for h in handles
                   if h is not self._default)

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


class _ApiLoop:
    """One API's continuous loop: batch → transform → reply."""

    def __init__(self, server: ServingServer, api: ApiHandle,
                 model: Transformer,
                 input_parser: Callable[[ServingRequest], Dict[str, Any]],
                 output_col: str,
                 output_formatter: Callable[[Any], bytes],
                 batch_size: int, batch_timeout_s: float):
        self.server = server
        self.api = api
        self.model = model
        self.input_parser = input_parser
        self.output_col = output_col
        self.output_formatter = output_formatter
        self.batch_size = batch_size
        self.batch_timeout_s = batch_timeout_s
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            batch = self.api.get_batch(self.batch_size, self.batch_timeout_s)
            if not batch:
                continue
            try:
                rows = [self.input_parser(r) for r in batch]
                ds = Dataset.from_rows(rows)
                out = self.model.transform(ds)
                col = out[self.output_col]
                for req, val in zip(batch, col):
                    self.api.reply(req.id, ServingReply(
                        200, self.output_formatter(val),
                        {"Content-Type": "application/json"}))
            except Exception as e:  # noqa: BLE001 — serving must not die
                body = json.dumps({"error": str(e)}).encode()
                for req in batch:
                    self.api.reply(req.id, ServingReply(500, body))

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)


def _default_format(value: Any) -> bytes:
    if isinstance(value, np.ndarray):
        value = value.tolist()
    elif isinstance(value, (np.generic,)):
        value = value.item()
    return json.dumps({"prediction": value}).encode()


class PipelineServer:
    """Continuous serving loop for ONE model: requests → Dataset →
    ``model.transform`` → replies (the ``readStream.continuousServer()``
    pipeline of reference §3.5 collapsed into one object)."""

    def __init__(self, model: Transformer,
                 input_parser: Callable[[ServingRequest], Dict[str, Any]],
                 output_col: str = "prediction",
                 output_formatter: Optional[Callable[[Any], bytes]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", batch_size: int = 64,
                 batch_timeout_s: float = 0.01, max_queue: int = 1024):
        self.model = model
        self.server = ServingServer(host, port, api_path,
                                    max_queue=max_queue)
        self._loop = _ApiLoop(self.server, self.server._default, model,
                              input_parser, output_col,
                              output_formatter or _default_format,
                              batch_size, batch_timeout_s)

    _default_format = staticmethod(_default_format)

    @property
    def url(self) -> str:
        return self.server.url

    def close(self) -> None:
        self._loop.stop()
        self.server.close()


class MultiPipelineServer:
    """Several named pipelines on ONE server — request paths route to the
    API whose pipeline should serve them (reference: multiple named APIs
    with per-executor shared servers, HTTPSourceV2.scala:47-90,
    DistributedHTTPSource.scala:203).

    ``apis``: {path: spec} where spec is a dict with keys ``model``,
    ``input_parser`` and optional ``output_col``/``output_formatter``/
    ``batch_size``/``batch_timeout_s``/``max_queue``.
    """

    def __init__(self, apis: Dict[str, Dict[str, Any]],
                 host: str = "127.0.0.1", port: int = 0):
        if not apis:
            raise ValueError("MultiPipelineServer needs at least one API")
        first = next(iter(apis))
        self.server = ServingServer(
            host, port, api_path=first,
            max_queue=int(apis[first].get("max_queue", 1024)))
        self._loops: List[_ApiLoop] = []
        for path, spec in apis.items():
            handle = self.server.register_api(
                path, max_queue=int(spec.get("max_queue", 1024)))
            self._loops.append(_ApiLoop(
                self.server, handle, spec["model"], spec["input_parser"],
                spec.get("output_col", "prediction"),
                spec.get("output_formatter") or _default_format,
                int(spec.get("batch_size", 64)),
                float(spec.get("batch_timeout_s", 0.01))))

    def url_for(self, path: str) -> str:
        return self.server.url_for(path)

    def close(self) -> None:
        for loop in self._loops:
            loop.stop()
        self.server.close()
