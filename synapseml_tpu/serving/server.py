"""HTTP ⇄ Dataset serving.

Re-designs Spark Serving (reference: core/src/main/scala/org/apache/spark/
sql/execution/streaming/HTTPSourceV2.scala:56-90 — an HttpServer hosted in
a partition task turning requests into rows {id, request}; ServingUDFs.
scala:40-53 — ``sendReplyUDF`` routing response bytes back to the open
exchange by request id; DistributedHTTPSource.scala:88,203 — one server
per JVM).  Here the source/sink pair is explicit: :class:`ServingServer`
accepts requests into a micro-batch queue and parks each exchange on an
event until :meth:`reply` lands; :class:`PipelineServer` is the
continuous-serving loop — batch → ``model.transform`` → reply — so the
jitted model sees fixed-size batches instead of per-request calls.
"""

from __future__ import annotations

import json
import threading
import uuid
from dataclasses import dataclass, field
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from queue import Empty, Queue
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from ..core.dataset import Dataset
from ..core.pipeline import Transformer


@dataclass
class ServingRequest:
    """One pending request row (reference: HTTPSourceV2 row schema
    {id, request})."""
    id: str
    method: str
    path: str
    headers: Dict[str, str]
    body: bytes

    def json(self) -> Any:
        return json.loads(self.body.decode("utf-8"))


@dataclass
class ServingReply:
    status: int = 200
    body: bytes = b""
    headers: Dict[str, str] = field(default_factory=dict)


class _Exchange:
    __slots__ = ("request", "event", "reply")

    def __init__(self, request: ServingRequest):
        self.request = request
        self.event = threading.Event()
        self.reply: Optional[ServingReply] = None


class ServingServer:
    """HTTP source + reply sink (one server per host — the
    DistributedHTTPSource model; multi-host serving runs one per TPU-VM
    worker behind an external balancer)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", reply_timeout_s: float = 30.0):
        self.api_path = api_path.rstrip("/") or "/"
        self.reply_timeout_s = reply_timeout_s
        self._queue: "Queue[_Exchange]" = Queue()
        self._pending: Dict[str, _Exchange] = {}
        self._lock = threading.Lock()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _serve(self):
                if outer.api_path != "/" and \
                        not self.path.startswith(outer.api_path):
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0) or 0)
                body = self.rfile.read(length) if length else b""
                req = ServingRequest(
                    id=uuid.uuid4().hex, method=self.command,
                    path=self.path, headers=dict(self.headers), body=body)
                ex = _Exchange(req)
                with outer._lock:
                    outer._pending[req.id] = ex
                outer._queue.put(ex)
                ok = ex.event.wait(outer.reply_timeout_s)
                with outer._lock:
                    outer._pending.pop(req.id, None)
                if not ok or ex.reply is None:
                    self.send_error(504, "serving pipeline timeout")
                    return
                rep = ex.reply
                self.send_response(rep.status)
                for k, v in rep.headers.items():
                    self.send_header(k, v)
                self.send_header("Content-Length", str(len(rep.body)))
                self.end_headers()
                self.wfile.write(rep.body)

            do_GET = do_POST = do_PUT = _serve

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    @property
    def address(self) -> Tuple[str, int]:
        return self._httpd.server_address[:2]

    @property
    def url(self) -> str:
        h, p = self.address
        return f"http://{h}:{p}{'' if self.api_path == '/' else self.api_path}"

    # -- source side (micro-batch pull; HTTPSourceV2 getBatch analogue) ----
    def get_batch(self, max_rows: int = 64,
                  timeout_s: float = 0.05) -> List[ServingRequest]:
        """Block up to ``timeout_s`` for the first request, then drain only
        what is already queued — continuous-mode semantics: a lone request
        is served immediately instead of waiting out the batch window,
        while a burst still rides one batched transform."""
        out: List[_Exchange] = []
        try:
            out.append(self._queue.get(timeout=timeout_s))
        except Empty:
            return []
        while len(out) < max_rows:
            try:
                out.append(self._queue.get_nowait())
            except Empty:
                break
        return [e.request for e in out]

    # -- sink side (ServingUDFs.sendReplyUDF analogue) ---------------------
    def reply(self, request_id: str, reply: ServingReply) -> bool:
        with self._lock:
            ex = self._pending.get(request_id)
        if ex is None:
            return False
        ex.reply = reply
        ex.event.set()
        return True

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)


class PipelineServer:
    """Continuous serving loop: requests → Dataset → ``model.transform`` →
    replies (the ``readStream.continuousServer()`` pipeline of reference
    §3.5 collapsed into one object).

    ``input_parser(request) -> dict`` produces one row; the transformed
    column ``output_col`` is JSON-encoded back (override with
    ``output_formatter``).
    """

    def __init__(self, model: Transformer,
                 input_parser: Callable[[ServingRequest], Dict[str, Any]],
                 output_col: str = "prediction",
                 output_formatter: Optional[Callable[[Any], bytes]] = None,
                 host: str = "127.0.0.1", port: int = 0,
                 api_path: str = "/", batch_size: int = 64,
                 batch_timeout_s: float = 0.01):
        self.model = model
        self.input_parser = input_parser
        self.output_col = output_col
        self.output_formatter = output_formatter or self._default_format
        self.batch_size = batch_size
        self.batch_timeout_s = batch_timeout_s
        self.server = ServingServer(host, port, api_path)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    @staticmethod
    def _default_format(value: Any) -> bytes:
        if isinstance(value, np.ndarray):
            value = value.tolist()
        elif isinstance(value, (np.generic,)):
            value = value.item()
        return json.dumps({"prediction": value}).encode()

    @property
    def url(self) -> str:
        return self.server.url

    def _loop(self) -> None:
        while not self._stop.is_set():
            batch = self.server.get_batch(self.batch_size,
                                          self.batch_timeout_s)
            if not batch:
                continue
            try:
                rows = [self.input_parser(r) for r in batch]
                ds = Dataset.from_rows(rows)
                out = self.model.transform(ds)
                col = out[self.output_col]
                for req, val in zip(batch, col):
                    self.server.reply(req.id, ServingReply(
                        200, self.output_formatter(val),
                        {"Content-Type": "application/json"}))
            except Exception as e:  # noqa: BLE001 — serving must not die
                body = json.dumps({"error": str(e)}).encode()
                for req in batch:
                    self.server.reply(req.id, ServingReply(500, body))

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self.server.close()
